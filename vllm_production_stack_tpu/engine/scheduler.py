"""Continuous-batching scheduler with chunked prefill and preemption.

The reference's engines run vLLM's scheduler (external, controlled via flags
like --enable-chunked-prefill, helm deployment-vllm-multi.yaml:140-146); this
is the TPU engine's own: it emits fixed-*logical* work items (one prefill
chunk, or one decode batch) which the model runner pads into bucketed device
shapes. Policy: decode and prefill alternate when both are pending, so a long
prompt can't stall token generation (the point of chunked prefill) and decode
can't starve admissions.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field

from ..qos import FairShareClock, TenantAccounting
from .config import CacheConfig, ModelConfig, SchedulerConfig
from .kv_cache import KVBlockPool, chain_hash
from .request import Request, RequestStatus
from .saturation import GoodputLedger
from .spec_decode import propose_ngram


def _is_stop_token(
    tok: int, sampling, eos: int | None, n_outputs: int
) -> bool:
    """THE stop-token predicate — shared by the bulk-accept cut scan and
    _maybe_finish so a new stop condition can't be added to one and silently
    missed by the other. n_outputs counts output tokens INCLUDING `tok`
    (min_tokens suppresses eos/stop finishes until satisfied)."""
    if n_outputs < sampling.min_tokens:
        return False
    return (eos is not None and tok == eos) or tok in sampling.stop_token_ids


@dataclass
class PrefillWork:
    """One batched prefill dispatch: one chunk from each of N requests, padded
    by the runner into a (B, T) device shape. Per-request `sample[i]` is set
    when row i's chunk reaches the end of its prompt (its last-token logits
    produce the first output token). Batching prompts into one dispatch is
    where prefill throughput comes from — B=1-per-dispatch serializes the MXU
    on small matmuls."""

    requests: list[Request] = field(default_factory=list)
    token_ids: list[list[int]] = field(default_factory=list)
    positions: list[list[int]] = field(default_factory=list)
    slot_mappings: list[list[int]] = field(default_factory=list)
    context_lens: list[int] = field(default_factory=list)
    sample: list[bool] = field(default_factory=list)

    def add_row(
        self,
        request: Request,
        token_ids: list[int],
        positions: list[int],
        slot_mapping: list[int],
        context_len: int,
        sample: bool,
    ) -> None:
        self.requests.append(request)
        self.token_ids.append(token_ids)
        self.positions.append(positions)
        self.slot_mappings.append(slot_mapping)
        self.context_lens.append(context_len)
        self.sample.append(sample)


@dataclass
class DecodeWork:
    """A window of `window` decode steps for each request in the batch, fused
    into one device dispatch (engine/model_runner.py decode-window program).
    Blocks are pre-allocated to cover the whole window; tokens sampled past a
    per-request stop condition are discarded in postprocess."""

    requests: list[Request]
    window: int = 1
    token_ids: list[int] = field(default_factory=list)  # first token per req
    positions: list[int] = field(default_factory=list)  # first position per req
    # async pipeline: row index into the IN-FLIGHT DecodeWork whose last
    # sampled (device-resident) token is this row's input, or -1 when
    # token_ids[i] carries a host-known token. Empty = all host (sync path).
    chain_rows: list[int] = field(default_factory=list)


@dataclass
class VerifyWork:
    """One speculative-verification dispatch (engine/spec_decode.py): each
    row feeds [current token] + its proposal; the model's argmax at every
    position confirms or replaces proposals, yielding 1..k+1 tokens per
    row in one dispatch. Rows without a proposal feed just their current
    token (a plain greedy decode step). On the pipelined step loop a
    verify dispatch is in-flight work like a decode window: its rows
    advance speculatively by their fed length (full acceptance), and a
    partial acceptance at resolve time invalidates whatever was chained on
    top — the same rollback a mid-window stop takes. A verify can itself
    be CHAINED on an in-flight verify (chain_rows): the in-flight step's
    fed proposals are host-known values under the full-acceptance
    speculation, so the proposer extends them, and only the in-flight
    bonus token — this row's first fed token — is spliced device-side."""

    requests: list[Request] = field(default_factory=list)
    token_ids: list[list[int]] = field(default_factory=list)  # fed tokens
    positions: list[list[int]] = field(default_factory=list)
    proposals: list[list[int]] = field(default_factory=list)
    context_lens: list[int] = field(default_factory=list)  # resident after
    # which proposer drafted each row ("ngram" | "draft") — acceptance
    # counters attribute per proposer (tpu:spec_decode_*_tokens_total)
    proposers: list[str] = field(default_factory=list)
    # async pipeline: row index into the IN-FLIGHT VerifyWork whose
    # device-resident bonus token is this row's first fed token, or -1
    # when token_ids[i][0] carries a host-known token (non-chained row /
    # sync path). Empty = all host.
    chain_rows: list[int] = field(default_factory=list)


ScheduleOutput = PrefillWork | DecodeWork | VerifyWork

# chained decode windows a verify-capable row rides after a failed propose
# attempt before it sits one step out to re-propose with resolved values —
# without the sit-out the steady two-deep pipeline never hands such a row a
# proposal window again (its tokens are perpetually in flight at schedule
# time), and speculation would silently disengage after the first window
SPEC_RETRY_WINDOWS = 1


class Scheduler:
    def __init__(
        self,
        model_config: ModelConfig,
        cache_config: CacheConfig,
        scheduler_config: SchedulerConfig,
        host_tier=None,
        need_slot_mappings: bool = False,
        accounting: TenantAccounting | None = None,
        flow=None,
        hydrator=None,
    ):
        self.model_config = model_config
        self.cache_config = cache_config
        self.config = scheduler_config
        self.block_size = cache_config.block_size
        # per-token slot mappings feed only the sp>1 prefill path (row
        # scatter); the paged path commits blockwise, so skipping ~T Python
        # _slot calls per scheduled chunk keeps the host off the hot path
        self.need_slot_mappings = need_slot_mappings
        self.pool = KVBlockPool(
            cache_config.num_blocks,
            cache_config.block_size,
            cache_config.enable_prefix_caching,
            host_tier=host_tier,
        )
        self.waiting: deque[Request] = deque()
        self.running: list[Request] = []
        self._hash_chains: dict[str, list[int]] = {}  # req id -> per-block hashes
        self._last_was_prefill = False
        self._last_was_verify = False
        self.total_preemptions = 0
        # speculative-decoding counters (vLLM metric parity:
        # spec_decode_num_draft_tokens / num_accepted_tokens), plus the
        # per-proposer split behind tpu:spec_decode_proposed_tokens_total /
        # accepted_tokens_total{proposer=} (closed label set)
        self.spec_proposed_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_proposed_by = {"ngram": 0, "draft": 0}
        self.spec_accepted_by = {"ngram": 0, "draft": 0}
        # draft-model proposer (engine/spec_decode.DraftModelProposer) —
        # the engine attaches it when --speculative-config draft; n-gram
        # stays the zero-weight fallback for rows it declines
        self.draft_proposer = None
        # requests whose deadline expired while queued or decoding (the
        # admission-time "would queue past deadline" rejections are counted
        # by the engine — they never reach the scheduler)
        self.deadline_expired_total = 0
        # requests finished outside a step (e.g. resumed request that outgrew
        # the pool) — the engine drains these to emit terminal outputs
        self._finished_externally: list[Request] = []
        # -- multi-tenant QoS (docs/27-multitenancy.md) --------------------
        # per-tenant weighted fair share + accounting. _qos_active latches
        # True on the first request carrying non-default tenant stamps;
        # until then every pick/victim path short-circuits to the pre-QoS
        # FIFO behavior, so an unconfigured stack pays nothing.
        self.accounting = accounting or TenantAccounting()
        self._fair = FairShareClock()
        self._qos_active = False
        # shed evictions: the admission gate (HTTP threads, lock-free) marks
        # a lowest-priority WAITING request for eviction when a higher-
        # priority request would otherwise be refused at a full queue; the
        # step thread applies the marks at the top of schedule().
        self._evict_lock = threading.Lock()
        self._evict_rids: set[str] = set()
        self.shed_evictions = 0
        # goodput ledger (docs/29-saturation-slo.md): every device-sampled
        # token classified exactly once as delivered or wasted{reason}.
        # Mutated only under the engine lock (postprocess / finish /
        # preempt here, plus the engine's pipeline-rollback sites).
        self.ledger = GoodputLedger()
        # KV flow meter (docs/30-kv-flow-telemetry.md): _admit records each
        # request's hydration partition here exactly once. Standalone
        # construction (tests) gets its own meter so the attribution
        # counters always exist; the engine passes its shared one.
        if flow is None:
            from .kv_flow import KVFlowMeter

            flow = KVFlowMeter()
        self.flow = flow
        # compute-or-load hydration planner (docs/31-hydration-planner.md,
        # engine/hydration.Hydrator): when set, first admissions with a
        # disk/remote-resident prefix split it into chunks decided
        # load-vs-recompute, and _try_add_chunk consumes fetched chunks as
        # they land instead of blocking the whole reload in match_prefix.
        # None (unit tests, engines without lower tiers) = legacy path.
        self.hydrator = hydrator

    # -- admission ---------------------------------------------------------

    def add_request(self, req: Request) -> None:
        if req.num_prompt_tokens >= self.model_config.max_model_len:
            raise ValueError(
                f"prompt of {req.num_prompt_tokens} tokens exceeds "
                f"max_model_len={self.model_config.max_model_len}"
            )
        if self._blocks_needed(req.num_prompt_tokens + 1) > self.pool.num_usable:
            raise ValueError(
                f"prompt of {req.num_prompt_tokens} tokens cannot fit the KV "
                f"pool ({self.pool.num_usable} blocks of {self.block_size})"
            )
        if req.tenant_id != "default" or req.priority != 1 or req.weight != 1.0:
            self._qos_active = True
        req.status = RequestStatus.WAITING
        self.waiting.append(req)

    def abort_request(self, request_id: str) -> Request | None:
        for q in (self.running, self.waiting):
            for req in q:
                if req.request_id == request_id:
                    q.remove(req)
                    self._finish(req, RequestStatus.FINISHED_ABORTED)
                    return req
        return None

    @property
    def num_waiting(self) -> int:
        return len(self.waiting) + sum(
            1 for r in self.running if not r.prefill_done
        )

    @property
    def num_running(self) -> int:
        return sum(1 for r in self.running if r.prefill_done)

    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running)

    # -- scheduling --------------------------------------------------------

    def expire_deadlines(self, now: float | None = None) -> int:
        """Sweep waiting + running for requests whose deadline passed and
        finish them with FINISHED_DEADLINE — an expired request must not
        burn another prefill chunk or decode window on a reply nobody will
        read. Finished requests surface through take_finished_externally
        (terminal output with finish reason "deadline"). Requests with
        tokens in flight (async pipeline) are finished too: postprocess
        voids their resolved rows and speculation_valid rolls back any step
        dispatched on top of them — the same path aborts take."""
        import time as _time

        now = _time.monotonic() if now is None else now

        def alive(r: Request) -> bool:
            return r.deadline is None or now <= r.deadline

        expired = [
            r for q in (self.waiting, self.running) for r in q if not alive(r)
        ]
        if not expired:
            return 0
        # rebuild each queue once — per-request remove() would make the
        # sweep O(expired × queue_len) at the top of every schedule() call
        kept_waiting = [r for r in self.waiting if alive(r)]
        self.waiting.clear()
        self.waiting.extend(kept_waiting)
        self.running = [r for r in self.running if alive(r)]
        for req in expired:
            self._finish(req, RequestStatus.FINISHED_DEADLINE)
            self._finished_externally.append(req)
            self.deadline_expired_total += 1
        return len(expired)

    # -- multi-tenant QoS: shed eviction + fair-share pick ------------------

    def mark_shed_victim(self, than_rank: int) -> bool:
        """Called LOCK-FREE from the admission gate (HTTP threads) when the
        waiting queue is full: pick the newest waiting request whose
        priority rank is strictly worse than `than_rank` and mark it for
        eviction, making room for the higher-priority arrival. Returns
        False (the arrival is shed instead) when no such victim exists.
        The actual eviction happens on the step thread (apply_evictions) —
        this only snapshots the deque, same retry discipline as
        queue_depth()."""
        if not self._qos_active:
            return False
        snap: list[Request] | None = None
        for _ in range(5):
            try:
                snap = list(self.waiting)
                break
            except RuntimeError:  # deque mutated during iteration
                continue
        if snap is None:
            return False
        with self._evict_lock:
            for r in reversed(snap):
                if (
                    r.priority > than_rank
                    and r.request_id not in self._evict_rids
                ):
                    self._evict_rids.add(r.request_id)
                    return True
        return False

    def has_shed_victim(self, than_rank: int) -> bool:
        """Peek-only twin of mark_shed_victim for the pre-SSE admission
        check and readiness probes: would a rank-`than_rank` arrival find
        an evictable lower-priority waiting request? Marks nothing — only
        the submit-time check actually claims a victim, so the two-phase
        admission (precheck, then recheck at submit) can't evict twice for
        one request."""
        if not self._qos_active:
            return False
        for _ in range(5):
            try:
                snap = list(self.waiting)
            except RuntimeError:
                continue
            with self._evict_lock:
                return any(
                    r.priority > than_rank
                    and r.request_id not in self._evict_rids
                    for r in snap
                )
        return False

    def apply_evictions(self) -> int:
        """Step-thread half of mark_shed_victim: finish marked requests
        still in the waiting queue with FINISHED_SHED (terminal output via
        take_finished_externally; the HTTP layer maps it to 429). A mark
        that raced its request into running is dropped — the bound is a
        watermark, not an invariant."""
        with self._evict_lock:
            if not self._evict_rids:
                return 0
            rids, self._evict_rids = self._evict_rids, set()
        evicted = [r for r in self.waiting if r.request_id in rids]
        if not evicted:
            return 0
        kept = [r for r in self.waiting if r.request_id not in rids]
        self.waiting.clear()
        self.waiting.extend(kept)
        for req in evicted:
            self._finish(req, RequestStatus.FINISHED_SHED)
            self._finished_externally.append(req)
            self.shed_evictions += 1
            self.accounting.inc(req.tenant_id, "shed")
        return len(evicted)

    def _pick_waiting(self) -> Request | None:
        """Admission pick: FIFO until any request carries tenant stamps;
        then strict priority tiers (realtime < standard < batch) broken by
        the weighted fair-share virtual clock, FIFO within a tenant. The
        scan keeps the first (oldest) waiting request per tenant, so the
        pick is deterministic for a given queue state — a requirement for
        serial/async-pipeline stream equivalence."""
        if not self.waiting:
            return None
        if not self._qos_active:
            return self.waiting[0]
        best: Request | None = None
        best_key: tuple[int, float] | None = None
        seen: set[str] = set()
        for r in self.waiting:
            if r.tenant_id in seen:
                continue
            seen.add(r.tenant_id)
            key = (r.priority, self._fair.key(r.tenant_id))
            if best_key is None or key < best_key:
                best, best_key = r, key
        return best

    def _seat_victim(self, rank: int) -> Request | None:
        """Running request a rank-`rank` admission may preempt for its SEAT
        (max_num_seqs full): the newest running request of the strictly
        lowest priority class worse than `rank`, skipping rows with tokens
        in flight (their device step is still writing KV). None when every
        seat is held by equal-or-better traffic."""
        cands = [
            r
            for r in reversed(self.running)  # newest first
            if r.num_inflight_tokens == 0 and r.priority > rank
        ]
        if not cands:
            return None
        return max(cands, key=lambda r: r.priority)  # first maximal = newest

    def schedule(
        self, inflight: DecodeWork | VerifyWork | None = None
    ) -> ScheduleOutput | None:
        """Build the next work item. `inflight` (async pipeline) is the
        decode or verify step currently executing on device: rows carried
        by it are planned at their speculatively-advanced positions and
        chain their input token from its device-resident output
        (chain_rows) — a verify in flight additionally lets its rows
        PROPOSE again (its fed proposals are host-known values under full
        acceptance), so verify steps chain on verify steps and speculation
        stays engaged in the steady two-deep pipeline."""
        self.expire_deadlines()
        self.apply_evictions()
        if (
            self._qos_active
            and inflight is not None
            and self.waiting
            and len(self.running) >= self.config.max_num_seqs
        ):
            head = self._pick_waiting()
            if (
                head is not None
                and self._seat_victim(head.priority) is None
                and any(
                    r.num_inflight_tokens > 0 and r.priority > head.priority
                    for r in self.running
                )
            ):
                # a higher-priority arrival is blocked ONLY by in-flight
                # victims (their device step is still writing KV, so they
                # cannot be preempted yet — PR 1 invariant). Decline to
                # chain the next window: the step loop resolves the
                # in-flight step instead, and the NEXT schedule() finds a
                # resolvable victim. Bounds realtime priority inversion at
                # one decode window instead of a whole seat turnover.
                return None
        decode_ready = [r for r in self.running if r.prefill_done]
        prefilling = [r for r in self.running if not r.prefill_done]
        can_admit = bool(self.waiting) and (
            len(self.running) < self.config.max_num_seqs
            or (
                self._qos_active
                and (head := self._pick_waiting()) is not None
                and self._seat_victim(head.priority) is not None
            )
        )

        want_prefill = bool(prefilling) or can_admit
        if want_prefill and (not decode_ready or not self._last_was_prefill):
            work = self._schedule_prefill(prefilling)
            if work is not None:
                self._last_was_prefill = True
                return work
        if decode_ready:
            work = self._schedule_decode_or_verify(decode_ready, inflight)
            if work is not None:
                self._last_was_prefill = False
                return work
        return None

    def _schedule_decode_or_verify(
        self, ready: list[Request], inflight=None
    ) -> ScheduleOutput | None:
        """With speculative decoding on, greedy rows route through the
        verify program (which subsumes plain decode: no proposal -> 1 bonus
        token); sampled rows keep the fused decode window. When both kinds
        are ready the two dispatch types alternate.

        Composition with the pipeline (docs/36-speculative-decoding.md):
        a row whose in-flight step is a VERIFY can propose AGAIN — under
        the full-acceptance speculation, that step's fed proposals are
        already host-known token values, so the proposer extends the
        speculatively-advanced sequence and only the step's bonus token
        (this row's next input) is unknown; the chained verify splices it
        device-side (VerifyWork.chain_rows). Rows riding a DECODE window
        can't propose (every window token's value is unresolved) — they
        chain decode windows for SPEC_RETRY_WINDOWS steps after a failed
        attempt, then sit one step out so the next schedule() sees their
        resolved values. The greedy tokens are identical on every path
        (sampling.greedy_argmax is the one greedy pick, and acceptance
        only ever emits the model's own argmax chain), so the streams
        stay bitwise equal to the serial speculative loop."""
        k = self.config.num_speculative_tokens
        if k <= 0:
            return self._schedule_decode(ready, inflight)
        vrow: dict[str, int] = (
            {r.request_id: i for i, r in enumerate(inflight.requests)}
            if isinstance(inflight, VerifyWork)
            else {}
        )

        def capable(r: Request) -> bool:
            # logprobs and min_tokens requests stay on the decode-window
            # path (the verify program returns raw argmax ids — no logprob
            # collection, no min_tokens stop suppression)
            return (
                r.sampling.temperature == 0.0
                and r.sampling.logprobs is None
                and r.sampling.min_tokens <= 0
            )

        # candidates: rows with nothing in flight propose from resolved
        # values; rows whose in-flight step is a verify propose from the
        # speculatively-advanced sequence (its fed proposals, `tails`)
        cands: list[Request] = []
        tails: dict[str, list[int]] = {}
        chain_idx: dict[str, int] = {}
        for r in ready:
            if not capable(r):
                continue
            if r.num_inflight_tokens == 0:
                cands.append(r)
                continue
            if r.sampling.grammar is not None:
                # constrained rows never chain verify-on-verify: the
                # chained step's masks need the automaton state AFTER the
                # in-flight step's bonus token, which is device-resident
                # only — the row sits out until the verify resolves
                continue
            i = vrow.get(r.request_id)
            if i is None or r.num_inflight_tokens != len(
                inflight.token_ids[i]
            ):
                continue
            # a chained step for a row whose full acceptance would already
            # finish it (max_tokens / model length) is guaranteed waste —
            # it sits out until the in-flight verify resolves it
            eff_out = len(r.output_token_ids) + r.num_inflight_tokens
            eff_pos = r.num_computed_tokens + r.num_inflight_tokens
            if (
                r.sampling.max_tokens - eff_out <= 0
                or eff_pos + 1 >= self.model_config.max_model_len
            ):
                continue
            cands.append(r)
            tails[r.request_id] = list(inflight.proposals[i])
            chain_idx[r.request_id] = i
        proposals: dict[str, list[int]] = {}
        proposers: dict[str, str] = {}
        if self.draft_proposer is not None and cands:
            for rid, p in self.draft_proposer.propose_batch(
                cands, k, spec_tails=tails
            ).items():
                if p:
                    proposals[rid] = p
                    proposers[rid] = "draft"
        for r in cands:
            rid = r.request_id
            if rid in proposals:
                continue
            if rid in tails:
                # chained: match against seq + in-flight proposals, ask one
                # extra token and drop it — cont[0] predicts the unknown
                # bonus position the device-chained first fed token covers
                cont = propose_ngram(
                    r.all_token_ids + tails[rid], k + 1,
                    self.config.speculative_min_ngram,
                )
                p = cont[1:] if cont else None
            else:
                p = propose_ngram(
                    r.all_token_ids, k, self.config.speculative_min_ngram
                )
            if p:
                proposals[rid] = p
                proposers[rid] = "ngram"
            else:
                r.spec_retry_in = SPEC_RETRY_WINDOWS
        # only rows whose proposer actually fires go through verify;
        # proposal-less greedy rows keep the fused decode window (1 token
        # per verify dispatch would re-expose the per-token round-trip the
        # window amortizes), as do sampled rows
        spec = [r for r in ready if r.request_id in proposals]
        plain = []
        retry_riders: set[str] = set()
        for r in ready:
            if r.request_id in proposals:
                continue
            if (
                capable(r)
                and r.num_inflight_tokens > 0
                and r.request_id not in vrow
            ):
                # verify-capable row riding a chained decode window: burn
                # its retry budget, then sit out one step so it can
                # propose against resolved values next schedule(). The
                # budget counts windows actually RIDDEN — it is debited
                # below only for rows the dispatched decode work carries
                # (the verify group may win this turn instead).
                if r.spec_retry_in <= 0:
                    continue
                retry_riders.add(r.request_id)
            plain.append(r)
        first, second = (
            (spec, plain) if not self._last_was_verify else (plain, spec)
        )
        for group in (first, second):
            if not group:
                continue
            if group is spec:
                work = self._schedule_verify(
                    group, proposals, proposers, chain_idx
                )
            else:
                work = self._schedule_decode(group, inflight)
                if work is not None and retry_riders:
                    for r in work.requests:
                        if r.request_id in retry_riders:
                            r.spec_retry_in -= 1
            if work is not None:
                self._last_was_verify = group is spec
                return work
        return None

    def _schedule_verify(
        self,
        ready: list[Request],
        proposals: dict[str, list[int]],
        proposers: dict[str, str],
        chain_idx: dict[str, int] | None = None,
    ) -> VerifyWork | None:
        chain_idx = chain_idx or {}
        work = VerifyWork()
        for req in ready[: self.config.max_num_seqs]:
            if req not in self.running:
                continue
            # chained rows plan from their speculatively-advanced position
            # (num_inflight_tokens is the in-flight verify's fed length);
            # non-chained rows have nothing in flight and reduce to the
            # plain num_computed_tokens
            start = req.num_computed_tokens + req.num_inflight_tokens
            src = chain_idx.get(req.request_id, -1)
            proposal = list(proposals.get(req.request_id, []))
            # bound by remaining model length (the fed chunk itself must fit)
            room = self.model_config.max_model_len - start - 1
            proposal = proposal[: max(0, room)]
            # clamp to pool headroom, mirroring the decode window's clamp: a
            # proposal must never make _ensure_blocks preempt the request
            # ITSELF (re-admit, recompute, re-propose — a livelock); shrunk
            # to nothing it degrades to a plain 1-token verify, the same
            # exposure as decode at window 1
            while proposal and (
                self._blocks_needed(start + 1 + len(proposal))
                - len(req.block_table)
                > self.pool.num_free
            ):
                proposal.pop()
            if not self._ensure_blocks(req, start + 1 + len(proposal)):
                continue  # req preempted itself; others may still verify
            # a chained row's first fed token is the in-flight verify's
            # bonus token — device-resident only; the runner splices it
            # (placeholder 0 here, chain_rows carries the source row)
            fed = [0 if src >= 0 else req.token_at(start), *proposal]
            work.requests.append(req)
            work.token_ids.append(fed)
            work.positions.append(list(range(start, start + len(fed))))
            work.proposals.append(proposal)
            work.context_lens.append(start + len(fed))
            work.proposers.append(proposers.get(req.request_id, "ngram"))
            work.chain_rows.append(src)
        # a later _ensure_blocks may have preempted an earlier row's request
        if any(r not in self.running for r in work.requests):
            keep = [
                i for i, r in enumerate(work.requests) if r in self.running
            ]
            for name in (
                "requests", "token_ids", "positions", "proposals",
                "context_lens", "proposers", "chain_rows",
            ):
                setattr(work, name, [getattr(work, name)[i] for i in keep])
        return work if work.requests else None

    def _schedule_prefill(self, prefilling: list[Request]) -> PrefillWork | None:
        """Pack chunks from multiple requests into one dispatch: in-flight
        prefills continue first (FIFO), then new admissions, until the
        per-step token budget (max_num_batched_tokens) or the seat limit
        (max_num_seqs rows) is hit."""
        work = PrefillWork()
        budget = self.config.max_num_batched_tokens

        for req in prefilling:
            if budget <= 0 or len(work.requests) >= self.config.max_num_seqs:
                break
            budget -= self._try_add_chunk(work, req, budget)

        while (
            budget > 0
            and self.waiting
            and len(work.requests) < self.config.max_num_seqs
        ):
            req = self._pick_waiting()
            if req is None:
                break
            if len(self.running) >= self.config.max_num_seqs:
                # seats full: a strictly-higher-priority class may claim
                # one by preempting the newest lowest-priority running
                # request (QoS only — equal-priority traffic waits, the
                # pre-QoS behavior)
                victim = self._seat_victim(req.priority)
                if victim is None:
                    break
                need = self._blocks_needed(req.prefill_target + 1)
                if need > self.pool.num_usable:
                    self._can_admit(req)  # aborts the impossible fit
                    continue
                if self.pool.num_free + len(victim.block_table) < need:
                    # the memory watermark would still block the admission
                    # even with the victim's blocks back — don't pay a
                    # preemption (full recompute for the victim) for
                    # nothing
                    break
                self._preempt(victim)
            if not self._can_admit(req):
                if req in self.waiting:
                    break  # watermark: stop admitting until memory frees
                continue  # impossible-fit request was aborted; try the next
            self.waiting.remove(req)
            self._admit(req)
            if self._qos_active:
                # fair-share charge: expected device work (prompt left to
                # compute + output budget) over the tenant's weight
                self._fair.charge(
                    req.tenant_id,
                    req.prefill_target + req.sampling.max_tokens,
                    req.weight,
                )
            if req.num_preemptions == 0:
                # first admission only: queue wait + per-tenant served
                # count (a preempted request re-admitting is not a new
                # request, and its wait was already observed)
                import time as _time

                req.first_seat_time = _time.monotonic()
                self.accounting.inc(req.tenant_id, "requests")
                self.accounting.observe_wait(
                    req.tenant_id,
                    max(0.0, req.first_seat_time - req.arrival_time),
                )
            req.status = RequestStatus.RUNNING
            self.running.append(req)
            budget -= self._try_add_chunk(work, req, budget)

        # _ensure_blocks for a later row may have preempted an earlier row's
        # request (newest-victim policy); its slots now point at reallocated
        # blocks, so the row must be dropped — the request recomputes later
        if any(r not in self.running for r in work.requests):
            keep = [i for i, r in enumerate(work.requests) if r in self.running]
            for name in (
                "requests", "token_ids", "positions", "slot_mappings",
                "context_lens", "sample",
            ):
                setattr(work, name, [getattr(work, name)[i] for i in keep])
        return work if work.requests else None

    def _try_add_chunk(self, work: PrefillWork, req: Request, budget: int) -> int:
        """Add one chunk of `req` to the batch; returns tokens consumed.

        With an active hydration plan (docs/31-hydration-planner.md) this
        first consumes any landed load-chunks at the request's compute
        boundary (free tokens — adopted, not computed) and then bounds
        the prefill chunk at the next unresolved load boundary; a request
        parked exactly at a pending fetch contributes no row this step
        (decode and other prefills proceed around it)."""
        limit = (
            self._consume_hydrated(req)
            if req.hydration_plan is not None
            else None
        )
        target = req.prefill_target
        chunk = min(budget, target - req.num_computed_tokens)
        if limit is not None:
            chunk = min(chunk, limit)
        if chunk <= 0:
            return 0
        if not self._ensure_blocks(req, req.num_computed_tokens + chunk):
            return 0  # req preempted itself; it's back in waiting
        if req not in self.running:
            return 0
        start = req.num_computed_tokens
        idxs = range(start, start + chunk)
        work.add_row(
            request=req,
            token_ids=[req.token_at(i) for i in idxs],
            positions=list(idxs),
            slot_mapping=(
                [self._slot(req, i) for i in idxs]
                if self.need_slot_mappings
                else []
            ),
            context_len=start + chunk,
            # sample only when this chunk completes a *fresh* prompt; resumed
            # requests already know their next token
            sample=start + chunk == target and not req.output_token_ids,
        )
        return chunk

    def _schedule_decode(
        self, ready: list[Request], inflight: DecodeWork | None = None
    ) -> DecodeWork | None:
        # async pipeline: rows with tokens in flight plan from their
        # speculatively-advanced position / output count (assumes the whole
        # in-flight window gets accepted — any deviation finishes the
        # request, which the engine's reconcile turns into a rollback of
        # the step scheduled here). On the sync path inflight counts are 0
        # and these reduce to the plain fields.
        def eff_computed(r: Request) -> int:
            return r.num_computed_tokens + r.num_inflight_tokens

        def eff_outputs(r: Request) -> int:
            return len(r.output_token_ids) + r.num_inflight_tokens

        # rows whose speculative state already exhausted max_tokens /
        # max_model_len would have FINISHED in the resolved world — they
        # must sit out until the in-flight step resolves them (no-op on the
        # sync path: exhausted rows finish in postprocess and leave running)
        cand = [
            r for r in ready[: self.config.max_num_seqs]
            if r.sampling.max_tokens - eff_outputs(r) > 0
            and self.model_config.max_model_len - eff_computed(r) > 0
            # constrained rows chain decode-on-decode (the window program
            # hands the next window its post-window automaton states on
            # device) but cannot chain onto a verify step, which carries no
            # state vector — those sit one step out
            and not (
                r.sampling.grammar is not None
                and r.num_inflight_tokens > 0
                and not isinstance(inflight, DecodeWork)
            )
        ]
        if not cand:
            return None
        # window bounded by model length per seq and by the batch's largest
        # remaining output budget rounded UP to a power of two: past that
        # every token of every row would be discarded, but rounding up keeps
        # the window inside the same {1,2,4,...} compile set as the snap
        # below (each distinct window value is a ~20 s XLA compile; walking
        # the window down through arbitrary integers at the tail of a run
        # compiled fresh programs for tokens that cost microseconds to
        # overshoot)
        window = max(1, self.config.decode_window)
        max_remaining = max(
            r.sampling.max_tokens - eff_outputs(r) for r in cand
        )
        window = min(
            window,
            1 << max(0, max_remaining - 1).bit_length(),
            min(self.model_config.max_model_len - eff_computed(r)
                for r in cand),
        )
        # clamp to pool headroom: the batch's total new-block demand at this
        # window must fit in currently-free blocks, so _ensure_blocks below
        # never preempts a candidate to grow another candidate's window (a
        # sole running request preempting *itself* is a livelock: it re-admits,
        # recomputes, and hits the same wall forever)
        while window > 1 and self._extra_blocks(cand, window) > self.pool.num_free:
            window -= 1
        # snap down to a power of two: `window` is a static jit arg of the
        # fused decode program, so every distinct value costs a compile —
        # bound the set to {1, 2, 4, ...} instead of walking through every
        # integer as free-block headroom fluctuates
        window = 1 << (window.bit_length() - 1)
        picked: list[Request] = []
        for req in cand:
            if req not in self.running:
                continue  # preempted while building this batch
            if not self._ensure_blocks(req, eff_computed(req) + window):
                continue  # req preempted itself; others may still decode
            picked.append(req)
        # a later _ensure_blocks may have preempted an earlier pick
        picked = [r for r in picked if r in self.running]
        if not picked:
            return None
        inflight_row = (
            {r.request_id: i for i, r in enumerate(inflight.requests)}
            if inflight is not None
            else {}
        )
        batch = DecodeWork(requests=picked, window=window)
        for req in picked:
            pos = eff_computed(req)
            batch.positions.append(pos)
            if req.num_inflight_tokens > 0:
                # input token is still device-resident (last column of the
                # in-flight step's output matrix) — the runner chains it
                # without a D2H→H2D round trip
                src = inflight_row.get(req.request_id)
                if src is None:
                    raise RuntimeError(
                        f"request {req.request_id} has "
                        f"{req.num_inflight_tokens} in-flight tokens but is "
                        "not in the in-flight decode step"
                    )
                batch.token_ids.append(0)  # placeholder; chained on device
                batch.chain_rows.append(src)
            else:
                batch.token_ids.append(req.token_at(pos))
                batch.chain_rows.append(-1)
        return batch

    # -- memory ------------------------------------------------------------

    def _blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    def _extra_blocks(self, reqs: list[Request], window: int) -> int:
        """New blocks the batch needs to decode `window` tokens per request
        (from the speculative position when tokens are in flight)."""
        return sum(
            max(
                0,
                self._blocks_needed(
                    r.num_computed_tokens + r.num_inflight_tokens + window
                )
                - len(r.block_table),
            )
            for r in reqs
        )

    def _can_admit(self, req: Request) -> bool:
        """Admission watermark: only admit when the pool can hold the whole
        recompute target plus one decode token — prevents admission/preemption
        thrash (the oldest running request must always be able to finish)."""
        need = self._blocks_needed(req.prefill_target + 1)
        if need > self.pool.num_usable:
            # can never fit (e.g. resumed request outgrew the pool)
            self.waiting.remove(req)
            self._finish(req, RequestStatus.FINISHED_ABORTED)
            self._finished_externally.append(req)
            return False
        return self.pool.num_free >= need

    def take_finished_externally(self) -> list[Request]:
        out, self._finished_externally = self._finished_externally, []
        return out

    def goodput_balance(self) -> dict:
        """Ledger balance audit: sampled == delivered + wasted + pending
        tokens on live requests (docs/29-saturation-slo.md). Lives HERE —
        the single definition of "live requests" — so the invariant can't
        drift between the engine's view and scheduler-level tests."""
        snap = self.ledger.snapshot()
        snap["pending"] = sum(
            r.ledger_pending for q in (self.running, self.waiting) for r in q
        )
        snap["balanced"] = (
            snap["sampled"]
            == snap["delivered"] + snap["wasted_total"] + snap["pending"]
        )
        return snap

    def _chain_root(self, req: Request) -> int:
        """Root of a request's KV hash chain. Base model = the pool root;
        LoRA requests salt it with their adapter's load-unique id — adapter
        KV differs from base KV (k/v-projection deltas), so letting the two
        cross-match would be silent attention corruption."""
        if req.lora_cache_salt:
            return chain_hash(self.pool.root_hash(), (req.lora_cache_salt,))
        return self.pool.root_hash()

    def _admit(self, req: Request) -> None:
        """Prefix-cache lookup for a waiting (possibly resumed) request.
        The matchable sequence is everything that will be recomputed.

        With a hydrator (docs/31-hydration-planner.md), a FIRST admission
        whose resident run continues past the local rungs consumes the
        HBM/host-ring prefix synchronously (cheap — RAM→device dispatch)
        and plans the disk/remote remainder as chunked async loads
        pipelined with the recompute of whatever the planner prices as
        cheaper; resumed (preempted) requests keep the legacy blocking
        match — their blocks are usually still local, and their
        attribution is already settled."""
        seq = req.all_token_ids
        root = self._chain_root(req)
        plan = None
        if (
            self.hydrator is not None
            and req.hydration is None  # first admission only
            and self.pool.enable_prefix_caching
        ):
            matched, plan = self._admit_planned(req, seq, root)
        else:
            matched = self.pool.match_prefix(seq, parent=root)
        # keep at least one token to actually compute (its logits / its KV
        # write are what the next step needs)
        while matched and len(matched) * self.block_size >= req.prefill_target:
            self.pool.free_block(matched.pop())
        self._attribute_hydration(
            req, len(matched),
            deferred=plan.deferred_tokens() if plan is not None else 0,
        )
        req.block_table = matched
        req.num_computed_tokens = len(matched) * self.block_size
        req.num_cached_prompt_tokens = min(
            req.num_computed_tokens, req.num_prompt_tokens
        )
        chain = [root]
        for i in range(len(matched)):
            chunk = tuple(seq[i * self.block_size : (i + 1) * self.block_size])
            chain.append(chain_hash(chain[-1], chunk))
        self._hash_chains[req.request_id] = chain
        if plan is not None:
            req.hydration_plan = plan
            self.hydrator.launch(plan)

    def _admit_planned(self, req: Request, seq: list[int], root: int):
        """Planner half of _admit: probe residency without moving bytes,
        take the leading HBM/ring run synchronously, and build a chunk
        plan over the disk/remote remainder. Returns (matched, plan);
        plan None means the caller behaves exactly like the legacy path
        (matched is then a full-hierarchy blocking match — the auto-mode
        fallback, whose transfers are what feed the bandwidth estimator
        past its sample floor)."""
        # kill switch / bench compute-only arm: lower-tier residency is
        # ignored and — crucially — neither the remote store nor any peer
        # is ever probed (a sick store/peer is exactly why an operator
        # flips this off)
        off = self.hydrator.mode == "off"
        hashes, tiers, peer_owner = self.pool.probe_prefix(
            seq, parent=root, local_only=off,
            peer=None if off else self.hydrator.peer,
            owner_hint=None if off else req.kv_owner_hint,
        )
        # keep-one-token rule applied to the whole resident run: the plan
        # region must end at least one token short of the prefill target
        cap = max(0, (req.prefill_target - 1) // self.block_size)
        hashes, tiers = hashes[:cap], tiers[:cap]
        n_sync = 0
        while n_sync < len(tiers) and tiers[n_sync] in ("hbm", "host"):
            n_sync += 1
        if off:
            return (
                self.pool.match_prefix(seq, parent=root, limit_blocks=n_sync),
                None,
            )
        if n_sync == len(tiers):
            # nothing beyond the local rungs — the legacy match never
            # blocks on disk/remote here
            return self.pool.match_prefix(seq, parent=root), None
        plan = self.hydrator.build_plan(
            req.request_id, n_sync, hashes[n_sync:], tiers[n_sync:],
            self.block_size, peer_owner=peer_owner,
        )
        if plan is None:
            return self.pool.match_prefix(seq, parent=root), None
        matched = self.pool.match_prefix(
            seq, parent=root, limit_blocks=n_sync
        )
        if len(matched) != n_sync:
            # an eviction raced the probe: the plan's block indices no
            # longer line up — drop the plan (the region recomputes; the
            # next identical prompt re-plans against fresh residency)
            return matched, None
        # region blocks are real cache queries; hits count at adoption
        self.pool.stats.queries += len(hashes) - n_sync
        return matched, plan

    # -- hydration plan consumption (docs/31-hydration-planner.md) ---------

    def _consume_hydrated(self, req: Request) -> int | None:
        """Resolve the request's hydration plan at its compute boundary:
        adopt landed chunks (extend the block table without computing),
        flip failed/expired load-chunks to recompute, and return how many
        tokens prefill may compute before the next unresolved load
        boundary — 0 parks the request this step, None means
        unconstrained (plan exhausted or no load chunk ahead)."""
        import time as _time

        plan = req.hydration_plan
        bs = self.block_size
        while plan is not None and not plan.done():
            cur = plan.current()
            start_tok = cur.start_block * bs
            end_tok = start_tok + cur.tokens(bs)
            if req.num_computed_tokens >= end_tok:
                if cur.status == "pending":
                    cur.status = "recomputed"  # computed straight through
                plan.advance()
                continue
            if req.num_computed_tokens != start_tok or cur.decision != "load":
                break  # mid-compute of a recompute chunk
            with plan.lock:
                status = cur.status
                expired = (
                    status == "pending"
                    and _time.monotonic() > plan.deadline
                )
                if expired:
                    # claim the flip under the lock so a late fetcher
                    # landing can't resurrect the chunk
                    cur.status = "fallback"
            if status == "landed":
                blocks = self.pool.adopt_planned_run(cur.hashes, cur.arrays)
                if blocks is None:
                    # allocation/geometry/upload failure: recompute keeps
                    # liveness (the compute path can preempt for blocks;
                    # adoption must not)
                    self._flip_chunk(req, cur, "adopt_failed")
                    continue
                cur.arrays = None
                cur.status = "adopted"
                req.block_table.extend(blocks)
                req.num_computed_tokens = end_tok
                req.num_cached_prompt_tokens = min(
                    req.num_computed_tokens, req.num_prompt_tokens
                )
                chain = self._hash_chains.setdefault(
                    req.request_id, [self._chain_root(req)]
                )
                chain.extend(cur.hashes)
                self._record_outcome(req, cur, "adopted")
                for tier in cur.tiers:
                    self._attribute_increment(
                        req, self._HYDRATION_BY_TIER[tier], bs
                    )
                plan.advance()
                continue
            if expired:
                self._flip_chunk(req, cur, "timeout", already_claimed=True)
                continue
            if status in ("failed", "cancelled"):
                self._flip_chunk(req, cur, status)
                continue
            return 0  # pending within its deadline: park this request
        if plan is not None and plan.done():
            req.hydration_plan = None
            plan = None
        if plan is None:
            return None
        nxt = None
        for c in plan.chunks[plan.cursor:]:
            if c.decision == "load":
                nxt = c.start_block * bs
                break
        if nxt is None:
            return None
        return max(0, nxt - req.num_computed_tokens)

    def _flip_chunk(
        self, req: Request, chunk, why: str, already_claimed: bool = False,
    ) -> None:
        """A load chunk's fetch failed, expired, or could not adopt: it
        becomes a recompute chunk (choice counter: fallback_recompute)
        and its tokens classify as recomputed — the partition invariant
        holds no matter which way a chunk resolves."""
        plan = req.hydration_plan
        if not already_claimed and plan is not None:
            with plan.lock:
                chunk.status = "fallback"
        elif plan is None:
            chunk.status = "fallback"
        chunk.decision = "recompute"
        chunk.arrays = None
        self.flow.record_decision("fallback_recompute")
        self._record_outcome(req, chunk, f"fallback:{why}")
        self._attribute_increment(
            req, "recomputed", chunk.tokens(self.block_size)
        )

    def _record_outcome(self, req: Request, chunk, outcome: str) -> None:
        if req.hydration_outcomes is None:
            req.hydration_outcomes = []
        req.hydration_outcomes.append({
            "chunk": chunk.index,
            "start_block": chunk.start_block,
            "tokens": chunk.tokens(self.block_size),
            "tiers": sorted(set(chunk.tiers)),
            "decision": "load",
            "outcome": outcome,
        })

    def hydration_parked(self) -> bool:
        """True when some running request still has an active hydration
        plan — the engine's step loop sleeps a beat instead of busy-
        spinning when such a request is the only schedulable work (its
        fetch needs the CPU the spin would burn)."""
        return any(r.hydration_plan is not None for r in self.running)

    def _settle_hydration_plan(self, req: Request) -> None:
        """Cancel an active plan and classify every still-open load chunk
        as recomputed — a request leaving the scheduler mid-hydration
        (preemption, abort, deadline, shed) must not strand deferred
        tokens outside the audited partition. In-flight fetch jobs see
        the cancel flag and drop their results."""
        plan = req.hydration_plan
        if plan is None:
            return
        req.hydration_plan = None
        open_chunks = plan.unresolved()
        plan.cancel()
        for chunk in open_chunks:
            chunk.decision = "recompute"
            chunk.arrays = None
            self._record_outcome(req, chunk, "cancelled")
            self._attribute_increment(
                req, "recomputed", chunk.tokens(self.block_size)
            )

    _HYDRATION_BY_TIER = {
        "hbm": "hbm_hit",
        "host": "host_reload",
        "disk": "disk_load",
        "remote": "remote_fetch",
        "peer": "peer_fetch",
        # device-collective peer pulls attribute as peer_fetch too — the
        # SOURCE (a peer engine) is the same, only the wire differs, and
        # KV_HYDRATION_SOURCES is a closed contract set (docs/39)
        "device": "peer_fetch",
    }

    def _attribute_hydration(
        self, req: Request, n_matched: int, deferred: int = 0
    ) -> None:
        """Classify the request's prompt tokens by KV origin, EXACTLY once
        (first admission only — a preempted request re-admitting keeps its
        original attribution; the recompute cost is the goodput ledger's
        preempted_recompute story, not a hydration event). The partition is
        exact by construction: matched blocks are full blocks of the
        prompt's head (trimmed below prefill_target == prompt tokens at
        first admission), so

            hbm_hit + host_reload + disk_load + remote_fetch + peer_fetch
                + recomputed == prompt_tokens

        with recomputed >= 1 (the keep-one-token-to-compute rule).

        `deferred` (hydration planner) excludes the plan's load-decided
        chunk tokens from the admission-time counts: each classifies via
        _attribute_increment when its fate resolves — adopted under its
        tier's source, fallback/cancelled as recomputed — so the
        partition stays exact at every settle point."""
        if req.hydration is not None:
            return
        counts = dict.fromkeys(self._HYDRATION_BY_TIER.values(), 0)
        for tier in self.pool.last_match_sources[:n_matched]:
            counts[self._HYDRATION_BY_TIER[tier]] += self.block_size
        counts["recomputed"] = (
            req.num_prompt_tokens - n_matched * self.block_size - deferred
        )
        req.hydration = counts
        self.flow.record_hydration(counts)

    def _attribute_increment(self, req: Request, source: str, n: int) -> None:
        """Deferred-chunk classification (hydration planner): move n of
        the request's prompt tokens into `source`, mirrored into the
        shared flow counters without bumping hydrated_requests."""
        if req.hydration is None:  # planner admissions always attribute
            req.hydration = dict.fromkeys(
                (*self._HYDRATION_BY_TIER.values(), "recomputed"), 0
            )
        req.hydration[source] = req.hydration.get(source, 0) + n
        self.flow.record_hydration({source: n}, requests=0)

    def _ensure_blocks(self, req: Request, num_tokens: int) -> bool:
        """Grow req's block table to cover num_tokens. On pool exhaustion the
        NEWEST running request is preempted — possibly req itself (returns
        False, req is back in waiting) — so the oldest request always makes
        forward progress. A sole running request that can never fit its own
        next token is aborted at re-admission by `_can_admit`'s usable-pool
        check; the decode-window headroom clamp in `_schedule_decode` keeps
        windowed decode from self-preempting before that point."""
        need = self._blocks_needed(num_tokens)
        while len(req.block_table) < need:
            blk = self.pool.allocate()
            if blk is None:
                # LOWEST-priority-class-first, newest-within-class loses
                # (pure-default traffic: every rank ties, so this reduces
                # to the historical newest-admission rule) — but never a
                # request with tokens in flight (async pipeline): its
                # device step is still writing KV into its blocks and its
                # unresolved tokens would be lost, so it cannot be safely
                # recomputed yet
                cands = [
                    r
                    for r in reversed(self.running)  # newest first
                    if r.num_inflight_tokens == 0
                ]
                victim = (
                    max(cands, key=lambda r: r.priority)  # first max = newest
                    if cands
                    else None
                )
                if victim is None:
                    return False
                self._preempt(victim)
                if victim is req:
                    return False
                continue
            req.block_table.append(blk)
        return True

    def _preempt(self, req: Request) -> None:
        self.running.remove(req)
        # preemption mid-hydration: the plan dies with the seat (its
        # deferred tokens settle as recomputed — partition stays exact);
        # re-admission runs the legacy match, which will find whatever
        # the fetches already promoted into the ring
        self._settle_hydration_plan(req)
        # preemption mid-draft: the draft's scratch blocks go back to the
        # pool with the seat (the draft KV recomputes from scratch at
        # re-admission via the catch-up feed — cheap, it's a small model)
        if self.draft_proposer is not None:
            self.draft_proposer.release(req.request_id)
        self._release_blocks(req)
        # goodput ledger: nothing to classify here — the preempted
        # request's pending tokens keep their unknown fate (the VALUES
        # survive in output_token_ids; they settle at finish). The
        # recompute cost lands when resumed prefill actually re-processes
        # generated positions (postprocess charges preempted_recompute
        # chunk-exactly).
        req.num_computed_tokens = 0
        req.num_preemptions += 1
        self.total_preemptions += 1
        req.status = RequestStatus.PREEMPTED
        self.waiting.appendleft(req)

    def _release_blocks(self, req: Request) -> None:
        # tail-first so deep prefix blocks stay hottest in the LRU
        for blk in reversed(req.block_table):
            self.pool.free_block(blk)
        req.block_table = []
        self._hash_chains.pop(req.request_id, None)

    def _slot(self, req: Request, token_idx: int) -> int:
        blk = req.block_table[token_idx // self.block_size]
        return blk * self.block_size + token_idx % self.block_size

    # -- async pipeline bookkeeping (engine/engine.py pipelined step loop) --
    #
    # A dispatched-but-unresolved decode OR verify step advances its rows
    # SPECULATIVELY: num_inflight_tokens carries the row's window (the
    # decode window, or a verify row's fed length — full acceptance) so the
    # next step can be scheduled (and dispatched, chaining its input tokens
    # device-side) before the sampled tokens ever reach the host. The
    # speculation is "every row accepts its whole window" — any deviation
    # (mid-window stop token, PARTIAL draft acceptance, max-tokens/
    # model-len finish, abort, stop-string hit) moves the reconciled base,
    # which is what speculation_valid detects and the engine answers with
    # rollback_speculative on the step dispatched on top of it. A
    # mispredicted draft is therefore just another rollback — the unifying
    # move that lets speculation ride the pipeline (docs/36).

    @staticmethod
    def _row_windows(work: DecodeWork | VerifyWork) -> list[int]:
        """Per-row speculative advance of a dispatched step: the uniform
        decode window, or each verify row's fed length (current token +
        proposals == the tokens a full acceptance would resolve)."""
        if isinstance(work, VerifyWork):
            return [len(t) for t in work.token_ids]
        return [work.window] * len(work.requests)

    @staticmethod
    def _row_positions(work: DecodeWork | VerifyWork) -> list[int]:
        if isinstance(work, VerifyWork):
            return [p[0] for p in work.positions]
        return list(work.positions)

    def begin_speculative(self, work: DecodeWork | VerifyWork) -> None:
        """Mark `work`'s window as in flight on its rows (called right
        after the engine dispatches the step to the device)."""
        for req, w in zip(work.requests, self._row_windows(work)):
            req.num_inflight_tokens += w

    def end_speculative(self, work: DecodeWork | VerifyWork) -> None:
        """Clear `work`'s window from its rows — the step has resolved and
        postprocess() is about to apply its real results."""
        for req, w in zip(work.requests, self._row_windows(work)):
            req.num_inflight_tokens = max(0, req.num_inflight_tokens - w)

    def speculation_valid(self, work: DecodeWork | VerifyWork) -> bool:
        """After the PREVIOUS step resolved, is the speculatively dispatched
        `work` still consistent? Every row must still be running with its
        reconciled base position exactly where the dispatch assumed — a
        mid-window stop, a partial draft acceptance, max-tokens finish,
        stop-string hit, or abort moves (or removes) it."""
        for req, pos, w in zip(
            work.requests, self._row_positions(work), self._row_windows(work)
        ):
            if req.status.finished or req not in self.running:
                return False
            base = req.num_computed_tokens + req.num_inflight_tokens - w
            if base != pos:
                return False
        return True

    def rollback_speculative(self, work: DecodeWork | VerifyWork) -> None:
        """Discard a dispatched-but-invalidated decode/verify step: clear
        its in-flight window and free the blocks allocated beyond each
        row's real residency. The device still executes the discarded step,
        but its writes land only at positions >= the speculative base —
        beyond every registered prefix-cache block, and fully overwritten
        (in device order) by whichever dispatch next owns those slots."""
        for req, w in zip(work.requests, self._row_windows(work)):
            req.num_inflight_tokens = max(0, req.num_inflight_tokens - w)
            if req.status.finished or req not in self.running:
                continue  # blocks already released by its finish
            keep = self._blocks_needed(
                req.num_computed_tokens + req.num_inflight_tokens
            )
            while len(req.block_table) > keep:
                self.pool.free_block(req.block_table.pop())

    # -- post-step ---------------------------------------------------------

    def postprocess(
        self, work: ScheduleOutput, sampled: list[list[int]]
    ) -> list[tuple[Request, list[int]]]:
        """Apply one step's results. `sampled` carries one row per request
        (prefill: 0 or 1 tokens; decode: up to `window` candidates). Returns
        [(request, accepted_new_tokens)] — an empty list marks a prefill chunk
        that didn't finish the prompt. Decode candidates past a stop condition
        are discarded."""
        results: list[tuple[Request, list[int]]] = []
        proposal_lens: list[int] | None = None
        row_proposers: list[str] | None = None
        if isinstance(work, VerifyWork):
            # acceptance: the model's argmax m[j] at fed position j is valid
            # output iff every earlier proposal matched; the first mismatch
            # position still yields m[j] itself (the "bonus" token) — so a
            # row emits 1..k+1 tokens, and a proposal-less row emits exactly
            # its plain greedy token. Acceptance COUNTERS are bumped in the
            # decode loop below, after the max_tokens/stop cut, so the
            # acceptance-rate metric never counts tokens that were clipped
            # before emission.
            proposal_lens = [len(p) for p in work.proposals]
            row_proposers = list(work.proposers) or ["ngram"] * len(
                work.requests
            )
            accepted_rows: list[list[int]] = []
            for i, req in enumerate(work.requests):
                m = sampled[i]
                p = work.proposals[i]
                accepted: list[int] = []
                for j in range(len(p) + 1):
                    accepted.append(int(m[j]))
                    if j < len(p) and int(m[j]) != p[j]:
                        break
                accepted_rows.append(accepted)
                # goodput ledger: the device argmax-sampled len(p)+1
                # positions; everything past the first mismatch is a
                # mispredicted draft — just another rollback (the accepted
                # prefix is ledgered by the decode loop below)
                self.ledger.rollback(len(p) + 1 - len(accepted))
            work = DecodeWork(requests=work.requests)  # shared accounting
            sampled = accepted_rows
        if isinstance(work, PrefillWork):
            for i, req in enumerate(work.requests):
                start = req.num_computed_tokens
                end = work.context_lens[i]
                req.num_computed_tokens = end
                self._register_full_blocks(req, start, work.context_lens[i])
                # goodput ledger: chunk positions past the prompt are
                # GENERATED tokens being re-computed after a preemption
                # dropped their KV — the device samples through them again
                # and the re-pass is pure waste (the values were already
                # known). Counting them as sampled+wasted here keeps the
                # partition exact: each token's FATE (pending → delivered/
                # wasted at finish) is still classified exactly once.
                recomputed = max(0, end - max(start, req.num_prompt_tokens))
                self.ledger.sampled(recomputed)
                self.ledger.waste("preempted_recompute", recomputed)
                if work.sample[i]:
                    tok = sampled[i][0]
                    if req.grammar is not None and req.sampling.grammar is not None:
                        req.grammar.sync(req.output_token_ids)
                        req.grammar.advance(int(tok))
                    req.output_token_ids.append(tok)
                    # goodput ledger: one sampled first token, pending until
                    # the request's fate is known (finish / preemption)
                    self.ledger.sampled(1)
                    req.ledger_pending += 1
                    self._maybe_finish(req)
                    results.append((req, [tok]))
                else:
                    results.append((req, []))
        else:
            for i, (req, row) in enumerate(zip(work.requests, sampled)):
                if req.status.finished:
                    # finished while the step was in flight (async abort /
                    # stop-string hit): its blocks are already released and
                    # its stream is closed — the sampled row is void.
                    # Ledger: the device executed the row for a request
                    # nobody is waiting on — pipeline machinery waste
                    self.ledger.rollback(len(row))
                    results.append((req, []))
                    continue
                # bulk accept: a decode window hands up to `window` candidate
                # tokens per row — the previous token-at-a-time loop
                # (computed += 1, register, append, finish-check per token)
                # cost ~0.4 s of host time per 256x128 wave. Compute the cut
                # point first, then apply in one pass; semantics match the
                # per-token loop exactly (first eos/stop token is ACCEPTED
                # then finishes; length caps clip the row).
                s = req.sampling
                n = min(
                    len(row),
                    s.max_tokens - len(req.output_token_ids),
                    self.model_config.max_model_len - req.num_tokens,
                )
                cut = n
                eos = None if s.ignore_eos else req.eos_token_id
                # structured output: the host cursor advances ONLY here, on
                # accepted tokens — discarded speculative steps never touch
                # it, so it needs no rollback and survives preemption with
                # output_token_ids. The admissibility check is belt-and-
                # suspenders (the device mask already guarantees sampled
                # tokens are admissible): a violating token cuts the row
                # BEFORE itself and the tail lands in the same "overshoot"
                # waste bucket as a stop cut, keeping the ledger partition
                # exact.
                gram = req.grammar if s.grammar is not None else None
                if gram is not None:
                    gram.sync(req.output_token_ids)
                if eos is not None or s.stop_token_ids or gram is not None:
                    n_out0 = len(req.output_token_ids)
                    for j in range(n):
                        if _is_stop_token(row[j], s, eos, n_out0 + j + 1):
                            cut = j + 1
                            if gram is not None:
                                # EOS is a terminator (state untouched); a
                                # non-EOS stop token is a real grammar byte
                                gram.advance(int(row[j]))
                            break
                        if gram is not None:
                            if not gram.allows(int(row[j])):
                                cut = j
                                break
                            gram.advance(int(row[j]))
                accepted = [int(t) for t in row[:cut]]
                # goodput ledger: every candidate in the row was sampled on
                # device; the tail past the stop/length cut is discarded
                # overshoot, the accepted prefix stays pending on the
                # request until its fate is known
                self.ledger.sampled(len(row))
                self.ledger.waste("overshoot", len(row) - len(accepted))
                req.ledger_pending += len(accepted)
                if proposal_lens is not None:
                    # every emitted token past the first rode a matched
                    # proposal; the first is the plain greedy/bonus token
                    n_acc = max(0, len(accepted) - 1)
                    self.spec_proposed_tokens += proposal_lens[i]
                    self.spec_accepted_tokens += n_acc
                    by = row_proposers[i]
                    self.spec_proposed_by[by] += proposal_lens[i]
                    self.spec_accepted_by[by] += n_acc
                    # per-window acceptance for the tracing spine's
                    # decode_window event — consumed (and cleared) by
                    # LLMEngine._make_output on this step's output
                    req.spec_window = (proposal_lens[i], n_acc, by)
                if accepted:
                    # outputs FIRST: _register_full_blocks hashes block
                    # contents via token_at over positions that include the
                    # just-accepted tokens
                    req.output_token_ids.extend(accepted)
                    start = req.num_computed_tokens
                    req.num_computed_tokens += len(accepted)
                    self._register_full_blocks(
                        req, start, req.num_computed_tokens
                    )
                    self._maybe_finish(req)
                results.append((req, accepted))
        # per-tenant decode-share observability (tpu:tenant_generation_
        # tokens_total) — batched to one counter bump per tenant per step
        tok_counts: dict[str, int] = {}
        for req, toks in results:
            if toks:
                tok_counts[req.tenant_id] = (
                    tok_counts.get(req.tenant_id, 0) + len(toks)
                )
        for t, n in tok_counts.items():
            self.accounting.inc(t, "generation_tokens", n)
        return results

    def _register_full_blocks(self, req: Request, start: int, end: int) -> None:
        chain = self._hash_chains.setdefault(
            req.request_id, [self._chain_root(req)]
        )
        first_new = start // self.block_size
        last_full = end // self.block_size  # blocks [0, last_full) are full
        for i in range(first_new, last_full):
            if i + 1 < len(chain):
                continue  # already registered (cached prefix)
            tokens = tuple(
                req.token_at(j)
                for j in range(i * self.block_size, (i + 1) * self.block_size)
            )
            h = self.pool.register_full_block(req.block_table[i], chain[i], tokens)
            chain.append(h)

    def _maybe_finish(self, req: Request) -> None:
        s = req.sampling
        last = req.output_token_ids[-1]
        eos = None if s.ignore_eos else req.eos_token_id
        if _is_stop_token(last, s, eos, len(req.output_token_ids)):
            status = RequestStatus.FINISHED_STOPPED
        elif len(req.output_token_ids) >= s.max_tokens:
            status = RequestStatus.FINISHED_LENGTH
        elif req.num_tokens >= self.model_config.max_model_len:
            status = RequestStatus.FINISHED_LENGTH
        else:
            return
        self.running.remove(req)
        self._finish(req, status)

    def finish_request(self, req: Request, status: RequestStatus) -> None:
        """Externally finish a running request (e.g. stop-string hit found by
        the engine's detokenizer)."""
        if req in self.running:
            self.running.remove(req)
        self._finish(req, status)

    def _finish(self, req: Request, status: RequestStatus) -> None:
        import time

        req.status = status
        req.finish_time = time.monotonic()
        # a request finishing mid-hydration (abort / deadline / shed)
        # settles its plan first: deferred tokens classify as recomputed,
        # in-flight fetches drop their results
        self._settle_hydration_plan(req)
        # draft scratch blocks die with the request (abort mid-draft
        # included) — they were never content-addressed, so nothing to
        # unpublish
        if self.draft_proposer is not None:
            self.draft_proposer.release(req.request_id)
        # goodput ledger: the request's fate is sealed — classify its
        # pending tokens (delivered for stop/length; deadline_expired /
        # shed_evicted / severed for the rest, saturation.FINISH_REASONS)
        self.ledger.classify_finish(status.name, req.ledger_pending)
        req.ledger_pending = 0
        self._release_blocks(req)
