"""OpenAI tool calling (function calling) for the engine server.

Reference parity: vLLM engines serve `tools`/`tool_choice` via per-model
tool parsers (`--enable-auto-tool-choice --tool-call-parser hermes` in the
reference's tool-enabled install, tutorials/13-tool-enabled-installation.md);
the router proxies the surface untouched. Here the engine implements the
Hermes-style convention directly — the most widely fine-tuned format and
the one vLLM's default parser family targets:

- tool definitions are injected as a system block listing JSON schemas;
- the model emits calls as `<tool_call>{"name": ..., "arguments": {...}}
  </tool_call>` blocks;
- assistant tool_calls / tool-result messages in the history are rendered
  back into the same textual convention, so multi-turn tool use works
  through ANY chat template (HF template or the byte fallback — the
  rendering happens before apply_chat_template and uses plain content).

`tool_choice="required"` / a named function does both prompt-level
steering (the system block announces the constraint) AND grammar-level
enforcement: the server compiles a forced-tool-call grammar
(engine/grammar.py `tool_choice_spec`) over this module's exact
`<tool_call>{"name":...,"arguments":{...}}</tool_call>` surface, so a
forced call always parses. `tool_choice="auto"` remains best-effort
prompt steering, same as vLLM without guided decoding.

The streaming parser holds back any text that could be the start of a
`<tool_call>` tag so clients never see half-emitted markup, and releases
it verbatim when it turns out not to be a call.
"""

from __future__ import annotations

import json
import re
import uuid

TOOL_OPEN = "<tool_call>"
TOOL_CLOSE = "</tool_call>"

_BLOCK_RE = re.compile(
    re.escape(TOOL_OPEN) + r"\s*(.*?)\s*" + re.escape(TOOL_CLOSE),
    re.DOTALL,
)


def call_id() -> str:
    return "call_" + uuid.uuid4().hex[:24]


def _content_str(content) -> str:
    """Flatten OpenAI content (str | parts array | None) to plain text —
    clients routinely send [{"type": "text", "text": ...}, ...] and the
    renderer must never concatenate a list into a template string."""
    if content is None:
        return ""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        parts = []
        for p in content:
            if isinstance(p, dict) and p.get("type") == "text":
                parts.append(str(p.get("text", "")))
            elif isinstance(p, str):
                parts.append(p)
        return "".join(parts)
    return str(content)


def tools_system_block(tools: list[dict], tool_choice) -> str:
    """The system-prompt block advertising the tools and the required
    output convention."""
    specs = []
    for t in tools:
        fn = t.get("function", t) or {}
        specs.append(json.dumps({
            "name": fn.get("name"),
            "description": fn.get("description", ""),
            "parameters": fn.get("parameters", {}),
        }, ensure_ascii=False))
    lines = [
        "You have access to the following tools:",
        *specs,
        "To call a tool, respond with exactly:",
        f'{TOOL_OPEN}{{"name": "<tool-name>", "arguments": {{...}}}}'
        f"{TOOL_CLOSE}",
        "Emit one block per call. Use valid JSON inside the block.",
    ]
    if tool_choice == "required":
        lines.append("You MUST call at least one tool before answering.")
    elif isinstance(tool_choice, dict):
        name = (tool_choice.get("function") or {}).get("name")
        if name:
            lines.append(f'You MUST call the tool named "{name}".')
    return "\n".join(lines)


def render_messages(messages: list[dict], tools: list[dict] | None,
                    tool_choice) -> list[dict]:
    """Template-agnostic pre-render: inject the tools block and convert
    tool-role / assistant-tool_calls messages into plain content so any
    chat template (HF or byte fallback) can format the conversation."""
    out: list[dict] = []
    for m in messages:
        role = m.get("role")
        if role == "assistant" and m.get("tool_calls"):
            parts = [_content_str(m.get("content"))]
            for tc in m["tool_calls"]:
                fn = tc.get("function", {})
                args = fn.get("arguments", "{}")
                if not isinstance(args, str):
                    args = json.dumps(args, ensure_ascii=False)
                parts.append(
                    f'{TOOL_OPEN}{{"name": {json.dumps(fn.get("name"))}, '
                    f'"arguments": {args}}}{TOOL_CLOSE}'
                )
            out.append({"role": "assistant",
                        "content": "\n".join(p for p in parts if p)})
        elif role == "tool":
            body = _content_str(m.get("content"))
            name = m.get("name") or m.get("tool_call_id") or "tool"
            out.append({
                "role": "user",
                "content": f"<tool_response name={json.dumps(str(name))}>\n"
                           f"{body}\n</tool_response>",
            })
        else:
            out.append({"role": role, "content": _content_str(m.get("content"))})
    if tools and tool_choice != "none":
        block = tools_system_block(tools, tool_choice)
        if out and out[0]["role"] == "system":
            out[0] = {"role": "system",
                      "content": _content_str(out[0]["content"])
                      + "\n\n" + block}
        else:
            out.insert(0, {"role": "system", "content": block})
    return out


def parse_tool_calls(text: str) -> tuple[str | None, list[dict]]:
    """(content, tool_calls) from a complete generation. Content outside
    the blocks survives (None when empty); malformed JSON inside a block
    degrades to text rather than a fake call."""
    calls: list[dict] = []

    def _try(block: str) -> bool:
        try:
            obj = json.loads(block)
        except json.JSONDecodeError:
            return False
        if not isinstance(obj, dict) or "name" not in obj:
            return False
        args = obj.get("arguments", {})
        if not isinstance(args, str):
            args = json.dumps(args, ensure_ascii=False)
        calls.append({
            "id": call_id(),
            "type": "function",
            "function": {"name": str(obj["name"]), "arguments": args},
        })
        return True

    remainder: list[str] = []
    pos = 0
    for m in _BLOCK_RE.finditer(text):
        remainder.append(text[pos:m.start()])
        if not _try(m.group(1)):
            remainder.append(m.group(0))  # malformed: keep as visible text
        pos = m.end()
    remainder.append(text[pos:])
    content = "".join(remainder).strip()
    return (content or None), calls


class ToolCallStreamParser:
    """Incremental splitter for SSE: feed() returns the text that is safe
    to show the user NOW; anything that might be (part of) a tool-call
    block is held until it resolves. finish() flushes and parses."""

    def __init__(self):
        self._buf = ""
        self._calls: list[dict] = []

    def feed(self, delta: str) -> str:
        self._buf += delta
        visible: list[str] = []
        while True:
            i = self._buf.find(TOOL_OPEN)
            if i >= 0:
                visible.append(self._buf[:i])
                j = self._buf.find(TOOL_CLOSE, i)
                if j < 0:
                    self._buf = self._buf[i:]  # inside a block: hold
                    break
                block = self._buf[i + len(TOOL_OPEN):j].strip()
                content, calls = parse_tool_calls(
                    TOOL_OPEN + block + TOOL_CLOSE
                )
                if calls:
                    self._calls.extend(calls)
                elif content:
                    visible.append(content)
                self._buf = self._buf[j + len(TOOL_CLOSE):]
                continue
            # no full opener: hold back only a tail that could grow into one
            keep = 0
            for k in range(1, min(len(TOOL_OPEN), len(self._buf)) + 1):
                if TOOL_OPEN.startswith(self._buf[-k:]):
                    keep = k
            if keep:
                visible.append(self._buf[:-keep])
                self._buf = self._buf[-keep:]
            else:
                visible.append(self._buf)
                self._buf = ""
            break
        return "".join(visible)

    def finish(self) -> tuple[str, list[dict]]:
        """(trailing visible text, all calls) — an unterminated block at
        EOS is released as text (the model never closed it)."""
        tail = self._buf
        self._buf = ""
        return tail, self._calls
