"""Host-RAM KV offload tier (the LMCache CPU-offload equivalent).

Reference: engines get `LMCACHE_LOCAL_CPU=True` + `LMCACHE_MAX_LOCAL_CPU_SIZE`
(deployment-vllm-multi.yaml:306-313; vllmruntime_controller.go:337-347) so
evicted GPU KV parks in host RAM instead of being recomputed. TPU analogue:
when the HBM pool evicts a content-addressed block, its pages are copied
HBM→host into this LRU ring; a later prompt whose hash chain continues into
the ring gets the block uploaded back into a fresh HBM page — KV reuse across
a working set larger than HBM.

The tier stores by content hash (the pool's chain hash), so entries stay
valid across sleep/wake: bytes are bytes, and a reload re-registers them
under the same hash.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from .kv_codec import EncodedKVBlock, decode_block, wire_nbytes
from .kv_flow import NULL_FLOW


def _is_resolved(entry) -> bool:
    """Ring entries are either resolved host bytes (ndarray, or
    EncodedKVBlock when the ring itself is held at rest) or pending
    device parts still in flight from the HBM→host copy."""
    return isinstance(entry, (np.ndarray, EncodedKVBlock))


@dataclass
class HostTierStats:
    offloads: int = 0  # blocks copied HBM -> host
    reloads: int = 0  # blocks served host -> HBM
    evictions: int = 0  # blocks dropped off the ring


class HostKVTier:
    """LRU ring of `num_blocks` full KV blocks in host RAM, keyed by the
    pool's content hash. fetch/upload callbacks bind to the ModelRunner
    (device transfers); the pool calls `store` from its eviction hook and
    `reload_into` from prefix matching."""

    def __init__(self, num_blocks: int, fetch_block, upload_block,
                 remote=None, upload_blocks=None, disk=None, flow=None,
                 codec=None, encode_ring=False):
        self.num_blocks = num_blocks
        # at-rest codec (engine/kv_codec.KVAtRestCodec). encode_ring=True
        # holds RING entries encoded too (cache.kv_at_rest_host_ring):
        # resolved offloads encode once, and disk/remote write-through
        # reuses the encoded form — the ring's block budget then buys
        # wire-ratio-times more blocks (engine.py scales num_host_blocks)
        self.codec = codec
        self.encode_ring = bool(
            encode_ring and codec is not None and codec.enabled
        )
        # KV flow meter (engine/kv_flow.py): tier moves record bytes/
        # blocks/latency here; NULL_FLOW no-ops when metering is off or
        # the tier is constructed standalone
        self.flow = flow if flow is not None else NULL_FLOW
        # fetch returns per-layer device slices with host copies STARTED
        # (ModelRunner.fetch_block); entries resolve to numpy one store
        # behind, so the device→host transfer overlaps the next step instead
        # of stalling the scheduler loop
        self._fetch = fetch_block
        self._upload = upload_block  # (device_block_id, np.ndarray) -> None
        # optional batched form: (block_ids, stacked np.ndarray) -> None —
        # one device dispatch for N blocks (remote-fetch promotion path)
        self._upload_many = upload_blocks
        self._data: OrderedDict[int, object] = OrderedDict()
        self._pending: list[int] = []  # hashes whose entry is still on device
        # optional kvstore.client.RemoteKVTier: resolved blocks write
        # through (its writer thread dedupes), so the remote store holds a
        # superset of the ring and cross-engine prefills can warm from it
        self.remote = remote
        # optional DiskKVTier (kv_disk_tier.py): ring evictions persist to
        # local disk — the middle rung between RAM and the remote store
        self.disk = disk
        # cluster-KV-index hook (set by KVBlockPool): called whenever an
        # entry leaves the ring; the pool's handler checks whether the hash
        # is still locally reloadable (HBM / this ring / disk) before
        # emitting a cluster evict event
        self.on_drop = None
        # migration-aware victim ordering (set by KVBlockPool, docs/39):
        # hash -> bool "a peer engine holds a copy"; budget evictions
        # prefer replicated entries from the oldest end of the ring
        self.is_replicated = None
        self.stats = HostTierStats()

    def _resolve(self, h: int):
        """The ring's RESOLVED entry for h (ndarray, or EncodedKVBlock
        under encode_ring) — materializing the device→host copy and
        encoding/writing-through on first touch."""
        entry = self._data.get(h)
        if entry is None:
            return None
        if not _is_resolved(entry):
            # the HBM→host hop materializes HERE: np.asarray blocks until
            # the async device→host copy lands, then the stack builds the
            # block's host bytes — the honest wall cost of the offload
            t0 = time.perf_counter()
            arr = np.stack([np.asarray(p) for p in entry])
            entry = self.codec.encode(arr) if self.encode_ring else arr
            self.flow.record(
                "host", "out", wire_nbytes(entry), 1,
                time.perf_counter() - t0, logical_nbytes=arr.nbytes,
            )
            self._data[h] = entry
            if self.remote is not None:
                # write through in whatever form the ring holds — the
                # remote writer ships encoded entries as-is (no
                # decode+re-encode round trip)
                self.remote.put_async(h, entry)
        return entry

    def _drain_pending(self, keep_latest: int = 1) -> None:
        while len(self._pending) > keep_latest:
            self._resolve(self._pending.pop(0))

    def flush(self) -> None:
        """Resolve every pending device transfer (and write each through to
        the remote tier when configured) — used before engine shutdown/sleep
        and by tests that need the remote store to be current."""
        self._drain_pending(keep_latest=0)

    def __contains__(self, h: int) -> bool:
        # ring or disk: both are locally reloadable, so prefix matching and
        # the /kv/lookup probe treat them as one local tier
        return h in self._data or (self.disk is not None and h in self.disk)

    def location(self, h: int) -> str:
        """Which local rung serves hash h without moving bytes: "host"
        (ring), "disk", or "" — the hydration planner's residency probe
        (docs/31-hydration-planner.md)."""
        if h in self._data:
            return "host"
        if self.disk is not None and h in self.disk:
            return "disk"
        return ""

    def peek_bytes(self, h: int):
        """Resolved host-RAM bytes for a ring-resident hash, or None —
        an ndarray, or EncodedKVBlock under encode_ring (both downstream
        consumers cope: adopt_planned_run dequantizes on adopt, the peer
        serving path frames the encoded form directly). STEP THREAD ONLY
        (mutates the ring's pending/entry state) — the hydrator
        pre-resolves ring blocks here at plan launch so its fetcher
        thread never touches the ring."""
        return self._resolve(h) if h in self._data else None

    def __len__(self) -> int:
        return len(self._data)

    def resident_hashes(self) -> list[int]:
        """Every locally reloadable hash: ring + disk. Must agree with
        `__contains__` — this set feeds the cluster-index resync snapshot,
        and a snapshot narrower than containment would permanently
        under-report (the admit-suppression in register_full_block keys on
        containment, so a hash dropped only by the snapshot is never
        re-published)."""
        if self.disk is None:
            return list(self._data)
        return list({*self._data, *self.disk.resident_hashes()})

    @property
    def usage_perc(self) -> float:
        return len(self._data) / self.num_blocks if self.num_blocks else 0.0

    def store(self, h: int, device_block: int) -> None:
        """Offload an evicted device block's pages under hash h. The fetch is
        dispatched here; the host bytes materialize on the NEXT store (or on
        reload) — the device buffer must be sliced before the block id is
        reused, which this call order guarantees."""
        if self.num_blocks == 0:
            return
        if h in self._data:  # already offloaded earlier; refresh recency
            self._data.move_to_end(h)
            return
        self._data[h] = self._fetch(device_block)
        self._pending.append(h)
        self._drain_pending(keep_latest=1)
        self.stats.offloads += 1
        self._evict_to_budget()

    # oldest-end window scanned for a peer-replicated victim (mirrors
    # KVBlockPool._VICTIM_SCAN — same migration-aware ordering, ring rung)
    _VICTIM_SCAN = 32

    def _pick_evict(self) -> tuple[int, object]:
        isrep = self.is_replicated
        if isrep is not None:
            for i, h in enumerate(self._data):
                if i >= self._VICTIM_SCAN:
                    break
                if isrep(h):
                    return h, self._data.pop(h)
        return self._data.popitem(last=False)

    def _evict_to_budget(self) -> None:
        while len(self._data) > self.num_blocks:
            evicted, entry = self._pick_evict()
            if evicted in self._pending:
                self._pending.remove(evicted)
            need_bytes = self.disk is not None or (
                self.remote is not None and not _is_resolved(entry)
            )
            if need_bytes and not _is_resolved(entry):
                arr = np.stack([np.asarray(p) for p in entry])
                entry = self.codec.encode(arr) if self.encode_ring else arr
            if self.disk is not None:
                # ring → disk: the evicted block stays reloadable locally
                # (an encoded entry flows to disk in wire form as-is)
                self.disk.store(evicted, entry)
            if self.remote is not None and _is_resolved(entry):
                # an entry evicted before it was ever resolved hasn't been
                # written through yet — push now, or the remote tier
                # silently misses exactly the blocks that fell off (the
                # RemoteKVTier dedupes already-pushed hashes)
                self.remote.put_async(evicted, entry)
            if self.on_drop is not None:
                self.on_drop(evicted)
            self.stats.evictions += 1

    def reload_into(self, h: int, device_block: int) -> str:
        """Upload hash h's pages into a freshly allocated device block.
        Returns the serving rung — "host" (ring hit) or "disk" (disk hit,
        promoted back into the ring) — or "" if h is resident in neither
        (falsy, so boolean call sites keep working). The entry stays
        resident (it may be needed again after the device copy is
        evicted)."""
        source = "host"
        data = self._resolve(h)
        if data is None:
            if self.disk is None:
                return ""
            data = self.disk.load(h)  # records the disk/in hop itself
            if data is None:
                return ""
            source = "disk"
            self.insert_resolved(h, data)  # promote: next match stays in RAM
        else:
            if h in self._pending:
                self._pending.remove(h)
            self._data.move_to_end(h)
        wire = wire_nbytes(data)
        t0 = time.perf_counter()
        # dequant at the device boundary: a ring-encoded entry decodes
        # here, right before the upload (the ring keeps the wire form)
        arr = decode_block(data)
        self._upload(device_block, arr)
        self.flow.record(
            "host", "in", wire, 1, time.perf_counter() - t0,
            logical_nbytes=arr.nbytes,
        )
        self.stats.reloads += 1
        return source

    # -- remote-tier cooperation (kvstore.client.RemoteKVTier) -------------

    def upload(self, device_block: int, data: np.ndarray) -> None:
        """Host→HBM upload for blocks sourced OUTSIDE the ring (remote
        fetches) — same runner callback the reload path uses."""
        t0 = time.perf_counter()
        self._upload(device_block, data)
        self.flow.record(
            "host", "in", data.nbytes, 1, time.perf_counter() - t0
        )

    def upload_many(self, device_blocks: list[int], data) -> None:
        """Batched host→HBM for remote-fetched runs: one device dispatch
        when the runner supports it, per-block otherwise."""
        t0 = time.perf_counter()
        if self._upload_many is not None:
            stacked = np.stack(data)
            self._upload_many(device_blocks, stacked)
            nbytes = stacked.nbytes
        else:
            nbytes = 0
            for blk, d in zip(device_blocks, data):
                self._upload(blk, d)
                nbytes += np.asarray(d).nbytes
        self.flow.record(
            "host", "in", nbytes, len(device_blocks),
            time.perf_counter() - t0,
        )

    def insert_resolved(self, h: int, data) -> None:
        """Promote a fetched block into the ring so the next match is
        local. Budget enforced; no write-through needed (the remote tier's
        dedupe set already knows h). Accepts either form and normalizes
        to the ring's configured one: encode_ring rings hold wire form
        (an already-encoded fetch inserts with NO transcode), plain rings
        hold the logical array."""
        if self.num_blocks == 0 or h in self._data:
            return
        if self.encode_ring:
            if not isinstance(data, EncodedKVBlock):
                data = self.codec.encode(data)
        elif isinstance(data, EncodedKVBlock):
            data = decode_block(data)
        self._data[h] = data
        self._evict_to_budget()
