"""Local-disk KV tier: the LMCache disk-offload equivalent.

Middle rung of the KV hierarchy (HBM → host ring → THIS → remote store):
blocks evicted off the host RAM ring persist to a local directory (one
.npy per content hash) under a byte budget, so a working set larger than
RAM still reloads from NVMe instead of recomputing — and survives engine
restarts, since content-hashed bytes stay valid as long as the model
fingerprint matches (reference: `LMCACHE_LOCAL_DISK` /
`LMCACHE_MAX_LOCAL_DISK_SIZE`, vllmruntime_controller.go:337-374).

Writes happen at ring-eviction time on the engine thread; one block is
~0.1-2 MB, well under a millisecond on local SSD — cheap next to the
device round trips the eviction path already pays. Loads are one np.load
on the prefix-match path, each saving an entire chunk of prefill compute.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ..utils.logging import init_logger
from .kv_flow import NULL_FLOW

logger = init_logger(__name__)


@dataclass
class DiskTierStats:
    stores: int = 0
    loads: int = 0
    evictions: int = 0


class DiskKVTier:
    """Byte-budget LRU of KV blocks as `.kvb` files keyed by content hash.

    Files use the stack's shared block-frame format (kv_transfer.raw_frame
    / FrameParser — 4-byte header length, JSON header, raw bytes), NOT
    np.save: numpy's format silently degrades ml_dtypes arrays (bfloat16 →
    '|V2', float8_e4m3fn → '|V1' void dtypes), which would crash the
    device upload for every production pool dtype. Writes are atomic
    (temp + rename) so a crash mid-store can never leave a half-written
    block that wedges the index.

    The fingerprint namespaces the directory — a model/dtype change gets a
    fresh subdirectory instead of silently serving stale KV bytes."""

    SUFFIX = ".kvb"

    def __init__(self, directory: str, max_bytes: int, fingerprint: str = "",
                 flow=None, codec=None):
        self.dir = os.path.join(directory, fingerprint or "default")
        os.makedirs(self.dir, exist_ok=True)
        self.max_bytes = max_bytes
        # at-rest codec (engine/kv_codec.KVAtRestCodec): files persist in
        # wire form (int4+scales / fp8) and decode on load. The
        # fingerprint the directory is namespaced by includes the codec
        # spec, so a codec change never reads incompatible bytes.
        self.codec = codec
        self.stats = DiskTierStats()
        # loads may run on the hydration fetcher thread concurrently with
        # step-thread stores/evictions (docs/31-hydration-planner.md) —
        # one small lock guards the LRU index + file operations
        self._mu = threading.RLock()
        # KV flow meter (engine/kv_flow.py): store/load record bytes +
        # wall latency under tier="disk"
        self.flow = flow if flow is not None else NULL_FLOW
        # cluster-KV-index hook (wired by KVBlockPool): called when a hash
        # leaves this tier (budget eviction or corrupt-file unlink) — the
        # last local rung, so a drop here can end local matchability
        self.on_drop = None
        # LRU index rebuilt from the directory on start (restart survival):
        # oldest-mtime first
        self._index: OrderedDict[int, int] = OrderedDict()  # hash -> nbytes
        self.total_bytes = 0
        entries = []
        for name in os.listdir(self.dir):
            if not name.endswith(self.SUFFIX):
                continue
            path = os.path.join(self.dir, name)
            try:
                h = int(name[: -len(self.SUFFIX)])
                st = os.stat(path)
            except (ValueError, OSError):
                continue
            entries.append((st.st_mtime, h, st.st_size))
        for _, h, size in sorted(entries):
            self._index[h] = size
            self.total_bytes += size

    def _path(self, h: int) -> str:
        return os.path.join(self.dir, f"{h}{self.SUFFIX}")

    def __contains__(self, h: int) -> bool:
        with self._mu:
            return h in self._index

    def __len__(self) -> int:
        with self._mu:
            return len(self._index)

    def resident_hashes(self) -> list[int]:
        with self._mu:
            return list(self._index)

    def store(self, h: int, arr: np.ndarray) -> None:
        """File I/O runs OUTSIDE the lock (a multi-MB write must not
        stall the fetcher thread's loads or the step thread's probes);
        only the duplicate check and the index/eviction bookkeeping hold
        it. A same-hash double store is impossible by construction (only
        the step thread's ring eviction stores)."""
        with self._mu:
            if self.max_bytes <= 0 or h in self._index:
                return
        from .kv_codec import EncodedKVBlock, logical_nbytes
        from .kv_transfer import encoded_frame

        path = self._path(h)
        tmp = f"{path}.tmp{os.getpid()}"
        # encode to at-rest form unless the caller already did (a ring-
        # encoded eviction flows through without a decode+re-encode)
        obj = arr
        if (
            self.codec is not None
            and self.codec.enabled
            and not isinstance(arr, EncodedKVBlock)
        ):
            obj = self.codec.encode(arr)
        payload = encoded_frame(h, obj)
        logical = logical_nbytes(obj)
        t0 = time.perf_counter()
        try:
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except OSError as e:  # full/readonly disk: a cache degrades, never fails
            logger.warning("disk KV store of %x failed: %s", h, e)
            # the attempt's wall time is real (a dying disk shows up as
            # collapsing disk/out bandwidth, not silence)
            self.flow.record(
                "disk", "out", 0, 0, time.perf_counter() - t0
            )
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        self.flow.record(
            "disk", "out", len(payload), 1, time.perf_counter() - t0,
            logical_nbytes=logical,
        )
        with self._mu:
            self._index[h] = len(payload)
            self.total_bytes += len(payload)
            self.stats.stores += 1
            while self.total_bytes > self.max_bytes and len(self._index) > 1:
                old, old_size = self._index.popitem(last=False)
                try:
                    os.unlink(self._path(old))
                except OSError:
                    pass
                self.total_bytes -= old_size
                self.stats.evictions += 1
                if self.on_drop is not None:
                    self.on_drop(old)

    def load(self, h: int) -> np.ndarray | None:
        """Like store, the read+parse runs outside the lock — a budget
        eviction racing the read just unlinks the file under us, which
        lands in the corrupt-miss path below (the honest outcome)."""
        with self._mu:
            if h not in self._index:
                return None
            self._index.move_to_end(h)  # LRU touch on the attempt
        from .kv_transfer import FrameParser

        t0 = time.perf_counter()
        try:
            with open(self._path(h), "rb") as f:
                data = f.read()
            # the parser dequantizes codec-tagged frames (at-rest files
            # land as logical arrays here — disk is a local hop, the RAM
            # saving of deferred decode doesn't apply)
            frames = FrameParser().feed(data)
            if not frames or frames[0][0] != h:
                raise ValueError("truncated or mismatched block frame")
            arr = frames[0][1]
        except Exception as e:
            # broad on purpose: ANY corrupt-bytes failure (truncated frame,
            # garbled JSON header, unrecognized dtype string → TypeError/
            # AttributeError from the dtype lookup) must degrade to a cache
            # miss and unlink — never kill the prefix-match path
            logger.warning("disk KV load of %x failed: %s", h, e)
            with self._mu:
                size = self._index.pop(h, 0)
                self.total_bytes -= size
                # unlink the corrupt file: leaving it would leak untracked
                # bytes AND re-index the dead entry on every restart
                try:
                    os.unlink(self._path(h))
                except OSError:
                    pass
                if size and self.on_drop is not None:
                    self.on_drop(h)
            self.flow.record(
                "disk", "in", 0, 0, time.perf_counter() - t0
            )
            return None
        # wire bytes = the file that was actually read (mirrors store's
        # whole-frame accounting); logical = the decoded array
        self.flow.record(
            "disk", "in", len(data), 1, time.perf_counter() - t0,
            logical_nbytes=arr.nbytes,
        )
        with self._mu:
            self.stats.loads += 1
        return arr
