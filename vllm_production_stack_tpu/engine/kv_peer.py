"""Peer-engine KV tier: pull prefix KV from another engine's memory.

A prefix resident only in engine A's HBM/host tiers used to be useless to
engine B — B recomputed or pulled from disk/remote even though the cluster
KV index knows A has it and the device-path KV transfer already ships
blocks bit-identically between meshes. This module treats *other engines'
tiers* as one more rung of the hydration hierarchy (the LMCache
enterprise thesis + BanaServe's KV-migration half, PAPERS.md): the
compute-or-load planner (engine/hydration.py) prices a peer fetch against
recompute/disk/remote per chunk from the measured ``tier="peer"``
bandwidth, and the router's priced route-vs-migrate policy
(docs/35-peer-kv-reuse.md) decides when trading ICI/DCN bandwidth for
seat availability beats chasing the prefix owner.

:class:`PeerKVTier` is the CLIENT half, one per engine:

- ``cluster_lookup`` asks the embedded/controller ``ClusterKVIndex``
  (``POST {lookup_url}/peer_lookup``) which engine holds the longest run
  of a hash chain — the rediscovery path when the router didn't stamp an
  owner hint (``x-kv-owner-hint``) upstream.
- ``contains_run`` confirms the owner's ACTUAL consecutive residency
  (``POST {owner}/kv/peer_contains``) — the index can be seconds stale,
  and planning chunks the owner already evicted would just burn fallback
  recomputes.
- ``fetch_run`` pulls block payloads (``POST {owner}/kv/peer_fetch``,
  the kvstore framing — engine/kv_transfer.FrameParser) and records the
  transfer under ``tier="peer", direction="in"`` — including failures at
  0 bytes, so a dying peer reads as collapsing bandwidth, exactly what
  flips the planner back to recompute.

The serving half lives in engine/server.py (``/kv/peer_contains`` +
``/kv/peer_fetch``, always mounted — an engine can be an owner without
consuming the tier) and meters served bytes as ``peer/out``.

Connection discipline mirrors the remote store client
(kvstore/client.py): keep-alive :class:`_Conn` objects, a cooldown per
unreachable target so a dead peer costs one timeout per ``cooldown_s``
instead of one per prompt, and DEDICATED fetch connections for the
hydration fetcher thread (``new_fetch_conn``) so multi-second chunk pulls
never serialize behind the step thread's admission probes.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from dataclasses import dataclass
from urllib.parse import urlsplit

import numpy as np

from ..utils.logging import init_logger
from .kv_flow import NULL_FLOW

logger = init_logger(__name__)

# the router→engine owner hint (docs/35-peer-kv-reuse.md): stamped by the
# KV-aware policy when its priced scoring routes a request AWAY from the
# prefix owner, so the target engine's hydration planner skips cluster
# rediscovery. Inbound copies are stripped by the router whenever it
# stamps (same spoof rule as the tenant/fleet headers).
KV_OWNER_HINT_HEADER = "x-kv-owner-hint"

# bound one peer round trip's hash list: chunk plans fetch spans of
# chunk_blocks (default 16); 1024 blocks is far above any real span and
# far below anything that could balloon a request body or a reply
MAX_PEER_RUN_BLOCKS = 1024


def peer_hint_from_headers(headers) -> str | None:
    """The validated x-kv-owner-hint value, or None. Only http(s) base
    URLs are accepted — the hint is used as a fetch target, and anything
    else (a spoofed garbage value that slipped past a hintless router)
    must degrade to rediscovery, never to a malformed connect."""
    raw = headers.get(KV_OWNER_HINT_HEADER)
    if not raw:
        return None
    raw = raw.strip().rstrip("/")
    parts = urlsplit(raw)
    if parts.scheme not in ("http", "https") or not parts.hostname:
        return None
    return raw


def _host_port(url: str) -> tuple[str, int]:
    parts = urlsplit(url)
    if not parts.hostname:
        raise ValueError(f"invalid peer URL {url!r}")
    return parts.hostname, parts.port or (443 if parts.scheme == "https" else 80)


@dataclass
class PeerTierStats:
    lookups: int = 0  # cluster /peer_lookup round trips
    lookup_hits: int = 0  # lookups that named an owner
    contains_probes: int = 0  # owner /kv/peer_contains round trips
    fetches: int = 0  # /kv/peer_fetch round trips
    fetched_blocks: int = 0  # blocks pulled peer -> this engine
    bootstrap_fetches: int = 0  # measurement-only fetches (sample floor)
    errors: int = 0


class PeerKVTier:
    """Client half of the peer-engine KV tier, one per engine.

    Thread model: ``cluster_lookup``/``contains_run`` run on the STEP
    thread (admission probes — bounded timeout, cooldown on failure, one
    shared keep-alive connection per purpose under a small lock);
    ``fetch_run`` runs on the hydration FETCHER thread over dedicated
    per-owner connections the :class:`~.hydration.Hydrator` manages via
    ``new_fetch_conn``. All hashes travel as decimal strings (128-bit;
    string form sidesteps any JSON integer-width trap, same as the
    kvstore wire)."""

    def __init__(
        self,
        fingerprint: str,
        self_url: str = "",
        lookup_url: str = "",
        timeout: float = 5.0,
        cooldown_s: float = 5.0,
        flow=None,
    ):
        self.fingerprint = fingerprint
        self.self_url = (self_url or "").rstrip("/")
        self.lookup_url = (lookup_url or "").rstrip("/")
        self.timeout = timeout
        self.cooldown_s = cooldown_s
        self.flow = flow if flow is not None else NULL_FLOW
        self.stats = PeerTierStats()
        # device-transport negotiation (docs/39-device-peer-kv.md): this
        # engine's mesh/process-group identity (None = HTTP only), plus the
        # per-owner transport learned from /peer_lookup hints and
        # /kv/peer_contains replies. probe_prefix reads transport_for() to
        # label the continuation "device" vs "peer".
        self.transport_identity: dict | None = None
        self._owner_transport: dict[str, str] = {}
        # step-thread probe connections: one to the lookup host, one per
        # owner — guarded by one lock (admission is single-threaded today;
        # the lock keeps that an implementation detail, not a contract)
        self._probe_mu = threading.Lock()
        self._probe_conns: dict[str, object] = {}
        # per-target cooldown: a dead lookup service / peer costs one
        # timeout per cooldown_s, never one per admission
        self._down_until: dict[str, float] = {}

    # -- availability ------------------------------------------------------

    def _available(self, target: str) -> bool:
        return time.monotonic() >= self._down_until.get(target, 0.0)

    def _trip(self, target: str, err: Exception) -> None:
        self.stats.errors += 1
        self._down_until[target] = time.monotonic() + self.cooldown_s
        logger.warning(
            "peer KV target %s unreachable (%s); cooling down %.0fs",
            target, err, self.cooldown_s,
        )

    def _conn_for(self, url: str):
        from ..kvstore.client import _Conn  # shared keep-alive idiom

        conn = self._probe_conns.get(url)
        if conn is None:
            host, port = _host_port(url)
            conn = self._probe_conns[url] = _Conn(host, port, self.timeout)
        return conn

    # -- discovery (step thread) -------------------------------------------

    def cluster_lookup(
        self, hashes: list[int], block_size: int
    ) -> tuple[str, int]:
        """(owner url, matched BLOCKS) of the engine holding the longest
        locally-resident run of `hashes` per the cluster KV index — the
        rediscovery path when no router owner hint arrived. ("", 0) when
        no lookup service is configured, it is cooling down, or nothing
        matched. The index excludes THIS engine server-side (its own
        residency is what probe_prefix already walked)."""
        if not self.lookup_url or not self._available(self.lookup_url):
            return "", 0
        self.stats.lookups += 1
        req = {
            "hashes": [f"{h:x}" for h in hashes[:MAX_PEER_RUN_BLOCKS]],
            "block_size": block_size,
            "exclude": self.self_url,
        }
        if self.transport_identity:
            # the index negotiates a per-pair transport hint from this
            # identity and the owner's registered one (docs/39)
            req["transport"] = self.transport_identity
        body = json.dumps(req).encode()
        try:
            with self._probe_mu:
                status, _, payload = self._conn_for(self.lookup_url).request(
                    "POST", "/peer_lookup", body=body,
                    headers={"Content-Type": "application/json"},
                )
        except (OSError, http.client.HTTPException) as e:
            # _Conn re-raises HTTPException (a malformed status line from a
            # proxy, say) which is NOT an OSError — and this runs on the
            # step thread, where an escape would abort every in-flight
            # request instead of degrading this one probe
            self._trip(self.lookup_url, e)
            return "", 0
        if status != 200:
            return "", 0
        try:
            data = json.loads(payload)
        except ValueError:
            return "", 0
        owner = (data.get("url") or "").rstrip("/")
        matched = int(data.get("matched_blocks") or 0)
        if not owner or matched <= 0 or owner == self.self_url:
            return "", 0
        # transport hint from the lookup reply ("device"|"http"; absent on
        # pre-39 services = http) — remembered per owner for probe_prefix
        self._owner_transport[owner] = (
            "device" if data.get("transport") == "device" else "peer"
        )
        self.stats.lookup_hits += 1
        return owner, matched

    def contains_run(self, owner: str, hashes: list[int]) -> int:
        """How many of `hashes` (in order, consecutively) `owner` can serve
        RIGHT NOW — the staleness guard between the index's view and the
        owner's actual residency. 0 on any failure (the region simply
        recomputes)."""
        owner = owner.rstrip("/")
        if not owner or not hashes or not self._available(owner):
            return 0
        if owner == self.self_url:
            return 0  # self-fetch would deadlock on the engine lock
        self.stats.contains_probes += 1
        body = json.dumps({
            "fingerprint": self.fingerprint,
            "hashes": [str(h) for h in hashes[:MAX_PEER_RUN_BLOCKS]],
        }).encode()
        try:
            with self._probe_mu:
                status, _, payload = self._conn_for(owner).request(
                    "POST", "/kv/peer_contains", body=body,
                    headers={"Content-Type": "application/json"},
                )
        except (OSError, http.client.HTTPException) as e:
            self._trip(owner, e)  # same step-thread contract as above
            return 0
        if status != 200:
            return 0
        try:
            data = json.loads(payload)
        except ValueError:
            return 0
        # the owner echoes its mesh identity; negotiating HERE (not just at
        # /peer_lookup) covers the router's owner-hint path — which never
        # touches the lookup service — and re-validates a possibly-stale
        # index-side hint against the owner's live identity
        from ..kv_index import negotiate_transport

        self._owner_transport[owner] = (
            "device"
            if negotiate_transport(
                self.transport_identity, data.get("transport")
            ) == "device"
            else "peer"
        )
        return max(0, int(data.get("matched") or 0))

    def transport_for(self, owner: str) -> str:
        """Tier label for a confirmed continuation on `owner`: "device"
        when the last lookup/contains negotiation agreed on the device
        path, else "peer" (host-staged HTTP)."""
        return self._owner_transport.get(owner.rstrip("/"), "peer")

    # -- fetch (hydration fetcher thread) ----------------------------------

    def new_fetch_conn(self, owner: str):
        """A dedicated keep-alive connection to one owner for the hydration
        fetcher thread — its multi-second chunk pulls must never hold the
        probe lock the step thread's admissions contend on (the
        kvstore new_fetch_conn idiom)."""
        from ..kvstore.client import _Conn

        host, port = _host_port(owner)
        return _Conn(host, port, self.timeout)

    def fetch_run(
        self, owner: str, hashes: list[int], conn=None, bootstrap: bool = False,
    ) -> list:
        """The consecutive prefix of `hashes` the owner served — plain
        frames as arrays, at-rest frames (the owner runs a kv_codec) as
        EncodedKVBlock dequantized at the pool's adopt boundary — one
        batched round trip over `conn` (or a throwaway connection).
        Every round trip records under (peer, in): WIRE payload bytes on
        success, 0 bytes + real elapsed on failure, so the TierBandwidth
        estimate the planner prices against tracks the link as the codec
        actually uses it. `bootstrap` marks measurement-only fetches
        (docs/35-peer-kv-reuse.md — how the peer tier crosses the sample
        floor with no sync fallback to feed it)."""
        owner = owner.rstrip("/")
        if not owner or not hashes or not self._available(owner):
            return []
        from .kv_transfer import FrameParser

        own_conn = conn is None
        if own_conn:
            conn = self.new_fetch_conn(owner)
        t0 = time.perf_counter()
        out: list = []

        def _flow(nbytes: int, logical: int | None = None) -> None:
            self.flow.record(
                "peer", "in", nbytes, len(out), time.perf_counter() - t0,
                logical_nbytes=logical,
            )

        body = json.dumps({
            "fingerprint": self.fingerprint,
            "hashes": [str(h) for h in hashes[:MAX_PEER_RUN_BLOCKS]],
        }).encode()
        try:
            status, _, payload = conn.request(
                "POST", "/kv/peer_fetch", body=body,
                headers={"Content-Type": "application/json"},
            )
        except (OSError, http.client.HTTPException) as e:
            _flow(0)  # a dead peer IS ~0 fetch bandwidth — record it
            self._trip(owner, e)
            return []
        finally:
            if own_conn:
                conn.close()
        if status != 200:
            _flow(0)
            return []
        if bootstrap:
            self.stats.bootstrap_fetches += 1
        else:
            self.stats.fetches += 1
        # decode_codec=False: dequant happens at the adopt boundary, and
        # the fetcher holds wire-size RAM while chunks await adoption
        parser = FrameParser(decode_codec=False)
        for h, arr in parser.feed_partial(payload):
            if len(out) >= len(hashes) or h != hashes[len(out)]:
                break  # non-consecutive frame; stop clean
            # copy: a frombuffer view would pin the whole multi-block
            # response buffer for as long as any one block stays adopted
            # (EncodedKVBlock payloads are already-detached bytes)
            out.append(arr.copy() if isinstance(arr, np.ndarray) else arr)
        self.stats.fetched_blocks += len(out)
        meta = parser.frame_meta[: len(out)]
        _flow(sum(w for w, _ in meta), sum(lg for _, lg in meta))
        if parser.error is not None:
            logger.warning(
                "malformed peer_fetch response from %s after %d valid "
                "frames: %s", owner, len(out), parser.error,
            )
            self.stats.errors += 1
        return out

    # -- lifecycle / introspection -----------------------------------------

    def close(self) -> None:
        with self._probe_mu:
            for conn in self._probe_conns.values():
                conn.close()
            self._probe_conns.clear()

    def snapshot(self) -> dict:
        """Operator view for GET /debug/hydration's peer section."""
        now = time.monotonic()
        return {
            "lookup_url": self.lookup_url,
            "self_url": self.self_url,
            "lookups": self.stats.lookups,
            "lookup_hits": self.stats.lookup_hits,
            "contains_probes": self.stats.contains_probes,
            "fetches": self.stats.fetches,
            "fetched_blocks": self.stats.fetched_blocks,
            "bootstrap_fetches": self.stats.bootstrap_fetches,
            "errors": self.stats.errors,
            "transport_identity": self.transport_identity,
            "owner_transports": dict(self._owner_transport),
            "cooling_down": sorted(
                t for t, until in self._down_until.items() if until > now
            ),
        }
