"""Grammar-constrained decoding: JSON-schema -> token-class automaton
(docs/41-structured-output.md).

Compilation pipeline, all off the hot path:

    JSON schema / generic-JSON / forced-tool-call spec
      -> regex-like AST (lit / charclass / seq / alt / star / opt)
      -> Thompson NFA over the BYTE alphabet
      -> subset-construction byte-DFA, dead-end states pruned
      -> token lifting: run every vocab token's content bytes from every
         DFA state -> dense dest matrix (S, V)
      -> token-CLASS compression: vocab columns with identical cross-state
         behaviour collapse to one class (np.unique over columns), leaving
         token_class (V,), class_dest (S, C), accepting (S,)

Per-step work is then pure table lookups: the (V,) logit mask for a state
is `class_dest[state][token_class] >= 0` (memoized per state), and
advancing on a sampled token is one (state, class) indexed read. The
tables are plain numpy — small enough to ship to the device as DATA, so
the jitted decode window advances the automaton on-device without the
mask ever becoming a program shape (model_runner pads them up exactly
like batch/width buckets).

This module must stay importable WITHOUT jax: the router calls
validate_spec() for its 400-on-uncompilable-schema path and must not pay
(or even have) a jax import.

EOS is not a grammar byte: it is allowed exactly in accepting states and
is never consumed by the automaton. Tokens with no content bytes
(BOS/PAD, model-vocab padding beyond the tokenizer) are never allowed.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import OrderedDict

import numpy as np

__all__ = [
    "GrammarCompileError",
    "GrammarCache",
    "GrammarState",
    "TokenGrammar",
    "extract_spec",
    "tool_choice_spec",
    "validate_spec",
    "schema_instance",
    "spec_key",
]


class GrammarCompileError(ValueError):
    """Schema/grammar cannot be compiled (unsupported construct, caps
    exceeded, or dead-end automaton). Routers/servers map this to 400 in
    `enforce` mode and to an unconstrained `fallback` serve otherwise —
    never to a 500."""


# Compile-time caps: pathological inputs (deeply nested schemas, huge
# enums, exponential alternations) must fail with GrammarCompileError
# instead of wedging the process that compiles them.
MAX_SCHEMA_DEPTH = 32
MAX_ENUM_VALUES = 256
MAX_LITERAL_BYTES = 16384  # total literal bytes across the AST
MAX_NFA_STATES = 50_000
MAX_DFA_STATES = 4096
MAX_REPEAT = 64  # minItems/maxItems expansion bound
JSON_OBJECT_DEPTH = 4  # nesting budget for {"type": "json_object"}


def _canon(value) -> str:
    """Canonical compact JSON — the exact bytes constrained output uses
    (no optional whitespace; object keys in declaration order)."""
    return json.dumps(
        value, ensure_ascii=False, separators=(",", ":"), sort_keys=False
    )


def spec_key(spec: dict) -> str:
    """Cache key for a grammar spec. Declaration order is significant
    (objects emit properties in order), so no sort_keys."""
    return _canon(spec)


# ---------------------------------------------------------------------------
# AST: nodes are plain tuples so construction stays allocation-cheap.
#   ("lit", bytes)          exact byte string
#   ("cls", frozenset[int]) one byte from the set
#   ("seq", (nodes...))     concatenation (empty tuple = empty string)
#   ("alt", (nodes...))     alternation (must be non-empty)
#   ("star", node)          zero or more
#   ("opt", node)           zero or one
# ---------------------------------------------------------------------------

_EMPTY = ("seq", ())


def _lit(data: bytes):
    return ("lit", data)


def _cls(byteset):
    return ("cls", frozenset(byteset))


def _seq(*nodes):
    flat = []
    for n in nodes:
        if n[0] == "seq":
            flat.extend(n[1])
        else:
            flat.append(n)
    return ("seq", tuple(flat))


def _alt(*nodes):
    if not nodes:
        raise GrammarCompileError("empty alternation")
    return nodes[0] if len(nodes) == 1 else ("alt", tuple(nodes))


def _star(node):
    return ("star", node)


def _opt(node):
    return ("opt", node)


_DIGIT = frozenset(b"0123456789")
_DIGIT19 = frozenset(b"123456789")
_HEX = frozenset(b"0123456789abcdefABCDEF")
_ESC_SINGLE = frozenset(b'"\\/bfnrt')
# Any byte allowed raw inside a JSON string: 0x20..0xFF minus '"' and '\'.
# Bytes >= 0x80 are permitted (UTF-8 content); byte-level validity of the
# encoding itself is not enforced — the tokenizer's content bytes are.
_STR_PLAIN = frozenset(range(0x20, 0x100)) - {0x22, 0x5C}

_STRING_AST = _seq(
    _lit(b'"'),
    _star(
        _alt(
            _cls(_STR_PLAIN),
            _seq(
                _lit(b"\\"),
                _alt(
                    _cls(_ESC_SINGLE),
                    _seq(_lit(b"u"), _cls(_HEX), _cls(_HEX), _cls(_HEX), _cls(_HEX)),
                ),
            ),
        )
    ),
    _lit(b'"'),
)

# Canonical integer: no leading zeros, optional minus.
_INT_AST = _seq(
    _opt(_lit(b"-")),
    _alt(_lit(b"0"), _seq(_cls(_DIGIT19), _star(_cls(_DIGIT)))),
)

_NUMBER_AST = _seq(
    _INT_AST,
    _opt(_seq(_lit(b"."), _cls(_DIGIT), _star(_cls(_DIGIT)))),
    _opt(
        _seq(
            _cls(frozenset(b"eE")),
            _opt(_cls(frozenset(b"+-"))),
            _cls(_DIGIT),
            _star(_cls(_DIGIT)),
        )
    ),
)

_BOOL_AST = _alt(_lit(b"true"), _lit(b"false"))
_NULL_AST = _lit(b"null")

# Constructs we refuse rather than silently mis-enforce.
_UNSUPPORTED_KEYS = ("$ref", "allOf", "not", "if", "patternProperties")


def _comma_items(item, between=b","):
    """item ("," item)* — as a `loop` node, which reuses ONE copy of the
    item fragment with a separator back-edge instead of duplicating it
    (Thompson star would); keeps deeply-nested generic-JSON grammars from
    exploding the NFA."""
    return ("loop", (item, between))


def _array_ast(item, min_items: int, max_items: int | None):
    if min_items < 0 or min_items > MAX_REPEAT:
        raise GrammarCompileError(f"minItems {min_items} out of range")
    if max_items is not None:
        if max_items > MAX_REPEAT:
            raise GrammarCompileError(f"maxItems {max_items} exceeds cap {MAX_REPEAT}")
        if max_items < min_items:
            raise GrammarCompileError("maxItems < minItems")
    comma_item = _seq(_lit(b","), item)
    if max_items is None:
        if min_items == 0:
            body = _opt(_comma_items(item))
        else:
            body = _seq(item, *([comma_item] * (min_items - 1)), _star(comma_item))
    elif max_items == 0:
        body = _EMPTY
    else:
        tail = _EMPTY
        for _ in range(max_items - max(min_items, 1)):
            tail = _opt(_seq(comma_item, tail))
        head = _seq(item, *([comma_item] * (min_items - 1)), tail)
        body = head if min_items > 0 else _opt(head)
    return _seq(_lit(b"["), body, _lit(b"]"))


def _value_ast(depth: int):
    """Generic JSON value with a bounded nesting budget (used for
    {"type": "json_object"} and schema-less subtrees)."""
    scalars = _alt(_STRING_AST, _NUMBER_AST, _BOOL_AST, _NULL_AST)
    if depth <= 0:
        return scalars
    inner = _value_ast(depth - 1)
    return _alt(scalars, _object_ast_generic(depth, inner), _array_ast(inner, 0, None))


def _object_ast_generic(depth: int, inner=None):
    """{ "k": v (, "k": v)* } with generic keys/values."""
    if inner is None:
        inner = _value_ast(depth - 1)
    member = _seq(_STRING_AST, _lit(b":"), inner)
    return _seq(_lit(b"{"), _opt(_comma_items(member)), _lit(b"}"))


def _schema_ast(schema, depth: int):
    if depth > MAX_SCHEMA_DEPTH:
        raise GrammarCompileError(f"schema nesting exceeds cap {MAX_SCHEMA_DEPTH}")
    if schema is True or schema == {}:
        return _value_ast(min(JSON_OBJECT_DEPTH, MAX_SCHEMA_DEPTH - depth))
    if not isinstance(schema, dict):
        raise GrammarCompileError(f"schema must be an object, got {type(schema).__name__}")
    for key in _UNSUPPORTED_KEYS:
        if key in schema:
            raise GrammarCompileError(f"unsupported schema construct {key!r}")
    if "enum" in schema:
        values = schema["enum"]
        if not isinstance(values, list) or not values and values != []:
            raise GrammarCompileError("enum must be a list")
        if not values:
            raise GrammarCompileError("empty enum matches nothing")
        if len(values) > MAX_ENUM_VALUES:
            raise GrammarCompileError(
                f"enum with {len(values)} values exceeds cap {MAX_ENUM_VALUES}"
            )
        return _alt(*[_lit(_canon(v).encode("utf-8")) for v in values])
    if "const" in schema:
        return _lit(_canon(schema["const"]).encode("utf-8"))
    for union_key in ("anyOf", "oneOf"):
        if union_key in schema:
            subs = schema[union_key]
            if not isinstance(subs, list) or not subs:
                raise GrammarCompileError(f"{union_key} must be a non-empty list")
            return _alt(*[_schema_ast(s, depth + 1) for s in subs])
    stype = schema.get("type")
    if isinstance(stype, list):
        if not stype:
            raise GrammarCompileError("empty type list")
        return _alt(
            *[_schema_ast({**schema, "type": t}, depth + 1) for t in stype]
        )
    if stype is None:
        if "properties" in schema:
            stype = "object"
        elif "items" in schema:
            stype = "array"
        else:
            return _value_ast(min(JSON_OBJECT_DEPTH, MAX_SCHEMA_DEPTH - depth))
    if stype == "object":
        props = schema.get("properties")
        if not props:
            return _object_ast_generic(
                min(JSON_OBJECT_DEPTH, MAX_SCHEMA_DEPTH - depth)
            )
        if not isinstance(props, dict):
            raise GrammarCompileError("properties must be an object")
        # Every declared property is emitted, in declaration order, in
        # canonical compact form — `required` narrowing is not supported
        # (documented limitation; keeps the automaton linear in schema
        # size instead of exponential in optional-property subsets).
        parts = [_lit(b"{")]
        for i, (name, sub) in enumerate(props.items()):
            prefix = ("," if i else "") + _canon(str(name)) + ":"
            parts.append(_lit(prefix.encode("utf-8")))
            parts.append(_schema_ast(sub, depth + 1))
        parts.append(_lit(b"}"))
        return _seq(*parts)
    if stype == "array":
        items = schema.get("items")
        item_ast = (
            _schema_ast(items, depth + 1)
            if items is not None
            else _value_ast(min(JSON_OBJECT_DEPTH, MAX_SCHEMA_DEPTH - depth))
        )
        return _array_ast(
            item_ast,
            int(schema.get("minItems", 0)),
            None if schema.get("maxItems") is None else int(schema["maxItems"]),
        )
    if stype == "string":
        if "pattern" in schema:
            raise GrammarCompileError("unsupported schema construct 'pattern'")
        return _STRING_AST
    if stype == "integer":
        return _INT_AST
    if stype == "number":
        return _NUMBER_AST
    if stype == "boolean":
        return _BOOL_AST
    if stype == "null":
        return _NULL_AST
    raise GrammarCompileError(f"unsupported schema type {stype!r}")


def _tool_call_ast(tools: list[dict]):
    """Forced tool call: the exact surface `tool_calls.parse_tool_calls`
    consumes — <tool_call>{"name":<fn>,"arguments":<schema>}</tool_call>
    with canonical compact JSON, so a forced call ALWAYS parses."""
    from .tool_calls import TOOL_CLOSE, TOOL_OPEN

    options = []
    for tool in tools:
        name = tool.get("name")
        if not isinstance(name, str) or not name:
            raise GrammarCompileError("tool without a function name")
        params = tool.get("parameters")
        if params:
            args_ast = _schema_ast(params, 1)
        else:
            args_ast = _object_ast_generic(JSON_OBJECT_DEPTH)
        options.append(
            _seq(
                _lit(('{"name":' + _canon(name) + ',"arguments":').encode("utf-8")),
                args_ast,
                _lit(b"}"),
            )
        )
    return _seq(_lit(TOOL_OPEN.encode()), _alt(*options), _lit(TOOL_CLOSE.encode()))


def build_ast(spec: dict):
    kind = spec.get("kind")
    if kind == "json_schema":
        return _schema_ast(spec.get("schema"), 0)
    if kind == "json_object":
        return _value_ast(JSON_OBJECT_DEPTH)
    if kind == "tool_call":
        return _tool_call_ast(spec.get("tools") or [])
    raise GrammarCompileError(f"unknown grammar kind {kind!r}")


# ---------------------------------------------------------------------------
# AST -> NFA (Thompson) -> byte-DFA (subset construction) -> prune.
# ---------------------------------------------------------------------------


class _NFA:
    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[frozenset, int]]] = []
        self.lit_bytes = 0

    def state(self) -> int:
        if len(self.eps) >= MAX_NFA_STATES:
            raise GrammarCompileError(
                f"grammar NFA exceeds cap {MAX_NFA_STATES} states"
            )
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def build(self, node) -> tuple[int, int]:
        """Return (start, end) of the fragment for `node`."""
        kind, payload = node
        if kind == "lit":
            self.lit_bytes += len(payload)
            if self.lit_bytes > MAX_LITERAL_BYTES:
                raise GrammarCompileError(
                    f"grammar literals exceed cap {MAX_LITERAL_BYTES} bytes"
                )
            start = cur = self.state()
            for b in payload:
                nxt = self.state()
                self.edges[cur].append((frozenset((b,)), nxt))
                cur = nxt
            return start, cur
        if kind == "cls":
            start, end = self.state(), self.state()
            self.edges[start].append((payload, end))
            return start, end
        if kind == "seq":
            start = cur = self.state()
            for sub in payload:
                s, e = self.build(sub)
                self.eps[cur].append(s)
                cur = e
            return start, cur
        if kind == "alt":
            start, end = self.state(), self.state()
            for sub in payload:
                s, e = self.build(sub)
                self.eps[start].append(s)
                self.eps[e].append(end)
            return start, end
        if kind == "star":
            start, end = self.state(), self.state()
            s, e = self.build(payload)
            self.eps[start] += [s, end]
            self.eps[e] += [s, end]
            return start, end
        if kind == "opt":
            start, end = self.state(), self.state()
            s, e = self.build(payload)
            self.eps[start] += [s, end]
            self.eps[e].append(end)
            return start, end
        if kind == "loop":  # item (sep item)*, single shared item fragment
            item, sep = payload
            s, e = self.build(item)
            cur = e
            for b in sep:
                nxt = self.state()
                self.edges[cur].append((frozenset((b,)), nxt))
                cur = nxt
            self.eps[cur].append(s)
            return s, e
        raise GrammarCompileError(f"bad AST node {kind!r}")


def _ast_to_dfa(ast) -> tuple[np.ndarray, np.ndarray]:
    """(table (S, 256) int32 with -1 = reject, accepting (S,) bool);
    state 0 is the start state. Dead-end states (no path to acceptance)
    are pruned so a mask never steers generation into a stuck state."""
    nfa = _NFA()
    start, end = nfa.build(ast)

    eps_closure_memo: dict[int, frozenset] = {}

    def closure(states) -> frozenset:
        seen = set()
        stack = list(states)
        while stack:
            s = stack.pop()
            if s in seen:
                continue
            seen.add(s)
            memo = eps_closure_memo.get(s)
            if memo is not None:
                seen |= memo
                continue
            stack.extend(nfa.eps[s])
        return frozenset(seen)

    start_set = closure((start,))
    index: dict[frozenset, int] = {start_set: 0}
    order = [start_set]
    rows: list[dict[int, int]] = []
    i = 0
    while i < len(order):
        current = order[i]
        i += 1
        moves: dict[int, set] = {}
        for s in current:
            for byteset, dst in nfa.edges[s]:
                for b in byteset:
                    moves.setdefault(b, set()).add(dst)
        row: dict[int, int] = {}
        for b, dsts in moves.items():
            nxt = closure(dsts)
            j = index.get(nxt)
            if j is None:
                if len(order) >= MAX_DFA_STATES:
                    raise GrammarCompileError(
                        f"grammar DFA exceeds cap {MAX_DFA_STATES} states"
                    )
                j = len(order)
                index[nxt] = j
                order.append(nxt)
            row[b] = j
        rows.append(row)

    n = len(order)
    table = np.full((n, 256), -1, dtype=np.int32)
    for s, row in enumerate(rows):
        for b, j in row.items():
            table[s, b] = j
    accepting = np.array([end in st for st in order], dtype=bool)

    # Prune byte transitions into states that cannot reach acceptance.
    live = accepting.copy()
    changed = True
    while changed:
        changed = False
        reach_live = (table >= 0) & live[np.maximum(table, 0)]
        new_live = live | reach_live.any(axis=1)
        if (new_live != live).any():
            live = new_live
            changed = True
    if not live[0]:
        raise GrammarCompileError("grammar matches no string")
    table[(table >= 0) & ~live[np.maximum(table, 0)]] = -1
    return _minimize(table, accepting)


def _minimize(table: np.ndarray, accepting: np.ndarray):
    """Moore partition refinement over the dense byte table. Subset
    construction leaves many behaviourally-identical states (shared
    sub-grammars reached through different contexts); merging them shrinks
    every downstream per-state table — including the padded device tables
    the decode window ships."""
    labels = accepting.astype(np.int64)
    n_classes = int(labels.max()) + 1
    while True:
        succ = np.where(table >= 0, labels[np.maximum(table, 0)], np.int64(-1))
        sig = np.concatenate([labels[:, None], succ], axis=1)
        _, labels = np.unique(sig, axis=0, return_inverse=True)
        labels = labels.reshape(-1)
        new_n = int(labels.max()) + 1
        if new_n == n_classes:
            break
        n_classes = new_n
    # Renumber so the start state's class is 0, then collapse.
    start_cls = int(labels[0])
    if start_cls != 0:
        perm = np.arange(n_classes)
        perm[start_cls], perm[0] = 0, start_cls
        labels = perm[labels]
    reps = np.zeros(n_classes, dtype=np.int64)
    seen = np.zeros(n_classes, dtype=bool)
    for s, c in enumerate(labels):
        if not seen[c]:
            seen[c] = True
            reps[c] = s
    rep_table = table[reps]
    min_table = np.where(
        rep_table >= 0, labels[np.maximum(rep_table, 0)], np.int64(-1)
    ).astype(np.int32)
    return min_table, accepting[reps]


# ---------------------------------------------------------------------------
# Token lifting + class compression.
# ---------------------------------------------------------------------------

_UID = itertools.count(1)


class TokenGrammar:
    """Compiled, immutable token-class automaton. Shared by every request
    using the same spec (via GrammarCache); per-request cursor state lives
    in GrammarState.

    Tables (all numpy, device-shippable as data):
      token_class (V,)  int32  vocab token -> behaviour class
      class_dest  (S,C) int32  destination state, -1 = not allowed
      accepting   (S,)  bool   EOS allowed here
    """

    def __init__(self, spec: dict, token_table: list[bytes], eos_token_id: int):
        t0 = time.perf_counter()
        self.spec = spec
        self.eos_token_id = int(eos_token_id)
        table, accepting = _ast_to_dfa(build_ast(spec))
        n_states = table.shape[0]
        vocab = len(token_table)
        if n_states * vocab > 64_000_000:
            raise GrammarCompileError(
                f"grammar too large to lift: {n_states} states x {vocab} tokens"
            )
        # Walk each distinct byte string once across ALL states at once.
        dest = np.full((n_states, vocab), -1, dtype=np.int32)
        by_bytes: dict[bytes, list[int]] = {}
        for tid, data in enumerate(token_table):
            if data and tid != self.eos_token_id:
                by_bytes.setdefault(bytes(data), []).append(tid)
        all_states = np.arange(n_states, dtype=np.int32)
        for data, tids in by_bytes.items():
            states = all_states
            for b in data:
                states = np.where(
                    states >= 0, table[np.maximum(states, 0), b], np.int32(-1)
                )
                if not (states >= 0).any():
                    break
            dest[:, tids] = states[:, None]
        class_dest, token_class = np.unique(dest, axis=1, return_inverse=True)
        self.token_class = np.ascontiguousarray(
            token_class.reshape(-1), dtype=np.int32
        )
        self.class_dest = np.ascontiguousarray(class_dest, dtype=np.int32)
        self.accepting = accepting
        self.n_states = n_states
        self.n_classes = self.class_dest.shape[1]
        self.vocab_size = vocab
        self.uid = next(_UID)
        self._mask_memo: dict[int, np.ndarray] = {}
        self._memo_lock = threading.Lock()
        # Token-level liveness: every reachable state must admit at least
        # one token (or EOS) — otherwise generation would wedge with an
        # all-masked step. Byte-DFA pruning above isn't enough when the
        # vocabulary can't spell a byte path.
        has_token = (self.class_dest >= 0).any(axis=1)
        reachable = np.zeros(n_states, dtype=bool)
        stack = [0]
        while stack:
            s = stack.pop()
            if reachable[s]:
                continue
            reachable[s] = True
            for d in self.class_dest[s]:
                if d >= 0 and not reachable[d]:
                    stack.append(int(d))
        stuck = reachable & ~has_token & ~accepting
        if stuck.any():
            raise GrammarCompileError(
                "vocabulary cannot spell this grammar "
                f"({int(stuck.sum())} reachable dead-end states)"
            )
        self.build_s = time.perf_counter() - t0

    def mask_for(self, state: int) -> np.ndarray:
        """(V,) bool allowed-token mask for `state` — memoized; treat as
        read-only. Pure table lookups: this is the per-step hot path."""
        mask = self._mask_memo.get(state)
        if mask is None:
            with self._memo_lock:
                mask = self._mask_memo.get(state)
                if mask is None:
                    mask = self.class_dest[state][self.token_class] >= 0
                    if self.accepting[state]:
                        mask = mask.copy()
                        mask[self.eos_token_id] = True
                    mask.setflags(write=False)
                    self._mask_memo[state] = mask
        return mask

    def advance(self, state: int, tid: int) -> int:
        """Destination state for consuming `tid`, or -1 if not allowed.
        EOS is never consumed (returns -1; check accepting instead)."""
        if not 0 <= tid < self.vocab_size or tid == self.eos_token_id:
            return -1
        return int(self.class_dest[state, self.token_class[tid]])

    def allows(self, state: int, tid: int) -> bool:
        if tid == self.eos_token_id:
            return bool(self.accepting[state])
        return self.advance(state, tid) >= 0

    def verify_masks(self, state: int, proposal, width: int) -> np.ndarray:
        """(width, V) bool masks for a verify dispatch feeding
        [current, *proposal]: row j constrains the token SAMPLED at fed
        position j, i.e. the mask of the state after proposal[:j]. Once a
        proposal token is itself invalid the remaining rows are all-True —
        harmless, because the masked verifier's argmax at the violating
        position necessarily mismatches the proposal, so acceptance cuts
        there and later positions are discarded (the PR 14 rollback)."""
        out = np.ones((width, self.vocab_size), dtype=bool)
        out[0] = self.mask_for(state)
        s = state
        for j, tok in enumerate(proposal):
            if j + 1 >= width:
                break
            s = self.advance(s, int(tok))
            if s < 0:
                break
            out[j + 1] = self.mask_for(s)
        return out


class GrammarState:
    """Per-request automaton cursor. Advanced ONLY on accepted tokens in
    scheduler.postprocess, so it needs no speculative rollback of its own:
    a discarded StepHandle simply never advanced it, and QoS preemption
    (which preserves output_token_ids) carries it across preempt/resume
    untouched. sync() is the defensive resynchronisation if the cursor
    ever disagrees with the accepted-output length."""

    __slots__ = ("grammar", "state", "consumed")

    def __init__(self, grammar: TokenGrammar):
        self.grammar = grammar
        self.state = 0
        self.consumed = 0

    @property
    def accepting(self) -> bool:
        return self.state >= 0 and bool(self.grammar.accepting[self.state])

    def mask(self) -> np.ndarray:
        if self.state < 0:  # dead: nothing is admissible
            return np.zeros(self.grammar.vocab_size, dtype=bool)
        return self.grammar.mask_for(self.state)

    def allows(self, tid: int) -> bool:
        return self.state >= 0 and self.grammar.allows(self.state, tid)

    def advance(self, tid: int) -> bool:
        """Consume one ACCEPTED output token. EOS is a terminator, not a
        grammar byte: it counts toward the cursor but leaves the state
        alone, so accepting-at-finish still reflects the body. An
        inadmissible token parks the cursor in the dead state (-1),
        mirroring the device automaton's dead sink — the cursor keeps
        counting so it stays aligned with output_token_ids."""
        self.consumed += 1
        if int(tid) == self.grammar.eos_token_id:
            return True
        if self.state >= 0:
            nxt = self.grammar.advance(self.state, int(tid))
            self.state = nxt if nxt >= 0 else -1
        return self.state >= 0

    def sync(self, output_token_ids) -> None:
        if self.consumed == len(output_token_ids):
            return
        self.state = 0
        self.consumed = 0
        for tid in output_token_ids:
            self.advance(int(tid))


class GrammarCache:
    """LRU of compiled TokenGrammars keyed by canonical spec JSON, owned
    by the engine (the only place that has both tokenizer and model vocab
    size). Records per-compile build times for the metrics histogram —
    drained by the engine's stats() like tenant queue waits."""

    def __init__(self, tokenizer, vocab_size: int, max_entries: int = 64):
        self._tokenizer = tokenizer
        self._vocab_size = int(vocab_size)
        self._max_entries = max_entries
        self._entries: OrderedDict[str, TokenGrammar] = OrderedDict()
        self._token_table: list[bytes] | None = None
        self._lock = threading.Lock()
        self._build_times: list[float] = []
        # single-flight: a swarm of concurrent first requests for one
        # spec must pay ONE build, not one per request
        self._building: dict[str, threading.Event] = {}

    def _tokens(self) -> list[bytes]:
        if self._token_table is None:
            tok = self._tokenizer
            specials = {
                getattr(tok, name, None)
                for name in ("bos_token_id", "eos_token_id", "pad_token_id")
            }
            repr_fn = getattr(tok, "token_repr", None)
            table = []
            for tid in range(self._vocab_size):
                if tid in specials:
                    table.append(b"")
                elif repr_fn is not None:
                    try:
                        table.append(repr_fn(tid)[1])
                    except Exception:
                        table.append(b"")
                elif tid < 256:  # bare ByteTokenizer: id IS the byte
                    table.append(bytes([tid]))
                else:
                    table.append(b"")
            self._token_table = table
        return self._token_table

    def get(self, spec: dict) -> tuple[TokenGrammar, bool]:
        """(grammar, was_cached). Raises GrammarCompileError on failure."""
        key = spec_key(spec)
        while True:
            with self._lock:
                hit = self._entries.get(key)
                if hit is not None:
                    self._entries.move_to_end(key)
                    return hit, True
                building = self._building.get(key)
                if building is None:
                    building = self._building[key] = threading.Event()
                    break
            # another thread is building this exact spec: wait for it,
            # then re-check (a hit counts as cached; a failed build makes
            # this thread the next builder and it surfaces its own error)
            building.wait()
        try:
            eos = getattr(self._tokenizer, "eos_token_id", None)
            if eos is None:
                raise GrammarCompileError("tokenizer has no EOS token")
            grammar = TokenGrammar(spec, self._tokens(), eos)
            with self._lock:
                self._entries[key] = grammar
                self._entries.move_to_end(key)
                while len(self._entries) > self._max_entries:
                    self._entries.popitem(last=False)
                self._build_times.append(grammar.build_s)
        finally:
            with self._lock:
                self._building.pop(key, None)
            building.set()
        return grammar, False

    def drain_build_times(self) -> list[float]:
        with self._lock:
            out = self._build_times
            self._build_times = []
        return out


# ---------------------------------------------------------------------------
# Request-surface helpers (jax-free; the router imports these).
# ---------------------------------------------------------------------------


def extract_spec(response_format, guided_json) -> dict | None:
    """Grammar spec from the OpenAI request surface, or None when the
    request is unconstrained. Raises GrammarCompileError on a malformed
    surface (callers map to 400/fallback per the structured_output mode).
    `guided_json` (vLLM extension) wins over `response_format`."""
    if guided_json is not None:
        schema = guided_json
        if isinstance(schema, str):
            try:
                schema = json.loads(schema)
            except (TypeError, ValueError) as exc:
                raise GrammarCompileError(f"guided_json is not valid JSON: {exc}")
        if not isinstance(schema, dict):
            raise GrammarCompileError("guided_json must be a JSON schema object")
        return {"kind": "json_schema", "schema": schema}
    if response_format is None:
        return None
    if not isinstance(response_format, dict):
        raise GrammarCompileError("response_format must be an object")
    rtype = response_format.get("type")
    if rtype in (None, "text"):
        return None
    if rtype == "json_object":
        return {"kind": "json_object"}
    if rtype == "json_schema":
        wrapper = response_format.get("json_schema")
        schema = wrapper.get("schema") if isinstance(wrapper, dict) else None
        if not isinstance(schema, dict):
            raise GrammarCompileError(
                "response_format.json_schema.schema must be a schema object"
            )
        return {"kind": "json_schema", "schema": schema}
    raise GrammarCompileError(f"unsupported response_format type {rtype!r}")


def tool_choice_spec(tools, tool_choice) -> dict | None:
    """Spec forcing a tool call when tool_choice is "required" or names a
    function; None when tool choice stays model-decided ("auto"/None)."""
    if not tools:
        return None
    if isinstance(tool_choice, dict):
        name = (tool_choice.get("function") or {}).get("name")
        chosen = [
            t for t in tools if (t.get("function") or t).get("name") == name
        ]
        if not chosen:
            raise GrammarCompileError(
                f"tool_choice names unknown function {name!r}"
            )
    elif tool_choice == "required":
        chosen = list(tools)
    else:
        return None
    norm = []
    for t in chosen:
        fn = t.get("function") or t
        norm.append(
            {"name": fn.get("name"), "parameters": fn.get("parameters") or None}
        )
    return {"kind": "tool_call", "tools": norm}


def validate_spec(spec: dict) -> None:
    """Structural validation WITHOUT a tokenizer: AST + NFA + byte-DFA
    with all caps enforced. The router's 400 path — catches unsupported
    constructs, depth/enum/state blowups, and impossible grammars, so a
    doomed request never reaches an engine."""
    _ast_to_dfa(build_ast(spec))


def schema_instance(schema, depth: int = 0):
    """A minimal instance satisfying `schema` (best effort) — what
    testing/fake_engine.py echoes for response_format requests."""
    if depth > MAX_SCHEMA_DEPTH or not isinstance(schema, dict):
        return {}
    if "const" in schema:
        return schema["const"]
    if isinstance(schema.get("enum"), list) and schema["enum"]:
        return schema["enum"][0]
    for union_key in ("anyOf", "oneOf"):
        if isinstance(schema.get(union_key), list) and schema[union_key]:
            return schema_instance(schema[union_key][0], depth + 1)
    stype = schema.get("type")
    if isinstance(stype, list) and stype:
        stype = stype[0]
    if stype is None and "properties" in schema:
        stype = "object"
    if stype == "object":
        props = schema.get("properties") or {}
        return {
            k: schema_instance(v, depth + 1) for k, v in props.items()
        }
    if stype == "array":
        if int(schema.get("minItems", 0)) > 0:
            return [schema_instance(schema.get("items") or {}, depth + 1)]
        return []
    if stype == "string":
        return "x"
    if stype == "integer":
        return 1
    if stype == "number":
        return 1
    if stype == "boolean":
        return True
    if stype == "null":
        return None
    return {}
