"""N-gram (prompt-lookup) speculative decoding.

The reference stack's engines inherit vLLM's `--speculative-config
{"method": "ngram", ...}`: propose the next k tokens by matching the tail
of the sequence against its own history, then VERIFY all k in one model
step — the model's argmax at each proposed position either confirms the
proposal token or replaces it, so one dispatch yields 1..k+1 tokens
instead of 1. Greedy-only (verification of sampled tokens needs rejection
sampling; vLLM's ngram path is typically used the same way).

TPU shape of the idea: verification is exactly a chunked-prefill step with
argmax at EVERY position (models/llama.py:forward over the paged pool —
static (batch, k+1) shapes, no new kernel), and a row with no n-gram match
simply proposes nothing and gets its 1 bonus token — so the verify program
SUBSUMES plain decode for greedy rows and the scheduler can route all of
them through it.
"""

from __future__ import annotations

MAX_NGRAM = 4
# history window the proposer searches: bounds the per-step host cost at
# long context (this runs in the scheduler loop for every greedy row every
# decode step; vLLM's ngram speculator has the same knob)
MAX_LOOKBACK = 1024


def propose_ngram(
    tokens: list[int],
    k: int,
    min_ngram: int = 2,
    max_ngram: int = MAX_NGRAM,
    max_lookback: int = MAX_LOOKBACK,
) -> list[int] | None:
    """Propose up to k continuation tokens by matching the sequence's tail
    n-gram against its recent history (longest n first, most recent match
    wins). Returns None when no n-gram of length >= min_ngram recurs in the
    lookback window."""
    if k <= 0 or len(tokens) < min_ngram + 1:
        return None
    lo = max(0, len(tokens) - max_lookback)
    window = tokens[lo:]
    for n in range(min(max_ngram, len(window) - 1), min_ngram - 1, -1):
        tail = window[-n:]
        first = tail[0]
        # scan right-to-left over history (exclude the tail match itself)
        for start in range(len(window) - n - 1, -1, -1):
            # cheap first-element pre-check before the slice+compare
            if window[start] != first or window[start : start + n] != tail:
                continue
            cont = window[start + n : start + n + k]
            if cont:
                return cont
    return None
