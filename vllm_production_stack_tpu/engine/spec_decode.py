"""Speculative decoding proposers: n-gram prompt lookup and a draft model.

The reference stack's engines inherit vLLM's `--speculative-config
{"method": "ngram", ...}`: propose the next k tokens by matching the tail
of the sequence against its own history, then VERIFY all k in one model
step — the model's argmax at each proposed position either confirms the
proposal token or replaces it, so one dispatch yields 1..k+1 tokens
instead of 1. Greedy-only (verification of sampled tokens needs rejection
sampling; vLLM's ngram path is typically used the same way).

TPU shape of the idea: verification is exactly a chunked-prefill step with
argmax at EVERY position (models/llama.py:forward over the paged pool —
static (batch, k+1) shapes, no new kernel), and a row with no proposal
simply proposes nothing and gets its 1 bonus token — so the verify program
SUBSUMES plain decode for greedy rows and the scheduler can route all of
them through it.

`--speculative-config draft --draft-model <name>` (docs/36-speculative-
decoding.md) graduates past prompt lookup: DraftModelProposer runs a small
model from the models/ registry autoregressively for the k proposals. The
draft shares the target's paged KVBlockPool through a SCRATCH block-table
namespace (kv_cache.allocate_scratch): same allocator and byte budget, its
own device pages, and never content-addressed — a draft block can never
satisfy a prefix match, peer lookup, or KV export. N-gram stays the
zero-weight fallback for rows the draft declines (pool pressure).
"""

from __future__ import annotations

MAX_NGRAM = 4
# history window the proposer searches: bounds the per-step host cost at
# long context (this runs in the scheduler loop for every greedy row every
# decode step; vLLM's ngram speculator has the same knob)
MAX_LOOKBACK = 1024


def propose_ngram(
    tokens: list[int],
    k: int,
    min_ngram: int = 2,
    max_ngram: int = MAX_NGRAM,
    max_lookback: int = MAX_LOOKBACK,
) -> list[int] | None:
    """Propose up to k continuation tokens by matching the sequence's tail
    n-gram against its recent history (longest n first, most recent match
    wins). Returns None when no n-gram of length >= min_ngram recurs in the
    lookback window.

    When the continuation runs out of history before k tokens — the match
    sits right behind the tail, i.e. the sequence looks PERIODIC with the
    match-to-tail distance as its period — the proposal extrapolates by
    tiling that period. A cyclic decode (the workload n-gram speculation
    exists for) would otherwise cap every proposal at one period, no
    matter how large k is; a wrong extrapolation costs nothing beyond the
    normal verify rejection."""
    if k <= 0 or len(tokens) < min_ngram + 1:
        return None
    lo = max(0, len(tokens) - max_lookback)
    window = tokens[lo:]
    for n in range(min(max_ngram, len(window) - 1), min_ngram - 1, -1):
        tail = window[-n:]
        first = tail[0]
        # scan right-to-left over history (exclude the tail match itself)
        for start in range(len(window) - n - 1, -1, -1):
            # cheap first-element pre-check before the slice+compare
            if window[start] != first or window[start : start + n] != tail:
                continue
            cont = window[start + n : start + n + k]
            if not cont:
                continue
            if len(cont) < k:
                # periodic extrapolation: the hypothesis behind the match
                # is "the sequence repeats with period = match-to-end
                # distance" — keep tiling it past the history's edge
                period = len(window) - (start + n)
                base = window[start + n :]
                cont = [base[j % period] for j in range(k)]
            return cont
    return None


class _DraftState:
    """Per-request draft-model KV state: the scratch blocks holding the
    draft's paged KV for this request, and how many leading positions hold
    KV of TRUE (accepted) tokens. Positions at or beyond `valid` may hold
    stale speculative writes; the next catch-up feed overwrites them in
    place (slot = position via the block table), and attention never reads
    past the fed context length."""

    __slots__ = ("block_table", "valid", "shadow")

    def __init__(self, shadow):
        self.block_table: list[int] = []
        self.valid = 0
        self.shadow = shadow  # runner-facing Request double


class DraftModelProposer:
    """Autoregressive draft-model proposer sharing the target's paged pool.

    One small ModelRunner (the draft) proposes k tokens per eligible row:
    a batched catch-up feed pushes every accepted-but-unfed token through
    the draft (a prefill-shaped dispatch, sampling the first draft token at
    the tail), then ONE fused decode window of k-1 steps drafts the rest —
    two draft dispatches per proposal round for the whole batch, padded
    through the runner's existing bucket ladder + pad-up program cache so
    draft-batch shapes never retrigger compilation on the hot path.

    Pool discipline: scratch blocks come from the shared KVBlockPool
    (allocate_scratch — never registered, never matchable) and are refused
    rather than fought over: a row whose allocation would squeeze the pool
    below `min_free_reserve` skips drafting this round (the scheduler falls
    back to n-gram), so the draft can never preempt target requests."""

    name = "draft"

    def __init__(
        self, runner, pool, max_model_len: int, min_free_reserve: int = 8
    ):
        self.runner = runner  # the DRAFT ModelRunner
        self.pool = pool  # the SHARED KVBlockPool
        self.block_size = pool.block_size
        self.max_model_len = max_model_len
        self.min_free_reserve = min_free_reserve
        self._states: dict[str, _DraftState] = {}
        sched = runner.config.scheduler
        self._chunk_cap = max(sched.prefill_buckets)
        # observability: rows that fell back to n-gram on pool pressure
        # (surfaced on /debug/timing's spec section)
        self.declined_rows = 0
        # proposal memo: the scheduler's verify/decode alternation can
        # discard a whole propose_batch after the draft already ran (the
        # plain group won the turn) — the next schedule() re-asks with the
        # request state unchanged, so the answer is reusable. Keyed on
        # (true length, spec tail): a request's true sequence is
        # append-only, so equal length + equal tail == equal sequence.
        # Dropped with the state on release().
        self._memo: dict[str, tuple[tuple, list[int]]] = {}

    def _state(self, req) -> _DraftState:
        st = self._states.get(req.request_id)
        if st is None:
            from .request import Request, SamplingParams

            shadow = Request(
                request_id=f"draft:{req.request_id}",
                prompt_token_ids=[],
                sampling=SamplingParams(
                    max_tokens=1 << 30, temperature=0.0, ignore_eos=True
                ),
            )
            st = _DraftState(shadow)
            self._states[req.request_id] = st
        return st

    def release(self, request_id: str) -> None:
        """Free a request's draft scratch blocks (finish/preempt/abort)."""
        self._memo.pop(request_id, None)
        st = self._states.pop(request_id, None)
        if st is not None:
            for blk in reversed(st.block_table):
                self.pool.free_scratch(blk)

    def propose_batch(
        self, reqs: list, k: int, spec_tails: dict | None = None
    ) -> dict[str, list[int]]:
        """Draft up to k tokens for each request. Rows the draft declines
        (pool pressure, position past max_model_len) are absent from the
        returned map — the scheduler's n-gram fallback covers them.
        Deterministic per (request sequence): greedy drafting, so the
        serial and pipelined loops see identical proposals.

        `spec_tails[rid]` (pipelined verify-on-verify, docs/36) appends a
        row's in-flight verify proposals to its sequence: the draft feeds
        through them — host-known values under the full-acceptance
        speculation — and the returned proposal DROPS its first drafted
        token, whose position the in-flight bonus token (device-chained by
        the runner) covers. The tail's KV is speculative, so `valid` stays
        at the TRUE length and the next catch-up overwrites it in place."""
        if k <= 0 or not reqs:
            return {}
        from .scheduler import DecodeWork, PrefillWork

        spec_tails = spec_tails or {}
        bs = self.block_size
        rows: list[tuple] = []  # (req, st, seq, true_len)
        memo_hits: dict[str, list[int]] = {}
        for req in reqs:
            true_seq = req.all_token_ids
            tail = tuple(spec_tails.get(req.request_id, ()))
            seq = true_seq + list(tail)
            # the draft writes KV for positions < len(seq) + k and the
            # verify feed itself must stay inside the model length
            if len(seq) + k >= self.max_model_len:
                continue
            memo = self._memo.get(req.request_id)
            if memo is not None and memo[0] == (len(true_seq), tail, k):
                # the alternation discarded this exact proposal last
                # schedule() — reuse it, no draft dispatch
                memo_hits[req.request_id] = list(memo[1])
                continue
            st = self._state(req)
            need = -(-(len(seq) + k) // bs)
            grow = need - len(st.block_table)
            if grow > 0:
                if self.pool.num_free - grow < self.min_free_reserve:
                    self.declined_rows += 1
                    continue
                ok = True
                while len(st.block_table) < need:
                    blk = self.pool.allocate_scratch()
                    if blk is None:
                        ok = False
                        break
                    st.block_table.append(blk)
                if not ok:
                    self.declined_rows += 1
                    continue  # keep what we got; next round may free up
            st.shadow.block_table = st.block_table
            rows.append((req, st, seq, len(true_seq)))
        if not rows:
            return memo_hits
        # -- batched catch-up: feed every not-yet-valid true token ---------
        # (first proposal: the whole prompt; steady state: the tokens the
        # last verify accepted). Chunked at the draft's largest prefill
        # bucket; only the FINAL chunk of a row samples (its tail logits
        # are the first draft token).
        first: dict[str, int] = {}
        # every row re-feeds at least its current tail token (a re-propose
        # after a dropped verify row has nothing new to feed, but still
        # needs the tail logits sampled; rewriting one position's KV with
        # the same token is a no-op)
        pending = {
            id(st): min(st.valid, len(seq) - 1) for _, st, seq, _ in rows
        }
        while True:
            work = PrefillWork()
            for req, st, seq, _ in rows:
                start = pending[id(st)]
                if start >= len(seq):
                    continue
                end = min(len(seq), start + self._chunk_cap)
                idxs = range(start, end)
                work.add_row(
                    request=st.shadow,
                    token_ids=[seq[i] for i in idxs],
                    positions=list(idxs),
                    slot_mapping=[],
                    context_len=end,
                    sample=end == len(seq),
                )
                pending[id(st)] = end
            if not work.requests:
                break
            sampled = self.runner.execute(work)
            for i, shadow in enumerate(work.requests):
                if work.sample[i]:
                    rid = shadow.request_id[len("draft:"):]
                    first[rid] = int(sampled[i][0])
        full: dict[str, list[int]] = {}
        for req, st, seq, true_len in rows:
            # spec-tail positions (>= true_len) hold unconfirmed KV — the
            # next round's catch-up re-feeds them with whatever the verify
            # actually accepted, overwriting in place
            st.valid = true_len
            full[req.request_id] = [first[req.request_id]]
        # -- one fused decode window drafts the remaining tokens -----------
        # (window k so tailed rows still return k proposals after dropping
        # their first draft — untailed rows just ignore the extra token)
        dec = DecodeWork(
            requests=[st.shadow for _, st, _, _ in rows],
            window=k,
            token_ids=[full[r.request_id][0] for r, _, _, _ in rows],
            positions=[len(seq) for _, _, seq, _ in rows],
        )
        tail = self.runner.execute(dec)
        for i, (req, _, _, _) in enumerate(rows):
            full[req.request_id].extend(int(t) for t in tail[i])
        out: dict[str, list[int]] = dict(memo_hits)
        for req, _, _, true_len in rows:
            rid = req.request_id
            drafted = full[rid]
            # tailed rows: drafted[0] predicts the in-flight bonus position
            # (covered by the device-chained first fed token) — drop it
            p = drafted[1 : k + 1] if rid in spec_tails else drafted[:k]
            out[rid] = p
            self._memo[rid] = (
                (true_len, tuple(spec_tails.get(rid, ())), k), list(p)
            )
        return out
