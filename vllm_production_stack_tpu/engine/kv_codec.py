"""At-rest KV codec: int4 + per-group scales / fp8 passthrough.

ROADMAP item 4's byte-path lever: KV blocks leave the HBM pool at pool
precision today, so every cold-tier hop — disk file, remote kvstore PUT,
peer `/kv/peer_fetch` — moves full-width bytes, and the hydration planner
prices those transfers on every compute-or-load crossover. RTP-LLM
(PAPERS.md) ships quantized KV end-to-end for exactly this reason: offload
tiers are bandwidth-bound, so shrinking bytes ~3.5-4x shifts planner
crossovers toward load and multiplies effective tier capacity.

Two codecs, chosen per deployment (`--kv-at-rest-codec`):

- **int4**: symmetric per-group quantization over the flattened block.
  Each group of `group_size` elements stores one float16 scale
  (max|x|/7) and packed 4-bit signed codes (two per byte). At the
  default group of 32 against a 2-byte pool element the wire ratio is
  2 / (0.5 + 2/32) = ~3.55x. Error is bounded per element by scale/2.
- **fp8**: cast to float8_e4m3fn (2x vs bf16 pools; a free passthrough
  when the pool itself is fp8). Cheaper to encode/decode than int4 —
  the middle setting.

Encoding happens when a block leaves the pool for an at-rest tier
(disk store, remote writer, peer serving; optionally the host ring).
Decoding happens at the compute boundary — `pool.adopt_planned_run` /
`pool._match_remote` dequantize `EncodedKVBlock`s right before the
device upload — so fetch threads move and land WIRE bytes, never
logical ones.

The codec is part of the engine's model fingerprint
(`engine.py` fingerprint tuple): engines with different at-rest codecs
produce incompatible at-rest bytes, and the fingerprint namespace is
what keeps a mixed-precision fleet from adopting bytes it would corrupt.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

KV_AT_REST_CODECS = ("none", "fp8", "int4")

# int4 per-group scales travel as float16: 2 bytes per group, enough
# dynamic range for KV activations and half the overhead of float32
_SCALE_DTYPE = np.float16
_SCALE_ITEMSIZE = 2


def np_dtype_from_name(name: str) -> np.dtype:
    """Resolve a dtype NAME from the wire (frame headers, kvstore meta)
    to a numpy dtype — including the ml_dtypes names (bfloat16,
    float8_e4m3fn) jax pools use. A name this host cannot resolve raises
    KVDtypeError (a ValueError): every consumer of tier bytes treats a
    parse failure as a degraded MISS, so an fp8-tagged frame landing on
    a host without ml_dtypes becomes a clean cache miss instead of an
    unhandled TypeError on the step thread."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes  # bfloat16 / float8_e4m3fn (jax dep)
    except ImportError as e:
        raise KVDtypeError(
            f"KV frame dtype {name!r} needs ml_dtypes, which is not "
            f"importable on this host — degrading to a tier miss"
        ) from e
    try:
        return np.dtype(getattr(ml_dtypes, name))
    except (AttributeError, TypeError) as e:
        raise KVDtypeError(
            f"KV frame carries unknown dtype {name!r} (not a numpy or "
            f"ml_dtypes name) — degrading to a tier miss"
        ) from e


class KVDtypeError(ValueError):
    """A tier frame's dtype/codec tag cannot be decoded on this host.

    Subclasses ValueError so every existing degrade-to-miss handler
    (disk load's broad except, FrameParser.feed_partial's dead-parser
    error, kv_import's 400 path) already catches it — the point is the
    MESSAGE names the dtype and the remedy instead of surfacing a bare
    TypeError from np.dtype()."""


@dataclass(frozen=True)
class EncodedKVBlock:
    """One KV block in at-rest form: wire payload + enough metadata to
    reconstruct the logical array. Travels through tier plumbing (disk
    files, kvstore bodies, peer frames, hydration chunk landings) in
    place of the logical ndarray — RAM and wire cost is `nbytes`, not
    `logical_nbytes` — and is decoded at the adopt boundary."""

    codec: str            # "int4" | "fp8"
    group: int            # int4 group size (0 for fp8)
    dtype: str            # LOGICAL element dtype name (e.g. "bfloat16")
    shape: tuple          # LOGICAL shape
    payload: bytes        # scales || packed codes (int4) / fp8 bytes
    scale_nbytes: int     # leading payload bytes that are scales

    @property
    def nbytes(self) -> int:
        """Wire bytes — what this block costs to store or move."""
        return len(self.payload)

    @property
    def logical_nbytes(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n * np_dtype_from_name(self.dtype).itemsize


def _encode_int4(arr: np.ndarray, group: int) -> tuple[bytes, int]:
    """(payload, scale_nbytes): float16 per-group scales followed by
    packed nibbles. The block flattens to 1-D; the last group may be
    ragged (padded with zeros for the pack, truncated on decode)."""
    flat = np.ascontiguousarray(arr).astype(np.float32).reshape(-1)
    ngroups = -(-flat.size // group)
    pad = ngroups * group - flat.size
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    grouped = flat.reshape(ngroups, group)
    amax = np.max(np.abs(grouped), axis=1)
    scale = np.maximum(amax, 1e-8) / 7.0
    q = np.clip(np.rint(grouped / scale[:, None]), -7, 7).astype(np.int8)
    nib = (q.reshape(-1) + 8).astype(np.uint8)  # 1..15, unsigned for pack
    if nib.size % 2:
        nib = np.append(nib, np.uint8(8))  # dead nibble (code 0)
    packed = (nib[0::2] << 4) | nib[1::2]
    scales = scale.astype(_SCALE_DTYPE)
    return scales.tobytes() + packed.tobytes(), scales.nbytes


def _decode_int4(
    payload: bytes, scale_nbytes: int, group: int,
    dtype: str, shape: tuple,
) -> np.ndarray:
    scales = np.frombuffer(payload[:scale_nbytes], dtype=_SCALE_DTYPE)
    packed = np.frombuffer(payload[scale_nbytes:], dtype=np.uint8)
    nib = np.empty(packed.size * 2, dtype=np.uint8)
    nib[0::2] = packed >> 4
    nib[1::2] = packed & 0x0F
    q = nib.astype(np.int8) - 8
    n = 1
    for d in shape:
        n *= int(d)
    ngroups = len(scales)
    total = ngroups * group
    if q.size < total or total < n:
        raise ValueError(
            f"int4 payload holds {q.size} codes for {ngroups} groups of "
            f"{group} covering {n} elements — corrupt at-rest block"
        )
    vals = (
        q[:total].astype(np.float32).reshape(ngroups, group)
        * scales.astype(np.float32)[:, None]
    ).reshape(-1)[:n]
    return vals.astype(np_dtype_from_name(dtype)).reshape(shape)


def _encode_fp8(arr: np.ndarray) -> bytes:
    import ml_dtypes

    return (
        np.ascontiguousarray(arr)
        .astype(ml_dtypes.float8_e4m3fn)
        .tobytes()
    )


def _decode_fp8(payload: bytes, dtype: str, shape: tuple) -> np.ndarray:
    fp8 = np_dtype_from_name("float8_e4m3fn")
    arr = np.frombuffer(payload, dtype=fp8)
    return arr.astype(np_dtype_from_name(dtype)).reshape(shape)


def decode_payload(
    codec: str, group: int, dtype: str, shape, payload: bytes,
    scale_nbytes: int = 0,
) -> np.ndarray:
    """Decode wire payload bytes back to the logical array — the shared
    primitive behind FrameParser (frames tagged with codec metadata) and
    decode_block. Any host can decode any codec; only np_dtype_from_name
    can fail (KVDtypeError → degraded miss)."""
    shape = tuple(int(d) for d in shape)
    if codec == "int4":
        return _decode_int4(payload, scale_nbytes, group, dtype, shape)
    if codec == "fp8":
        return _decode_fp8(payload, dtype, shape)
    raise KVDtypeError(
        f"KV frame carries unknown at-rest codec {codec!r} "
        f"(known: {KV_AT_REST_CODECS[1:]}) — degrading to a tier miss"
    )


def decode_block(obj) -> np.ndarray:
    """Logical array out of an at-rest object: EncodedKVBlock → decode,
    ndarray → passthrough. The adopt-boundary call."""
    if isinstance(obj, EncodedKVBlock):
        return decode_payload(
            obj.codec, obj.group, obj.dtype, obj.shape, obj.payload,
            obj.scale_nbytes,
        )
    return obj


def logical_shape(obj) -> tuple:
    """Geometry of the DECODED block — what pool shape validation must
    compare against, whether the tier handed back wire or logical form."""
    if isinstance(obj, EncodedKVBlock):
        return tuple(obj.shape)
    return tuple(np.shape(obj))


def wire_nbytes(obj) -> int:
    return obj.nbytes


def logical_nbytes(obj) -> int:
    if isinstance(obj, EncodedKVBlock):
        return obj.logical_nbytes
    return obj.nbytes


class KVAtRestCodec:
    """The per-engine at-rest codec, built once from CacheConfig and
    handed to every tier that writes pool bytes out (disk store, remote
    writer, peer serving, host ring when enabled)."""

    def __init__(self, kind: str = "none", group_size: int = 32):
        if kind not in KV_AT_REST_CODECS:
            raise ValueError(
                f"unknown kv_at_rest_codec {kind!r} "
                f"(choices: {KV_AT_REST_CODECS})"
            )
        if kind == "int4" and group_size < 1:
            raise ValueError(
                f"kv_at_rest_group_size must be >= 1, got {group_size}"
            )
        self.kind = kind
        self.group = int(group_size) if kind == "int4" else 0

    @classmethod
    def from_config(cls, cache_cfg) -> "KVAtRestCodec":
        return cls(
            getattr(cache_cfg, "kv_at_rest_codec", "none"),
            getattr(cache_cfg, "kv_at_rest_group_size", 32),
        )

    @property
    def enabled(self) -> bool:
        return self.kind != "none"

    @property
    def spec(self) -> str:
        """Fingerprint component: engines whose at-rest bytes are not
        interchangeable MUST produce different specs (group size changes
        the scale layout, so it is part of the spec)."""
        if self.kind == "int4":
            return f"int4g{self.group}"
        return self.kind

    def encode(self, arr: np.ndarray):
        """ndarray → EncodedKVBlock (or passthrough when disabled)."""
        if self.kind == "none":
            return arr
        shape = tuple(int(d) for d in arr.shape)
        if self.kind == "int4":
            payload, scale_nbytes = _encode_int4(arr, self.group)
            return EncodedKVBlock(
                "int4", self.group, arr.dtype.name, shape, payload,
                scale_nbytes,
            )
        return EncodedKVBlock(
            "fp8", 0, arr.dtype.name, shape, _encode_fp8(arr), 0
        )

    def wire_ratio(self, dtype_name: str) -> float:
        """Analytic logical/wire compression ratio for a pool element
        dtype — the hydration planner and kv_bytes_per_token price
        transfers with this BEFORE any block has moved (measured ratios
        then show up in the tpu:kv_tier_compression_ratio gauge)."""
        itemsize = np_dtype_from_name(dtype_name).itemsize
        if self.kind == "fp8":
            return float(itemsize)  # 1 byte/elem at rest
        if self.kind == "int4":
            # 0.5 byte/elem of codes + one 2-byte scale per group
            return itemsize / (0.5 + _SCALE_ITEMSIZE / self.group)
        return 1.0

    def wire_block_bytes(self, logical_bytes: int, dtype_name: str) -> int:
        return max(1, round(logical_bytes / self.wire_ratio(dtype_name)))


NO_CODEC = KVAtRestCodec("none")
