"""Batched on-device sampling: greedy / temperature / top-k / top-p.

Fixed-shape and jit-fused: the sampled token ids are the only per-step
device→host transfer. Within the sampling pipeline, per-row variation uses
where-masks (no Python control flow), but the pipeline as a whole sits
behind ONE runtime lax.cond — an all-greedy batch (the serving default)
skips the (B, V) sort + gumbel draw entirely, which at 128K vocab would
otherwise dwarf the decode step's own FLOPs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _row_keys(
    base_key: jax.Array,
    seeds: jax.Array,  # (B,) uint32, meaningful where has_seed
    has_seed: jax.Array,  # (B,) bool
    counts: jax.Array,  # (B,) int32 tokens generated so far by that request
) -> jax.Array:
    """Per-row PRNG keys. Seeded rows depend ONLY on (seed, count) so a
    request with an explicit seed reproduces its sample stream regardless of
    batching, preemption, or engine uptime; unseeded rows derive from the
    advancing step key."""

    def one(seed, has, count, row):
        seeded = jax.random.fold_in(jax.random.PRNGKey(seed), count)
        unseeded = jax.random.fold_in(base_key, row)
        return jnp.where(has, seeded, unseeded)

    b = seeds.shape[0]
    return jax.vmap(one)(seeds, has_seed, counts, jnp.arange(b, dtype=jnp.int32))


def sample(
    logits: jax.Array,  # (B, V) float32
    temperature: jax.Array,  # (B,) 0.0 = greedy
    top_p: jax.Array,  # (B,) 1.0 = disabled
    top_k: jax.Array,  # (B,) int32, 0 = disabled
    base_key: jax.Array,
    seeds: jax.Array,  # (B,) int32
    has_seed: jax.Array,  # (B,) bool
    counts: jax.Array,  # (B,) int32
) -> jax.Array:
    """Returns sampled token ids (B,) int32."""
    b, v = logits.shape
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled(_):
        scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
        sorted_desc = -jnp.sort(-scaled, axis=-1)  # (B, V) descending

        # top-k threshold: the k-th largest logit (k=0 -> keep all)
        k = jnp.where(top_k > 0, top_k, v).astype(jnp.int32)
        kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)

        # top-p threshold: smallest logit whose *exclusive* cumulative
        # prob < p
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum_excl = jnp.cumsum(probs, axis=-1) - probs
        keep = cum_excl < top_p[:, None]
        num_keep = jnp.maximum(jnp.sum(keep, axis=-1), 1)
        pth = jnp.take_along_axis(sorted_desc, (num_keep - 1)[:, None], axis=-1)

        thresh = jnp.maximum(kth, pth)
        masked = jnp.where(scaled >= thresh, scaled, NEG_INF)

        keys = _row_keys(base_key, seeds, has_seed, counts)
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (v,), jnp.float32)
        )(keys)
        return jnp.argmax(masked + gumbel, axis=-1).astype(jnp.int32)

    # the sampling pipeline sorts (B, V) and draws (B, V) gumbel noise per
    # step — for a 128K vocab that dwarfs the model's own decode FLOPs. An
    # all-greedy batch (the common serving default) skips it entirely at
    # runtime via cond; mixed batches pay it once for the whole batch
    sampled_tok = jax.lax.cond(
        jnp.any(temperature != 0.0), sampled, lambda _: greedy_tok, None
    )
    return jnp.where(temperature == 0.0, greedy_tok, sampled_tok)
