"""Batched on-device sampling: greedy / temperature / top-k / top-p.

Fixed-shape and jit-fused: the sampled token ids are the only per-step
device→host transfer. Within the sampling pipeline, per-row variation uses
where-masks (no Python control flow); top-k/top-p thresholds come from a
binary search over the logit value domain (~30 cheap VPU reductions — a
full (B, V) sort at 128K vocab would dwarf the decode step's own FLOPs).
The pipeline as a whole sits behind ONE runtime lax.cond so an all-greedy
batch (the serving default) skips even that.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# stop ids suppressible on device per row: eos + up to 7 stop_token_ids
# (OpenAI allows 4 stop sequences; longer internal lists keep correct
# TERMINATION via the scheduler's host-side predicate, they just lose the
# vLLM-style guarantee that the token never appears below min_tokens)
SUPPRESS_IDS = 8


# suppression value: low enough that exp() underflows to exactly 0 after
# the softmax shift (real logits live within ~±100), but NOT -1e30 — the
# top-k/top-p thresholds come from a binary search over [min(logits),
# max(logits)] (sample() below), and a 1e30-wide range leaves the 30
# halvings with ~1e21 resolution, silently disabling truncation for the
# whole row
SUPPRESS_NEG = -1e5


def suppress_stop_tokens(
    logits: jax.Array,  # (B, V) float32
    counts: jax.Array,  # (B,) output tokens BEFORE this sample
    min_toks: jax.Array,  # (B,) min_tokens per row
    stop_ids: jax.Array,  # (B, SUPPRESS_IDS) int32, -1 = unused slot
) -> jax.Array:
    """vLLM min_tokens semantics: below min_tokens the eos/stop tokens are
    masked out of the distribution entirely — never sampled, never fed back
    as context, never reported in logprobs."""
    v = logits.shape[-1]
    # out-of-range ids are inert (they used to be harmless host-side
    # comparisons; clipping one onto token V-1 would suppress a real token)
    suppress = (
        (counts < min_toks)[:, None] & (stop_ids >= 0) & (stop_ids < v)
    )  # (B, K)
    ids = jnp.clip(stop_ids, 0, v - 1)
    cur = jnp.take_along_axis(logits, ids, axis=1)
    new = jnp.where(suppress, SUPPRESS_NEG, cur)
    b = logits.shape[0]
    # scatter-min: padding slots clip onto real ids, so duplicate-index
    # writes happen — min() is order-independent (set() is not) and
    # unsuppressed slots write back their own value
    return logits.at[jnp.arange(b)[:, None], ids].min(new)


def apply_grammar_mask(
    logits: jax.Array,  # (B, V) float32
    allowed: jax.Array,  # (B, V) bool — True = token admissible here
) -> jax.Array:
    """Grammar-constrained decoding (docs/41-structured-output.md): zero
    out the disallowed mass. Same SUPPRESS_NEG rationale as above — the
    top-k/top-p binary search needs the masked logits to stay inside a
    searchable range. The mask is DATA, not shape: an all-True row is the
    identity, so unconstrained rows ride a grammar-enabled program
    unchanged and program cache keys never depend on mask contents."""
    return jnp.where(allowed, logits, SUPPRESS_NEG)


def _row_keys(
    base_key: jax.Array,
    seeds: jax.Array,  # (B,) uint32, meaningful where has_seed
    has_seed: jax.Array,  # (B,) bool
    counts: jax.Array,  # (B,) int32 tokens generated so far by that request
) -> jax.Array:
    """Per-row PRNG keys. Seeded rows depend ONLY on (seed, count) so a
    request with an explicit seed reproduces its sample stream regardless of
    batching, preemption, or engine uptime; unseeded rows derive from the
    advancing step key."""

    def one(seed, has, count, row):
        seeded = jax.random.fold_in(jax.random.PRNGKey(seed), count)
        unseeded = jax.random.fold_in(base_key, row)
        return jnp.where(has, seeded, unseeded)

    b = seeds.shape[0]
    return jax.vmap(one)(seeds, has_seed, counts, jnp.arange(b, dtype=jnp.int32))


def greedy_argmax(logits: jax.Array) -> jax.Array:
    """THE greedy pick (ties break lowest-id, jnp.argmax semantics) —
    shared by sample()'s temperature-0 branch and the speculative-verify
    program (model_runner._build_verify_fn), so a verified greedy token can
    never diverge from what a plain decode window would have sampled (the
    bitwise serial↔pipelined↔speculative equivalence bar rests on it)."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample(
    logits: jax.Array,  # (B, V) float32
    temperature: jax.Array,  # (B,) 0.0 = greedy
    top_p: jax.Array,  # (B,) 1.0 = disabled
    top_k: jax.Array,  # (B,) int32, 0 = disabled
    base_key: jax.Array,
    seeds: jax.Array,  # (B,) int32
    has_seed: jax.Array,  # (B,) bool
    counts: jax.Array,  # (B,) int32
) -> jax.Array:
    """Returns sampled token ids (B,) int32."""
    b, v = logits.shape
    greedy_tok = greedy_argmax(logits)

    def sampled(_):
        scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

        # Thresholds by BINARY SEARCH over the logit value domain instead of
        # a full (B, V) sort: a 128K-vocab sort per decode step dwarfs the
        # model's own FLOPs on TPU, while ~30 masked reductions are cheap
        # VPU sweeps. Masks use `scaled >= threshold`, so value ties are
        # included exactly like the sorted-kth-value formulation.
        lo0 = jnp.min(scaled, axis=-1)  # (B,)
        hi0 = jnp.max(scaled, axis=-1)

        def search(pred_ge):
            """Largest t (per row, to f32 precision) with pred_ge(t) True,
            where pred_ge is monotone decreasing in t. Returns (B,)."""

            def body(_, carry):
                lo, hi = carry
                mid = 0.5 * (lo + hi)
                ok = pred_ge(mid)
                return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

            lo, hi = jax.lax.fori_loop(0, 30, body, (lo0, hi0))
            return lo

        # top-k: the largest t with count(scaled >= t) >= k equals the k-th
        # largest value (k=0 -> keep all)
        k = jnp.where(top_k > 0, top_k, v).astype(jnp.int32)
        kth = search(
            lambda t: jnp.sum(scaled >= t[:, None], axis=-1) >= k
        )

        # top-p: the sorted formulation keeps the smallest prefix whose
        # cumulative prob reaches p; equivalently the k-th value where the
        # EXCLUSIVE mass above it is < p — i.e. the largest t whose
        # inclusive mass(scaled >= t) reaches p
        probs = jax.nn.softmax(scaled, axis=-1)
        pth = search(
            lambda t: jnp.sum(
                jnp.where(scaled >= t[:, None], probs, 0.0), axis=-1
            )
            >= jnp.minimum(top_p, 1.0) - 1e-6
        )

        # disabled filters keep EVERYTHING exactly (the searches would only
        # approach the row minimum to f32 precision)
        kth = jnp.where(k >= v, lo0, kth)
        pth = jnp.where(top_p >= 1.0, lo0, pth)
        thresh = jnp.maximum(kth, pth)
        masked = jnp.where(scaled >= thresh[:, None], scaled, NEG_INF)

        keys = _row_keys(base_key, seeds, has_seed, counts)
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (v,), jnp.float32)
        )(keys)
        return jnp.argmax(masked + gumbel, axis=-1).astype(jnp.int32)

    # the sampled branch still runs ~30 (B, V) reductions + a (B, V)
    # gumbel draw per step; an all-greedy batch (the common serving
    # default) skips it entirely at runtime via cond — mixed batches pay
    # it once for the whole batch
    sampled_tok = jax.lax.cond(
        jnp.any(temperature != 0.0), sampled, lambda _: greedy_tok, None
    )
    return jnp.where(temperature == 0.0, greedy_tok, sampled_tok)
