"""HBM budgeting: size the KV pool from what's left after weights.

The reference engines size their pools via vLLM's --gpu-memory-utilization
(deployment-vllm-multi.yaml:160-195; values.yaml `gpuMemoryUtilization`);
`CacheConfig.hbm_utilization` is the TPU analogue. Weights and KV bytes are
computed analytically from the model config (both are exact for our stacked
layouts), so sizing needs no trial allocation."""

from __future__ import annotations

import math

import jax

from ..utils.logging import init_logger
from .config import CacheConfig, ModelConfig, ParallelConfig

logger = init_logger(__name__)

DEFAULT_HBM_BYTES = 16 * 1024**3  # v5e-class chip
# XLA workspace + fragmentation + activation headroom per device
RESERVE_BYTES = 1024**3
# extra pool capacity beyond live-sequence needs, kept as LRU prefix-cache room
PREFIX_CACHE_OVERPROVISION = 4
# context tokens per row the pool sizing sets aside (inside hbm_utilization)
# for the decode window's hoisted contiguous history copy — the runner hoists
# the loop-invariant gather only for programs whose footprint fits this
# funded headroom (model_runner._compute_hoist_budget); contexts past the
# allowance fall back to the per-iteration gather
HOIST_CTX_TOKENS = 256


def dtype_bytes(dtype: str) -> int:
    # jnp.dtype resolves the ml_dtypes names too (float8_e4m3fn, bfloat16)
    return jax.numpy.dtype(dtype).itemsize


def param_bytes(cfg: ModelConfig, tp: int = 1, pp: int = 1) -> int:
    """Per-device bytes of the stacked Llama param tree (models/llama.py
    init_params) under tensor parallelism `tp` and pipeline stages `pp`
    (per-layer leaves shard their L axis over pp, parallel/sharding.py)."""
    if cfg.quantization:
        from ..models.quantization import quantized_param_bytes

        return quantized_param_bytes(cfg, tp, pp)
    h, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, it, L = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size, cfg.num_layers
    attn = h * nh * hd + 2 * h * nkv * hd + nh * hd * h
    mlp = 3 * h * it
    norms = 2 * h
    layers_per_stage = (L + pp - 1) // pp
    per_layer = (attn + mlp) // tp + norms
    embed = cfg.vocab_size * h // tp
    head = 0 if cfg.tie_word_embeddings else h * cfg.vocab_size // tp
    total = embed + layers_per_stage * per_layer + h + head
    if cfg.attention_bias:
        total += layers_per_stage * (nh * hd + 2 * nkv * hd) // tp
    return total * dtype_bytes(cfg.dtype)


def kv_block_bytes(cfg: ModelConfig, block_size: int, tp: int = 1,
                   pp: int = 1, kv_dtype: str | None = None) -> int:
    """Per-device bytes of ONE pool block across all layers: kv heads shard
    over tp and the block axis shards over pp, so a device holds every
    layer's pages for 1/pp of the blocks — per-device cost of adding a
    block is therefore 1/pp of its global bytes. kv_dtype overrides the
    model dtype when the pool is quantized (CacheConfig.kv_cache_dtype)."""
    kvh = max(1, cfg.num_kv_heads // tp)
    return max(1, (
        cfg.num_layers * 2 * block_size * kvh * cfg.head_dim
        * dtype_bytes(kv_dtype or cfg.dtype)
    ) // pp)


def hoist_reserve_bytes(
    model: ModelConfig,
    cache: CacheConfig,
    parallel: ParallelConfig,
    max_num_seqs: int | None,
) -> int:
    """Per-device bytes set aside for hoisted decode-window history
    (HOIST_CTX_TOKENS of context per live row; same kv_block_bytes layout
    arithmetic the budget check uses, so funded == admissible)."""
    if max_num_seqs is None:
        return 0
    tokens = min(model.max_model_len, HOIST_CTX_TOKENS)
    blocks = math.ceil(tokens / cache.block_size)
    b_local = math.ceil(max_num_seqs / parallel.data_parallel_size)
    return b_local * blocks * kv_block_bytes(
        model, cache.block_size, parallel.tensor_parallel_size,
        parallel.pipeline_parallel_size,
        kv_dtype=cache.resolved_kv_dtype(model.dtype),
    )


def headroom_budget(
    model: ModelConfig,
    cache: CacheConfig,
    parallel: ParallelConfig,
    hbm_bytes: int | None = None,
) -> int:
    """Utilization-capped HBM minus weights minus reserve — the ONE
    definition of the engine's allocatable budget, shared by pool sizing
    (derive_num_blocks) and the runner's hoist admission
    (model_runner._compute_hoist_budget) so the two can't drift."""
    hbm = hbm_bytes if hbm_bytes is not None else device_hbm_bytes()
    return (
        int(hbm * cache.hbm_utilization)
        - param_bytes(
            model, parallel.tensor_parallel_size,
            parallel.pipeline_parallel_size,
        )
        - RESERVE_BYTES
    )


def device_hbm_bytes() -> int:
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return DEFAULT_HBM_BYTES


def derive_num_blocks(
    model: ModelConfig,
    cache: CacheConfig,
    parallel: ParallelConfig,
    hbm_bytes: int | None = None,
    max_num_seqs: int | None = None,
) -> int:
    """Blocks that fit in hbm_utilization × HBM after weights + reserve.

    The fused decode window keeps the pool loop-invariant (staged-KV design,
    ops/attention.py:paged_attention_with_staged), so compile-time temps no
    longer scale with pool size and the pool really can take ~the whole
    post-weights budget. When `max_num_seqs` is known the pool is still
    capped at what the workload can use — live-sequence capacity, times
    PREFIX_CACHE_OVERPROVISION when prefix caching is on (LRU cache room) —
    so tiny models on big chips don't hold HBM they can never reference."""
    hbm = hbm_bytes if hbm_bytes is not None else device_hbm_bytes()
    tp = parallel.tensor_parallel_size
    pp = parallel.pipeline_parallel_size
    budget = headroom_budget(
        model, cache, parallel, hbm
    ) - hoist_reserve_bytes(model, cache, parallel, max_num_seqs)
    per_block = kv_block_bytes(
        model, cache.block_size, tp, pp,
        kv_dtype=cache.resolved_kv_dtype(model.dtype),
    )
    # pp shards the block axis, so the pool must hold >= pp blocks (and the
    # pp-divisibility rounding below must never round UP past the budget)
    if budget < 2 * per_block * max(1, pp):
        raise ValueError(
            f"model weights ({param_bytes(model, tp, pp) / 1024**3:.2f} GiB/device) "
            "+ reserve leave no room for a KV pool in "
            f"{cache.hbm_utilization:.0%} of {hbm / 1024**3:.2f} GiB HBM — "
            f"raise hbm_utilization, shard wider (tp={tp}), or shrink the model"
        )
    n = budget // per_block
    if max_num_seqs is not None:
        per_seq = cache.max_blocks_per_seq(model.max_model_len)
        over = PREFIX_CACHE_OVERPROVISION if cache.enable_prefix_caching else 1
        # +1: block 0 is the reserved null page, not usable capacity
        n = min(n, over * max_num_seqs * per_seq + 1)
    if pp > 1:
        # the pool's block axis shards over pp stages — keep it divisible
        # (round DOWN: the guard above ensures n >= 2*pp, so this never
        # under-runs the 2-block minimum or overruns the budget)
        n = (n // pp) * pp
    logger.info(
        "KV pool: %d blocks of %d tokens (%.2f GiB of %.2f GiB HBM; weights %.2f GiB)",
        n,
        cache.block_size,
        n * per_block / 1024**3,
        hbm / 1024**3,
        param_bytes(model, tp, pp) / 1024**3,
    )
    return int(n)
