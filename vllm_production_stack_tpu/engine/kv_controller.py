"""KV controller: the cluster-level KV-prefix lookup service.

The reference embeds an LMCacheControllerManager inside the router process
(routing_logic.py:222-344, which is why its kvaware image builds on the vLLM
image), while its Go gateway picker assumes a clean REST controller
(`/lookup` → instance with the longest KV prefix, kv_aware_picker.go:90-133).
This service is that REST shape: a standalone aiohttp app that fans a lookup
out to every registered engine's /kv/lookup (HBM + host tiers,
engine/server.py) and answers with the engine holding the longest match. The
router's `kvaware` policy (router/routing.py) points at it via
--kv-controller-url.

Run:
    python -m vllm_production_stack_tpu.engine.kv_controller \
        --port 9000 --engines http://e1:8000,http://e2:8000
Engines can also (de)register dynamically via POST /register /deregister
(the deployment layer wires this like the reference wires
LMCACHE_CONTROLLER_URL into engine pods, deployment-vllm-multi.yaml:324-339).
"""

from __future__ import annotations

import argparse
import asyncio

import aiohttp
from aiohttp import web

from ..utils.logging import init_logger

logger = init_logger(__name__)


class KVController:
    def __init__(self, engine_urls: list[str] | None = None,
                 timeout_s: float = 2.0):
        self.engines: set[str] = {u.rstrip("/") for u in engine_urls or []}
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self._session: aiohttp.ClientSession | None = None

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(timeout=self._timeout)
        return self._session

    async def lookup(self, payload: dict) -> dict:
        """Fan out to every engine; return the longest resident prefix."""

        async def probe(url: str) -> tuple[str, int]:
            try:
                async with self._sess().post(
                    url + "/kv/lookup", json=payload
                ) as resp:
                    data = await resp.json()
                    return url, int(data.get("matched_tokens", 0))
            except Exception as e:
                logger.debug("kv lookup to %s failed: %s", url, e)
                return url, -1

        results = await asyncio.gather(*(probe(u) for u in sorted(self.engines)))
        reachable = [(u, n) for u, n in results if n >= 0]
        if not reachable:
            return {"url": None, "matched_tokens": 0}
        url, n = max(reachable, key=lambda r: r[1])
        return {"url": url, "matched_tokens": n}

    # -- HTTP surface ------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/lookup", self._handle_lookup)
        app.router.add_post("/register", self._handle_register)
        app.router.add_post("/deregister", self._handle_deregister)
        app.router.add_get("/engines", self._handle_engines)
        app.router.add_get("/health", self._handle_health)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_cleanup(self, app: web.Application) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def _handle_lookup(self, request: web.Request) -> web.Response:
        body = await request.json()
        if body.get("text") is None and body.get("token_ids") is None:
            return web.json_response(
                {"error": "text or token_ids is required"}, status=400
            )
        payload = {
            k: body[k] for k in ("text", "token_ids") if body.get(k) is not None
        }
        return web.json_response(await self.lookup(payload))

    async def _handle_register(self, request: web.Request) -> web.Response:
        body = await request.json()
        url = (body.get("url") or "").rstrip("/")
        if not url:
            return web.json_response({"error": "url is required"}, status=400)
        self.engines.add(url)
        return web.json_response({"status": "ok", "engines": sorted(self.engines)})

    async def _handle_deregister(self, request: web.Request) -> web.Response:
        body = await request.json()
        self.engines.discard((body.get("url") or "").rstrip("/"))
        return web.json_response({"status": "ok", "engines": sorted(self.engines)})

    async def _handle_engines(self, request: web.Request) -> web.Response:
        return web.json_response({"engines": sorted(self.engines)})

    async def _handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "engines": len(self.engines)})


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="TPU stack KV controller")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9000)
    p.add_argument("--engines", default="",
                   help="comma-separated engine base URLs")
    args = p.parse_args(argv)
    urls = [u for u in args.engines.split(",") if u]
    controller = KVController(urls)
    logger.info("KV controller on %s:%d over %d engines",
                args.host, args.port, len(urls))
    web.run_app(controller.build_app(), host=args.host, port=args.port,
                access_log=None)


if __name__ == "__main__":
    main()
