"""KV controller: the cluster-level KV-prefix lookup service.

The reference embeds an LMCacheControllerManager inside the router process
(routing_logic.py:222-344, which is why its kvaware image builds on the vLLM
image), while its Go gateway picker assumes a clean REST controller
(`/lookup` → instance with the longest KV prefix, kv_aware_picker.go:90-133).
This service is that REST shape: a standalone aiohttp app the router's
`kvaware` policy (router/routing.py) points at via --kv-controller-url.

Two lookup paths:

- **indexed** (default): engines push batched, sequenced KV events
  (engine/kv_events.py → POST /kv/events here); the controller maintains a
  per-engine chain-hash index (kv_index.ClusterKVIndex) and answers /lookup
  from it — tokenize once (shared tokenizer + native chain hasher,
  utils/native.py), walk the chain, ZERO per-request engine probes.
- **fanout** (legacy, also the automatic fallback): probe every engine's
  /kv/lookup and take the longest match. Used for engines that don't
  publish events, engines whose index slice is stale (sequence gap pending
  resync, or publisher silent past the liveness TTL), LoRA-model lookups
  (the adapter chain salt is engine-local; any /lookup model name not in
  --base-models is assumed to be an adapter), and text lookups when the
  controller has no tokenizer configured.

A mixed cluster gets a mixed answer: the indexed result over publishing
engines is combined with probes of only the non-publishing ones — probe
traffic shrinks to the legacy stragglers instead of scaling O(QPS x
num_engines).

Run:
    python -m vllm_production_stack_tpu.engine.kv_controller \
        --port 9000 --engines http://e1:8000,http://e2:8000 \
        --tokenizer /models/llama  # or "byte" for the byte fallback
Engines (de)register dynamically via POST /register /deregister (the
deployment layer wires this like the reference wires LMCACHE_CONTROLLER_URL
into engine pods, deployment-vllm-multi.yaml:324-339) and publish events to
POST /kv/events.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

import aiohttp
from aiohttp import web

from .. import metrics_contract as mc
from ..fleet import FleetView
from ..kv_index import ClusterKVIndex
from ..utils.http import LazyClientSession
from ..utils.logging import init_logger

logger = init_logger(__name__)

LOOKUP_MODES = ("indexed", "fanout")


class KVController:
    def __init__(self, engine_urls: list[str] | None = None,
                 timeout_s: float = 2.0, mode: str = "indexed",
                 tokenizer=None, base_models: list[str] | None = None,
                 tenant_table=None, fleet_rate_window_s: float = 30.0,
                 replicate_threshold: int = 0,
                 replicate_window_s: float = 10.0,
                 replicate_max_blocks: int = 16,
                 replicate_cooldown_s: float = 30.0,
                 rebalance=None):
        if mode not in LOOKUP_MODES:
            raise ValueError(f"unknown KV lookup mode: {mode}")
        self.engines: set[str] = {u.rstrip("/") for u in engine_urls or []}
        self.mode = mode
        # anything with .encode(text) -> list[int]; None means text lookups
        # cannot be hashed locally and fall back to fan-out
        self.tokenizer = tokenizer
        # served base-model names: OpenAI-style clients put the model in
        # every request, and some forward it into /lookup — names listed
        # here hash like base traffic (indexed) instead of being assumed
        # LoRA adapters (fan-out, since adapter chains are engine-salted)
        self.base_models = set(base_models or [])
        self.index = ClusterKVIndex()
        # the controller renders its convergence meter cumulatively on
        # /metrics and never drains it — don't buffer raw observations
        self.index.convergence.buffer_pending = False
        # fleet-coherence aggregate (docs/32-fleet-telemetry.md): router
        # replicas POST /fleet/report; GET /fleet is the operator view.
        # tenant_table (qos.TenantTable, optional) supplies the per-tenant
        # budget fleet-wide utilization is measured against.
        self.fleet = FleetView(tenant_table=tenant_table,
                               rate_window_s=fleet_rate_window_s)
        self._http = LazyClientSession(
            timeout=aiohttp.ClientTimeout(total=timeout_s)
        )
        # event-loop starvation probe (docs/37-flight-recorder.md): the
        # controller is pure asyncio — a starved loop stalls every
        # lookup/event-apply while its request metrics just go quiet.
        # Started on app startup, rendered as
        # tpu:router_event_loop_lag_seconds like the other shared names.
        from .flightrec import EventLoopLagProbe

        self.loop_lag_probe = EventLoopLagProbe()
        # counters for /metrics and the zero-probe guarantee tests
        self.probes_sent = 0
        # "peer" = /peer_lookup rediscovery calls (docs/35-peer-kv-reuse
        # .md) — seeded like the routed-lookup modes so the series exists
        # from the first scrape
        self.lookup_counts = {
            "indexed": 0, "fanout": 0, "mixed": 0, "peer": 0,
        }
        # proactive flash-crowd replication (docs/39-device-peer-kv.md,
        # the BanaServe half): /peer_lookup hits per prefix are counted
        # over a sliding window; a prefix crossing replicate_threshold
        # lookups/window gets push-replicated to the least-loaded fresh
        # non-holder, and the owner is told so its eviction can prefer
        # the now-replicated blocks as victims. threshold 0 = off.
        self.replicate_threshold = int(replicate_threshold)
        self.replicate_window_s = float(replicate_window_s)
        self.replicate_max_blocks = int(replicate_max_blocks)
        self.replicate_cooldown_s = float(replicate_cooldown_s)
        self._crowd: dict[int, object] = {}  # head hash -> deque[monotonic]
        self._replicated_at: dict[int, float] = {}
        self.replications_ordered = 0
        # pool rebalancing (docs/40-pool-rebalancing.md): the role-flip
        # state machine. Constructed even when disabled so /rebalance and
        # the contract series render; the tick loop only starts when
        # rebalance.enabled. Roles engines advertise at registration are
        # tracked here — fresher than the scrape-lagged fleet view right
        # after a flip.
        from .flightrec import ThreadRegistry
        from .rebalancer import PoolRebalancer, RebalanceConfig

        self.roles: dict[str, str] = {}
        self.threads = ThreadRegistry()
        cfg = rebalance or RebalanceConfig()
        self.rebalancer = PoolRebalancer(
            cfg,
            pool_stats_fn=self.fleet.pool_stats,
            session_fn=self._sess,
            registered_roles_fn=lambda: self.roles,
            # liveness: a wedged rebalancer must be a NAMED stall, not a
            # quietly persisting starvation (PR 15 watchdog discipline)
            heartbeat=self.threads.register(
                "rebalancer",
                stall_after_s=max(60.0, 10 * cfg.interval_s),
            ),
        )

    async def _sess(self) -> aiohttp.ClientSession:
        return await self._http.get()

    # -- lookup ------------------------------------------------------------

    async def _fanout(self, payload: dict, urls: set[str]) -> tuple[str | None, int]:
        """Probe each url's /kv/lookup; return the longest resident prefix."""
        sess = await self._sess()

        async def probe(url: str) -> tuple[str, int]:
            self.probes_sent += 1
            try:
                async with sess.post(url + "/kv/lookup", json=payload) as resp:
                    data = await resp.json()
                    return url, int(data.get("matched_tokens", 0))
            except Exception as e:
                logger.debug("kv lookup to %s failed: %s", url, e)
                return url, -1

        results = await asyncio.gather(*(probe(u) for u in sorted(urls)))
        reachable = [(u, n) for u, n in results if n >= 0]
        if not reachable:
            return None, 0
        url, n = max(reachable, key=lambda r: r[1])
        return url, n

    async def lookup(self, payload: dict) -> dict:
        """Longest locally-resident KV prefix across the cluster. Indexed
        where the index is authoritative, fanned out where it is not, and
        the max of both in a mixed cluster."""
        token_ids = payload.get("token_ids")
        text = payload.get("text")
        lora_model = payload.get("model")
        if lora_model in self.base_models:
            lora_model = None  # base traffic hashes unsalted: stay indexed
        indexable = set()
        best_url: str | None = None
        best_n = 0
        if self.mode == "indexed" and lora_model is None:
            # LoRA chains are salted per adapter with an engine-local salt
            # (engine._cache_root) — only the engine can hash them.
            # fresh_engines BEFORE tokenizing: a cluster with no publishers
            # must not pay a per-request tokenize just to throw it away
            try:
                fresh = self.index.fresh_engines(self.engines)
                if fresh:
                    if token_ids is None and self.tokenizer is not None:
                        # tokenize off-loop: a multi-KB prompt must not
                        # stall event ingestion and concurrent lookups
                        token_ids = await asyncio.get_running_loop(
                        ).run_in_executor(
                            None, self.tokenizer.encode, text or ""
                        )
                    if token_ids is not None:
                        best_url, best_n = self.index.lookup_token_ids(
                            list(token_ids), fresh
                        )
                        indexable = fresh
            except Exception as e:
                # a tokenizer/index fault (malformed text payloads included)
                # must degrade to fan-out, not turn /lookup into a 500 —
                # engines hash the prompt themselves either way
                logger.debug(
                    "indexed lookup failed (%s); falling back to fan-out", e
                )
                indexable, best_url, best_n = set(), None, 0
        legacy = self.engines - indexable
        if legacy:
            url, n = await self._fanout(payload, legacy)
            if n > best_n or best_url is None:
                best_url, best_n = url, n
        mode = ("indexed" if not legacy else
                "mixed" if indexable else "fanout")
        self.lookup_counts[mode] += 1
        return {"url": best_url, "matched_tokens": best_n, "mode": mode}

    # -- HTTP surface ------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_post("/lookup", self._handle_lookup)
        app.router.add_post("/peer_lookup", self._handle_peer_lookup)
        app.router.add_post("/kv/events", self._handle_events)
        app.router.add_post("/register", self._handle_register)
        app.router.add_post("/deregister", self._handle_deregister)
        app.router.add_post("/fleet/report", self._handle_fleet_report)
        app.router.add_get("/fleet", self._handle_fleet)
        app.router.add_get("/engines", self._handle_engines)
        app.router.add_get("/rebalance", self._handle_rebalance)
        app.router.add_get("/health", self._handle_health)
        app.router.add_get("/metrics", self._handle_metrics)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app: web.Application) -> None:
        self.loop_lag_probe.start()
        self.rebalancer.start()

    async def _on_cleanup(self, app: web.Application) -> None:
        await self.rebalancer.stop()
        await self.loop_lag_probe.stop()
        await self._http.close()

    async def _handle_lookup(self, request: web.Request) -> web.Response:
        body = await request.json()
        if body.get("text") is None and body.get("token_ids") is None:
            return web.json_response(
                {"error": "text or token_ids is required"}, status=400
            )
        payload = {
            k: body[k] for k in ("text", "token_ids", "model")
            if body.get(k) is not None
        }
        t0 = time.perf_counter()
        result = await self.lookup(payload)
        self.index.lookups.observe(
            result.get("mode", "fanout"), time.perf_counter() - t0
        )
        return web.json_response(result)

    async def _handle_peer_lookup(self, request: web.Request) -> web.Response:
        """Peer-tier rediscovery (docs/35-peer-kv-reuse.md): which engine
        holds the longest consecutively-resident run of an ALREADY-HASHED
        chain. Pure index set walks — no tokenization, no fan-out (an
        engine that doesn't publish events can't be a peer owner: nobody
        would learn its residency in time to plan against it)."""
        body = await request.json()
        raw = body.get("hashes")
        block_size = int(body.get("block_size") or 0)
        if not isinstance(raw, list) or block_size <= 0:
            return web.json_response(
                {"error": "hashes (hex list) and block_size are required"},
                status=400,
            )
        try:
            hashes = [int(h, 16) for h in raw]
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "hashes must be hex strings"}, status=400
            )
        url, matched = self.index.lookup_hashes(
            hashes, block_size, self.engines,
            exclude=body.get("exclude") or None,
        )
        self.lookup_counts["peer"] += 1
        reply: dict = {"url": url, "matched_blocks": matched}
        if url:
            # per-pair transport hint (docs/39): negotiate the requester's
            # advertised mesh identity against the owner's registered one.
            # Attached only when the answer is "device" — absent means
            # HTTP, which keeps pre-39 askers (and their reply-shape
            # expectations) untouched. The asking engine still
            # re-validates against the owner's /kv/peer_contains echo
            # before any collective.
            from ..kv_index import negotiate_transport

            hint = negotiate_transport(
                body.get("transport"), self.index.get_transport(url)
            )
            if hint == "device":
                reply["transport"] = hint
            if matched and self.replicate_threshold > 0:
                self._note_crowd(hashes[:matched], block_size, url)
        return web.json_response(reply)

    def _note_crowd(
        self, hashes: list[int], block_size: int, owner: str
    ) -> None:
        """Count a /peer_lookup hit against its prefix (keyed by the run's
        head hash) and order replication when the window rate crosses the
        threshold — fire-and-forget, never blocking the lookup reply."""
        from collections import deque

        key = hashes[0]
        now = time.monotonic()
        if len(self._crowd) > 4096:  # bound: crowd tracking is best-effort
            self._crowd.clear()
        dq = self._crowd.setdefault(key, deque())
        dq.append(now)
        while dq and now - dq[0] > self.replicate_window_s:
            dq.popleft()
        if len(dq) < self.replicate_threshold:
            return
        if now - self._replicated_at.get(key, -1e9) < (
            self.replicate_cooldown_s
        ):
            return
        self._replicated_at[key] = now
        dq.clear()
        asyncio.get_running_loop().create_task(
            self._replicate_prefix(list(hashes), block_size, owner)
        )

    async def _replicate_prefix(
        self, hashes: list[int], block_size: int, owner: str
    ) -> None:
        """Push-replicate a flash-crowd prefix (docs/39): pick the least-
        loaded fresh engine not already holding the run, order it to pull
        from the owner (POST target /kv/peer_replicate), and on success
        tell the owner (POST owner /kv/replicated) so migration-aware
        eviction prefers those blocks as victims. Every failure is soft —
        replication is an optimization, never a correctness dependency."""
        try:
            run = hashes[: self.replicate_max_blocks]
            holders = set(self.index.holders(run, block_size, self.engines))
            positions = self.index.positions()
            candidates = [
                u for u in self.index.fresh_engines(self.engines)
                if u not in holders and u != owner
                and positions.get(u, {}).get("block_size") == block_size
            ]
            if not candidates:
                return
            # least-loaded proxy: the smallest index slice has the least
            # KV resident, hence the most room to host a replica
            target = min(
                candidates, key=lambda u: (positions[u]["hashes"], u)
            )
            sess = await self._sess()
            wire = [str(h) for h in run]
            async with sess.post(
                target + "/kv/peer_replicate",
                json={"owner": owner, "hashes": wire},
            ) as resp:
                data = await resp.json()
            adopted = int(data.get("adopted") or 0)
            if not adopted:
                return
            self.replications_ordered += 1
            logger.info(
                "replicated %d-block crowd prefix %s -> %s",
                adopted, owner, target,
            )
            async with sess.post(
                owner + "/kv/replicated", json={"hashes": wire[:adopted]}
            ) as resp:
                await resp.read()
        except Exception as e:
            logger.debug("crowd-prefix replication failed: %s", e)

    async def _handle_events(self, request: web.Request) -> web.Response:
        raw = await request.text()
        # off-loop: a resync snapshot parses a whole pool's hashes — keep
        # the multi-MB json.loads off the event loop along with the hex walk
        body = await asyncio.get_running_loop().run_in_executor(
            None, json.loads, raw
        )
        url = (body.get("engine") or "").rstrip("/")
        if url:
            # publishing IS registering: a pushed engine joins the cluster
            # view even if the deployment never POSTed /register
            self.engines.add(url)
        reply = await asyncio.get_running_loop().run_in_executor(
            None, self.index.apply, body
        )
        return web.json_response(reply)

    async def _handle_register(self, request: web.Request) -> web.Response:
        body = await request.json()
        url = (body.get("url") or "").rstrip("/")
        if not url:
            return web.json_response({"error": "url is required"}, status=400)
        self.engines.add(url)
        # mesh identity rides the registration (docs/39): a falsy value
        # CLEARS a previous identity — a pod restarted without
        # KV_MESH_GROUP must stop negotiating "device"
        self.index.set_transport(url, body.get("transport"))
        # live pool role (docs/40-pool-rebalancing.md): set when valid,
        # untouched otherwise — a roleless re-registration (an engine
        # outside any disaggregated pool) must not erase what the fleet
        # view knows from scrapes
        role = body.get("role")
        if role in mc.POOL_ROLE_VALUES:
            self.roles[url] = role
        return web.json_response({"status": "ok", "engines": sorted(self.engines)})

    async def _handle_deregister(self, request: web.Request) -> web.Response:
        body = await request.json()
        url = (body.get("url") or "").rstrip("/")
        self.engines.discard(url)
        self.index.remove_engine(url)
        self.roles.pop(url, None)
        return web.json_response({"status": "ok", "engines": sorted(self.engines)})

    async def _handle_fleet_report(self, request: web.Request) -> web.Response:
        """One router replica's periodic coherence report (router/fleet.py
        FleetReporter): ring-membership hash, embedded-index positions,
        breaker states, per-tenant drained totals. The reply carries the
        fleet view back (this replica's index divergence vs the
        controller's authoritative index, fleet tenant utilization, the
        ring-divergence flag) so every replica re-exports the fleet
        signals on its own /metrics."""
        body = await request.json()
        reply = self.fleet.apply_report(
            body, authoritative_positions=self.index.positions()
        )
        status = 400 if reply.get("status") == "error" else 200
        return web.json_response(reply, status=status)

    async def _handle_fleet(self, request: web.Request) -> web.Response:
        """Operator view of fleet coherence: per-replica index seq
        positions + divergence estimates, ring-membership agreement, and
        cluster-wide tenant accounting — next to the controller's own
        authoritative index positions."""
        # ONE positions snapshot for both consumers: two calls would take
        # the index lock twice and could even disagree mid-request
        positions = self.index.positions()
        return web.json_response({
            "controller": {
                "engines": positions,
                "stats": self.index.stats(),
                "convergence": self.index.convergence.stats(),
                "mode": self.mode,
            },
            **self.fleet.snapshot(authoritative_positions=positions),
        })

    async def _handle_engines(self, request: web.Request) -> web.Response:
        return web.json_response({
            "engines": sorted(self.engines),
            "publishing": sorted(self.index.fresh_engines(self.engines)),
            "mode": self.mode,
        })

    async def _handle_rebalance(self, request: web.Request) -> web.Response:
        """Operator view of the pool-rebalancer state machine: current
        phase, active episode (if any), outcome totals, cooldowns, and
        the per-pool signals it is acting on."""
        from .rebalancer import _PoolView  # the same split the ticker uses

        view = _PoolView()
        for url, p in (self.fleet.pool_stats() or {}).items():
            role = self.roles.get(url) or p.get("role") or ""
            if role in mc.POOL_ROLE_VALUES:
                view.pool(role)[url] = p
        return web.json_response({
            **self.rebalancer.snapshot(),
            "pools": {
                "prefill": view.prefill,
                "decode": view.decode,
            },
            "registered_roles": dict(self.roles),
        })

    async def _handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "engines": len(self.engines)})

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        st = self.index.stats()
        lines = [
            f"# TYPE {mc.CLUSTER_KV_INDEX_HASHES} gauge",
            f"{mc.CLUSTER_KV_INDEX_HASHES} {st['hashes']}",
            f"{mc.CLUSTER_KV_INDEX_ENGINES} {st['engines']}",
            f"{mc.CLUSTER_KV_INDEX_STALE_ENGINES} {st['stale_engines']}",
            f"# TYPE {mc.CLUSTER_KV_EVENTS} counter",
            f"{mc.CLUSTER_KV_EVENTS} {st['events_applied']}",
            f"{mc.CLUSTER_KV_RESYNCS} {st['resyncs_requested']}",
            f"# TYPE {mc.CLUSTER_KV_LOOKUPS} counter",
        ]
        for mode, n in sorted(self.lookup_counts.items()):
            lines.append(f'{mc.CLUSTER_KV_LOOKUPS}{{mode="{mode}"}} {n}')
        lines.append(f"# TYPE {mc.CLUSTER_KV_REPLICATIONS} counter")
        lines.append(f"{mc.CLUSTER_KV_REPLICATIONS} {self.replications_ordered}")
        # pool rebalancing (docs/40-pool-rebalancing.md): outcome totals +
        # phase one-hot, plus the tick loop's liveness age under the same
        # closed thread name the engine exporter seeds
        lines += self.rebalancer.metrics_lines()
        # 0 when the loop isn't running (rebalancing disabled) — the same
        # "loop not running in this deployment" convention the engine
        # exporter applies to unregistered loops
        rb_age = (
            self.threads.ages().get("rebalancer", 0.0)
            if self.rebalancer.config.enabled else 0.0
        )
        lines.append(f"# TYPE {mc.THREAD_HEARTBEAT_AGE} gauge")
        lines.append(
            f'{mc.THREAD_HEARTBEAT_AGE}{{thread="rebalancer"}} {rb_age:.3f}'
        )
        lines += self.index.lookups.render(mc.CLUSTER_KV_LOOKUP_LATENCY)
        # event-loop starvation (docs/37-flight-recorder.md): same name
        # wherever an asyncio control-plane loop lives (router replicas
        # export it from their registry)
        lines.append(f"# TYPE {mc.ROUTER_EVENT_LOOP_LAG} gauge")
        lines.append(
            f"{mc.ROUTER_EVENT_LOOP_LAG} {self.loop_lag_probe.lag_s:.6f}"
        )
        # fleet-coherence telemetry (docs/32-fleet-telemetry.md): the
        # controller-vantage convergence lag, per-engine applied seq
        # positions, per-replica index divergence, and the fleet-wide
        # tenant accounting rollup
        lines += self.index.convergence.render(mc.CLUSTER_KV_CONVERGENCE_LAG)
        lines.append(f"# TYPE {mc.CLUSTER_KV_ENGINE_SEQ} gauge")
        for url, pos in sorted(self.index.positions().items()):
            lines.append(
                f'{mc.CLUSTER_KV_ENGINE_SEQ}{{engine="{url}"}} {pos["seq"]}'
            )
        lines.append(f"# TYPE {mc.CLUSTER_KV_INDEX_DIVERGENCE} gauge")
        for rid, d in sorted(self.fleet.divergence_by_replica().items()):
            if d is not None:
                lines.append(
                    f'{mc.CLUSTER_KV_INDEX_DIVERGENCE}{{replica="{rid}"}} {d}'
                )
        rollup = self.fleet.tenant_rollup()
        lines.append(f"# TYPE {mc.FLEET_TENANT_UTILIZATION} gauge")
        for tenant, row in sorted(rollup.items()):
            if "limit_utilization" in row:
                lines.append(
                    f'{mc.FLEET_TENANT_UTILIZATION}{{tenant="{tenant}"}} '
                    f'{row["limit_utilization"]}'
                )
        lines.append(f"# TYPE {mc.FLEET_TENANT_OVERADMISSION} gauge")
        for tenant, row in sorted(rollup.items()):
            if "overadmission_ratio" in row:
                lines.append(
                    f'{mc.FLEET_TENANT_OVERADMISSION}{{tenant="{tenant}"}} '
                    f'{row["overadmission_ratio"]}'
                )
        return web.Response(
            text="\n".join(lines) + "\n", content_type="text/plain"
        )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU stack KV controller")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9000)
    p.add_argument("--engines", default="",
                   help="comma-separated engine base URLs")
    p.add_argument("--mode", choices=LOOKUP_MODES, default="indexed",
                   help="indexed: answer /lookup from the event-driven "
                        "cluster index (fan-out only to non-publishing or "
                        "stale engines); fanout: legacy per-request probes")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer for hashing text lookups locally: an HF "
                        "checkpoint/tokenizer dir, or 'byte' for the byte "
                        "fallback. Unset = text lookups fan out; token_ids "
                        "lookups are still indexed")
    p.add_argument("--base-models", default="",
                   help="comma-separated served base-model names: a /lookup "
                        "naming one of these stays on the indexed path "
                        "(any OTHER model name is assumed to be a LoRA "
                        "adapter, whose engine-salted chains only engine "
                        "probes can hash)")
    p.add_argument("--tenant-table-file", default=None,
                   help="tenant policy table (same YAML/JSON shape the "
                        "router takes): supplies the per-tenant budgets "
                        "the fleet-wide accounting measures router "
                        "reports against (tpu:fleet_tenant_* on /metrics "
                        "and GET /fleet). Unset = fleet reports are still "
                        "aggregated, utilization gauges are absent")
    p.add_argument("--replicate-threshold", type=int, default=0,
                   help="proactive flash-crowd replication (docs/39-device-"
                        "peer-kv.md): /peer_lookup hits per prefix per "
                        "--replicate-window crossing this order a push "
                        "replica of the hot run onto the least-loaded "
                        "fresh non-holder. 0 (default) disables the loop")
    p.add_argument("--replicate-window", type=float, default=10.0,
                   help="seconds of /peer_lookup history the flash-crowd "
                        "rate is measured over")
    p.add_argument("--replicate-max-blocks", type=int, default=16,
                   help="longest run (KV blocks) one replication order "
                        "ships — bounds the target's adoption burst")
    p.add_argument("--replicate-cooldown", type=float, default=30.0,
                   help="seconds before the same prefix may be replicated "
                        "again (lets the index catch up with the new "
                        "holder before re-evaluating the crowd)")
    p.add_argument("--fleet-rate-window", type=float, default=30.0,
                   help="seconds of router-report history the fleet-wide "
                        "per-tenant admission RATE is measured over "
                        "(utilization/over-admission smooth over this "
                        "window; shorter reacts faster, longer dampens "
                        "report jitter)")
    p.add_argument("--rebalance", action="store_true", default=False,
                   help="enable the prefill/decode pool rebalancer "
                        "(docs/40-pool-rebalancing.md): on sustained "
                        "seat starvation, drain the least-loaded engine "
                        "of the rich pool and flip its role via POST "
                        "/role. Off = observe-only (/rebalance and the "
                        "tpu:pool_rebalance_* series still render)")
    p.add_argument("--rebalance-interval", type=float, default=2.0,
                   help="rebalancer tick cadence in seconds (each phase "
                        "advances at most once per tick)")
    p.add_argument("--rebalance-observe", type=float, default=10.0,
                   help="hysteresis: seconds one imbalance direction must "
                        "hold before an episode starts")
    p.add_argument("--rebalance-cooldown", type=float, default=60.0,
                   help="seconds after any finished episode before the "
                        "next may start")
    p.add_argument("--rebalance-verify-window", type=float, default=30.0,
                   help="seconds a completed flip gets to prove itself; "
                        "a starved-pool queue wait worse than the "
                        "episode baseline inside it is rolled back once")
    p.add_argument("--rebalance-min-prefill", type=int, default=1,
                   help="floor on the prefill pool: an episode never "
                        "starts if flipping would leave fewer prefill "
                        "engines than this")
    p.add_argument("--rebalance-min-decode", type=int, default=1,
                   help="floor on the decode pool (see "
                        "--rebalance-min-prefill)")
    p.add_argument("--rebalance-queue-wait-trigger", type=float,
                   default=1.0,
                   help="queue-wait p95 seconds past which a pool counts "
                        "as starved (mirrors the TpuSeatStarvation rule)")
    p.add_argument("--rebalance-occupancy-rich-max", type=float,
                   default=0.5,
                   help="decode-seat occupancy below which the decode "
                        "pool counts as rich (idle seats while prefill "
                        "queues = the flip-to-prefill signal)")
    p.add_argument("--rebalance-drain-timeout", type=float, default=30.0,
                   help="bound on each POST /drain?wait=true barrier "
                        "attempt during the drain phase")
    p.add_argument("--rebalance-state-file", default="",
                   help="path the episode phase + outcome counters are "
                        "persisted to (atomic JSON): a controller crash "
                        "mid-flip resumes or safely abandons the episode "
                        "on restart. Empty = in-memory only")
    return p


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)
    from ..utils.tokenizer import hashing_tokenizer

    urls = [u for u in args.engines.split(",") if u]
    tenant_table = None
    if args.tenant_table_file:
        from ..qos import TenantTable

        tenant_table = TenantTable.load(args.tenant_table_file)
    from .rebalancer import RebalanceConfig

    controller = KVController(
        urls, mode=args.mode, tokenizer=hashing_tokenizer(args.tokenizer),
        base_models=[m for m in args.base_models.split(",") if m],
        tenant_table=tenant_table,
        fleet_rate_window_s=args.fleet_rate_window,
        replicate_threshold=args.replicate_threshold,
        replicate_window_s=args.replicate_window,
        replicate_max_blocks=args.replicate_max_blocks,
        replicate_cooldown_s=args.replicate_cooldown,
        rebalance=RebalanceConfig(
            enabled=args.rebalance,
            interval_s=args.rebalance_interval,
            observe_s=args.rebalance_observe,
            cooldown_s=args.rebalance_cooldown,
            verify_window_s=args.rebalance_verify_window,
            min_prefill=args.rebalance_min_prefill,
            min_decode=args.rebalance_min_decode,
            queue_wait_trigger_s=args.rebalance_queue_wait_trigger,
            occupancy_rich_max=args.rebalance_occupancy_rich_max,
            drain_timeout_s=args.rebalance_drain_timeout,
            state_file=args.rebalance_state_file,
        ),
    )
    logger.info("KV controller on %s:%d over %d engines (mode=%s)",
                args.host, args.port, len(urls), args.mode)
    web.run_app(controller.build_app(), host=args.host, port=args.port,
                access_log=None)


if __name__ == "__main__":
    main()
