"""Async bridge over the synchronous LLMEngine for the HTTP server.

A dedicated step thread drives the device (JAX dispatch must not block the
event loop — a single TPU step is milliseconds-to-tens-of-ms of host work);
per-request asyncio queues carry outputs back to handler coroutines. This is
the TPU stack's analogue of vLLM's AsyncLLMEngine, which the reference stack
always talks to over HTTP (request.py:99-105).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections.abc import AsyncIterator

from .engine import LLMEngine
from .request import RequestOutput, SamplingParams

logger = logging.getLogger(__name__)


class EngineSleepingError(RuntimeError):
    """Request submitted while the engine is parked (router should have
    filtered this endpoint out via the sleeping label — discovery contract,
    reference service_discovery.py:414-496)."""


class AsyncEngine:
    def __init__(self, engine: LLMEngine):
        self.engine = engine
        self._lock = threading.Lock()
        self._queues: dict[str, asyncio.Queue[RequestOutput]] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._wake = threading.Event()
        self._stop = False
        self._thread: threading.Thread | None = None
        self._step_error: Exception | None = None
        # served-stack profiling (exposed via /debug/timing): where the step
        # thread's wall time goes, and how long submissions wait on the
        # engine lock behind it
        self.loop_timing = {
            "steps": 0, "busy_s": 0.0, "idle_s": 0.0,
            "submits": 0, "submit_lock_wait_s": 0.0,
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self, loop: asyncio.AbstractEventLoop) -> None:
        if self._thread is not None and self._thread.is_alive():
            self.shutdown()  # restartable (server rebuilt around one engine)
        self._loop = loop
        self._stop = False
        self._thread = threading.Thread(
            target=self._step_loop, name="engine-step", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        host_tier = getattr(self.engine, "host_tier", None)
        remote = getattr(self.engine, "remote_tier", None)
        if host_tier is not None:
            # resolve pending device transfers (write-through to remote)
            host_tier.flush()
        if remote is not None:
            remote.drain(timeout=5)
            remote.close()

    @property
    def is_healthy(self) -> bool:
        return (
            self._thread is not None
            and self._thread.is_alive()
            and self._step_error is None
        )

    def _step_loop(self) -> None:
        lt = self.loop_timing
        while not self._stop:
            t0 = time.perf_counter()
            try:
                with self._lock:
                    has_work = (
                        not self.engine.is_sleeping and self.engine.has_unfinished()
                    )
                    outputs = self.engine.step() if has_work else []
            except Exception as e:  # surface to /health, fail queued requests
                logger.exception("engine step failed")
                self._step_error = e
                self._fail_all(e)
                return
            if has_work:
                lt["steps"] += 1
                lt["busy_s"] += time.perf_counter() - t0
            for out in outputs:
                self._dispatch(out)
            if not has_work:
                t1 = time.perf_counter()
                self._wake.wait(timeout=0.02)
                self._wake.clear()
                lt["idle_s"] += time.perf_counter() - t1

    def _dispatch(self, out: RequestOutput) -> None:
        q = self._queues.get(out.request_id)
        if q is None or self._loop is None:
            return
        self._loop.call_soon_threadsafe(q.put_nowait, out)

    def _fail_all(self, exc: Exception) -> None:
        if self._loop is None:
            return
        for rid, q in list(self._queues.items()):
            out = RequestOutput(
                request_id=rid, new_token_ids=[], finished=True,
                finish_reason="error",
            )
            out.text_delta = f"engine error: {exc}"
            self._loop.call_soon_threadsafe(q.put_nowait, out)

    # -- serving API -------------------------------------------------------

    def _submit(
        self, request_id, prompt, prompt_token_ids, sampling, q, lora_name=None
    ) -> str:
        """Runs in an executor: the step thread may hold the lock for a full
        device step (or a 10-40s first compile) — never block the event loop
        on it."""
        t0 = time.perf_counter()
        self._lock.acquire()
        self.loop_timing["submits"] += 1
        self.loop_timing["submit_lock_wait_s"] += time.perf_counter() - t0
        try:
            if self.engine.is_sleeping:
                raise EngineSleepingError(
                    "engine is sleeping; wake it before sending requests"
                )
            if request_id is not None and (
                request_id in self._queues or self.engine.has_request(request_id)
            ):
                # client-supplied ids (X-Request-Id) must not collide with an
                # in-flight request: colliding ids would cross-wire output
                # queues and prefix-cache hash chains
                request_id = f"{request_id}-{id(q) & 0xFFFFFF:x}"
            rid = self.engine.add_request(
                request_id=request_id,
                prompt=prompt,
                prompt_token_ids=prompt_token_ids,
                sampling=sampling,
                lora_name=lora_name,
            )
            self._queues[rid] = q
        finally:
            self._lock.release()
        self._wake.set()
        return rid

    async def generate(
        self,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        sampling: SamplingParams | None = None,
        request_id: str | None = None,
        lora_name: str | None = None,
    ) -> AsyncIterator[RequestOutput]:
        """Submit a request and yield its incremental outputs."""
        if self._step_error is not None:
            raise RuntimeError(f"engine is dead: {self._step_error}")
        q: asyncio.Queue[RequestOutput] = asyncio.Queue()
        loop = asyncio.get_running_loop()
        rid = await loop.run_in_executor(
            None, self._submit, request_id, prompt, prompt_token_ids, sampling,
            q, lora_name,
        )
        finished = False
        try:
            while True:
                out = await q.get()
                yield out
                if out.finished:
                    finished = True
                    return
        finally:
            self._queues.pop(rid, None)
            if not finished:
                # consumer went away (disconnect/cancel): reap the engine-side
                # request or it would decode to max_tokens holding KV blocks
                loop.run_in_executor(None, self._abort_sync, rid)

    def _abort_sync(self, request_id: str) -> bool:
        with self._lock:
            return self.engine.abort_request(request_id)

    async def abort(self, request_id: str) -> bool:
        ok = await asyncio.get_running_loop().run_in_executor(
            None, self._abort_sync, request_id
        )
        self._queues.pop(request_id, None)
        return ok

    # -- control -----------------------------------------------------------

    async def stats_async(self):
        return await asyncio.get_running_loop().run_in_executor(None, self.stats)

    def stats(self):
        with self._lock:
            return self.engine.stats()

    def tokenize(self, text: str) -> list[int]:
        return self.engine.tokenizer.encode(text)

    def detokenize(self, ids: list[int]) -> str:
        return self.engine.tokenizer.decode(ids)

    def chat_prompt(self, messages: list[dict]) -> str:
        return self.engine.tokenizer.chat_prompt(messages)

    @property
    def is_sleeping(self) -> bool:
        return self.engine.is_sleeping

    def sleep(self, level: int = 1) -> None:
        deadline = time.monotonic() + 30.0
        while True:
            with self._lock:
                if not self.engine.scheduler.has_unfinished():
                    self.engine.sleep(level)
                    return
            if time.monotonic() > deadline:
                raise RuntimeError("engine busy; cannot sleep")
            time.sleep(0.05)

    def wake(self) -> None:
        with self._lock:
            self.engine.wake()

    async def kv_lookup(self, text=None, token_ids=None, lora_name=None) -> int:
        def work():
            # tokenize OUTSIDE the lock: the controller fans lookups to every
            # engine per routed request, and encode() needs no engine state —
            # holding the lock for it would serialize probes against decode
            ids = (
                token_ids
                if token_ids is not None
                else self.engine.tokenizer.encode(text or "")
            )
            with self._lock:
                return self.engine.kv_lookup(token_ids=ids, lora_name=lora_name)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def embed(self, inputs) -> tuple[list[list[float]], int]:
        """Chunked so a large embedding batch can't monopolize the engine
        lock — decode steps interleave between chunks."""
        loop = asyncio.get_running_loop()
        vectors: list[list[float]] = []
        total_tokens = 0
        CHUNK = 16
        for i in range(0, len(inputs), CHUNK):
            chunk = inputs[i : i + CHUNK]

            def work(c=chunk):
                with self._lock:
                    return self.engine.embed(c)

            v, n = await loop.run_in_executor(None, work)
            vectors.extend(v)
            total_tokens += n
        return vectors, total_tokens

    async def kv_export(self, text=None, token_ids=None, lora_name=None):
        def work():
            ids = (
                token_ids
                if token_ids is not None
                else self.engine.tokenizer.encode(text or "")
            )
            with self._lock:
                return self.engine.kv_export(token_ids=ids, lora_name=lora_name)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_export_lazy(self, text=None, token_ids=None, lora_name=None):
        """Lock held only for the chain walk + device fetch dispatch; the
        per-block numpy resolution happens in the streaming handler."""
        def work():
            ids = (
                token_ids
                if token_ids is not None
                else self.engine.tokenizer.encode(text or "")
            )
            with self._lock:
                return self.engine.kv_export_lazy(
                    token_ids=ids, lora_name=lora_name
                )

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_import(self, hashes, blocks, fingerprint="") -> int:
        def work():
            with self._lock:
                return self.engine.kv_import(hashes, blocks, fingerprint)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def load_lora(self, name: str, path: str) -> None:
        def work():
            with self._lock:
                self.engine.load_lora(name, path)

        await asyncio.get_running_loop().run_in_executor(None, work)

    async def unload_lora(self, name: str) -> None:
        def work():
            with self._lock:
                self.engine.unload_lora(name)

        await asyncio.get_running_loop().run_in_executor(None, work)
