"""Async bridge over the synchronous LLMEngine for the HTTP server.

A dedicated step thread drives the device (JAX dispatch must not block the
event loop — a single TPU step is milliseconds-to-tens-of-ms of host work);
per-request asyncio queues carry outputs back to handler coroutines. This is
the TPU stack's analogue of vLLM's AsyncLLMEngine, which the reference stack
always talks to over HTTP (request.py:99-105).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import threading
import time
from collections import deque
from collections.abc import AsyncIterator

from .engine import LLMEngine
from .request import RequestOutput, SamplingParams

logger = logging.getLogger(__name__)


class EngineSleepingError(RuntimeError):
    """Request submitted while the engine is parked (router should have
    filtered this endpoint out via the sleeping label — discovery contract,
    reference service_discovery.py:414-496)."""


class EngineDrainingError(RuntimeError):
    """Request submitted while the engine is draining (SIGTERM / POST
    /drain): admissions are stopped so in-flight streams can finish and the
    process can exit inside its grace period. The HTTP layer answers 503
    with X-Engine-Draining so the router fails the request over instead of
    surfacing the refusal to the client."""


def _same_request(rid: str, parent: str) -> bool:
    """True when `rid` is `parent` itself or one of its n>1 sibling choice
    ids (server._choice_rids derivation: parent, parent-1, parent-2, …)."""
    return rid == parent or rid.startswith(parent + "-")


class AsyncEngine:
    def __init__(self, engine: LLMEngine):
        self.engine = engine
        # ONE quiescence lock shared with the engine (engine.step_lock):
        # the Hydrator's device-collective peer pull takes it on the
        # fetcher thread, so "holding it" must mean "no step in flight"
        # for the step loop here too. getattr keeps pre-step_lock test
        # doubles working.
        self._lock = getattr(engine, "step_lock", None) or threading.Lock()
        self._queues: dict[str, asyncio.Queue[RequestOutput]] = {}
        # deferred admissions: (rid, token_ids, sampling, lora_name).
        # Submissions NEVER take the engine lock — on a busy engine the step
        # thread holds it nearly continuously (a full device step each
        # time), and Python locks aren't fair, so a contending submit sat
        # behind multiple steps (measured: 1.7s mean submit wait under the
        # north-star load). The step thread drains this queue at the top of
        # every iteration instead.
        self._pending: deque = deque()
        self._pending_lock = threading.Lock()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._wake = threading.Event()
        self._stop = False
        # graceful drain: False stops NEW admissions (submit raises
        # EngineDrainingError) while in-flight requests keep stepping;
        # _admitting counts requests popped from _pending but not yet in
        # the scheduler (wait_idle must not miss them)
        self.accepting = True
        self._admitting = 0
        self._thread: threading.Thread | None = None
        self._step_error: Exception | None = None
        # thread-liveness heartbeat (docs/37-flight-recorder.md): the step
        # loop beats every iteration — including the idle wait — so a beat
        # older than its threshold means the loop is WEDGED inside a step
        # (collective stall, runaway compile under the engine lock), not
        # merely quiet. Registered in start() so restartable servers
        # refresh rather than duplicate it.
        self._heartbeat = None
        # fatal-wedge hook: called ONCE with the exception when the step
        # loop marks the engine dead (the server points this at the
        # postmortem dumper — the dying step thread writes its own black
        # box before the /health flip is even scraped)
        self.on_fatal = None
        # served-stack profiling (exposed via /debug/timing): where the step
        # thread's wall time goes, and how long submissions wait on the
        # engine lock behind it
        self.loop_timing = {
            "steps": 0, "busy_s": 0.0, "idle_s": 0.0,
            "submits": 0, "submit_s": 0.0,  # tokenize+validate+queue time
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self, loop: asyncio.AbstractEventLoop) -> None:
        if self._thread is not None and self._thread.is_alive():
            self.shutdown()  # restartable (server rebuilt around one engine)
        self._loop = loop
        self._stop = False
        # background program compiles defer to traffic (model_runner
        # _bg_compile_job): compile only when nothing is queued or running
        def idle() -> bool:
            return (
                not self.engine.scheduler.has_unfinished()
                and not self._pending
            )

        runner = getattr(self.engine, "runner", None)
        if runner is not None:
            runner.idle_check = idle
        draft = getattr(self.engine, "draft_runner", None)
        if draft is not None:
            draft.idle_check = idle
        threads = getattr(self.engine, "threads", None)
        if threads is not None:
            self._heartbeat = threads.register("step")
        self._thread = threading.Thread(
            target=self._step_loop, name="engine-step", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        threads = getattr(self.engine, "threads", None)
        if threads is not None:
            # a deliberate stop must not read as a wedge at the next check
            threads.unregister("step")
            self._heartbeat = None
        runner = getattr(self.engine, "runner", None)
        if runner is not None and hasattr(runner, "shutdown"):
            runner.shutdown()  # cancel queued background compiles
        draft = getattr(self.engine, "draft_runner", None)
        if draft is not None and hasattr(draft, "shutdown"):
            draft.shutdown()  # the draft proposer's runner compiles too
        hydrator = getattr(self.engine, "hydrator", None)
        if hydrator is not None:
            hydrator.close()  # stop the hydration fetcher thread
        peer = getattr(self.engine, "peer_tier", None)
        if peer is not None:
            peer.close()  # idempotent; hydrator.close already closed it
        host_tier = getattr(self.engine, "host_tier", None)
        remote = getattr(self.engine, "remote_tier", None)
        if host_tier is not None:
            # resolve pending device transfers (write-through to remote)
            host_tier.flush()
        if remote is not None:
            remote.drain(timeout=5)
            remote.close()

    @property
    def is_healthy(self) -> bool:
        return (
            self._thread is not None
            and self._thread.is_alive()
            and self._step_error is None
        )

    # consecutive step failures tolerated before the engine reports dead:
    # transient device faults (a dropped remote-compile connection, a
    # preempted dispatch) fail the in-flight requests but must not brick
    # the server — the reference stack gets this resilience from k8s
    # restart + readiness probes; a self-healing step loop is strictly
    # better (no pod churn, warm compile caches survive)
    MAX_CONSECUTIVE_STEP_FAILURES = 3

    def _step_loop(self) -> None:
        lt = self.loop_timing
        hb = self._heartbeat
        failures = 0
        while not self._stop:
            t0 = time.perf_counter()
            if hb is not None:
                # every iteration, idle path included: staleness then means
                # "wedged inside a step", never "no traffic"
                hb.beat()
            try:
                with self._lock:
                    self._drain_pending()
                    has_work = (
                        not self.engine.is_sleeping and self.engine.has_unfinished()
                    )
                    outputs = self.engine.step() if has_work else []
                failures = 0
            except Exception as e:
                failures += 1
                fr = getattr(self.engine, "flightrec", None)
                if fr is not None:
                    fr.fault(str(e))
                if failures >= self.MAX_CONSECUTIVE_STEP_FAILURES:
                    # persistent fault: surface to /health, fail everything
                    logger.exception(
                        "engine step failed %d times consecutively; "
                        "marking engine dead", failures,
                    )
                    self._step_error = e
                    self._notify_fatal(e)
                    self._fail_all(e)
                    return
                # transient fault: the failed step may have left requests
                # half-executed — abort ALL in-flight work (clients get a
                # terminal error output), then keep serving new requests
                logger.exception(
                    "engine step failed (attempt %d/%d); aborting in-flight "
                    "requests and continuing",
                    failures, self.MAX_CONSECUTIVE_STEP_FAILURES,
                )
                try:
                    with self._lock:
                        self._abort_all_inflight(e)
                except Exception:
                    logger.exception("in-flight abort failed; engine dead")
                    self._step_error = e
                    self._notify_fatal(e)
                    self._fail_all(e)
                    return
                continue
            if has_work:
                lt["steps"] += 1
                lt["busy_s"] += time.perf_counter() - t0
            for out in outputs:
                self._dispatch(out)
            if not has_work:
                t1 = time.perf_counter()
                self._wake.wait(timeout=0.02)
                self._wake.clear()
                lt["idle_s"] += time.perf_counter() - t1

    def _drain_pending(self) -> None:
        """Admit queued submissions (caller holds the engine lock). The
        per-item work is trivial (token ids precomputed, validation done at
        submit time); a failure here is a race (e.g. LoRA unloaded after
        validation) and fails that request's stream, never the loop."""
        while True:
            with self._pending_lock:
                if not self._pending:
                    return
                (rid, token_ids, sampling, lora_name, deadline, tenant,
                 kv_owner_hint) = self._pending.popleft()
                # popped but not yet in the scheduler: wait_idle must not
                # read this window as "drained" (pending empty + scheduler
                # empty) while the request is mid-admission
                self._admitting += 1
            try:
                self._admit_one(
                    rid, token_ids, sampling, lora_name, deadline, tenant,
                    kv_owner_hint,
                )
            finally:
                with self._pending_lock:
                    self._admitting -= 1

    def _admit_one(
        self, rid, token_ids, sampling, lora_name, deadline, tenant=None,
        kv_owner_hint=None,
    ):
        """Move one popped submission into the engine (step thread, engine
        lock held). A failure fails that request's stream, never the loop."""
        if rid not in self._queues:
            return  # consumer vanished (disconnect/abort) pre-admission
        if self.engine.is_sleeping:
            # raced sleep(): a silent hang (admitted but never stepped)
            # becomes the same deterministic error the submit-time check
            # gives
            self._fail_stream(
                rid,
                "engine is sleeping; wake it before sending requests",
            )
            return
        try:
            self.engine.add_request(
                request_id=rid,
                prompt_token_ids=token_ids,
                sampling=sampling,
                lora_name=lora_name,
                deadline=deadline,
                tenant=tenant,
                kv_owner_hint=kv_owner_hint,
            )
        except Exception as e:
            logger.warning("deferred admission failed for %s: %s", rid, e)
            self._fail_stream(rid, str(e))

    def _notify_fatal(self, exc: Exception) -> None:
        """Fire the fatal-wedge hook exactly where the engine dies (the
        step thread) — the postmortem must capture the dying stacks, not
        whatever the event loop looks like at the next scrape."""
        hook = self.on_fatal
        if hook is None:
            return
        try:
            hook(exc)
        except Exception:
            logger.exception("on_fatal hook failed")

    def _fail_stream(self, rid: str, message: str) -> None:
        """Deliver a terminal error output to a request's stream queue."""
        q = self._queues.get(rid)
        if q is not None and self._loop is not None:
            out = RequestOutput(
                request_id=rid, new_token_ids=[], finished=True,
                finish_reason="error",
            )
            out.text_delta = f"engine error: {message}"
            self._loop.call_soon_threadsafe(q.put_nowait, out)

    def _abort_all_inflight(self, exc: Exception) -> None:
        """Terminal-error every queued request and reap its engine state
        (caller holds the engine lock)."""
        with self._pending_lock:
            # unadmitted requests die here too — leaving them pending would
            # re-admit them (rid still in _queues until the event loop runs)
            # and decode to max_tokens into a stream that already ended.
            # Snapshot _queues under the SAME lock _submit inserts under, so
            # a submission racing this abort either fully precedes it (and
            # dies here) or fully follows it (and survives to be admitted)
            self._pending.clear()
            rids = list(self._queues)
        for rid in rids:
            self.engine.abort_request(rid)
            q = self._queues.pop(rid, None)
            if q is not None and self._loop is not None:
                out = RequestOutput(
                    request_id=rid, new_token_ids=[], finished=True,
                    finish_reason="error",
                )
                out.text_delta = f"engine error: {exc}"
                self._loop.call_soon_threadsafe(q.put_nowait, out)

    def _dispatch(self, out: RequestOutput) -> None:
        q = self._queues.get(out.request_id)
        if q is None or self._loop is None:
            return
        self._loop.call_soon_threadsafe(q.put_nowait, out)

    def _fail_all(self, exc: Exception) -> None:
        if self._loop is None:
            return
        for rid, q in list(self._queues.items()):
            out = RequestOutput(
                request_id=rid, new_token_ids=[], finished=True,
                finish_reason="error",
            )
            out.text_delta = f"engine error: {exc}"
            self._loop.call_soon_threadsafe(q.put_nowait, out)

    # -- serving API -------------------------------------------------------

    _rid_counter = itertools.count()

    def pending_depth(
        self, exclude_prefix: str | None = None
    ) -> tuple[int, int]:
        """(requests, prompt tokens) queued for admission but not yet seen
        by the scheduler — the share of the backlog only this bridge knows
        about, fed into the engine's admission gate. exclude_prefix drops
        a request's own sibling choices ({rid}, {rid}-i) from the count."""
        with self._pending_lock:
            items = list(self._pending)
        if exclude_prefix is not None:
            items = [
                it for it in items
                if not _same_request(it[0], exclude_prefix)
            ]
        return len(items), sum(len(it[1]) for it in items)

    def precheck_admission(
        self, deadline: float | None = None, n_new_tokens: int = 0,
        record: bool = True, tenant=None,
    ) -> None:
        """Lock-free admission gate for HTTP handlers, run BEFORE a stream's
        SSE headers go out so overload/drain/deadline refusals keep their
        proper status codes (429/503). The same checks rerun at submit time
        — this is the common-case fast path, not the only line of defense.
        record=False is the would-this-shed probe (/ready, /health): probe
        polls must not inflate the shed counters."""
        if not self.accepting:
            raise EngineDrainingError(
                "engine is draining; retry against another endpoint"
            )
        extra_waiting, extra_tokens = self.pending_depth()
        self.engine.check_admission(
            n_new_tokens, deadline,
            extra_waiting=extra_waiting, extra_tokens=extra_tokens,
            record=record, tenant=tenant,
        )

    def _submit(
        self, request_id, prompt, prompt_token_ids, sampling, q,
        lora_name=None, deadline=None, admission_exclude_prefix=None,
        tenant=None, kv_owner_hint=None,
    ) -> str:
        """Runs in an executor. Deliberately LOCK-FREE: tokenization +
        validation need no engine state mutation, and admission is deferred
        to the step thread via the pending queue — a submit contending for
        the engine lock used to wait out whole device steps (unfair lock +
        near-100% hold time = 1.7s mean TTFT tax under load)."""
        t0 = time.perf_counter()
        if not self.accepting:
            raise EngineDrainingError(
                "engine is draining; retry against another endpoint"
            )
        if self.engine.is_sleeping:
            raise EngineSleepingError(
                "engine is sleeping; wake it before sending requests"
            )
        if prompt_token_ids is None:
            if prompt is None:
                raise ValueError("need prompt or prompt_token_ids")
            prompt_token_ids = self.engine.tokenizer.encode(prompt)
        # synchronous 4xx for invalid requests, even with deferred admission
        self.engine.validate_new_request(prompt_token_ids, lora_name)
        # load shedding + would-queue-past-deadline, against the scheduler
        # queue PLUS the pending deque (both feed the same backlog).
        # admission_exclude_prefix (the HTTP request's parent rid) keeps an
        # n>1 request's sibling choices out of its own count — without it a
        # single n=8 request would shed itself against max_waiting_requests
        # on an idle engine.
        extra_waiting, extra_tokens = self.pending_depth(
            exclude_prefix=admission_exclude_prefix
        )
        self.engine.check_admission(
            len(prompt_token_ids), deadline,
            extra_waiting=extra_waiting, extra_tokens=extra_tokens,
            exclude_prefix=admission_exclude_prefix,
            # submit time is where a higher-priority arrival actually
            # claims its lowest-priority eviction victim (QoS)
            tenant=tenant, evict=True,
        )
        with self._pending_lock:
            # re-check under the SAME lock wait_idle samples _pending with:
            # a drain beginning while this thread was tokenizing must not
            # let the request slip into _pending after the drain barrier
            # already observed it empty (the stream would be severed by
            # process exit despite /drain?wait=true reporting drained)
            if not self.accepting:
                raise EngineDrainingError(
                    "engine is draining; retry against another endpoint"
                )
            # check + insert must be atomic vs concurrent submits: two
            # requests sharing an X-Request-Id would otherwise both pass
            # the check and cross-wire their output queues
            if request_id is not None and (
                request_id in self._queues
                or self.engine.has_request(request_id)
            ):
                request_id = f"{request_id}-{id(q) & 0xFFFFFF:x}"
            rid = request_id or f"req-a{next(self._rid_counter)}"
            self._queues[rid] = q
            self._pending.append((rid, list(prompt_token_ids), sampling,
                                  lora_name, deadline, tenant,
                                  kv_owner_hint))
        self.loop_timing["submits"] += 1
        self.loop_timing["submit_s"] += time.perf_counter() - t0
        self._wake.set()
        return rid

    async def generate(
        self,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        sampling: SamplingParams | None = None,
        request_id: str | None = None,
        lora_name: str | None = None,
        deadline: float | None = None,
        admission_exclude_prefix: str | None = None,
        tenant=None,
        kv_owner_hint: str | None = None,
    ) -> AsyncIterator[RequestOutput]:
        """Submit a request and yield its incremental outputs.
        admission_exclude_prefix (the parent request id of an n>1 fan-out)
        keeps sibling choices out of this submission's admission count —
        choices gate against OTHER requests, never against their own.
        tenant (qos.TenantContext, from the router-stamped headers) drives
        fair-share admission and priority-aware shedding."""
        if self._step_error is not None:
            raise RuntimeError(f"engine is dead: {self._step_error}")
        q: asyncio.Queue[RequestOutput] = asyncio.Queue()
        loop = asyncio.get_running_loop()
        rid = await loop.run_in_executor(
            None, self._submit, request_id, prompt, prompt_token_ids, sampling,
            q, lora_name, deadline, admission_exclude_prefix, tenant,
            kv_owner_hint,
        )
        finished = False
        try:
            while True:
                out = await q.get()
                yield out
                if out.finished:
                    finished = True
                    return
        finally:
            self._queues.pop(rid, None)
            if not finished:
                # consumer went away (disconnect/cancel): reap the engine-side
                # request or it would decode to max_tokens holding KV blocks
                loop.run_in_executor(None, self._abort_sync, rid)

    def _abort_sync(self, request_id: str) -> bool:
        with self._pending_lock:
            # not yet admitted: dropping the pending entry is the abort
            for item in self._pending:
                if item[0] == request_id:
                    self._pending.remove(item)
                    return True
        with self._lock:
            return self.engine.abort_request(request_id)

    async def abort(self, request_id: str) -> bool:
        ok = await asyncio.get_running_loop().run_in_executor(
            None, self._abort_sync, request_id
        )
        self._queues.pop(request_id, None)
        return ok

    # -- control -----------------------------------------------------------

    async def stats_async(self):
        return await asyncio.get_running_loop().run_in_executor(None, self.stats)

    def stats(self):
        with self._lock:
            snap = self.engine.stats()
        snap.draining = not self.accepting
        return snap

    # -- graceful drain ----------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admissions; in-flight requests keep stepping to completion.
        Idempotent — the /drain handler and the SIGTERM path may both fire."""
        self.accepting = False

    def end_drain(self) -> None:
        """Reopen admissions after a rebalance drain (POST /role re-admits
        the engine under its new pool role — docs/40-pool-rebalancing.md).
        Idempotent; never called on the SIGTERM exit path."""
        self.accepting = True

    async def wait_idle(self, timeout_s: float) -> bool:
        """Wait (bounded) until every in-flight request has finished — the
        drain barrier between 'admissions stopped' and 'safe to exit'.
        Returns True when idle, False when the timeout expired with work
        still in flight (the caller exits anyway; clients of the stragglers
        see a severed stream rather than the process lingering forever)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._pending_lock:
                pending = bool(self._pending) or self._admitting > 0
            if not pending and not self.engine.scheduler.has_unfinished():
                return True
            await asyncio.sleep(0.05)
        return False

    def tokenize(self, text: str) -> list[int]:
        return self.engine.tokenizer.encode(text)

    def detokenize(self, ids: list[int]) -> str:
        return self.engine.tokenizer.decode(ids)

    def chat_prompt(self, messages: list[dict]) -> str:
        return self.engine.tokenizer.chat_prompt(messages)

    @property
    def is_sleeping(self) -> bool:
        return self.engine.is_sleeping

    def sleep(self, level: int = 1) -> None:
        deadline = time.monotonic() + 30.0
        while True:
            with self._lock:
                with self._pending_lock:
                    pending = bool(self._pending)
                if not pending and not self.engine.scheduler.has_unfinished():
                    self.engine.sleep(level)
                    return
            if time.monotonic() > deadline:
                raise RuntimeError("engine busy; cannot sleep")
            time.sleep(0.05)

    def wake(self) -> None:
        with self._lock:
            self.engine.wake()

    async def kv_lookup(self, text=None, token_ids=None, lora_name=None) -> int:
        def work():
            # tokenize OUTSIDE the lock: the controller fans lookups to every
            # engine per routed request, and encode() needs no engine state —
            # holding the lock for it would serialize probes against decode
            ids = (
                token_ids
                if token_ids is not None
                else self.engine.tokenizer.encode(text or "")
            )
            with self._lock:
                return self.engine.kv_lookup(token_ids=ids, lora_name=lora_name)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_events_snapshot(self) -> tuple[str, int, list[int]]:
        """Consistent (epoch, seq, hashes) resync snapshot for the cluster
        KV index — the pool is quiesced under the engine lock so the seq
        barrier and the hash set describe the same instant."""
        def work():
            with self._lock:
                return self.engine.scheduler.pool.snapshot_events()

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def embed(self, inputs) -> tuple[list[list[float]], int]:
        """Chunked so a large embedding batch can't monopolize the engine
        lock — decode steps interleave between chunks."""
        loop = asyncio.get_running_loop()
        vectors: list[list[float]] = []
        total_tokens = 0
        CHUNK = 16
        for i in range(0, len(inputs), CHUNK):
            chunk = inputs[i : i + CHUNK]

            def work(c=chunk):
                with self._lock:
                    return self.engine.embed(c)

            v, n = await loop.run_in_executor(None, work)
            vectors.extend(v)
            total_tokens += n
        return vectors, total_tokens

    async def kv_export(self, text=None, token_ids=None, lora_name=None):
        def work():
            ids = (
                token_ids
                if token_ids is not None
                else self.engine.tokenizer.encode(text or "")
            )
            with self._lock:
                return self.engine.kv_export(token_ids=ids, lora_name=lora_name)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_export_lazy(self, text=None, token_ids=None, lora_name=None):
        """Lock held only for the chain walk + device fetch dispatch; the
        per-block numpy resolution happens in the streaming handler."""
        def work():
            ids = (
                token_ids
                if token_ids is not None
                else self.engine.tokenizer.encode(text or "")
            )
            with self._lock:
                return self.engine.kv_export_lazy(
                    token_ids=ids, lora_name=lora_name
                )

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_peer_contains(self, hashes: list[int]) -> int:
        # deliberately LOCK-FREE: the walk is pure GIL-atomic dict/set
        # containment (pool map, host ring, disk index — each
        # thread-safe or atomic on its own), and the answer is
        # staleness-tolerant BY DESIGN (the asking planner re-validates
        # at fetch/adoption). Taking the engine lock here would also
        # let a mis-aimed self-probe (hint naming this engine under a
        # URL scheme _advertised_url can't recognize) stall an
        # admission for the full peer timeout: the step thread waits on
        # this HTTP reply while holding the very lock this handler
        # would need.
        return await asyncio.get_running_loop().run_in_executor(
            None, self.engine.kv_peer_contains, hashes
        )

    async def kv_peer_export(self, hashes: list[int]):
        """Lock held only for the residency walk + device fetch dispatch
        (same discipline as kv_export_lazy); the per-block numpy / disk
        resolution happens in the /kv/peer_fetch handler off the lock."""
        def work():
            with self._lock:
                return self.engine.kv_peer_export(hashes)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_peer_device_serve(self, hashes: list[int]) -> int:
        """Owner half of a device-collective peer pull (docs/39): join the
        cooperative transfer program as the source. Holds the engine lock
        for the whole collective — the gather reads kv_caches, and the
        puller's side donates its own; both step loops must be quiesced.
        Returns 0 (the source never adopts)."""
        def work():
            from .kv_device_transfer import pull_kv_device_crossproc

            with self._lock:
                return pull_kv_device_crossproc(
                    self.engine, True, list(hashes)
                )

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_peer_replicate(self, owner: str, hashes: list[int]) -> int:
        """Proactive flash-crowd replication target half (docs/39): fetch
        `hashes` from `owner` over the HTTP peer path and adopt them
        parked. The fetch runs OFF the lock (seconds of wire time);
        only the adoption quiesces the step loop."""
        def work():
            return self.engine.kv_peer_replicate(owner, hashes)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_mark_replicated(self, hashes: list[int]) -> int:
        """Record that a peer now holds copies of `hashes` — the owner's
        eviction policies prefer replicated blocks as victims from here
        on (pool + host ring, docs/39)."""
        def work():
            with self._lock:
                return self.engine.scheduler.pool.mark_replicated(hashes)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def kv_import(self, hashes, blocks, fingerprint="") -> int:
        def work():
            with self._lock:
                return self.engine.kv_import(hashes, blocks, fingerprint)

        return await asyncio.get_running_loop().run_in_executor(None, work)

    async def load_lora(self, name: str, path: str) -> None:
        def work():
            with self._lock:
                self.engine.load_lora(name, path)

        await asyncio.get_running_loop().run_in_executor(None, work)

    async def unload_lora(self, name: str) -> None:
        def work():
            with self._lock:
                self.engine.unload_lora(name)

        await asyncio.get_running_loop().run_in_executor(None, work)
