"""Device-path KV transfer: prefill-role → decode-role pools over ICI/DCN.

The reference ships KV GPU-direct through NIXL sender/receiver pairs over
UCX (deployment-vllm-multi.yaml:267-305; values-16-disagg-prefill.yaml).
The TPU-native answer needs no custom transport stack: KV pages are
jax.Arrays, and `jax.device_put` onto a sharding over a DIFFERENT device
set lowers to direct device-to-device copies — the XLA runtime moves bytes
over ICI within a slice and DCN across slices, exactly where NIXL/UCX sit
in the reference. No host staging, no serialization.

This module implements that path behind the SAME content-addressed
export/adopt bookkeeping as the host-staged HTTP path (kv_transfer.py):
chain hashes identify blocks, the fingerprint gate refuses foreign
weights, partial adoption degrades to recompute. Only the byte transport
changes — so the router's 2-phase PD orchestration cannot tell them apart.

Per transfer: ONE gather dispatch on the source mesh (compact the chain's
pages, per layer), one cross-mesh device_put (the actual ICI/DCN hop), one
scatter dispatch on the target mesh (drop the pages into the target pool's
free blocks). Gather/scatter pad the block-count to a pow2 bucket
(compile-count discipline); padding slots route to the reserved null page
0 on the target side, so oversized buckets are harmless.

Same-process engines (the dryrun and single-host PD case) use it as-is;
multi-host PD runs the two engines in one jax.distributed runtime
(parallel/distributed.py) where device_put spans hosts over DCN.
Design doc: docs/05-disaggregated-prefill.md. The host-staged HTTP path
remains the cross-cluster / cross-runtime fallback.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.logging import init_logger

logger = init_logger(__name__)


def _pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_blocks(kv_caches, moved, dst_idx):
    """Write the shipped pages into the (donated) target pool. dst_idx
    padding points at block 0 — the reserved null page, overwritten
    harmlessly."""
    return tuple(
        leaf.at[:, dst_idx].set(m.astype(leaf.dtype))
        for leaf, m in zip(kv_caches, moved)
    )


@jax.jit
def _gather_blocks(kv_caches, src_idx):
    """Compact the chain's pages out of the source pool: per-layer
    (2, n_pad, bs, kvh, D)."""
    return tuple(leaf[:, src_idx] for leaf in kv_caches)


def ship_kv_device(
    src_engine,
    dst_engine,
    token_ids: list[int],
    lora_name: str | None = None,
) -> int:
    """Ship the prompt's resident KV blocks from src_engine's pool into
    dst_engine's pool device-to-device. Returns blocks adopted (0 when
    nothing is resident or the destination pool is full — the decode
    engine recomputes, same degradation contract as the HTTP path).

    LOCKING CONTRACT: the caller must hold BOTH engines' step locks (or
    otherwise quiesce their step loops) for the duration — the scatter
    donates and reassigns dst_engine.runner.kv_caches, and a concurrent
    decode step's own donation would race it. This matches the HTTP
    path's discipline, where every KVTransfer method runs under
    AsyncEngine._lock (async_engine.py kv_import/kv_export)."""
    if src_engine.model_fingerprint != dst_engine.model_fingerprint:
        raise ValueError(
            f"KV fingerprint mismatch: sender "
            f"{src_engine.model_fingerprint!r} != receiver "
            f"{dst_engine.model_fingerprint!r} — refusing foreign KV"
        )
    src_pool = src_engine.scheduler.pool
    dst_pool = dst_engine.scheduler.pool
    root = src_engine._cache_root(lora_name)

    # chain walk on the source (same identity rule as kv_transfer.py)
    hashes: list[int] = []
    src_blocks: list[int] = []
    for h in src_pool._chain(list(token_ids), root):
        blk = src_pool._hash_to_block.get(h)
        if blk is None:
            break
        hashes.append(h)
        src_blocks.append(blk)
    if not hashes:
        return 0

    # allocate on the destination — staging/commit bookkeeping is the
    # pool's shared definition (kv_cache.stage_adoption: pins resident
    # chain members so this staging's allocations cannot evict them)
    src_by_hash = dict(zip(hashes, src_blocks))
    staged, pinned = dst_pool.stage_adoption(hashes)
    if not staged:
        dst_pool.abort_adoption(staged, pinned)
        return 0

    n_pad = _pow2(len(staged))
    # padding: source side re-reads its first block (cheap, discarded),
    # destination side targets the reserved null page 0
    src_idx = np.full(n_pad, src_by_hash[staged[0][0]], np.int32)
    dst_idx = np.zeros(n_pad, np.int32)
    for i, (h, dblk) in enumerate(staged):
        src_idx[i] = src_by_hash[h]
        dst_idx[i] = dblk

    try:
        gathered = _gather_blocks(
            src_engine.runner.kv_caches,
            jax.device_put(
                src_idx,
                NamedSharding(src_engine.runner.mesh, P()),
            ),
        )
        # (scatter below donates + reassigns dst kv_caches — see the
        # locking contract in this function's docstring)
        # THE transfer: cross-mesh device_put — ICI/DCN, no host staging.
        # KV heads stay tp-sharded on the target (each target chip receives
        # only its heads' bytes); the small block axis is not pp-sharded
        # (the compacted run is tiny relative to the pool)
        from ..parallel import mesh as mesh_lib

        dst_sharding = NamedSharding(
            dst_engine.runner.mesh,
            P(None, None, None, mesh_lib.TP_AXIS, None),
        )
        moved = tuple(
            jax.device_put(g, dst_sharding) for g in gathered
        )
        dst_engine.runner.kv_caches = _scatter_blocks(
            dst_engine.runner.kv_caches,
            moved,
            jax.device_put(
                dst_idx,
                NamedSharding(dst_engine.runner.mesh, P()),
            ),
        )
    except Exception:
        dst_pool.abort_adoption(staged, pinned)
        raise
    dst_pool.commit_adoption(staged, pinned)
    logger.info(
        "device-shipped %d KV blocks (%d offered) prefill→decode",
        len(staged), len(hashes),
    )
    return len(staged)
