"""Device-path KV transfer: prefill-role → decode-role pools over ICI/DCN.

The reference ships KV GPU-direct through NIXL sender/receiver pairs over
UCX (deployment-vllm-multi.yaml:267-305; values-16-disagg-prefill.yaml).
The TPU-native answer needs no custom transport stack: KV pages are
jax.Arrays, and `jax.device_put` onto a sharding over a DIFFERENT device
set lowers to direct device-to-device copies — the XLA runtime moves bytes
over ICI within a slice and DCN across slices, exactly where NIXL/UCX sit
in the reference. No host staging, no serialization.

This module implements that path behind the SAME content-addressed
export/adopt bookkeeping as the host-staged HTTP path (kv_transfer.py):
chain hashes identify blocks, the fingerprint gate refuses foreign
weights, partial adoption degrades to recompute. Only the byte transport
changes — so the router's 2-phase PD orchestration cannot tell them apart.

Per transfer: ONE gather dispatch on the source mesh (compact the chain's
pages, per layer), one cross-mesh device_put (the actual ICI/DCN hop), one
scatter dispatch on the target mesh (drop the pages into the target pool's
free blocks). Gather/scatter pad the block-count to a pow2 bucket
(compile-count discipline); padding slots route to the reserved null page
0 on the target side, so oversized buckets are harmless.

Same-process engines (the dryrun and single-host PD case) use it as-is;
multi-host PD runs the two engines in one jax.distributed runtime
(parallel/distributed.py) where device_put spans hosts over DCN.
Design doc: docs/05-disaggregated-prefill.md. The host-staged HTTP path
remains the cross-cluster / cross-runtime fallback.
"""

from __future__ import annotations

import functools
import os
import time

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib
from ..utils.logging import init_logger
from .kv_flow import NULL_FLOW

logger = init_logger(__name__)

# operator-assigned mesh-group name (helm: modelSpec.kvMeshGroup / the
# multihost StatefulSet sets it to the slice identity): engines sharing a
# value AND a 2-process jax.distributed runtime negotiate the device-path
# peer transport (docs/39-device-peer-kv.md)
ENV_MESH_GROUP = "KV_MESH_GROUP"


def device_transport_identity() -> dict | None:
    """This engine's mesh/process-group identity, advertised through KV
    registration so /peer_lookup replies (and /kv/peer_contains replies on
    the owner-hint path) can negotiate a per-pair transport. None when the
    engine cannot take part in device-path pulls: no mesh group assigned,
    or not running inside a multi-process jax.distributed program."""
    group = os.environ.get(ENV_MESH_GROUP, "")
    if not group:
        return None
    try:
        n = jax.process_count()
        i = jax.process_index()
    except Exception:  # noqa: BLE001 — uninitialized runtime ⇒ no identity
        return None
    if n < 2:
        return None
    return {
        "mesh_group": group,
        "process_index": int(i),
        "process_count": int(n),
    }


def _pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


def _block_nbytes(kv_caches) -> int:
    """Bytes of ONE pool block across all layers: each leaf is
    (2, num_blocks, block_size, kv_heads, head_dim), so a block's payload
    is everything but the block axis."""
    total = 0
    for leaf in kv_caches:
        shape = leaf.shape
        n = shape[0]
        for d in shape[2:]:
            n *= d
        total += n * leaf.dtype.itemsize
    return total


def _engine_flow(engine):
    """The engine's KV flow meter; NULL_FLOW for pre-telemetry test
    doubles."""
    return getattr(engine, "flow", None) or NULL_FLOW


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_blocks(kv_caches, moved, dst_idx):
    """Write the shipped pages into the (donated) target pool. dst_idx
    padding points at block 0 — the reserved null page, overwritten
    harmlessly."""
    return tuple(
        leaf.at[:, dst_idx].set(m.astype(leaf.dtype))
        for leaf, m in zip(kv_caches, moved)
    )


@jax.jit
def _gather_blocks(kv_caches, src_idx):
    """Compact the chain's pages out of the source pool: per-layer
    (2, n_pad, bs, kvh, D)."""
    return tuple(leaf[:, src_idx] for leaf in kv_caches)


@functools.lru_cache(maxsize=128)
def _flip_program(sharding):
    """Compiled pairwise shard flip for one (mesh, spec) — cached so
    repeated ships on the serving path reuse the program instead of
    recompiling the DCN collective per chunk per call (NamedSharding is
    hashable; jit then caches per input aval under it)."""
    import jax.numpy as jnp

    return jax.jit(lambda x: jnp.flip(x, axis=0), out_shardings=sharding)


def ship_kv_device(
    src_engine,
    dst_engine,
    token_ids: list[int],
    lora_name: str | None = None,
) -> int:
    """Ship the prompt's resident KV blocks from src_engine's pool into
    dst_engine's pool device-to-device. Returns blocks adopted (0 when
    nothing is resident or the destination pool is full — the decode
    engine recomputes, same degradation contract as the HTTP path).

    LOCKING CONTRACT: the caller must hold BOTH engines' step locks (or
    otherwise quiesce their step loops) for the duration — the scatter
    donates and reassigns dst_engine.runner.kv_caches, and a concurrent
    decode step's own donation would race it. This matches the HTTP
    path's discipline, where every KVTransfer method runs under
    AsyncEngine._lock (async_engine.py kv_import/kv_export)."""
    if src_engine.model_fingerprint != dst_engine.model_fingerprint:
        raise ValueError(
            "KV fingerprint mismatch: sender "
            f"{src_engine.model_fingerprint!r} != receiver "
            f"{dst_engine.model_fingerprint!r} — refusing foreign KV"
        )
    src_pool = src_engine.scheduler.pool
    dst_pool = dst_engine.scheduler.pool
    root = src_engine._cache_root(lora_name)

    # chain walk on the source (same identity rule as kv_transfer.py)
    hashes: list[int] = []
    src_blocks: list[int] = []
    for h in src_pool._chain(list(token_ids), root):
        blk = src_pool._hash_to_block.get(h)
        if blk is None:
            break
        hashes.append(h)
        src_blocks.append(blk)
    if not hashes:
        return 0

    # allocate on the destination — staging/commit bookkeeping is the
    # pool's shared definition (kv_cache.stage_adoption: pins resident
    # chain members so this staging's allocations cannot evict them)
    src_by_hash = dict(zip(hashes, src_blocks))
    staged, pinned = dst_pool.stage_adoption(hashes)
    if not staged:
        dst_pool.abort_adoption(staged, pinned)
        return 0

    n_pad = _pow2(len(staged))
    # padding: source side re-reads its first block (cheap, discarded),
    # destination side targets the reserved null page 0
    src_idx = np.full(n_pad, src_by_hash[staged[0][0]], np.int32)
    dst_idx = np.zeros(n_pad, np.int32)
    for i, (h, dblk) in enumerate(staged):
        src_idx[i] = src_by_hash[h]
        dst_idx[i] = dblk

    # flow metering (docs/30-kv-flow-telemetry.md): the source records a
    # device/out sample, the destination device/in — bytes are the useful
    # payload (staged blocks × per-block bytes; padding slots excluded).
    # Recorded in the failure path too with 0 bytes moved: a stalled or
    # faulted PD transfer must surface in tpu:kv_transfer_seconds{tier=
    # "device"} rather than vanish (the chaos harness asserts this).
    xfer_bytes = len(staged) * _block_nbytes(src_engine.runner.kv_caches)
    t0 = time.perf_counter()

    def _flow(ok: bool) -> None:
        elapsed = time.perf_counter() - t0
        nbytes = xfer_bytes if ok else 0
        nblocks = len(staged) if ok else 0
        _engine_flow(src_engine).record(
            "device", "out", nbytes, nblocks, elapsed
        )
        if dst_engine is not src_engine:
            _engine_flow(dst_engine).record(
                "device", "in", nbytes, nblocks, elapsed
            )

    try:
        gathered = _gather_blocks(
            src_engine.runner.kv_caches,
            jax.device_put(
                src_idx,
                NamedSharding(src_engine.runner.mesh, P()),
            ),
        )
        # (scatter below donates + reassigns dst kv_caches — see the
        # locking contract in this function's docstring)
        # THE transfer: cross-mesh device_put — ICI/DCN, no host staging.
        # KV heads stay tp-sharded on the target (each target chip receives
        # only its heads' bytes); the small block axis is not pp-sharded
        # (the compacted run is tiny relative to the pool)
        from ..parallel import mesh as mesh_lib

        dst_sharding = NamedSharding(
            dst_engine.runner.mesh,
            P(None, None, None, mesh_lib.TP_AXIS, None),
        )
        moved = tuple(
            jax.device_put(g, dst_sharding) for g in gathered
        )
        dst_engine.runner.kv_caches = _scatter_blocks(
            dst_engine.runner.kv_caches,
            moved,
            jax.device_put(
                dst_idx,
                NamedSharding(dst_engine.runner.mesh, P()),
            ),
        )
    except Exception:
        _flow(ok=False)
        dst_pool.abort_adoption(staged, pinned)
        raise
    _flow(ok=True)
    dst_pool.commit_adoption(staged, pinned)
    logger.info(
        "device-shipped %d KV blocks (%d offered) prefill→decode",
        len(staged), len(hashes),
    )
    return len(staged)


def ship_kv_device_crossproc(
    engine,
    role: str,  # "prefill" (source) | "decode" (destination)
    token_ids: list[int],
    lora_name: str | None = None,
) -> int:
    """Cross-PROCESS device-path KV ship: the multi-host PD deployment
    shape, where the prefill and decode engines live in DIFFERENT
    `jax.distributed` processes (different pods/hosts). BOTH processes
    call this with the same token_ids — multi-controller JAX is SPMD, so
    the byte movement is one cooperative jitted program over a union mesh
    of [source device(s), destination device(s)]: a shard flip along the
    mesh axis, which GSPMD lowers to a device-to-device collective permute
    — ICI within a slice, DCN across slices. This is where the reference's
    NIXL sender/receiver pair sits (deployment-vllm-multi.yaml:267-305);
    here the transport is the XLA runtime itself, no host staging.

    Control-plane handshake (host-side, small ints only): both sides walk
    the SAME chain hashes from token_ids (deterministic); the source
    publishes how many are resident, the destination stages that prefix
    and publishes which chain positions it allocated; both then build the
    padded index arrays and enter the cooperative transfer. Returns blocks
    adopted on the decode side (always 0 on the prefill side).

    Same degradation contract as ship_kv_device: nothing resident or a
    full destination pool → 0 adopted, decode recomputes."""
    if role not in ("prefill", "decode"):
        raise ValueError(f"role must be prefill|decode, got {role!r}")
    pool = engine.scheduler.pool
    root = engine._cache_root(lora_name)
    chain = list(pool._chain(list(token_ids), root))
    return kv_device_crossproc_transfer(engine, role == "prefill", chain)


def pull_kv_device_crossproc(
    engine, is_src: bool, hashes: list[int]
) -> int:
    """Peer-hydration device pull: the mesh-peer generalization of the PD
    ship above (docs/39-device-peer-kv.md). Both processes call this with
    the SAME explicit hash run — the puller's Hydrator hands the owner the
    run over HTTP (/kv/peer_device_pull) and then both sides meet inside
    the identical cooperative program: fingerprint allgather, residency
    publish, staging, go/no-go, pairwise shard flips. No token ids or
    chain derivation: hydration chunks start mid-chain, where only the
    hashes identify the blocks.

    Returns the number of run hashes resident on the puller after the
    transfer (freshly shipped + already-resident members, all parked at
    refcount 0 for the step thread's adopt_planned_run to re-acquire);
    always 0 on the owner. Degradation contract unchanged: nothing
    resident, a full pool, or a one-sided preparation failure → 0 / a
    raise, and the puller's chunk falls back to recompute."""
    return kv_device_crossproc_transfer(
        engine, is_src, list(hashes), kind="peer pull"
    )


def kv_device_crossproc_transfer(
    engine,
    is_src: bool,
    chain: list[int],
    kind: str = "prefill→decode",
) -> int:
    """The shared 2-process cooperative transfer program (see the public
    wrappers above for the two call shapes). `chain` must be identical on
    both sides; `is_src` must be True on exactly one."""
    import hashlib

    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh

    pool = engine.scheduler.pool
    if jax.process_count() != 2:
        # >2 processes (e.g. several decode hosts) needs a pairwise
        # rendezvous so only ONE destination stages/joins the transfer —
        # raising here beats deadlocking the distributed runtime
        # mid-collective with every decode host staged at once
        raise NotImplementedError(
            "cross-process device KV transfer is a 2-process (one source, "
            f"one destination) shape; got {jax.process_count()} processes"
        )

    # fingerprint gate across processes: publish a fixed-size digest
    fp = hashlib.sha256(
        engine.model_fingerprint.encode()
    ).digest()
    fp_arr = np.frombuffer(fp, np.uint8).astype(np.int32)
    all_fp = multihost_utils.process_allgather(fp_arr)
    if not (all_fp == all_fp[0]).all():
        raise ValueError(
            "KV fingerprint mismatch across PD processes — refusing "
            "foreign KV"
        )

    # the source counts its consecutive residency of the (shared) chain
    n_src = 0
    if is_src:
        for h in chain:
            if pool._hash_to_block.get(h) is None:
                break
            n_src += 1
    counts = multihost_utils.process_allgather(
        np.asarray([n_src], np.int64)
    )
    n_avail = int(counts.max())  # only the source published non-zero

    staged, pinned = [], []
    max_slots = max(1, len(chain))
    picked = np.full(max_slots, -1, np.int64)  # chain positions staged
    if not is_src and n_avail:
        pos_by_hash = {h: i for i, h in enumerate(chain[:n_avail])}
        staged, pinned = pool.stage_adoption(chain[:n_avail])
        for i, (h, _blk) in enumerate(staged):
            picked[i] = pos_by_hash[h]
    all_picked = multihost_utils.process_allgather(picked)
    # the destination's row is the one with staged entries
    dst_picked = picked if not is_src else all_picked[
        int(np.argmax((all_picked >= 0).sum(axis=1)))
    ]
    ship_pos = dst_picked[dst_picked >= 0].astype(np.int64)
    n_ship = len(ship_pos)
    if n_ship == 0:
        # unconditional: stage_adoption can pin already-resident chain
        # members (refcount+1) while returning staged=[] — skipping the
        # abort would leak those pins and make the blocks unevictable
        if staged or pinned:
            pool.abort_adoption(staged, pinned)
        # cooperative exit on both sides — no transfer program to run
        multihost_utils.sync_global_devices("kv-pd-ship-empty")
        return 0

    n_pad = _pow2(n_ship)

    # ---- local preparation, allowed to fail one-sided --------------------
    # Everything that can raise asymmetrically (device OOM in the gather,
    # a chain block evicted between the residency count and src_idx
    # construction, ...) happens BEFORE the go/no-go barrier below. After
    # the barrier both sides are inside the same collectives, where a
    # failure is fate-shared — one side raising while the peer sits in
    # block_until_ready would otherwise hang the peer until an external
    # timeout with the real error invisible.
    prep_err: Exception | None = None
    pieces: list = []  # canonical kvh-chunk order; pieces[j] = chunk j
    n_shard = 1
    src_devs = dst_devs = None
    try:
        kv_caches = engine.runner.kv_caches
        l_layers = len(kv_caches)
        leaf_shape = kv_caches[0].shape  # (2, num_blocks, bs, kvh, D)
        bs, kvh, d = leaf_shape[2], leaf_shape[3], leaf_shape[4]

        # canonical per-role device rows (sorted by id so BOTH processes
        # build identical union meshes): the source's process index is the
        # counts row that published residency
        src_pid = int(np.argmax(counts[:, 0]))
        by_proc: dict[int, list] = {}
        for dv in sorted(jax.devices(), key=lambda dv: dv.id):
            by_proc.setdefault(dv.process_index, []).append(dv)
        dst_pid = next(p for p in sorted(by_proc) if p != src_pid)
        src_devs, dst_devs = by_proc[src_pid], by_proc[dst_pid]
        if len(src_devs) != len(dst_devs):
            raise NotImplementedError(
                "PD roles must span equally many devices (src "
                f"{len(src_devs)} vs dst {len(dst_devs)}); asymmetric "
                "tp needs a resharding hop"
            )
        n_shard = len(src_devs)
        # the chunking below splits kvh into n_shard pieces and reassembles
        # into the destination pool's sharding — only valid when the
        # engine mesh is pure-TP over exactly these devices (dp/pp/sp/ep
        # shard axes the pairwise flips don't model; a dp=2/tp=1 mesh
        # would pass the device-count check but keep kvh whole)
        mesh_shape = dict(engine.runner.mesh.shape)
        tp_size = mesh_shape.get(mesh_lib.TP_AXIS, 1)
        others = 1
        for ax, size in mesh_shape.items():
            if ax != mesh_lib.TP_AXIS:
                others *= size
        if tp_size != n_shard or others != 1:
            raise NotImplementedError(
                "cross-process ship needs a pure-tp engine mesh with "
                f"tp == local devices (got mesh {mesh_shape} over "
                f"{n_shard} devices)"
            )
        if kvh % n_shard:
            raise NotImplementedError(
                f"kv heads ({kvh}) must divide over {n_shard} devices"
            )
        kvh_local = kvh // n_shard
        piece_shape = (l_layers, 2, n_pad, bs, kvh_local, d)

        # local payload stays ON DEVICE end to end: the source compacts
        # its pages (one gather dispatch on its own mesh), then ONE
        # resharding device_put lays kvh chunk j onto canonical device j —
        # correct for any engine mesh ordering or gather-output sharding
        # (GSPMD may well replicate the gather's output)
        my_canon = src_devs if is_src else dst_devs
        if is_src:
            src_idx = np.zeros(n_pad, np.int32)
            for i, p in enumerate(ship_pos):
                src_idx[i] = pool._hash_to_block[chain[int(p)]]
            if n_ship < n_pad:
                src_idx[n_ship:] = src_idx[0]  # cheap re-read, discarded
            gathered = _gather_blocks(
                kv_caches,
                jax.device_put(
                    src_idx, NamedSharding(engine.runner.mesh, P()),
                ),
            )
            canon_mesh = Mesh(np.asarray(my_canon), ("canon",))
            stacked = jax.device_put(
                jnp.stack(gathered),  # (L, 2, n_pad, bs, kvh, D)
                NamedSharding(
                    canon_mesh, P(None, None, None, None, "canon", None)
                ),
            )
            by_dev = {
                s.device: s.data for s in stacked.addressable_shards
            }
            pieces = [by_dev[my_canon[j]] for j in range(n_shard)]
        else:
            pieces = [
                jax.device_put(
                    jnp.zeros(piece_shape, kv_caches[0].dtype), my_canon[j]
                )
                for j in range(n_shard)
            ]
        jax.block_until_ready(pieces)
    except Exception as e:  # noqa: BLE001 — published to the peer below
        prep_err = e

    # go/no-go barrier: both sides publish readiness; either side failing
    # aborts BOTH cleanly before anyone enters the collectives
    ready = multihost_utils.process_allgather(
        np.asarray([0 if prep_err is not None else 1], np.int64)
    )
    if not bool(ready.min()):
        if staged or pinned:
            pool.abort_adoption(staged, pinned)
        if prep_err is not None:
            raise prep_err
        logger.warning(
            "cross-process KV ship aborted: peer failed preparation"
        )
        return 0

    # flow metering: each role records its own half of the hop (source
    # device/out, destination device/in); the failure path records the
    # elapsed wall at 0 bytes so a wedged collective is visible in
    # tpu:kv_transfer_seconds{tier="device"} instead of vanishing
    xfer_bytes = n_ship * _block_nbytes(engine.runner.kv_caches)
    t_xfer = time.perf_counter()

    def _flow(ok: bool) -> None:
        _engine_flow(engine).record(
            "device", "out" if is_src else "in",
            xfer_bytes if ok else 0, n_ship if ok else 0,
            time.perf_counter() - t_xfer,
        )

    try:
        # THE transfer: one pairwise shard flip per kvh chunk — each is a
        # collective permute between src_devs[j] and dst_devs[j] over
        # ICI/DCN. Both processes iterate the same dispatch loop (SPMD),
        # so the cooperative programs always line up; all flips dispatch
        # BEFORE the single block so the runtime overlaps the transfers.
        shipped_all: list = []
        for j in range(n_shard):
            mesh_j = Mesh(np.asarray([src_devs[j], dst_devs[j]]), ("pd",))
            sh_j = NamedSharding(mesh_j, P("pd"))
            local = pieces[j][None]  # (1, L, 2, n_pad, bs, kvh_local, D)
            garr = jax.make_array_from_single_device_arrays(
                (2, *local.shape[1:]), sh_j, [local]
            )
            shipped_all.append(_flip_program(sh_j)(garr))
        jax.block_until_ready(shipped_all)
        recv = (
            []
            if is_src
            else [s.addressable_shards[0].data[0] for s in shipped_all]
        )

        if not is_src:
            # chunk j sits on dst canonical device j. Assemble each
            # layer's global (2, n_pad, bs, kvh, D) array directly from
            # the single-device pieces, committed to the device the POOL's
            # own sharding keeps that kvh chunk on (mapped via
            # shard.index, so any mesh ordering works; a concatenate of
            # differently-committed arrays would be rejected by jax).
            kv_sh = NamedSharding(
                engine.runner.mesh,
                P(None, None, None, mesh_lib.TP_AXIS, None),
            )
            chunk_dev = {}
            for s in engine.runner.kv_caches[0].addressable_shards:
                sl = s.index[3]
                chunk_dev[(sl.start or 0) // kvh_local] = s.device
            dst_idx = np.zeros(n_pad, np.int32)
            for i, (_h, dblk) in enumerate(staged):
                dst_idx[i] = dblk
            moved = []
            for layer in range(l_layers):
                arrs = [
                    jax.device_put(recv[j][layer], chunk_dev[j])
                    for j in range(n_shard)
                ]
                moved.append(jax.make_array_from_single_device_arrays(
                    (2, n_pad, bs, kvh, d), kv_sh, arrs
                ))
            engine.runner.kv_caches = _scatter_blocks(
                engine.runner.kv_caches,
                tuple(moved),
                jax.device_put(
                    dst_idx, NamedSharding(engine.runner.mesh, P()),
                ),
            )
    except Exception:
        _flow(ok=False)
        if staged or pinned:
            pool.abort_adoption(staged, pinned)
        raise
    _flow(ok=True)
    if not is_src:
        pool.commit_adoption(staged, pinned)
        logger.info(
            "cross-process device-shipped %d KV blocks (%d offered, %d "
            "already resident) %s", len(staged), n_avail, len(pinned), kind,
        )
        # pinned members count: for a peer pull the caller needs "how much
        # of the run is resident NOW", and already-resident chain members
        # satisfy the run exactly like freshly shipped ones
        return len(staged) + len(pinned)
    return 0
