"""KV-hierarchy flow telemetry (docs/30-kv-flow-telemetry.md).

The tiering stack's occupancy gauges (tpu:engine_kv_tier_usage_perc) say
how FULL each tier is; this module measures how fast bytes actually MOVE
between tiers and where each request's prefix actually came from — the
measurement substrate the compute-or-load hydration planner (ROADMAP
item 3, "Compute Or Load KV Cache? Why Not Both?") needs before it can
pick load-vs-recompute per chunk by measured fetch bandwidth vs prefill
FLOP/s.

Two instruments, mirroring the PR 6 StepMeter/GoodputLedger split:

- **Transfer meters** (togglable, ``--kv-flow-metering false``): every
  tier move — host-ring offload/reload, disk store/load, remote
  put/fetch, device-path PD transfer — records bytes, blocks and wall
  latency into per-(tier, direction) counters, a fixed-bucket latency
  histogram, and a :class:`TierBandwidth` recent-mean estimator. Plain
  ints under one small lock (transfers are orders of magnitude rarer
  than steps); the exporter renders histograms from cumulative bucket
  counts at scrape time, so no prometheus objects ride the engine or
  writer threads.

- **Hydration attribution** (always on, like the goodput ledger — its
  counters are part of the metric contract): every admitted request's
  prompt tokens are classified EXACTLY once by KV origin, and the
  partition is audited::

      hbm_hit + host_reload + disk_load + remote_fetch + peer_fetch
          + recomputed == prompt_tokens

Direction semantics: ``"in"`` moves bytes toward the HBM pool
(hydration — reload/load/fetch/PD-adopt), ``"out"`` moves them away
(offload — store/put/PD-export). ``tier`` names the non-HBM side of the
hop, so a disk block promoted through the ring into HBM records one
``disk/in`` sample (disk → RAM) and one ``host/in`` sample (RAM → HBM):
per-tier meters count HOPS, not end-to-end journeys.
"""

from __future__ import annotations

import math
import threading
import time

from .. import metrics_contract as mc
from .saturation import _Hist

TRANSFER_TIERS = mc.KV_TRANSFER_TIERS
DIRECTIONS = mc.KV_TRANSFER_DIRECTIONS
HYDRATION_SOURCES = mc.KV_HYDRATION_SOURCES

# wall seconds per transfer batch: spans sub-µs host copies to multi-second
# remote fetches over a cold link
TRANSFER_SECONDS_BUCKETS = (
    1e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

_BW_TAU_S = 60.0  # horizon of the recent-mean bandwidth estimator


class TierBandwidth:
    """Time-decayed recent-mean bandwidth for one (tier, direction).

    Separate exponentially-decayed accumulators for bytes and busy
    seconds, decayed by the WALL gap since the previous sample; the
    estimate is their ratio. For a burst of back-to-back transfers this
    converges to total_bytes / total_seconds (a plain duration-weighted
    mean — robust to microsecond samples where a per-sample-value EWMA
    would be noise), while samples older than ~:data:`_BW_TAU_S` fade
    out. The estimate deliberately does NOT decay toward zero when idle:
    it answers "how fast CAN this tier move bytes", a capability the
    hydration planner consults exactly when the tier has been idle —
    unlike the occupancy EWMAs, which measure utilization and must fall.

    A failed transfer recorded as (0 bytes, elapsed) drags the estimate
    toward zero honestly: during a remote-store outage the measured
    fetch bandwidth IS ~0, which is precisely what should flip the
    planner to recompute.

    **Sample floor** (docs/31-hydration-planner.md): :attr:`measured` is
    False until at least :data:`MIN_SAMPLES` transfers totalling
    :data:`MIN_BYTES` undecayed bytes have been observed — a single tiny
    first transfer after startup must never become the estimate the
    compute-or-load planner trusts. ``hydration_signal()`` reports the
    flag per tier; the planner treats an unmeasured tier as
    recompute-only (forced mode) or declines the plan entirely (auto
    mode, where the synchronous fallback load is exactly what feeds the
    floor).
    """

    # floors for `measured`: enough independent samples that one outlier
    # can't own the ratio, and enough real payload that the estimate
    # reflects steady-state transfer, not connection setup
    MIN_SAMPLES = 2
    MIN_BYTES = 1 << 20

    __slots__ = ("_bytes", "_seconds", "_last_t", "samples", "total_bytes")

    def __init__(self) -> None:
        self._bytes = 0.0
        self._seconds = 0.0
        self._last_t: float | None = None
        self.samples = 0
        self.total_bytes = 0  # undecayed — feeds the measurement floor

    def record(self, nbytes: int, seconds: float, now: float) -> None:
        if self._last_t is not None:
            decay = math.exp(-max(0.0, now - self._last_t) / _BW_TAU_S)
            self._bytes *= decay
            self._seconds *= decay
        self._last_t = now
        self._bytes += nbytes
        self._seconds += max(seconds, 1e-9)
        self.samples += 1
        self.total_bytes += int(nbytes)

    @property
    def bytes_per_s(self) -> float:
        return self._bytes / self._seconds if self._seconds > 0 else 0.0

    @property
    def measured(self) -> bool:
        return (
            self.samples >= self.MIN_SAMPLES
            and self.total_bytes >= self.MIN_BYTES
        )


class KVFlowMeter:
    """Per-tier transfer meters + per-request hydration attribution.

    One instance per engine, shared by every tier object (host ring,
    disk tier, remote client, device-path transfer) — the engine thread,
    the remote writer thread and HTTP executor threads all record here,
    so mutation happens under ``_lock``. ``enabled=False`` turns
    :meth:`record` into a no-op (the bench's ``kvflow`` phase measures
    the difference); hydration attribution stays on regardless, because
    its counters are contract series the dashboard's hydration panel
    keys off (same always-on rule as the goodput ledger).
    """

    def __init__(self, enabled: bool = True, _null: bool = False):
        self.enabled = enabled
        # NULL_FLOW only: a complete no-op, bandwidth estimators included
        # (the singleton is shared by UNRELATED standalone tier objects —
        # cross-polluting their bandwidth samples would fabricate a
        # "measured" tier out of other objects' transfers)
        self._null = _null
        self._lock = threading.Lock()
        self.bytes: dict[tuple[str, str], int] = {}
        # LOGICAL (decoded) bytes per key: equals `bytes` on uncompressed
        # tiers; under an at-rest codec (kv_codec) `bytes` holds WIRE
        # bytes and logical/wire is the tier's measured compression ratio
        # (tpu:kv_tier_compression_ratio)
        self.logical_bytes: dict[tuple[str, str], int] = {}
        self.blocks: dict[tuple[str, str], int] = {}
        self.transfers: dict[tuple[str, str], int] = {}
        self.seconds: dict[tuple[str, str], _Hist] = {}
        self.bandwidth: dict[tuple[str, str], TierBandwidth] = {}
        for tier in TRANSFER_TIERS:
            for direction in DIRECTIONS:
                key = (tier, direction)
                self.bytes[key] = 0
                self.logical_bytes[key] = 0
                self.blocks[key] = 0
                self.transfers[key] = 0
                self.seconds[key] = _Hist(TRANSFER_SECONDS_BUCKETS)
                self.bandwidth[key] = TierBandwidth()
        # audited partition counters (tokens), keyed by HYDRATION_SOURCES
        self.hydration: dict[str, int] = {s: 0 for s in HYDRATION_SOURCES}
        self.hydrated_requests = 0
        # compute-or-load planner decisions per CHUNK (closed choice set,
        # docs/31-hydration-planner.md): load / recompute at plan time,
        # plus fallback_recompute when a load chunk misses its deadline
        # or its fetch fails. Contract counters — always on, like the
        # attribution partition.
        self.decisions: dict[str, int] = {
            c: 0 for c in mc.KV_HYDRATION_CHOICES
        }

    # -- transfer meters (togglable) ----------------------------------------

    def record(
        self, tier: str, direction: str, nbytes: int, blocks: int,
        seconds: float, logical_nbytes: int | None = None,
    ) -> None:
        """One transfer batch: `blocks` KV blocks totalling `nbytes` moved
        in `seconds` of wall time. `nbytes` is always WIRE bytes — what
        actually crossed the link or hit the disk — so the TierBandwidth
        estimators (and therefore the hydration planner) price the tier
        as it performs under the at-rest codec. `logical_nbytes` is the
        decoded size of the same batch (defaults to `nbytes` for
        uncompressed hops); the logical/wire quotient is the tier's
        compression-ratio gauge. A FAILED transfer should still be
        recorded with whatever partial batch completed (possibly 0 bytes)
        — the elapsed time is real, and losing it would overstate the
        tier's bandwidth exactly when the planner most needs the truth.

        ``enabled=False`` silences the METRIC side (bytes/blocks/latency
        counters) but the TierBandwidth estimators keep recording: they
        are the hydration planner's decision input, and starving them
        would silently disable compute-or-load (no tier could ever cross
        the sample floor). Their cost is a dict lookup + a few float ops
        per transfer — nothing next to the transfer itself."""
        if self._null:
            self.bandwidth[(tier, direction)]  # unknown key: still loud
            return
        key = (tier, direction)  # unknown tier/direction: KeyError, loud
        now = time.perf_counter()
        with self._lock:
            self.bandwidth[key].record(int(nbytes), seconds, now)
            if not self.enabled:
                return
            self.bytes[key] += int(nbytes)
            self.logical_bytes[key] += int(
                nbytes if logical_nbytes is None else logical_nbytes
            )
            self.blocks[key] += int(blocks)
            self.transfers[key] += 1
            self.seconds[key].observe(seconds)

    # -- hydration attribution (always on) ----------------------------------

    def record_hydration(
        self, counts: dict[str, int], requests: int = 1
    ) -> None:
        """One admitted request's prompt-token partition. Keys must come
        from HYDRATION_SOURCES (closed set — a typo fails loud, even at
        count 0: a mistyped key that's usually zero would otherwise drop
        tokens from the audited partition only on the rare nonzero hit).
        ``requests=0`` is the hydration planner's incremental form: a
        planned chunk's tokens are classified when its fate resolves
        (adopted → its tier's source, fallback/cancel → recomputed), so
        the partition stays exact while outcomes are still in flight."""
        with self._lock:
            for source, n in counts.items():
                self.hydration[source] += int(n)
            self.hydrated_requests += requests

    def record_decision(self, choice: str, n: int = 1) -> None:
        """One planner chunk decision (tpu:kv_hydration_decision_total).
        Closed choice set — unknown choices fail loud, like sources."""
        with self._lock:
            if choice not in self.decisions:
                raise KeyError(choice)
            self.decisions[choice] += n

    # -- reporting -----------------------------------------------------------

    def bandwidth_bytes_per_s(self) -> dict[tuple[str, str], float]:
        with self._lock:
            return {k: bw.bytes_per_s for k, bw in self.bandwidth.items()}

    def bandwidth_measured(self) -> dict[tuple[str, str], bool]:
        """Per-(tier, direction) sample-floor state — the planner's
        trust gate on each bandwidth estimate."""
        with self._lock:
            return {k: bw.measured for k, bw in self.bandwidth.items()}

    def snapshot(self) -> dict:
        """Cumulative counters + histograms + bandwidth estimates, in the
        shape EngineMetrics renders (keys are "tier/direction")."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "bytes": {f"{t}/{d}": v for (t, d), v in self.bytes.items()},
                "logical_bytes": {
                    f"{t}/{d}": v
                    for (t, d), v in self.logical_bytes.items()
                },
                # measured logical/wire ratio per key (1.0 until bytes
                # move — a ratio gauge that reads 0 would look like
                # infinite compression on dashboards)
                "compression_ratio": {
                    f"{t}/{d}": (
                        self.logical_bytes[(t, d)] / v if v > 0 else 1.0
                    )
                    for (t, d), v in self.bytes.items()
                },
                "blocks": {
                    f"{t}/{d}": v for (t, d), v in self.blocks.items()
                },
                "transfers": {
                    f"{t}/{d}": v for (t, d), v in self.transfers.items()
                },
                "seconds_hist": {
                    f"{t}/{d}": h.snapshot()
                    for (t, d), h in self.seconds.items()
                },
                "bandwidth_bytes_per_s": {
                    f"{t}/{d}": bw.bytes_per_s
                    for (t, d), bw in self.bandwidth.items()
                },
                # sample-floor state per key: the exporter gates the
                # bandwidth GAUGE on it (a sub-floor estimate is one tiny
                # transfer's noise — rendering it would let scrapers, e.g.
                # the router's migrate pricing, trust a number the planner
                # itself refuses to)
                "bandwidth_measured": {
                    f"{t}/{d}": bw.measured
                    for (t, d), bw in self.bandwidth.items()
                },
                "hydration": dict(self.hydration),
                "hydrated_requests": self.hydrated_requests,
                "decisions": dict(self.decisions),
            }


# Shared disabled singleton for tier objects constructed without an engine
# (unit tests, standalone tools): call sites never branch on `if flow:`.
NULL_FLOW = KVFlowMeter(enabled=False, _null=True)
