"""KV-hierarchy flow telemetry (docs/30-kv-flow-telemetry.md).

The tiering stack's occupancy gauges (tpu:engine_kv_tier_usage_perc) say
how FULL each tier is; this module measures how fast bytes actually MOVE
between tiers and where each request's prefix actually came from — the
measurement substrate the compute-or-load hydration planner (ROADMAP
item 3, "Compute Or Load KV Cache? Why Not Both?") needs before it can
pick load-vs-recompute per chunk by measured fetch bandwidth vs prefill
FLOP/s.

Two instruments, mirroring the PR 6 StepMeter/GoodputLedger split:

- **Transfer meters** (togglable, ``--kv-flow-metering false``): every
  tier move — host-ring offload/reload, disk store/load, remote
  put/fetch, device-path PD transfer — records bytes, blocks and wall
  latency into per-(tier, direction) counters, a fixed-bucket latency
  histogram, and a :class:`TierBandwidth` recent-mean estimator. Plain
  ints under one small lock (transfers are orders of magnitude rarer
  than steps); the exporter renders histograms from cumulative bucket
  counts at scrape time, so no prometheus objects ride the engine or
  writer threads.

- **Hydration attribution** (always on, like the goodput ledger — its
  counters are part of the metric contract): every admitted request's
  prompt tokens are classified EXACTLY once by KV origin, and the
  partition is audited::

      hbm_hit + host_reload + disk_load + remote_fetch + recomputed
          == prompt_tokens

Direction semantics: ``"in"`` moves bytes toward the HBM pool
(hydration — reload/load/fetch/PD-adopt), ``"out"`` moves them away
(offload — store/put/PD-export). ``tier`` names the non-HBM side of the
hop, so a disk block promoted through the ring into HBM records one
``disk/in`` sample (disk → RAM) and one ``host/in`` sample (RAM → HBM):
per-tier meters count HOPS, not end-to-end journeys.
"""

from __future__ import annotations

import math
import threading
import time

from .. import metrics_contract as mc
from .saturation import _Hist

TRANSFER_TIERS = mc.KV_TRANSFER_TIERS
DIRECTIONS = mc.KV_TRANSFER_DIRECTIONS
HYDRATION_SOURCES = mc.KV_HYDRATION_SOURCES

# wall seconds per transfer batch: spans sub-µs host copies to multi-second
# remote fetches over a cold link
TRANSFER_SECONDS_BUCKETS = (
    1e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

_BW_TAU_S = 60.0  # horizon of the recent-mean bandwidth estimator


class TierBandwidth:
    """Time-decayed recent-mean bandwidth for one (tier, direction).

    Separate exponentially-decayed accumulators for bytes and busy
    seconds, decayed by the WALL gap since the previous sample; the
    estimate is their ratio. For a burst of back-to-back transfers this
    converges to total_bytes / total_seconds (a plain duration-weighted
    mean — robust to microsecond samples where a per-sample-value EWMA
    would be noise), while samples older than ~:data:`_BW_TAU_S` fade
    out. The estimate deliberately does NOT decay toward zero when idle:
    it answers "how fast CAN this tier move bytes", a capability the
    hydration planner consults exactly when the tier has been idle —
    unlike the occupancy EWMAs, which measure utilization and must fall.

    A failed transfer recorded as (0 bytes, elapsed) drags the estimate
    toward zero honestly: during a remote-store outage the measured
    fetch bandwidth IS ~0, which is precisely what should flip the
    planner to recompute.
    """

    __slots__ = ("_bytes", "_seconds", "_last_t", "samples")

    def __init__(self) -> None:
        self._bytes = 0.0
        self._seconds = 0.0
        self._last_t: float | None = None
        self.samples = 0

    def record(self, nbytes: int, seconds: float, now: float) -> None:
        if self._last_t is not None:
            decay = math.exp(-max(0.0, now - self._last_t) / _BW_TAU_S)
            self._bytes *= decay
            self._seconds *= decay
        self._last_t = now
        self._bytes += nbytes
        self._seconds += max(seconds, 1e-9)
        self.samples += 1

    @property
    def bytes_per_s(self) -> float:
        return self._bytes / self._seconds if self._seconds > 0 else 0.0


class KVFlowMeter:
    """Per-tier transfer meters + per-request hydration attribution.

    One instance per engine, shared by every tier object (host ring,
    disk tier, remote client, device-path transfer) — the engine thread,
    the remote writer thread and HTTP executor threads all record here,
    so mutation happens under ``_lock``. ``enabled=False`` turns
    :meth:`record` into a no-op (the bench's ``kvflow`` phase measures
    the difference); hydration attribution stays on regardless, because
    its counters are contract series the dashboard's hydration panel
    keys off (same always-on rule as the goodput ledger).
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self.bytes: dict[tuple[str, str], int] = {}
        self.blocks: dict[tuple[str, str], int] = {}
        self.transfers: dict[tuple[str, str], int] = {}
        self.seconds: dict[tuple[str, str], _Hist] = {}
        self.bandwidth: dict[tuple[str, str], TierBandwidth] = {}
        for tier in TRANSFER_TIERS:
            for direction in DIRECTIONS:
                key = (tier, direction)
                self.bytes[key] = 0
                self.blocks[key] = 0
                self.transfers[key] = 0
                self.seconds[key] = _Hist(TRANSFER_SECONDS_BUCKETS)
                self.bandwidth[key] = TierBandwidth()
        # audited partition counters (tokens), keyed by HYDRATION_SOURCES
        self.hydration: dict[str, int] = {s: 0 for s in HYDRATION_SOURCES}
        self.hydrated_requests = 0

    # -- transfer meters (togglable) ----------------------------------------

    def record(
        self, tier: str, direction: str, nbytes: int, blocks: int,
        seconds: float,
    ) -> None:
        """One transfer batch: `blocks` KV blocks totalling `nbytes` moved
        in `seconds` of wall time. A FAILED transfer should still be
        recorded with whatever partial batch completed (possibly 0 bytes)
        — the elapsed time is real, and losing it would overstate the
        tier's bandwidth exactly when the planner most needs the truth."""
        if not self.enabled:
            return
        key = (tier, direction)  # unknown tier/direction: KeyError, loud
        now = time.perf_counter()
        with self._lock:
            self.bytes[key] += int(nbytes)
            self.blocks[key] += int(blocks)
            self.transfers[key] += 1
            self.seconds[key].observe(seconds)
            self.bandwidth[key].record(int(nbytes), seconds, now)

    # -- hydration attribution (always on) ----------------------------------

    def record_hydration(self, counts: dict[str, int]) -> None:
        """One admitted request's prompt-token partition. Keys must come
        from HYDRATION_SOURCES (closed set — a typo fails loud, even at
        count 0: a mistyped key that's usually zero would otherwise drop
        tokens from the audited partition only on the rare nonzero hit)."""
        with self._lock:
            for source, n in counts.items():
                self.hydration[source] += int(n)
            self.hydrated_requests += 1

    # -- reporting -----------------------------------------------------------

    def bandwidth_bytes_per_s(self) -> dict[tuple[str, str], float]:
        with self._lock:
            return {k: bw.bytes_per_s for k, bw in self.bandwidth.items()}

    def snapshot(self) -> dict:
        """Cumulative counters + histograms + bandwidth estimates, in the
        shape EngineMetrics renders (keys are "tier/direction")."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "bytes": {f"{t}/{d}": v for (t, d), v in self.bytes.items()},
                "blocks": {
                    f"{t}/{d}": v for (t, d), v in self.blocks.items()
                },
                "transfers": {
                    f"{t}/{d}": v for (t, d), v in self.transfers.items()
                },
                "seconds_hist": {
                    f"{t}/{d}": h.snapshot()
                    for (t, d), h in self.seconds.items()
                },
                "bandwidth_bytes_per_s": {
                    f"{t}/{d}": bw.bytes_per_s
                    for (t, d), bw in self.bandwidth.items()
                },
                "hydration": dict(self.hydration),
                "hydrated_requests": self.hydrated_requests,
            }


# Shared disabled singleton for tier objects constructed without an engine
# (unit tests, standalone tools): call sites never branch on `if flow:`.
NULL_FLOW = KVFlowMeter(enabled=False)
