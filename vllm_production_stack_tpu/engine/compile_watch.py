"""XLA compile telemetry (docs/42-compile-telemetry.md).

The pad-up program cache (model_runner) exists so that serving never
stalls on a mid-traffic XLA compile — but until now nothing *watched*
whether that guarantee held in production. A shape that escapes the
bucket ladder freezes every decode stream for the compile wall and
reads as an anonymous latency spike. ``CompileWatch`` is the missing
observer: every program build lands here with its cache key, wall
time, and trigger class, and flows out three ways —

* a bounded program inventory served at ``GET /debug/programs``
  (key, compile wall, dispatch count, last-used age, HBM footprint
  from ``compiled.memory_analysis()`` where the backend provides it);
* the flight recorder ring and the blocked request's trace timeline
  (``compile_stall`` events name the request a sync compile blocked);
* contract series ``tpu:engine_compiles_total{phase,trigger}``,
  ``tpu:engine_compile_seconds``, the program-cache gauge and
  hit/miss counters, and ``tpu:engine_compile_storms_total``.

Trigger classes:

* ``warmup`` — builds during ``engine.warmup()`` / ``precompile_
  dominating()`` (fallback disabled, or explicitly tagged). Expected.
* ``bg`` — the background AOT thread absorbing a pad-up fallback.
  Expected; never blocks a request.
* ``mid_traffic`` — a synchronous compile on the dispatch path with
  fallbacks enabled: a shape no compiled program dominates. This is
  the failure the bucket ladder exists to prevent; each one stalls
  the batch it was dispatched for.

The recompile-storm detector follows the watchdog-episode idiom
(flightrec.Watchdog): a sliding window over mid-traffic builds,
edge-triggered — crossing the threshold emits ONE structured report
naming the offending shapes and bumps the storm counter once; the
episode re-arms only after the window drains below threshold.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict, deque

from .flightrec import redact

logger = logging.getLogger(__name__)

# inventory entries kept; FIFO-evicted beyond this. 256 programs is ~4x
# a full warmup lattice (bucket ladder x variants x grammar keys) — a
# healthy engine never evicts; an evicting inventory is itself a signal
DEFAULT_CAPACITY = 256

# compile-wall observations buffered between stats() drains (the
# grammar_build_times idiom in engine.metrics): bounded so a scrape
# outage cannot grow the list without limit
_MAX_PENDING_WALLS = 1024

# storm-report shape list cap — a pathological storm names the top
# offenders, not an unbounded dump
_REPORT_SHAPE_CAP = 16

PHASES = ("prefill", "decode", "verify", "grammar")
TRIGGERS = ("warmup", "bg", "mid_traffic")

# grammar-table builds are numpy-side (not XLA programs): they appear
# in the inventory and compile counters but never count toward the
# program-cache hit/miss ratio or the storm window
_STORM_PHASES = ("prefill", "decode", "verify")


class CompileWatch:
    """Thread-safe recorder for program builds and cache dispatches.

    One instance is shared by the target runner and (when spec decode
    runs a draft model) the PR 14 draft runner — entries carry a
    ``role`` tag so ``/debug/programs`` tells the two caches apart.
    ``enabled=False`` turns every method into a cheap early return
    (the ``--compile-watch false`` path; bench pins the overhead of
    the *enabled* path at the noise floor too).
    """

    def __init__(
        self,
        enabled: bool = True,
        storm_threshold: int = 6,
        storm_window_s: float = 300.0,
        capacity: int = DEFAULT_CAPACITY,
        recorder=None,
        clock=time.monotonic,
    ):
        self.enabled = bool(enabled)
        self.storm_threshold = int(storm_threshold)
        self.storm_window_s = float(storm_window_s)
        self.capacity = int(capacity)
        self.recorder = recorder  # FlightRecorder | None
        self._clock = clock  # injectable for window-arithmetic tests
        self._lock = threading.Lock()
        # (role, key_str) -> inventory entry dict; FIFO-bounded
        self._inventory: OrderedDict[tuple, dict] = OrderedDict()
        # "phase/trigger" -> monotonic count (exporter reads deltas)
        self.compiles: dict[str, int] = {}
        self._pending_walls: list[float] = []
        self.cache_hits = 0
        self.cache_misses = 0
        self.storms_total = 0
        # sliding window of (t, shape_str) mid-traffic builds
        self._storm_events: deque = deque()
        self._in_storm = False  # edge flag: one report per episode
        self.last_storm_report: dict | None = None

    # -- writing (model_runner) -------------------------------------------

    def record_build(
        self,
        phase: str,
        key: tuple,
        wall_s: float,
        trigger: str,
        *,
        rid: str | None = None,
        role: str = "target",
        memory_bytes: int | None = None,
    ) -> None:
        """One program (or grammar-table) build landed."""
        if not self.enabled:
            return
        now = self._clock()
        key_str = _key_str(key)
        with self._lock:
            entry = self._inventory.get((role, key_str))
            if entry is None:
                entry = {
                    "key": key_str,
                    "phase": phase,
                    "role": role,
                    "trigger": trigger,
                    "compile_wall_s": round(float(wall_s), 4),
                    "dispatches": 0,
                    "built_t": now,
                    "last_used_t": now,
                    "rid": rid,
                    "hbm_bytes": memory_bytes,
                }
                self._inventory[(role, key_str)] = entry
                while len(self._inventory) > self.capacity:
                    self._inventory.popitem(last=False)
            else:
                # re-build of a known key (cache dropped and re-filled):
                # keep the freshest wall/trigger, it is the live program
                entry.update(
                    trigger=trigger,
                    compile_wall_s=round(float(wall_s), 4),
                    rid=rid or entry.get("rid"),
                )
                if memory_bytes is not None:
                    entry["hbm_bytes"] = memory_bytes
            ck = f"{phase}/{trigger}"
            self.compiles[ck] = self.compiles.get(ck, 0) + 1
            if len(self._pending_walls) < _MAX_PENDING_WALLS:
                self._pending_walls.append(float(wall_s))
            storm_hit = (
                trigger == "mid_traffic" and phase in _STORM_PHASES
            )
            if storm_hit:
                self._storm_events.append((now, key_str))
        rec = self.recorder
        if rec is not None:
            if trigger == "mid_traffic":
                rec.note(
                    "compile_stall", phase=phase, key=key_str,
                    wall_ms=round(wall_s * 1000.0, 1), rid=rid, role=role,
                )
            else:
                rec.note(
                    "compile_build", phase=phase, key=key_str,
                    wall_ms=round(wall_s * 1000.0, 1), trigger=trigger,
                    role=role,
                )
        if storm_hit:
            self._check_storm(now)

    def record_dispatch(self, served_key: tuple, hit: bool,
                        role: str = "target") -> None:
        """A dispatch was served: ``hit`` means the EXACT requested key
        was already compiled (no fallback, no sync compile)."""
        if not self.enabled:
            return
        with self._lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1
            entry = self._inventory.get((role, _key_str(served_key)))
            if entry is not None:
                entry["dispatches"] += 1
                entry["last_used_t"] = self._clock()

    # -- storm detection ---------------------------------------------------

    def _check_storm(self, now: float) -> None:
        """Edge-triggered sliding-window detector (watchdog-episode
        idiom): one report + one counter bump per episode."""
        report = None
        with self._lock:
            horizon = now - self.storm_window_s
            while self._storm_events and self._storm_events[0][0] < horizon:
                self._storm_events.popleft()
            n = len(self._storm_events)
            if n >= self.storm_threshold and not self._in_storm:
                self._in_storm = True
                self.storms_total += 1
                shapes: dict[str, int] = {}
                for _, s in self._storm_events:
                    shapes[s] = shapes.get(s, 0) + 1
                top = sorted(
                    shapes.items(), key=lambda kv: -kv[1]
                )[:_REPORT_SHAPE_CAP]
                report = {
                    "event": "compile_storm",
                    "mid_traffic_compiles": n,
                    "window_s": self.storm_window_s,
                    "threshold": self.storm_threshold,
                    "shapes": [
                        {"key": s, "compiles": c} for s, c in top
                    ],
                }
                self.last_storm_report = report
            elif n < self.storm_threshold:
                self._in_storm = False  # episode over; re-arm
        if report is not None:
            logger.warning(
                "recompile storm: %d mid-traffic compiles in %.0fs — "
                "shapes escaping the bucket ladder: %s",
                report["mid_traffic_compiles"], report["window_s"],
                json.dumps(redact(report)),
            )
            rec = self.recorder
            if rec is not None:
                rec.note("compile_storm", **{
                    "mid_traffic_compiles": report["mid_traffic_compiles"],
                    "shapes": [s["key"] for s in report["shapes"]],
                })

    # -- reading (exporter / debug / stats) --------------------------------

    def stats_snapshot(self) -> dict:
        """Per-stats()-call snapshot for EngineStatsSnapshot.compile.
        Drains the pending wall-clock list (the grammar_build_times
        idiom — each observation is exported exactly once)."""
        if not self.enabled:
            return {"enabled": False}
        with self._lock:
            walls, self._pending_walls = self._pending_walls, []
            return {
                "enabled": True,
                "programs": len(self._inventory),
                "compiles": dict(self.compiles),
                "hits": self.cache_hits,
                "misses": self.cache_misses,
                "storms": self.storms_total,
                "walls": walls,
                "mid_traffic": sum(
                    v for k, v in self.compiles.items()
                    if k.endswith("/mid_traffic")
                ),
            }

    def debug_payload(self) -> dict:
        """GET /debug/programs body."""
        now = self._clock()
        with self._lock:
            programs = [
                {
                    "key": e["key"],
                    "phase": e["phase"],
                    "role": e["role"],
                    "trigger": e["trigger"],
                    "compile_wall_s": e["compile_wall_s"],
                    "dispatches": e["dispatches"],
                    "last_used_age_s": round(now - e["last_used_t"], 1),
                    "rid": e["rid"],
                    "hbm_bytes": e["hbm_bytes"],
                }
                for e in self._inventory.values()
            ]
            return {
                "enabled": self.enabled,
                "programs": programs,
                "capacity": self.capacity,
                "compiles": dict(self.compiles),
                "cache": {
                    "hits": self.cache_hits,
                    "misses": self.cache_misses,
                },
                "storm": {
                    "threshold": self.storm_threshold,
                    "window_s": self.storm_window_s,
                    "total": self.storms_total,
                    "window_events": len(self._storm_events),
                    "last_report": self.last_storm_report,
                },
            }


def _key_str(key: tuple) -> str:
    """Stable human-readable form of a program cache key."""
    return repr(tuple(key))


def program_memory_bytes(compiled) -> int | None:
    """Best-effort HBM footprint of a compiled executable via
    ``memory_analysis()`` — absent on some backends/versions, so every
    failure degrades to None rather than breaking the compile path."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    total = 0
    seen = False
    for attr in (
        "generated_code_size_in_bytes",
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
    ):
        v = getattr(ma, attr, None)
        if isinstance(v, int):
            total += v
            seen = True
    return total if seen else None
