"""KV page transfer between engines (disaggregated prefill fabric).

The reference ships KV pages prefill→decode with NIXL over UCX
(GPU-direct/RDMA when available, TCP otherwise — deployment-vllm-multi.yaml:
267-305, values-16-disagg-prefill.yaml). The TPU stack's transfer is
content-addressed: a prompt's full blocks are identified by the same chain
hashes the prefix cache uses, so "shipping KV" is exporting (hash, pages)
pairs from the prefill engine's pool and adopting them into the decode
engine's pool — after which the decode request is an ordinary 100% prefix
hit.

Transports: this module defines the wire format (npz: hashes as uint64
hi/lo pairs + one stacked page tensor) served over the engines' HTTP
surface (/kv/export, /kv/import, /kv/pull). On multi-slice TPU deployments
the same export/adopt protocol can ride jax device-to-device transfers over
ICI instead of host-staged HTTP — the pool-side bookkeeping (this module)
is transport-agnostic, exactly like the reference's NIXL sender/receiver
split from LMCache's bookkeeping.
"""

from __future__ import annotations

import io

import numpy as np

from ..utils.logging import init_logger

logger = init_logger(__name__)


def serialize_blocks(
    hashes: list[int], blocks: np.ndarray, fingerprint: str = ""
) -> bytes:
    """npz payload: N 128-bit chain hashes (as (N, 2) uint64 hi/lo), the
    stacked page tensor (N, L, 2, block_size, kvH, D), and the sender's
    model fingerprint."""
    hi_lo = np.array(
        [(h >> 64, h & 0xFFFFFFFFFFFFFFFF) for h in hashes], dtype=np.uint64
    ).reshape(-1, 2)
    buf = io.BytesIO()
    # ml_dtypes (bf16, fp8 pools) aren't npz-portable everywhere; ship as
    # same-width unsigned bit patterns and re-view on the other side
    if blocks.dtype.name == "bfloat16":
        view = blocks.view(np.uint16)
    elif blocks.dtype.name == "float8_e4m3fn":
        view = blocks.view(np.uint8)
    else:
        view = blocks
    np.savez(
        buf, hashes=hi_lo, blocks=view, dtype=np.array(blocks.dtype.name),
        fingerprint=np.array(fingerprint),
    )
    return buf.getvalue()


def deserialize_blocks(payload: bytes) -> tuple[list[int], np.ndarray, str]:
    with np.load(io.BytesIO(payload)) as z:
        hi_lo = z["hashes"]
        blocks = z["blocks"]
        dtype = str(z["dtype"])
        fingerprint = str(z["fingerprint"]) if "fingerprint" in z else ""
    if dtype == "bfloat16":
        import ml_dtypes

        blocks = blocks.view(ml_dtypes.bfloat16)
    elif dtype == "float8_e4m3fn":
        import ml_dtypes

        blocks = blocks.view(ml_dtypes.float8_e4m3fn)
    hashes = [int(hi) << 64 | int(lo) for hi, lo in hi_lo]
    return hashes, blocks, fingerprint


class KVTransfer:
    """Pool-side export/adopt bookkeeping, bound to one engine's scheduler
    pool + runner. All methods assume the caller holds the engine lock."""

    def __init__(self, pool, runner):
        self.pool = pool
        self.runner = runner

    def block_shape(self) -> tuple[int, ...]:
        """(L, 2, block_size, kvH, D) — the only page geometry this engine
        can adopt."""
        leaf = self.runner.kv_caches[0]
        return (len(self.runner.kv_caches), 2, leaf.shape[2], *leaf.shape[3:])

    def export_prompt(
        self, token_ids: list[int], parent: int | None = None
    ) -> tuple[list[int], np.ndarray]:
        """(hashes, pages) for the prompt's HBM-resident full blocks —
        called on the prefill engine right after its max_tokens=1 pass.
        All fetches dispatch before any resolves, so the device→host copies
        pipeline instead of serializing under the engine lock."""
        root = self.pool.root_hash() if parent is None else parent
        pending: list[tuple[int, list]] = []
        for h in self.pool._chain(list(token_ids), root):
            blk = self.pool._hash_to_block.get(h)
            if blk is None:
                break
            pending.append((h, self.runner.fetch_block(blk)))
        if not pending:
            return [], np.empty((0,))
        hashes = [h for h, _ in pending]
        data = [
            np.stack([np.asarray(p) for p in parts]) for _, parts in pending
        ]
        return hashes, np.stack(data)

    def import_blocks(self, hashes: list[int], blocks: np.ndarray) -> int:
        """Adopt shipped pages into this engine's pool as evictable cached
        blocks. Returns blocks actually adopted (already-resident and
        pool-full blocks are skipped; a partial import still shortens the
        decode engine's recompute)."""
        want = self.block_shape()
        if len(hashes) and tuple(blocks.shape[1:]) != want:
            raise ValueError(
                f"KV page geometry mismatch: got {tuple(blocks.shape[1:])}, "
                f"this engine needs {want}"
            )
        adopted = 0
        for h, data in zip(hashes, blocks):
            if h in self.pool._hash_to_block:
                continue
            blk = self.pool.allocate()
            if blk is None:
                break
            try:
                self.runner.upload_block(blk, data)
            except Exception:
                self.pool.free_block(blk)  # don't leak the block on failure
                raise
            self.pool._hash_to_block[h] = blk
            self.pool._block_to_hash[blk] = h
            # park as an evictable cached block (refcount 0, addressable)
            self.pool.free_block(blk)
            adopted += 1
        return adopted
