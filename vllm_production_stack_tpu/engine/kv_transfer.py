"""KV page transfer between engines (disaggregated prefill fabric).

The reference ships KV pages prefill→decode with NIXL over UCX
(GPU-direct/RDMA when available, TCP otherwise — deployment-vllm-multi.yaml:
267-305, values-16-disagg-prefill.yaml). The TPU stack's transfer is
content-addressed: a prompt's full blocks are identified by the same chain
hashes the prefix cache uses, so "shipping KV" is exporting (hash, pages)
pairs from the prefill engine's pool and adopting them into the decode
engine's pool — after which the decode request is an ordinary 100% prefix
hit.

Transports: this module defines the wire format (self-delimiting
dtype-tagged frames — the same framing the kvstore and peer paths use)
served over the engines' HTTP surface (/kv/export, /kv/import, /kv/pull).
On multi-slice TPU deployments
the same export/adopt protocol can ride jax device-to-device transfers over
ICI instead of host-staged HTTP — the pool-side bookkeeping (this module)
is transport-agnostic, exactly like the reference's NIXL sender/receiver
split from LMCache's bookkeeping.
"""

from __future__ import annotations

import io
import json
import struct

import numpy as np

from .kv_codec import (  # noqa: F401  (np_dtype_from_name re-exported)
    EncodedKVBlock,
    KVDtypeError,
    decode_payload,
    np_dtype_from_name,
)
from ..utils.logging import init_logger

logger = init_logger(__name__)


# -- streaming wire format ---------------------------------------------------
#
# The fast PD path: instead of staging the whole prompt's KV as one npz
# (hundreds of MB at 32k context — VERDICT r2 weak #3), blocks travel as
# self-delimiting frames over a chunked HTTP response:
#     4-byte LE header length | JSON header | raw page bytes
# so the receiver can adopt block i while block i+1 is still in flight, and
# the sender can stream device→host copies straight onto the socket without
# ever materializing the full tensor.


def raw_frame(
    h, raw: bytes, dtype_name: str, shape: list[int],
    codec: str = "", group: int = 0, scale_nbytes: int = 0,
) -> bytes:
    """Frame pre-serialized block bytes (the kvstore server streams stored
    payloads without reconstructing arrays). `dtype`/`shape` are always the
    LOGICAL geometry; when `codec` is set the payload is at-rest encoded
    (int4 scales||codes or fp8 bytes) and the extra header fields carry
    what FrameParser needs to dequantize it."""
    head = {
        "hash": str(h),
        "dtype": dtype_name,
        "shape": list(shape),
        "nbytes": len(raw),
    }
    if codec:
        head["codec"] = codec
        head["group"] = int(group)
        head["scale_nbytes"] = int(scale_nbytes)
    head_b = json.dumps(head).encode()
    return struct.pack("<I", len(head_b)) + head_b + raw


def block_frame(h: int, arr: np.ndarray) -> bytes:
    """One streamed KV block. The raw bytes are the array's own buffer (one
    tobytes copy — no npz container, no re-stacking)."""
    view = np.ascontiguousarray(arr)
    return raw_frame(h, view.tobytes(), arr.dtype.name, list(arr.shape))


def encoded_frame(h: int, obj) -> bytes:
    """One streamed at-rest block: EncodedKVBlock frames carry their codec
    metadata, plain ndarrays degrade to block_frame — tier writers call
    this with whatever form the block is in (a ring-encoded block flows to
    disk/remote WITHOUT a decode+re-encode round trip)."""
    if isinstance(obj, EncodedKVBlock):
        return raw_frame(
            h, obj.payload, obj.dtype, list(obj.shape),
            codec=obj.codec, group=obj.group, scale_nbytes=obj.scale_nbytes,
        )
    return block_frame(h, obj)


class FrameParser:
    """Incremental parser for the streamed format: feed() network chunks in,
    get complete (hash, array) blocks out.

    `max_frame_bytes` bounds both the JSON header and the payload a single
    frame may claim (default 256 MiB — far above any real KV block, far below
    anything that could exhaust RAM). A corrupted or hostile stream claiming
    a huge frame fails fast with ValueError instead of making the receiver
    buffer the entire remaining response as residual bytes."""

    def __init__(
        self, max_frame_bytes: int = 256 << 20, decode_codec: bool = True
    ):
        self._buf = bytearray()
        self.max_frame_bytes = max_frame_bytes
        # first parse failure in partial mode (feed_partial); once set,
        # the parser is dead — further feeds return nothing
        self.error: Exception | None = None
        # decode_codec=True (default): codec-tagged frames dequantize to
        # logical arrays right here, so every legacy consumer keeps
        # seeing ndarrays. False: they come back as EncodedKVBlock and
        # the dequant is deferred to the pool's adopt boundary (the
        # fetch paths use this — landed chunks hold WIRE bytes in RAM).
        self.decode_codec = decode_codec
        # (wire payload bytes, logical bytes) per yielded frame, in
        # yield order — the flow meter's wire-vs-logical accounting
        self.frame_meta: list[tuple[int, int]] = []

    def _next_frame(self) -> tuple[int, np.ndarray] | None:
        """Parse ONE complete frame off the buffer, None if the buffered
        bytes don't yet hold a whole frame. Raises on a corrupt frame."""
        if len(self._buf) < 4:
            return None
        head_len = struct.unpack_from("<I", self._buf)[0]
        if head_len > self.max_frame_bytes:
            raise ValueError(
                f"frame header claims {head_len} bytes "
                f"(max {self.max_frame_bytes}) — corrupt stream"
            )
        if len(self._buf) < 4 + head_len:
            return None
        head = json.loads(bytes(self._buf[4 : 4 + head_len]))
        nbytes = int(head["nbytes"])
        if nbytes < 0 or nbytes > self.max_frame_bytes:
            raise ValueError(
                f"frame payload claims {nbytes} bytes "
                f"(max {self.max_frame_bytes}) — corrupt stream"
            )
        total = 4 + head_len + nbytes
        if len(self._buf) < total:
            return None
        raw = bytes(self._buf[4 + head_len : total])
        del self._buf[:total]
        codec = head.get("codec", "")
        if codec:
            enc = EncodedKVBlock(
                codec, int(head.get("group", 0)), head["dtype"],
                tuple(int(d) for d in head["shape"]), raw,
                int(head.get("scale_nbytes", 0)),
            )
            # resolve the logical dtype NOW even on the deferred path: a
            # frame this host can't decode must die in the parser (clean
            # degraded miss) rather than at adopt time on the step thread
            self.frame_meta.append((len(raw), enc.logical_nbytes))
            if self.decode_codec:
                return (int(head["hash"]), decode_payload(
                    codec, enc.group, enc.dtype, enc.shape, raw,
                    enc.scale_nbytes,
                ))
            return (int(head["hash"]), enc)
        arr = np.frombuffer(
            raw, dtype=np_dtype_from_name(head["dtype"])
        ).reshape(head["shape"])
        self.frame_meta.append((len(raw), arr.nbytes))
        return (int(head["hash"]), arr)

    def feed(self, data: bytes) -> list[tuple[int, np.ndarray]]:
        self._buf.extend(data)
        out: list[tuple[int, np.ndarray]] = []
        while (frame := self._next_frame()) is not None:
            out.append(frame)
        return out

    def feed_partial(self, data: bytes) -> list[tuple[int, np.ndarray]]:
        """Like feed(), but a corrupt frame KEEPS the frames completed
        before it instead of discarding the whole batch: the valid prefix
        is real data (the remote fetch path promotes it — losing it would
        turn a one-frame corruption into a full-run cache miss, and lose
        the timing of blocks that actually moved). `self.error` carries
        the failure; the parser is dead afterwards."""
        if self.error is not None:
            return []
        self._buf.extend(data)
        out: list[tuple[int, np.ndarray]] = []
        while True:
            try:
                frame = self._next_frame()
            except Exception as e:  # corrupt header/payload claim/dtype
                self.error = e
                break
            if frame is None:
                break
            out.append(frame)
        return out

    @property
    def residual(self) -> int:
        return len(self._buf)


def serialize_blocks(
    hashes: list[int], blocks: np.ndarray, fingerprint: str = ""
) -> bytes:
    """One-shot export payload (/kv/export → /kv/import): a JSON manifest
    frame {fingerprint, count} followed by one dtype-tagged block frame
    per hash — the SAME framing the kvstore/mget/peer paths speak, so
    ml_dtypes pools (bf16, fp8) ship natively instead of through the old
    npz detour's uint bit-pattern views (npz can't carry ml_dtypes)."""
    manifest = json.dumps({
        "fingerprint": fingerprint, "count": len(hashes),
    }).encode()
    frames = [struct.pack("<I", len(manifest)) + manifest]
    frames.extend(block_frame(h, arr) for h, arr in zip(hashes, blocks))
    return b"".join(frames)


def deserialize_blocks(payload: bytes) -> tuple[list[int], np.ndarray, str]:
    if payload[:2] == b"PK":  # legacy npz export from a pre-frame sender
        return _deserialize_blocks_npz(payload)
    head_len = struct.unpack_from("<I", payload)[0]
    manifest = json.loads(payload[4 : 4 + head_len])
    frames = FrameParser().feed(payload[4 + head_len:])
    if len(frames) != int(manifest.get("count", len(frames))):
        raise ValueError(
            f"KV export payload truncated: manifest promises "
            f"{manifest.get('count')} blocks, parsed {len(frames)}"
        )
    hashes = [h for h, _ in frames]
    if not frames:
        return [], np.empty((0,)), str(manifest.get("fingerprint", ""))
    blocks = np.stack([arr for _, arr in frames])
    return hashes, blocks, str(manifest.get("fingerprint", ""))


def _deserialize_blocks_npz(payload: bytes):
    """Read the pre-frame npz export format (rolling-upgrade peers)."""
    with np.load(io.BytesIO(payload)) as z:
        hi_lo = z["hashes"]
        blocks = z["blocks"]
        dtype = str(z["dtype"])
        fingerprint = str(z["fingerprint"]) if "fingerprint" in z else ""
    if dtype in ("bfloat16", "float8_e4m3fn"):
        blocks = blocks.view(np_dtype_from_name(dtype))
    hashes = [int(hi) << 64 | int(lo) for hi, lo in hi_lo]
    return hashes, blocks, fingerprint


def engine_block_shape(runner) -> tuple[int, ...]:
    """(L, 2, block_size, kvH, D) — the page geometry of one engine's pool.
    The ONE definition shared by import validation (KVTransfer), remote-match
    validation (KVBlockPool.expected_block_shape) and the stream receiver's
    frame-size bound: a layout change lands everywhere or nowhere."""
    leaf = runner.kv_caches[0]
    return (len(runner.kv_caches), 2, leaf.shape[2], *leaf.shape[3:])


def engine_block_nbytes(runner) -> int:
    """Bytes of one KV block as stored in this engine's pool."""
    shape = engine_block_shape(runner)
    n = 1
    for d in shape:
        n *= int(d)
    return n * runner.kv_caches[0].dtype.itemsize


class KVTransfer:
    """Pool-side export/adopt bookkeeping, bound to one engine's scheduler
    pool + runner. All methods assume the caller holds the engine lock."""

    def __init__(self, pool, runner):
        self.pool = pool
        self.runner = runner

    def block_shape(self) -> tuple[int, ...]:
        """(L, 2, block_size, kvH, D) — the only page geometry this engine
        can adopt."""
        return engine_block_shape(self.runner)

    def export_prompt(
        self, token_ids: list[int], parent: int | None = None
    ) -> tuple[list[int], np.ndarray]:
        """(hashes, pages) for the prompt's HBM-resident full blocks —
        called on the prefill engine right after its max_tokens=1 pass.
        All fetches dispatch before any resolves, so the device→host copies
        pipeline instead of serializing under the engine lock."""
        root = self.pool.root_hash() if parent is None else parent
        pending: list[tuple[int, list]] = []
        for h in self.pool._chain(list(token_ids), root):
            blk = self.pool._hash_to_block.get(h)
            if blk is None:
                break
            pending.append((h, self.runner.fetch_block(blk)))
        if not pending:
            return [], np.empty((0,))
        hashes = [h for h, _ in pending]
        data = [
            np.stack([np.asarray(p) for p in parts]) for _, parts in pending
        ]
        return hashes, np.stack(data)

    def export_prompt_lazy(
        self, token_ids: list[int], parent: int | None = None
    ) -> tuple[list[int], list[list]]:
        """(hashes, per-block device slices) for the prompt's resident full
        blocks — the STREAMING sender path. Only dispatches the device→host
        copies (fast, under the engine lock); the caller resolves each
        block's numpy OFF the lock while writing earlier blocks to the
        socket, so transfer pipelines with both the copies and decode."""
        root = self.pool.root_hash() if parent is None else parent
        hashes: list[int] = []
        parts: list[list] = []
        for h in self.pool._chain(list(token_ids), root):
            blk = self.pool._hash_to_block.get(h)
            if blk is None:
                break
            hashes.append(h)
            parts.append(self.runner.fetch_block(blk))
        return hashes, parts

    def contains_hashes(self, hashes: list[int]) -> int:
        """How many of `hashes` (in order, consecutively) this engine can
        serve from its local tiers (HBM + host ring + disk) — the
        /kv/peer_contains probe (docs/35-peer-kv-reuse.md). No data
        moves; pure GIL-atomic dict/containment walks, callable with OR
        without the engine lock (the probe is staleness-tolerant — the
        fetch/adoption path re-validates everything)."""
        host = self.pool.host_tier
        n = 0
        for h in hashes:
            if h in self.pool._hash_to_block or (
                host is not None and h in host
            ):
                n += 1
            else:
                break
        return n

    def export_hashes(self, hashes: list[int]):
        """(served, entries) for the consecutive locally-resident prefix of
        an ARBITRARY hash run — the /kv/peer_fetch sender half. Under the
        engine lock this only dispatches device→host copies (HBM blocks)
        and grabs ring references; entries resolve to numpy OFF the lock:

        - ("dev", parts)  — HBM block, per-layer device slices in flight
        - ("np", array)   — host-ring bytes, already resolved
        - ("disk", hash)  — disk-resident; the caller loads the file off
          the lock (DiskKVTier is fetch-thread-safe) so a multi-MB read
          never stalls the step thread's admissions
        """
        host = self.pool.host_tier
        served: list[int] = []
        entries: list[tuple[str, object]] = []
        for h in hashes:
            blk = self.pool._hash_to_block.get(h)
            if blk is not None:
                entries.append(("dev", self.runner.fetch_block(blk)))
            elif host is not None and len(host) and h in host._data:
                arr = host.peek_bytes(h)
                if arr is None:
                    break
                entries.append(("np", arr))
            elif (
                host is not None
                and host.disk is not None
                and h in host.disk
            ):
                entries.append(("disk", h))
            else:
                break
            served.append(h)
        return served, entries

    def import_blocks(self, hashes: list[int], blocks: np.ndarray) -> int:
        """Adopt shipped pages into this engine's pool as evictable cached
        blocks. Returns blocks actually adopted (already-resident and
        pool-full blocks are skipped; a partial import still shortens the
        decode engine's recompute)."""
        want = self.block_shape()
        if len(hashes) and tuple(blocks.shape[1:]) != want:
            raise ValueError(
                f"KV page geometry mismatch: got {tuple(blocks.shape[1:])}, "
                f"this engine needs {want}"
            )
        # allocate + upload the whole group in ONE device dispatch
        # (upload_blocks): per-block uploads cost a dispatch round trip
        # each, which dominates PD transfer on high-RTT device links.
        # Staging/commit bookkeeping is the pool's shared definition
        # (kv_cache.stage_adoption — also used by the device path)
        by_hash = {h: d for h, d in zip(hashes, blocks)}
        staged, pinned = self.pool.stage_adoption(hashes)
        if not staged:
            self.pool.abort_adoption(staged, pinned)
            return 0
        try:
            upload_many = getattr(self.runner, "upload_blocks", None)
            if upload_many is not None:
                upload_many(
                    [blk for _, blk in staged],
                    np.stack([by_hash[h] for h, _ in staged]),
                )
            else:
                for h, blk in staged:
                    self.runner.upload_block(blk, by_hash[h])
        except Exception:
            self.pool.abort_adoption(staged, pinned)
            raise
        self.pool.commit_adoption(staged, pinned)
        return len(staged)
