"""Saturation & goodput telemetry (docs/29-saturation-slo.md).

The tracing spine (docs/28-request-tracing.md) explains one slow request;
this module explains *why the chip isn't full*. Two instruments:

- **StepMeter** — per-resolved-step utilization accounting in the engine
  step loop: decode-seat occupancy (rows used / max_num_seqs), the
  prefill-vs-decode token split, the padding-waste fraction (useful tokens
  vs the padded device shape actually computed), and an achieved-FLOP/s →
  MFU estimate (analytic model FLOPs × tokens ÷ resolve-cadence wall).
  Cheap by construction: a handful of float ops and one bucket increment
  per resolved step, all on the step thread. ``enabled=False`` degrades to
  a no-op (the bench's ``saturation`` phase measures the difference).

- **GoodputLedger** — classifies every device-sampled token exactly once
  as *delivered* or *wasted* with a bounded reason label
  (:data:`WASTE_REASONS`). The invariant the tests and bench enforce:

      sampled == delivered + sum(wasted) + pending-on-live-requests

  where *pending* are accepted tokens whose request hasn't finished yet
  (classified at finish/preemption). At quiescence pending is zero, so
  ``delivered + wasted == sampled`` exactly — across the serial AND
  pipelined step loops, rollbacks, preemptions, deadline expiry, QoS shed
  evictions and severed/aborted streams.

Both feed the ``tpu:engine_*`` / ``tpu:goodput_*`` contract names
(metrics_contract.py) through EngineStatsSnapshot.saturation; the SLO rule
pack (observability/rules/) and the KEDA/prom-adapter autoscaling signal
path key off them.
"""

from __future__ import annotations

import math
import os
import time
from bisect import bisect_left

from ..metrics_contract import WASTE_REASON_VALUES

# Reason labels for tpu:wasted_tokens_total — a CLOSED set (exporter label
# cardinality is bounded by construction, not by a cap). The tuple itself
# lives in metrics_contract (single definition, validated against the
# exporters by the contract checker); the semantics live here:
#   rollback            sampled by a pipeline dispatch that was discarded
#                       (speculation invalidated / resolve fault), by a
#                       row whose request finished while the step was in
#                       flight, or by spec-decode verify positions past
#                       the first draft mismatch — the device executed
#                       them, nobody consumed them
#   preempted_recompute generated-token positions RE-computed by resumed
#                       prefill after a preemption dropped their KV —
#                       charged chunk-exactly as sampled+wasted per re-pass
#                       (the token values were already known; the request's
#                       own pending tokens keep their fate until finish)
#   deadline_expired    tokens of a request finished by deadline expiry —
#                       generated for a reply nobody will read
#   severed             tokens of an aborted request (client disconnect /
#                       router-severed stream / engine-side abort)
#   shed_evicted        tokens of a request evicted from the waiting queue
#                       by a higher-priority admission (QoS shedding)
#   overshoot           fused-decode-window candidates sampled past a
#                       per-request stop condition and discarded host-side
WASTE_REASONS = WASTE_REASON_VALUES

# finish-status → waste reason for a request's still-pending tokens
# (None = delivered). Keys are RequestStatus *names* so this module stays
# import-light (request.py imports nothing from here).
FINISH_REASONS = {
    "FINISHED_STOPPED": None,
    "FINISHED_LENGTH": None,
    "FINISHED_DEADLINE": "deadline_expired",
    "FINISHED_SHED": "shed_evicted",
    "FINISHED_ABORTED": "severed",
}


class GoodputLedger:
    """Monotonic token-fate counters, mutated only under the engine lock
    (scheduler postprocess/finish/preempt + the engine's rollback sites)."""

    def __init__(self) -> None:
        self.sampled_total = 0
        self.delivered_total = 0
        self.wasted: dict[str, int] = {r: 0 for r in WASTE_REASONS}

    def sampled(self, n: int) -> None:
        if n > 0:
            self.sampled_total += n

    def deliver(self, n: int) -> None:
        if n > 0:
            self.delivered_total += n

    def waste(self, reason: str, n: int) -> None:
        if n > 0:
            # an unknown reason is a programming error — fail loud in tests
            self.wasted[reason] += n

    def rollback(self, n: int) -> None:
        """Sampled AND wasted{rollback} in one motion — the shape every
        discard site shares (pipeline rollback, void rows for requests
        that finished in flight, rejected draft positions past a verify
        mismatch), so no site can count one half and drift the partition."""
        self.sampled(n)
        self.waste("rollback", n)

    def classify_finish(self, status_name: str, n: int) -> None:
        """Classify a finished request's pending tokens by its terminal
        status (FINISH_REASONS). Unknown statuses count as severed — a
        token must never escape the partition."""
        reason = FINISH_REASONS.get(status_name, "severed")
        if reason is None:
            self.deliver(n)
        else:
            self.waste(reason, n)

    @property
    def wasted_total(self) -> int:
        return sum(self.wasted.values())

    def snapshot(self) -> dict:
        return {
            "sampled": self.sampled_total,
            "delivered": self.delivered_total,
            "wasted": dict(self.wasted),
            "wasted_total": self.wasted_total,
        }


# -- analytic FLOP model -----------------------------------------------------


def matmul_params(cfg) -> int:
    """Dense matmul parameters touched per token (embedding GATHER
    excluded, unembedding matmul included — computed whether or not the
    weights are tied). For MoE, only the activated experts count."""
    h = cfg.hidden_size
    attn = (
        h * cfg.num_heads * cfg.head_dim  # q
        + 2 * h * cfg.num_kv_heads * cfg.head_dim  # k, v
        + cfg.num_heads * cfg.head_dim * h  # o
    )
    if cfg.num_experts > 0:
        mlp = (
            cfg.num_experts_per_tok * 3 * h * cfg.intermediate_size
            + h * cfg.num_experts  # router
        )
    else:
        mlp = 3 * h * cfg.intermediate_size
    return cfg.num_layers * (attn + mlp) + cfg.vocab_size * h


def step_flops(cfg, n_tokens: int, sum_context: int) -> float:
    """Forward-pass FLOPs for one dispatch: 2 × matmul-params per token
    plus the attention score/value term (4 × n_heads × head_dim per layer
    per (token, context-position) pair). `sum_context` is the summed
    attended context length over the dispatch's tokens — an estimate, like
    every MFU number."""
    return (
        2.0 * matmul_params(cfg) * n_tokens
        + 4.0 * cfg.num_heads * cfg.head_dim * cfg.num_layers * sum_context
    )


# bf16 peak FLOP/s per chip by accelerator generation (dense; public spec
# sheets). Override with TPU_PEAK_FLOPS (per chip) for new hardware.
_PEAK_BY_KIND = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),  # Trillium
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def detect_peak_flops() -> float:
    """Aggregate peak FLOP/s of this process's local devices, 0.0 when
    unknown (CPU backend / unrecognized chip) — MFU reads 0 rather than a
    made-up denominator."""
    env = os.environ.get("TPU_PEAK_FLOPS")
    per_chip = 0.0
    n = 1
    try:
        import jax

        devs = jax.local_devices()
        n = max(1, len(devs))
        kind = getattr(devs[0], "device_kind", "") or ""
        if env:
            per_chip = float(env)
        else:
            low = kind.lower()
            for marker, peak in _PEAK_BY_KIND:
                if marker in low:
                    per_chip = peak
                    break
    except Exception:
        per_chip = float(env) if env else 0.0
    return per_chip * n


# occupancy fraction (0..1] buckets; +Inf is appended by the exporter
OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)
# per-resolved-step wall seconds (resolve cadence)
STEP_WALL_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

_EWMA_TAU_S = 10.0  # time constant for the gauge-shaped signals


class _Hist:
    """Fixed-bucket histogram as plain ints (the exporter renders it as a
    Prometheus histogram family; prometheus_client objects never ride the
    step thread)."""

    __slots__ = ("buckets", "counts", "total", "count")

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # + the +Inf bucket
        self.total = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.buckets, v)] += 1
        self.total += v
        self.count += 1

    def snapshot(self) -> dict:
        return {
            "buckets": self.buckets,
            "counts": list(self.counts),
            "sum": self.total,
            "count": self.count,
        }


class StepMeter:
    """Per-resolved-step utilization accounting (step thread only).

    Padding accounting mirrors the runner's shape planner: decode rows pad
    to the decode bucket, prefill pads to (pow2 rows × prefill token
    bucket). The pad-up program fallback can promote a dispatch to a
    coarser shape while the exact program compiles in the background —
    that transient is NOT metered (the planner's shape is), so the waste
    fraction describes steady state.
    """

    def __init__(self, model_cfg=None, sched_cfg=None, enabled: bool = True):
        self.enabled = enabled
        self.model_cfg = model_cfg
        self.sched_cfg = sched_cfg
        # cumulative counters (exporter _bump pattern)
        self.step_tokens = {"prefill": 0, "decode": 0}
        self.padded_tokens = {"prefill": 0, "decode": 0}
        self.flops_total = 0.0
        self.steps = {"prefill": 0, "decode": 0}
        # gauge-shaped EWMAs
        self.seat_occupancy = 0.0
        self.padding_waste = 0.0
        self.achieved_flops = 0.0
        self._peak_flops: float | None = None  # lazy (jax touch)
        # per-step distributions
        self.occupancy_hist = _Hist(OCCUPANCY_BUCKETS)
        self.wall_hist = {
            "prefill": _Hist(STEP_WALL_BUCKETS),
            "decode": _Hist(STEP_WALL_BUCKETS),
        }
        self._last_t: float | None = None

    # -- recording (step thread) -------------------------------------------

    def _wall(self, now: float) -> float:
        """Resolve-cadence wall: time since the previous resolved step.
        This is the honest MFU denominator for the pipelined loop (where
        dispatch and resolve of different steps overlap inside one call)
        AND it charges idle gaps against utilization — an idle chip is
        exactly what this meter exists to surface. Clamped so one long
        idle stretch can't freeze the EWMAs at ~0 forever."""
        if self._last_t is None:
            self._last_t = now
            return 0.0
        wall = min(60.0, now - self._last_t)
        self._last_t = now
        return wall

    def _ewma(self, prev: float, value: float, wall: float) -> float:
        alpha = 1.0 - math.exp(-max(wall, 1e-4) / _EWMA_TAU_S)
        return prev + alpha * (value - prev)

    def record_decode(
        self, rows: int, window: int, accepted_tokens: int, sum_context: int
    ) -> None:
        """One RESOLVED decode (or verify) dispatch. `accepted_tokens` are
        the host-accepted tokens; `sum_context` the summed context length
        over the dispatch's sampled positions (FLOP estimate)."""
        if not self.enabled:
            return
        now = time.perf_counter()
        wall = self._wall(now)
        sched = self.sched_cfg
        capacity = sched.max_num_seqs if sched else rows
        try:
            padded_rows = (
                sched.bucket_for(rows, sched.decode_buckets) if sched else rows
            )
        except ValueError:
            padded_rows = rows
        occ = rows / capacity if capacity else 0.0
        padded = padded_rows * window
        self.step_tokens["decode"] += accepted_tokens
        self.padded_tokens["decode"] += padded
        self.steps["decode"] += 1
        self.occupancy_hist.observe(occ)
        if wall > 0.0:
            self.wall_hist["decode"].observe(wall)
        flops = 0.0
        if self.model_cfg is not None:
            flops = step_flops(self.model_cfg, rows * window, sum_context)
            self.flops_total += flops
        if wall > 0.0:
            self.seat_occupancy = self._ewma(self.seat_occupancy, occ, wall)
            # PURE bucket padding: dispatched slots (rows × window) vs the
            # padded device shape. Mid-window stop discards are the
            # ledger's wasted{overshoot} — charging them here too would
            # double-attribute one waste class and point the operator at
            # bucket tuning that can't help.
            waste = 1.0 - (rows * window) / padded if padded else 0.0
            self.padding_waste = self._ewma(self.padding_waste, waste, wall)
            self.achieved_flops = self._ewma(
                self.achieved_flops, flops / wall, wall
            )
        else:
            self.seat_occupancy = occ

    def record_prefill(
        self, rows: int, chunk_tokens: int, sum_context: int,
        max_chunk: int | None = None,
    ) -> None:
        """One resolved prefill dispatch: `chunk_tokens` useful prompt
        tokens over a (pow2 rows × token-bucket) padded device shape.
        `max_chunk` is the longest row's chunk — the planner pads every
        row to ITS bucket (mean-chunk fallback when not provided)."""
        if not self.enabled:
            return
        now = time.perf_counter()
        wall = self._wall(now)
        sched = self.sched_cfg
        padded = chunk_tokens
        if sched and rows > 0:
            t = max_chunk or max(1, -(-chunk_tokens // rows))
            try:
                t_pad = sched.bucket_for(t, sched.prefill_buckets)
            except ValueError:
                t_pad = t
            b_pad = 1 << max(0, rows - 1).bit_length()
            padded = b_pad * t_pad
        self.step_tokens["prefill"] += chunk_tokens
        self.padded_tokens["prefill"] += max(padded, chunk_tokens)
        self.steps["prefill"] += 1
        if wall > 0.0:
            self.wall_hist["prefill"].observe(wall)
        flops = 0.0
        if self.model_cfg is not None:
            flops = step_flops(self.model_cfg, chunk_tokens, sum_context)
            self.flops_total += flops
        if wall > 0.0:
            waste = (
                1.0 - chunk_tokens / padded if padded > 0 else 0.0
            )
            self.padding_waste = self._ewma(self.padding_waste, waste, wall)
            self.achieved_flops = self._ewma(
                self.achieved_flops, flops / wall, wall
            )

    # -- reporting ----------------------------------------------------------

    def peak_flops(self) -> float:
        if self._peak_flops is None:
            self._peak_flops = detect_peak_flops() if self.enabled else 0.0
        return self._peak_flops

    def _decay(self) -> float:
        """Idle decay factor for the EWMA gauges, applied at READ time
        (state stays untouched): with no steps resolving, the gauges must
        fall toward 0 — a frozen last-busy occupancy would hold the KEDA
        occupancy trigger above threshold forever and the fleet would
        never scale back in."""
        if self._last_t is None:
            return 1.0
        idle = max(0.0, time.perf_counter() - self._last_t)
        return math.exp(-idle / _EWMA_TAU_S)

    def snapshot(self) -> dict:
        peak = self.peak_flops()
        decay = self._decay()
        occupancy = self.seat_occupancy * decay
        achieved = self.achieved_flops * decay
        return {
            "enabled": self.enabled,
            "decode_seat_occupancy": occupancy,
            "padding_waste_frac": self.padding_waste * decay,
            "achieved_flops_per_s": achieved,
            "mfu": (achieved / peak) if peak > 0 else 0.0,
            "peak_flops_per_s": peak,
            "step_tokens": dict(self.step_tokens),
            "padded_tokens": dict(self.padded_tokens),
            "model_flops_total": self.flops_total,
            "steps": dict(self.steps),
            "occupancy_hist": self.occupancy_hist.snapshot(),
            "step_wall_hist": {
                k: h.snapshot() for k, h in self.wall_hist.items()
            },
        }
