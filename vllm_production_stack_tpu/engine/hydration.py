"""Compute-or-load KV hydration planner (docs/31-hydration-planner.md).

Lower-tier prefix hits (disk tier, remote store) used to be
all-or-nothing: `match_prefix` either BLOCKED the step thread loading the
whole resident run or the engine recomputed it. "Compute Or Load KV
Cache? Why Not Both?" (PAPERS.md) shows the right shape: split the
resident run into chunks, recompute the HEAD while the TAIL fetches
concurrently, and pick the split point from measured fetch bandwidth vs
measured prefill FLOP/s — so TTFT ≈ max(fetch tail, compute tail)
instead of their sum. PR 7 built exactly the inputs this needs:
`LLMEngine.hydration_signal()` carries per-tier measured bandwidth
(`kv_flow.TierBandwidth`), the StepMeter's achieved prefill FLOP/s, and
the analytic per-block KV size.

Three pieces, all OFF the jitted hot path:

- :func:`plan_decisions` — the PURE decision function (unit-testable on
  synthetic bandwidth/FLOP grids). Prefill is sequential, so a loaded
  chunk blocks every later compute chunk until it lands; the only
  overlap-correct shape is *recompute the head, load the tail*. The
  planner picks the split minimizing ``max(compute(head), fetch(tail))``.
  Tiers below the :class:`~.kv_flow.TierBandwidth` sample floor are
  never trusted: in ``auto`` mode the plan declines (the admission falls
  back to the legacy synchronous load, which is also what *measures* the
  tier); in forced ``planner`` mode unmeasured chunks are recomputed.

- :class:`HydrationPlan` / :class:`HydrationChunk` — per-request chunk
  state shared between the step thread (adoption / fallback) and the
  fetcher thread (landing arrays), guarded by one small per-plan lock.

- :class:`Hydrator` — the engine-owned coordinator: builds plans at
  admission, runs ONE background fetcher thread pulling chunk bytes from
  the disk tier / remote store into host RAM, and records every decision
  into the flow meter's ``tpu:kv_hydration_decision_total{choice=}``
  contract counters. The scheduler consumes landed chunks at chunked-
  prefill admission (`Scheduler._consume_hydrated`), falling back to
  recompute when a fetch misses its deadline or fails.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass

from .. import metrics_contract as mc
from ..utils.logging import init_logger

logger = init_logger(__name__)

# decision values for tpu:kv_hydration_decision_total{choice=} — the
# contract owns the closed set; this module records against it
HYDRATION_CHOICES = mc.KV_HYDRATION_CHOICES

# tiers whose bytes are effectively free to "fetch" (already in HBM /
# host RAM) — they never gate a plan on bandwidth measurement
_LOCAL_TIERS = ("hbm", "host")

# fixed per-chunk adoption overhead charged to the fetch timeline (block
# registration + the batched device upload dispatch) so zero-cost local
# chunks don't flap the split point
_CHUNK_OVERHEAD_S = 1e-4

# when the StepMeter has no achieved-FLOP/s sample yet, assume this
# fraction of chip peak (a deliberately conservative MFU guess — prefill
# is the compute-bound phase)
_COLD_MFU_GUESS = 0.3


def plan_decisions(
    chunk_tiers: list[list[str]],
    signal: dict,
    *,
    forced: bool = False,
    start_block: int = 0,
) -> tuple[list[str], dict] | None:
    """Per-chunk load-vs-recompute decisions for one resident run.

    ``chunk_tiers[i]`` is the per-block serving tier of chunk i (from
    ``KVBlockPool.probe_prefix``); ``signal`` is
    ``LLMEngine.hydration_signal()``. Returns ``(decisions, estimates)``
    with ``decisions[i] in ("load", "recompute")`` and ``estimates``
    carrying the per-chunk cost model plus ``est_fetch_total_s`` (the
    deadline input), or ``None`` when the planner cannot engage:

    - no usable compute-rate estimate (no achieved FLOP/s AND no known
      chip peak — a cold engine), or
    - ``forced=False`` (auto mode) and any resident DISK/REMOTE tier in
      the run is below the bandwidth sample floor — the caller falls back
      to the legacy synchronous load, whose transfers are exactly what
      crosses the floor. An unmeasured PEER or DEVICE tier never declines
      the plan (no sync path fetches from peers, over HTTP or over the
      device link); its chunks are priced recompute until the Hydrator's
      bootstrap fetches cross the floor.

    With ``forced=True`` unmeasured-tier chunks are decided "recompute"
    (never trust an estimate built from a single tiny transfer — the
    TierBandwidth sample-floor satellite), and the split is chosen over
    the remaining loadable chunks.

    Prefill is sequential, so overlap only works as *recompute head,
    load tail*: for split s, chunks [0, s) recompute while [s, n) fetch
    concurrently; the makespan model is
    ``max(sum(compute of head + forced-recompute tail), sum(fetch of
    loaded tail))`` and the planner minimizes it over s.
    """
    flops_per_s = float(signal.get("prefill_flops_per_s") or 0.0)
    if flops_per_s <= 0.0:
        flops_per_s = (
            float(signal.get("peak_flops_per_s") or 0.0) * _COLD_MFU_GUESS
        )
    flops_per_token = float(signal.get("flops_per_token") or 0.0)
    if flops_per_s <= 0.0 or flops_per_token <= 0.0:
        return None  # cannot price compute — planner cannot engage
    # an unmeasured DISK/REMOTE tier declines the whole plan in auto mode
    # (the sync fallback load is what feeds the bandwidth floor); an
    # unmeasured PEER or DEVICE tier must NOT — no sync path ever fetches
    # from a peer over either transport, so declining would starve the
    # estimator forever. Peer/device chunks below the floor are priced
    # recompute instead, and the Hydrator's bootstrap fetch
    # (measurement-only) crosses the floor out of band.
    unmeasured_nonpeer = False
    # attention score/value coefficient (FLOPs per token × attended
    # position): at long context this term dominates the matmul term, and
    # pricing recompute without it biases the split toward compute
    attn_coeff = float(signal.get("attn_flops_per_token_ctx") or 0.0)
    block_bytes = float(signal.get("block_bytes") or 0.0)
    # per-tier WIRE bytes per block (engine.hydration_signal): with an
    # at-rest codec the disk/remote/peer hops move int4+scales or fp8
    # payloads, so a fetch costs codec-compressed bytes — this is exactly
    # what shifts load/recompute crossovers in the codec's favor. Tiers
    # absent from the map price at the logical block_bytes.
    wire_bytes = signal.get("wire_block_bytes") or {}
    block_tokens = int(signal.get("block_size_tokens") or 1)
    bw = signal.get("fetch_bandwidth_bytes_per_s") or {}
    measured = signal.get("fetch_bandwidth_measured") or {}

    inf = float("inf")
    compute_s: list[float] = []
    fetch_s: list[float] = []
    pos_tok = start_block * block_tokens  # absolute chunk start position
    for tiers in chunk_tiers:
        n_tok = len(tiers) * block_tokens
        # chunk tokens attend ~their absolute positions: sum over
        # [pos, pos + n) is n × (2·pos + n − 1) / 2
        sum_ctx = n_tok * (2 * pos_tok + n_tok - 1) / 2.0
        compute_s.append(
            (n_tok * flops_per_token + attn_coeff * sum_ctx) / flops_per_s
        )
        pos_tok += n_tok
        cost = _CHUNK_OVERHEAD_S
        for tier in tiers:
            if tier in _LOCAL_TIERS:
                continue  # bytes already local: adoption cost only
            rate = float(bw.get(tier) or 0.0)
            if not measured.get(tier) or rate <= 0.0:
                cost = inf  # below the sample floor: never trusted
                if tier not in ("peer", "device"):
                    unmeasured_nonpeer = True
                break
            cost += float(wire_bytes.get(tier) or block_bytes) / rate
        fetch_s.append(cost)

    if not forced and unmeasured_nonpeer:
        return None  # auto mode: fall back to the sync path (it measures)

    n = len(chunk_tiers)
    best_s, best_cost = n, inf
    for s in range(n + 1):
        head_c = sum(compute_s[:s])
        forced_c = sum(
            compute_s[i] for i in range(s, n) if fetch_s[i] == inf
        )
        tail_f = sum(
            fetch_s[i] for i in range(s, n) if fetch_s[i] < inf
        )
        cost = max(head_c + forced_c, tail_f)
        # strict < keeps the SMALLEST s (most loads) among ties: loading
        # saves the FLOPs even when it doesn't change the makespan
        if cost < best_cost:
            best_s, best_cost = s, cost
    decisions = [
        "load" if i >= best_s and fetch_s[i] != inf else "recompute"
        for i in range(n)
    ]
    est = {
        "compute_s": compute_s,
        "fetch_s": [c if c != inf else -1.0 for c in fetch_s],
        "split": best_s,
        "est_makespan_s": best_cost,
        "est_fetch_total_s": sum(
            fetch_s[i] for i in range(n)
            if decisions[i] == "load" and fetch_s[i] != inf
        ),
        "flops_per_s": flops_per_s,
    }
    return decisions, est


@dataclass
class HydrationChunk:
    """One contiguous run of resident full blocks with a single fate."""

    index: int
    start_block: int  # absolute block index within the prompt
    hashes: list[int]
    tiers: list[str]
    decision: str  # "load" | "recompute"
    # pending → landed | failed (fetcher, under plan.lock) →
    # adopted | recomputed | cancelled (step thread)
    status: str = "pending"
    arrays: list | None = None
    est_fetch_s: float = 0.0
    est_compute_s: float = 0.0

    def tokens(self, block_size: int) -> int:
        return len(self.hashes) * block_size


class HydrationPlan:
    """Per-request chunk ledger shared by the step and fetcher threads.

    The step thread owns ``cursor`` (consumption order is strictly
    front-to-back — prefill is sequential); the fetcher only ever moves
    a chunk pending → landed/failed under ``lock``. ``cancel()`` makes
    in-flight fetch jobs drop their results (preemption, abort, finish
    — the request's attribution was already settled by the scheduler)."""

    def __init__(
        self, request_id: str, chunks: list[HydrationChunk],
        block_size: int, deadline: float, estimates: dict,
        peer_owner: str = "",
    ):
        self.request_id = request_id
        self.chunks = chunks
        self.block_size = block_size
        self.deadline = deadline  # monotonic: pending past this → fallback
        self.estimates = estimates
        # engine URL serving this plan's "peer"-tier blocks (one owner per
        # plan: the probe's peer continuation is a single engine's run)
        self.peer_owner = peer_owner
        self.lock = threading.Lock()
        self.cancelled = False
        self.cursor = 0  # first chunk not fully consumed (step thread)

    def done(self) -> bool:
        return self.cursor >= len(self.chunks)

    def current(self) -> HydrationChunk:
        return self.chunks[self.cursor]

    def advance(self) -> None:
        self.cursor += 1

    def cancel(self) -> None:
        with self.lock:
            self.cancelled = True

    def deferred_tokens(self) -> int:
        """Prompt tokens whose hydration attribution is deferred to chunk
        resolution (load-decided chunks) — the admission-time partition
        counts everything else."""
        return sum(
            c.tokens(self.block_size)
            for c in self.chunks
            if c.decision == "load"
        )

    def unresolved(self) -> list[HydrationChunk]:
        """Load-decided chunks whose fate is still open (pending/landed/
        failed but never adopted or flipped) — the ones settle must
        classify when the request leaves the scheduler early."""
        return [
            c for c in self.chunks
            if c.decision == "load"
            and c.status in ("pending", "landed", "failed")
        ]


class Hydrator:
    """Engine-owned planner coordinator + background chunk fetcher.

    ONE instance per engine (None when no disk/remote tier exists or
    ``--kv-hydration sync``). ``mode``:

    - ``auto`` (default): plan when every resident tier is measured and
      a compute-rate estimate exists; otherwise the admission uses the
      legacy synchronous load — which is also what feeds the bandwidth
      estimator past its sample floor, so auto self-bootstraps.
    - ``planner``: always plan; unmeasured tiers are recomputed
      (the sample-floor rule), never synchronously loaded.
    - ``off``: ignore disk/remote residency entirely (recompute) — the
      bench's compute-only arm and an operator kill switch.

    The fetcher is one daemon thread: chunk loads are bandwidth-bound
    (disk IO / one HTTP mget per remote span), so a second thread would
    only fight for the same pipe. Disk/remote tier objects are made
    fetch-thread-safe by their own small locks (kv_disk_tier /
    kvstore.client)."""

    MODES = ("auto", "planner", "sync", "off")

    # at most one measurement-only peer fetch per owner per this interval:
    # the sample floor needs MIN_SAMPLES small fetches, not a storm
    BOOTSTRAP_MIN_INTERVAL_S = 5.0

    def __init__(
        self,
        mode: str = "auto",
        chunk_blocks: int = 16,
        timeout_s: float = 0.0,
        flow=None,
        signal_fn=None,
        host_tier=None,
        peer=None,
        heartbeat=None,
        device_pull_fn=None,
    ):
        if mode not in self.MODES:
            raise ValueError(
                f"kv_hydration mode {mode!r}; expected one of {self.MODES}"
            )
        if chunk_blocks < 1:
            raise ValueError("hydration chunk_blocks must be >= 1")
        self.mode = mode
        self.chunk_blocks = chunk_blocks
        # 0 = auto: 3x the plan's estimated fetch total, clamped — a plan
        # that blows 3x past its own estimate was priced off stale
        # bandwidth and recompute is the honest answer
        self.timeout_s = timeout_s
        if flow is None:
            from .kv_flow import NULL_FLOW

            flow = NULL_FLOW
        self.flow = flow
        self.signal_fn = signal_fn
        self.host_tier = host_tier
        # peer-engine KV tier client (engine/kv_peer.PeerKVTier, None when
        # --kv-peer-fetch is off): "peer"-tier chunks fetch from the plan's
        # owner engine over dedicated per-owner connections
        self.peer = peer
        # device-collective peer pull (engine._device_peer_pull, None when
        # no mesh identity): "device"-tier chunks land straight in THIS
        # engine's HBM pool via ICI/DCN collectives — (owner_url, hashes)
        # -> resident block count, parked at refcount 0 for adoption
        self.device_pull_fn = device_pull_fn
        self._q: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._closed = False
        # thread-liveness heartbeat (docs/37-flight-recorder.md,
        # flightrec.ThreadRegistry "hydration_fetch"): beaten per fetch
        # job, idle while blocked on the empty queue — a stale-while-busy
        # beat is the fetcher-deadlocked-under-a-tier-lock wedge
        self.heartbeat = heartbeat
        # dedicated remote connection for the fetcher thread: its chunk
        # mgets can run for seconds and must never hold the shared fetch
        # lock the step thread's probes contend on (kvstore/client.py)
        self._remote_conn = None
        # per-owner dedicated peer fetch connections (same rationale)
        self._peer_conns: dict[str, object] = {}
        # last measurement-only bootstrap per owner (step thread writes,
        # monotonic clock) — rate-limits the sample-floor warmup
        self._bootstrap_t: dict[str, float] = {}

    # -- planning (step thread) -------------------------------------------

    def build_plan(
        self,
        request_id: str,
        start_block: int,
        hashes: list[int],
        tiers: list[str],
        block_size: int,
        peer_owner: str = "",
    ) -> HydrationPlan | None:
        """Plan the resident run [start_block, start_block + len(hashes))
        or return None (caller falls back to the legacy sync path).
        `peer_owner` is the engine URL serving the run's "peer"-tier
        blocks (probe_prefix's peer continuation)."""
        if self.mode in ("sync", "off") or not hashes:
            return None
        chunk_tiers: list[list[str]] = [
            tiers[i : i + self.chunk_blocks]
            for i in range(0, len(tiers), self.chunk_blocks)
        ]
        signal = self.signal_fn()
        if peer_owner:
            # sample-floor warmup: the peer/device tiers have no sync
            # fallback to measure them, so an unmeasured one triggers a
            # bounded measurement-only fetch on the fetcher thread
            # (rate-limited per owner); until it crosses the floor, its
            # chunks price as recompute and the request loses nothing
            for wire_tier in ("peer", "device"):
                if wire_tier in tiers:
                    self._maybe_bootstrap(
                        peer_owner,
                        [h for h, t in zip(hashes, tiers)
                         if t == wire_tier],
                        signal, tier=wire_tier,
                    )
        planned = plan_decisions(
            chunk_tiers, signal,
            forced=self.mode == "planner", start_block=start_block,
        )
        if planned is None:
            return None
        decisions, est = planned
        chunks: list[HydrationChunk] = []
        off = 0
        for i, ct in enumerate(chunk_tiers):
            chunks.append(HydrationChunk(
                index=i,
                start_block=start_block + off,
                hashes=hashes[off : off + len(ct)],
                tiers=list(ct),
                decision=decisions[i],
                est_fetch_s=max(0.0, est["fetch_s"][i]),
                est_compute_s=est["compute_s"][i],
            ))
            off += len(ct)
        timeout = self.timeout_s
        if timeout <= 0.0:
            timeout = min(30.0, max(0.5, 3.0 * est["est_fetch_total_s"]))
        return HydrationPlan(
            request_id, chunks, block_size,
            deadline=time.monotonic() + timeout, estimates=est,
            peer_owner=peer_owner,
        )

    def _maybe_bootstrap(
        self, owner: str, peer_hashes: list[int], signal: dict,
        tier: str = "peer",
    ) -> None:
        """Enqueue one measurement-only fetch against `owner` when the
        wire tier's bandwidth estimate is still below the sample floor
        (step thread; the fetch itself runs on the fetcher thread). For
        ``tier="peer"`` the payload is DISCARDED — only the TierBandwidth
        samples matter; for ``tier="device"`` the pulled blocks land
        parked in the pool (a collective has no discard path) and the
        next admission re-plans against both the measured link and the
        now-HBM-resident run."""
        if tier == "device":
            if self.device_pull_fn is None or not peer_hashes:
                return
        elif self.peer is None or not peer_hashes:
            return
        if (signal.get("fetch_bandwidth_measured") or {}).get(tier):
            return
        now = time.monotonic()
        key = (owner, tier)
        if now - self._bootstrap_t.get(key, -1e9) < (
            self.BOOTSTRAP_MIN_INTERVAL_S
        ):
            return
        self._bootstrap_t[key] = now
        # enough blocks to cross MIN_BYTES in two samples where possible
        from .kv_flow import TierBandwidth

        block_bytes = float(signal.get("block_bytes") or 0.0)
        want = TierBandwidth.MIN_SAMPLES * max(
            1, int(TierBandwidth.MIN_BYTES // block_bytes) + 1
        ) if block_bytes > 0 else len(peer_hashes)
        self._ensure_thread()
        self._q.put(("bootstrap", owner, peer_hashes[:want], tier))

    def launch(self, plan: HydrationPlan) -> None:
        """Record the plan's decisions and enqueue its load chunks for the
        fetcher (step thread, right after the request admits). Host-ring
        blocks inside load chunks are resolved HERE — the ring is step-
        thread state the fetcher must never touch."""
        for chunk in plan.chunks:
            self.flow.record_decision(chunk.decision)
            if chunk.decision != "load":
                continue
            arrays: list = [None] * len(chunk.hashes)
            for i, (h, tier) in enumerate(zip(chunk.hashes, chunk.tiers)):
                if tier == "host" and self.host_tier is not None:
                    arrays[i] = self.host_tier.peek_bytes(h)
            chunk.arrays = arrays
            self._ensure_thread()
            self._q.put((plan, chunk))

    # -- fetcher (background thread) --------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._fetch_loop, name="kv-hydration-fetch",
                daemon=True,
            )
            self._thread.start()

    def _fetch_loop(self) -> None:
        hb = self.heartbeat
        while True:
            if hb is not None:
                hb.idle()  # parked on an empty queue is not a stall
            item = self._q.get()
            if hb is not None:
                hb.beat()  # busy: silence from here on IS a stall signal
            if item is None:
                if hb is not None:
                    hb.idle()
                return
            if item[0] == "bootstrap":
                _, owner, hashes, tier = item
                try:
                    self._bootstrap_fetch(owner, hashes, tier)
                except Exception:
                    logger.exception(
                        "%s bandwidth bootstrap against %s faulted",
                        tier, owner,
                    )
                continue
            plan, chunk = item
            try:
                self._fetch_chunk(plan, chunk)
            except Exception:
                logger.exception(
                    "hydration fetch of chunk %d (request %s) faulted",
                    chunk.index, plan.request_id,
                )
                with plan.lock:
                    if chunk.status == "pending":
                        chunk.status = "failed"

    def _bootstrap_fetch(
        self, owner: str, hashes: list[int], tier: str = "peer"
    ) -> None:
        """Measurement-only peer fetches (fetcher thread): split the hash
        list into MIN_SAMPLES round trips so one warmup crosses both
        halves of the sample floor. HTTP payloads are discarded — adopting
        them would need the step thread's pool, and the next admission
        re-plans against the now-measured tier anyway. Device pulls land
        parked blocks instead (the collective IS the adoption); the pull
        records its own flow samples under tier="device"."""
        from .kv_flow import TierBandwidth

        if tier == "device":
            if self.device_pull_fn is None or not hashes:
                return
            per = max(1, len(hashes) // TierBandwidth.MIN_SAMPLES)
            for i in range(0, len(hashes), per):
                got = self.device_pull_fn(owner, hashes[i : i + per])
                if not got:
                    return  # owner refused/evicted: stop burning pulls
            return
        if self.peer is None or not hashes:
            return
        per = max(1, len(hashes) // TierBandwidth.MIN_SAMPLES)
        conn = self._peer_conn(owner)
        for i in range(0, len(hashes), per):
            got = self.peer.fetch_run(
                owner, hashes[i : i + per], conn=conn, bootstrap=True
            )
            if not got:
                return  # owner unreachable/evicted: stop burning fetches

    def _peer_conn(self, owner: str):
        conn = self._peer_conns.get(owner)
        if conn is None and self.peer is not None:
            conn = self._peer_conns[owner] = self.peer.new_fetch_conn(owner)
        return conn

    def _fetch_chunk(self, plan: HydrationPlan, chunk: HydrationChunk) -> None:
        with plan.lock:
            if plan.cancelled:
                chunk.status = "cancelled"
                return
        host = self.host_tier
        disk = getattr(host, "disk", None) if host is not None else None
        remote = getattr(host, "remote", None) if host is not None else None
        arrays = list(chunk.arrays or [None] * len(chunk.hashes))
        ok = True
        i = 0
        while i < len(chunk.hashes):
            tier = chunk.tiers[i]
            if arrays[i] is not None or tier == "hbm":
                # hbm-tier blocks need no bytes: adoption re-acquires the
                # resident block (or the chunk falls back if it was
                # evicted in the meantime)
                i += 1
                continue
            if tier == "disk" and disk is not None:
                arr = disk.load(chunk.hashes[i])  # meters disk/in itself
                if arr is None:
                    ok = False
                    break
                arrays[i] = arr
                i += 1
            elif tier == "remote" and remote is not None:
                j = i
                while (
                    j < len(chunk.hashes)
                    and chunk.tiers[j] == "remote"
                    and arrays[j] is None
                ):
                    j += 1
                if self._remote_conn is None and hasattr(
                    remote, "new_fetch_conn"
                ):
                    self._remote_conn = remote.new_fetch_conn()
                got = remote.fetch_run(
                    chunk.hashes[i:j], conn=self._remote_conn
                )
                if len(got) < j - i:
                    ok = False  # run broke mid-span: partial is useless
                for k, arr in enumerate(got):
                    arrays[i + k] = arr
                if not ok:
                    break
                i = j
            elif (
                tier == "peer"
                and self.peer is not None
                and plan.peer_owner
            ):
                # one batched /kv/peer_fetch per consecutive peer span,
                # over this owner's dedicated connection — the owner
                # serves the run straight out of its HBM/host tiers
                j = i
                while (
                    j < len(chunk.hashes)
                    and chunk.tiers[j] == "peer"
                    and arrays[j] is None
                ):
                    j += 1
                got = self.peer.fetch_run(
                    plan.peer_owner, chunk.hashes[i:j],
                    conn=self._peer_conn(plan.peer_owner),
                )
                if len(got) < j - i:
                    ok = False  # owner evicted mid-run: partial is useless
                for k, arr in enumerate(got):
                    arrays[i + k] = arr
                if not ok:
                    break
                i = j
            elif (
                tier == "device"
                and self.device_pull_fn is not None
                and plan.peer_owner
            ):
                # one collective pull per consecutive device span: the
                # owner's blocks land straight in THIS engine's HBM pool
                # (parked at refcount 0), so arrays stay None and
                # adoption re-acquires them by hash — no host-RAM bytes
                # ever exist on this path
                j = i
                while (
                    j < len(chunk.hashes)
                    and chunk.tiers[j] == "device"
                    and arrays[j] is None
                ):
                    j += 1
                got = self.device_pull_fn(
                    plan.peer_owner, chunk.hashes[i:j]
                )
                if int(got or 0) < j - i:
                    # owner refused (fingerprint/geometry), evicted
                    # mid-run, or the trigger faulted: partial coverage
                    # is useless, the chunk falls back to recompute
                    ok = False
                    break
                i = j
            else:
                # a "host" block whose ring entry vanished before launch
                # could resolve it, or a tier with no backing object
                ok = False
                break
        with plan.lock:
            if plan.cancelled:
                chunk.status = "cancelled"
            elif chunk.status == "pending":
                # only a still-pending chunk takes the payload: a chunk
                # the step thread already flipped to fallback released
                # its arrays, and re-attaching them here would pin dead
                # multi-MB payloads on the live plan
                chunk.arrays = arrays
                chunk.status = "landed" if ok else "failed"

    def pending_jobs(self) -> int:
        return self._q.qsize()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=2)
        if self._remote_conn is not None:
            self._remote_conn.close()
            self._remote_conn = None
        for conn in self._peer_conns.values():
            conn.close()
        self._peer_conns.clear()
        if self.peer is not None:
            self.peer.close()

    def snapshot(self) -> dict:
        """Operator view for GET /debug/hydration."""
        snap = {
            "mode": self.mode,
            "chunk_blocks": self.chunk_blocks,
            "timeout_s": self.timeout_s,
            "queued_fetch_jobs": self._q.qsize(),
            "device_pull": self.device_pull_fn is not None,
        }
        if self.peer is not None:
            snap["peer"] = self.peer.snapshot()
        return snap
