"""LLMEngine: the synchronous serving core (scheduler + runner + detokenize).

The OpenAI HTTP layer (engine/server.py) drives `step()` from a background
loop; offline use (bench.py, tests) drives it directly. This composes the
pieces the reference gets from vLLM images, exporting the stats the router's
scraper contract expects (SURVEY §5 metrics contract).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass, field

from ..qos import TenantContext
from ..utils.tokenizer import IncrementalDetokenizer, TokenizerWrapper
from .config import EngineConfig
from .model_runner import ModelRunner, StepHandle
from .request import Request, RequestOutput, RequestStatus, SamplingParams
from .saturation import StepMeter
from .scheduler import DecodeWork, PrefillWork, Scheduler, VerifyWork

logger = logging.getLogger(__name__)


class EngineOverloadedError(RuntimeError):
    """Admission refused: the waiting queue / queued-token watermark is
    full. The HTTP layer answers 429 with Retry-After = `retry_after_s`
    (computed from observed decode throughput)."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(RuntimeError):
    """Admission refused: the request's deadline has already passed, or the
    estimated queue wait would blow through it — shedding at the door is
    strictly cheaper than prefilling a reply nobody will read (503)."""


@dataclass
class EngineStatsSnapshot:
    """Mirrors the metric contract the router scrapes from engines
    (reference: src/vllm_router/stats/engine_stats.py:42-85)."""

    num_requests_running: int = 0
    num_requests_waiting: int = 0
    kv_usage_perc: float = 0.0
    prefix_cache_hit_rate: float = 0.0
    prefix_cache_hits: int = 0
    prefix_cache_queries: int = 0
    num_preemptions: int = 0
    generation_tokens: int = 0
    prompt_tokens: int = 0
    # pipelined step loop: fraction of step-loop wall time in which host
    # scheduling/postprocess overlapped an in-flight device step
    step_overlap_frac: float = 0.0
    host_kv_usage_perc: float = 0.0
    host_kv_offloads: int = 0
    host_kv_reloads: int = 0
    remote_kv_stores: int = 0
    remote_kv_fetched_blocks: int = 0
    spec_draft_tokens: int = 0
    spec_accepted_tokens: int = 0
    # per-proposer split (closed set ngram|draft) behind the labeled
    # tpu:spec_decode_proposed/accepted_tokens_total contract counters
    spec_proposed_by: dict = field(default_factory=dict)
    spec_accepted_by: dict = field(default_factory=dict)
    # request-lifecycle robustness (metrics_contract REQUESTS_SHED /
    # REQUESTS_DEADLINE_EXPIRED / ENGINE_DRAINING)
    requests_shed: int = 0
    requests_deadline_expired: int = 0
    draining: bool = False
    # multi-tenant QoS (docs/27-multitenancy.md): cumulative per-tenant
    # counters {tenant: {requests, generation_tokens, shed, ...}} plus the
    # queue-wait observations drained for the exporter's histogram
    tenants: dict = field(default_factory=dict)
    tenant_queue_waits: list = field(default_factory=list)
    # saturation & goodput telemetry (docs/29-saturation-slo.md): the
    # StepMeter snapshot (occupancy / padding / MFU / per-step histograms)
    # plus "goodput" (the token-fate ledger) and "kv_tiers" (per-tier
    # occupancy hbm/host/disk/remote) — rendered by EngineMetrics
    saturation: dict = field(default_factory=dict)
    # KV flow telemetry (docs/30-kv-flow-telemetry.md): the KVFlowMeter
    # snapshot — per-(tier, direction) bytes/blocks/latency/bandwidth plus
    # the hydration-source partition counters — rendered by EngineMetrics
    kv_flow: dict = field(default_factory=dict)
    # disk-tier block counters (the rung between HOST_KV_* and REMOTE_KV_*)
    disk_kv_stores: int = 0
    disk_kv_loads: int = 0
    # peer-engine KV tier (docs/35-peer-kv-reuse.md): the migrate-pricing
    # constant (analytic KV bytes per token of this pool) the router reads
    # off /metrics as tpu:kv_bytes_per_token
    kv_bytes_per_token: float = 0.0
    # structured output (docs/41-structured-output.md): cumulative
    # {valid, invalid, fallback} terminal outcomes behind
    # tpu:structured_requests_total, plus the grammar-compile durations
    # drained for the exporter's build-time histogram (same drain pattern
    # as tenant_queue_waits)
    structured_outcomes: dict = field(default_factory=dict)
    grammar_build_times: list = field(default_factory=list)
    # XLA compile telemetry (docs/42-compile-telemetry.md): the
    # CompileWatch snapshot — program-inventory size, per-(phase, trigger)
    # compile counts, cache hits/misses, storm count, and the drained
    # compile-wall observations — rendered by EngineMetrics
    compile: dict = field(default_factory=dict)


@dataclass
class _RequestState:
    request: Request
    detok: IncrementalDetokenizer | None
    text: str = ""
    pending_text: str = ""


@dataclass
class _InflightStep:
    """A decode (or speculative-verify) step dispatched to the device but
    not yet resolved — the unit the pipelined step loop keeps in flight
    while the host schedules and postprocesses around it."""

    work: DecodeWork | VerifyWork
    handle: StepHandle
    # set once the handle's results were synced to the host — a step that
    # faults before this must be restored as the in-flight step
    resolved: bool = False
    # flight-recorder dispatch seq (docs/37-flight-recorder.md): the
    # resolve/discard record names the dispatch it closes, and the
    # watchdog's unresolved-step detection keys off the open cursor
    rec_seq: int = 0


class LLMEngine:
    def __init__(
        self,
        config: EngineConfig,
        params=None,
        mesh=None,
        tokenizer: TokenizerWrapper | None = None,
    ):
        if config.model.any_sliding:
            # the fused decode window's staged slots are globally
            # attendable — sound only while every staged position is
            # within the sliding window; sp ring prefill has no window
            # masking
            if config.model.sliding_window <= config.scheduler.decode_window:
                raise ValueError(
                    f"sliding_window ({config.model.sliding_window}) must "
                    "exceed decode_window "
                    f"({config.scheduler.decode_window})"
                )
            if config.parallel.sequence_parallel_size > 1:
                raise ValueError(
                    "sequence parallelism does not support sliding-window "
                    "models yet"
                )
        if config.cache.num_blocks is None:
            from dataclasses import replace

            from .memory import derive_num_blocks

            config = config.replace(
                cache=replace(
                    config.cache,
                    num_blocks=derive_num_blocks(
                        config.model,
                        config.cache,
                        config.parallel,
                        max_num_seqs=config.scheduler.max_num_seqs,
                    ),
                )
            )
        self.config = config
        self.tokenizer = tokenizer or TokenizerWrapper(
            config.model.tokenizer or config.model.checkpoint
        )
        self.runner = ModelRunner(config, params=params, mesh=mesh)
        # identity of the weights this engine serves (needed BEFORE the KV
        # tiers: the remote store namespaces blocks by it) — see the
        # model_fingerprint comment below
        import hashlib

        # at-rest KV codec (docs/38-kv-quantization.md): blocks leaving
        # the pool for disk/remote/peer (and optionally the host ring)
        # travel as int4+scales or fp8, dequantized on adopt
        from .kv_codec import KVAtRestCodec

        self.kv_codec = KVAtRestCodec.from_config(config.cache)
        fp_parts = (
            config.model,
            config.seed,
            config.cache.resolved_kv_dtype(config.model.dtype),
        )
        if self.kv_codec.enabled:
            # the codec joins the fingerprint so a mixed-precision fleet
            # can never adopt bytes it would misdecode — appended only
            # when enabled, keeping existing codec-less disk caches and
            # remote namespaces valid across the upgrade
            fp_parts = (*fp_parts, self.kv_codec.spec)
        self.model_fingerprint = hashlib.sha256(
            repr(fp_parts).encode()
        ).hexdigest()[:16]
        # KV flow meter (docs/30-kv-flow-telemetry.md): ONE instance shared
        # by every tier object — host ring, disk tier, remote client,
        # device-path PD transfer — plus the scheduler's hydration
        # attribution. Transfer metering is togglable
        # (--kv-flow-metering false); the hydration partition counters are
        # contract series and stay on, like the goodput ledger.
        from .kv_flow import KVFlowMeter

        self.flow = KVFlowMeter(enabled=config.kv_flow_metering)
        # flight recorder + thread-liveness registry (docs/37-flight-
        # recorder.md): created BEFORE every tier/thread owner so each
        # long-lived loop (remote writer, hydration fetcher, step thread,
        # bg compiles) can register its heartbeat at construction
        from .flightrec import (
            DEFAULT_BG_COMPILE_STALL_S,
            FlightRecorder,
            ThreadRegistry,
        )

        self.flightrec = FlightRecorder(
            capacity=config.flight_records, enabled=config.flight_recording
        )
        self.threads = ThreadRegistry()
        # step quiescence lock: anything that mutates runner.kv_caches or
        # pool bookkeeping off the step thread (the device-collective peer
        # pull donates + reassigns the cache arrays) takes this around the
        # mutation. AsyncEngine adopts it as ITS step-loop lock, and sync
        # generate() steps under it, so holding it == no step in flight.
        self.step_lock = threading.Lock()
        self.host_tier = None
        self.remote_tier = None
        num_host_blocks = config.cache.num_host_blocks
        if config.cache.host_kv_gib > 0:
            from .memory import kv_block_bytes

            per_block = kv_block_bytes(
                config.model,
                config.cache.block_size,
                config.parallel.tensor_parallel_size,
                config.parallel.pipeline_parallel_size,
                kv_dtype=config.cache.resolved_kv_dtype(config.model.dtype),
            )
            num_host_blocks = max(
                num_host_blocks,
                int(config.cache.host_kv_gib * 2**30) // per_block,
            )
        if config.cache.remote_kv_url:
            from ..kvstore.client import RemoteKVTier

            self.remote_tier = RemoteKVTier(
                config.cache.remote_kv_url, self.model_fingerprint,
                flow=self.flow,
                heartbeat=self.threads.register("kv_writer"),
                codec=self.kv_codec if self.kv_codec.enabled else None,
            )
            # the remote tier stages through the host ring; give it a
            # minimal ring even when CPU offload wasn't asked for
            num_host_blocks = max(num_host_blocks, 16)
        disk_tier = None
        if config.cache.disk_kv_dir and config.cache.disk_kv_gib > 0:
            from .kv_disk_tier import DiskKVTier

            disk_tier = DiskKVTier(
                config.cache.disk_kv_dir,
                int(config.cache.disk_kv_gib * 2**30),
                fingerprint=self.model_fingerprint,
                flow=self.flow,
                codec=self.kv_codec if self.kv_codec.enabled else None,
            )
            num_host_blocks = max(num_host_blocks, 16)
        # peer-engine KV tier (docs/35-peer-kv-reuse.md): pull a prefix
        # resident only in ANOTHER engine's HBM/host tiers instead of
        # recomputing it. Opt-in (--kv-peer-fetch): the consuming side
        # needs a host ring to stage adoptions through, so enabling it
        # floors the ring like the remote tier does. The SERVING side
        # (/kv/peer_contains, /kv/peer_fetch) is always mounted — an
        # engine can be an owner without consuming the tier.
        self.peer_tier = None
        peer_enabled = (
            config.kv_peer_fetch and config.cache.enable_prefix_caching
        )
        if peer_enabled:
            num_host_blocks = max(num_host_blocks, 16)
        encode_ring = (
            self.kv_codec.enabled and config.cache.kv_at_rest_host_ring
        )
        if encode_ring and num_host_blocks > 0:
            # ring entries are held in wire form, so the same host-RAM
            # budget buys wire-ratio× more blocks — effective CPU-offload
            # capacity scales with the codec's compression
            ratio = self.kv_codec.wire_ratio(
                config.cache.resolved_kv_dtype(config.model.dtype)
            )
            num_host_blocks = int(num_host_blocks * ratio)
        if num_host_blocks > 0:
            from .kv_host_tier import HostKVTier

            self.host_tier = HostKVTier(
                num_host_blocks,
                self.runner.fetch_block,
                self.runner.upload_block,
                remote=self.remote_tier,
                upload_blocks=self.runner.upload_blocks,
                disk=disk_tier,
                flow=self.flow,
                codec=self.kv_codec if self.kv_codec.enabled else None,
                encode_ring=encode_ring,
            )
        if peer_enabled:
            # lookup/identity wiring mirrors the KV event publisher's
            # (engine/server.py): POD_IP/ENGINE_PORT is this engine's
            # cluster-visible identity, and the first KV_CONTROLLER_URL
            # subscriber doubles as the cluster-lookup service for owner
            # rediscovery (the router's x-kv-owner-hint skips it)
            import os

            from .kv_peer import PeerKVTier

            pod_ip = os.environ.get("POD_IP") or ""
            self_url = (
                f"http://{pod_ip}:{os.environ.get('ENGINE_PORT', '8000')}"
                if pod_ip else ""
            )
            subscribers = [
                u.strip()
                for u in (os.environ.get("KV_CONTROLLER_URL") or "").split(",")
                if u.strip()
            ]
            self.peer_tier = PeerKVTier(
                fingerprint=self.model_fingerprint,
                self_url=self_url,
                lookup_url=subscribers[0] if subscribers else "",
                timeout=config.kv_peer_fetch_timeout_s,
                flow=self.flow,
            )
            if config.kv_peer_transport in ("auto", "device"):
                # mesh-peer transport (docs/39-device-peer-kv.md): attach
                # this process's mesh identity so lookups/registrations
                # advertise it and /peer_lookup replies can negotiate the
                # device path. No identity (no KV_MESH_GROUP, or
                # jax.distributed uninitialized) degrades to HTTP.
                from .kv_device_transfer import device_transport_identity

                identity = device_transport_identity()
                if identity is None and config.kv_peer_transport == "device":
                    logger.warning(
                        "kv_peer_transport=device but no mesh identity "
                        "(KV_MESH_GROUP unset or jax.distributed not "
                        "initialized); peer pulls stay on HTTP"
                    )
                self.peer_tier.transport_identity = identity
        # compute-or-load hydration planner (docs/31-hydration-planner.md):
        # only engines with a rung BELOW the host ring (disk / remote /
        # peer) ever face the blocking-load-vs-recompute choice; everything
        # else keeps the legacy admission path untouched.
        self.hydrator = None
        if (
            config.kv_hydration != "sync"
            and config.cache.enable_prefix_caching
            and self.host_tier is not None
            and (
                disk_tier is not None
                or self.remote_tier is not None
                or self.peer_tier is not None
            )
        ):
            from .hydration import Hydrator

            device_pull_fn = None
            if (
                self.peer_tier is not None
                and self.peer_tier.transport_identity is not None
            ):
                device_pull_fn = self._device_peer_pull
            self.hydrator = Hydrator(
                mode=config.kv_hydration,
                chunk_blocks=config.kv_hydration_chunk_blocks,
                timeout_s=config.kv_hydration_timeout_s,
                flow=self.flow,
                signal_fn=lambda: self.hydration_signal(),
                host_tier=self.host_tier,
                peer=self.peer_tier,
                heartbeat=self.threads.register("hydration_fetch"),
                device_pull_fn=device_pull_fn,
            )
        self.scheduler = Scheduler(
            config.model, config.cache, config.scheduler,
            host_tier=self.host_tier,
            need_slot_mappings=config.parallel.sequence_parallel_size > 1,
            flow=self.flow,
            hydrator=self.hydrator,
        )
        if self.runner.kv_caches:
            # page geometry the remote-match path validates fetched blocks
            # against — same definition the KV import path enforces
            from .kv_transfer import engine_block_shape

            self.scheduler.pool.expected_block_shape = engine_block_shape(
                self.runner
            )
        # draft-model proposer (--speculative-config draft, docs/36): a
        # second, small ModelRunner whose paged KV lives in its OWN device
        # arrays but whose block ids come from the SHARED KVBlockPool via
        # the scratch namespace — one allocator, one byte budget, and a
        # draft page can never satisfy a prefix match or peer lookup
        # (never content-addressed). N-gram stays the zero-weight fallback.
        self.draft_runner = None
        sch = config.scheduler
        if sch.num_speculative_tokens > 0 and sch.speculative_method == "draft":
            import dataclasses as _dc

            from ..models.registry import resolve_model_config
            from .spec_decode import DraftModelProposer

            draft_model = resolve_model_config(
                sch.draft_model, max_model_len=config.model.max_model_len
            )
            if draft_model.vocab_size != config.model.vocab_size:
                raise ValueError(
                    f"draft model {sch.draft_model!r} vocab "
                    f"({draft_model.vocab_size}) differs from the target "
                    f"model's ({config.model.vocab_size}) — the proposer "
                    "contract is a shared tokenizer: a larger draft vocab "
                    "can propose ids the target's embedding cannot gather "
                    "(XLA clamps out-of-range gathers SILENTLY — garbage "
                    "KV, not an error), a smaller one cannot ingest every "
                    "target id at catch-up"
                )
            draft_cfg = EngineConfig(
                model=draft_model,
                # same block geometry so pool block ids map 1:1 onto the
                # draft arrays' page axis; no lower tiers — the draft's KV
                # is recompute-cheap scratch, never offloaded
                cache=_dc.replace(
                    config.cache, num_host_blocks=0, host_kv_gib=0.0,
                    disk_kv_dir="", disk_kv_gib=0.0, remote_kv_url="",
                ),
                # same bucket ladders: draft batches pad up through the
                # identical program cache, so draft-batch shapes can't
                # retrigger compilation mid-traffic. The draft itself never
                # runs a verify program.
                scheduler=_dc.replace(
                    config.scheduler, num_speculative_tokens=0,
                    draft_model="",
                ),
                # same seed: a random-weight draft that happens to share
                # the target's exact config reproduces its weights — the
                # acceptance≈1 fixture tests and benches lean on
                seed=config.seed,
            )
            self.draft_runner = ModelRunner(draft_cfg)
            self.scheduler.draft_proposer = DraftModelProposer(
                self.draft_runner,
                self.scheduler.pool,
                max_model_len=config.model.max_model_len,
            )
        # background-compile liveness: both runners' bg compile jobs beat
        # ONE "bg_compile" heartbeat (busy only while a compile runs — a
        # beat older than the generous threshold while busy is the "XLA
        # compiles forever" wedge, docs/37-flight-recorder.md)
        bg_hb = self.threads.register(
            "bg_compile", stall_after_s=DEFAULT_BG_COMPILE_STALL_S
        )
        self.runner.heartbeat = bg_hb
        if self.draft_runner is not None:
            self.draft_runner.heartbeat = bg_hb
        # XLA compile telemetry (docs/42-compile-telemetry.md): ONE watch
        # shared by both runners (the draft's program cache is the same
        # failure axis) — entries carry role="target"/"draft"
        from .compile_watch import CompileWatch

        self.compile_watch = CompileWatch(
            enabled=config.compile_watch,
            storm_threshold=config.compile_storm_threshold,
            storm_window_s=config.compile_storm_window_s,
            recorder=self.flightrec,
        )
        self.runner.compile_watch = self.compile_watch
        if self.draft_runner is not None:
            self.draft_runner.compile_watch = self.compile_watch
            self.draft_runner.compile_role = "draft"
        self._states: dict[str, _RequestState] = {}
        self._lora_slots: dict[str, int] = {}  # adapter name -> slot index
        self._lora_paths: dict[str, str] = {}  # adapter name -> source path
        # KV-chain salts per adapter (name, path) — see load_lora
        self._lora_salts: dict[str, int] = {}
        self._req_counter = itertools.count()
        self._prompt_tokens = 0
        self._generation_tokens = 0
        # admission-time shedding counters (the scheduler counts mid-queue/
        # mid-decode deadline expiry separately — stats() sums them)
        self.shed_requests = 0
        self.deadline_admission_rejects = 0
        # step-phase wall-time decomposition (served-stack profiling; the
        # async server exposes this via /debug/timing). dispatch_s = host
        # time building + enqueueing device work; sync_s = host time
        # blocked in the per-step D2H result transfer; overlap_s = host
        # time that ran while a device step was in flight (the pipeline's
        # win); step_wall_s = total step() wall; rollback_n = speculative
        # steps discarded because a stop/finish/abort invalidated them.
        self.timing: dict[str, float | int] = {
            "sched_s": 0.0, "post_s": 0.0,
            "prefill_s": 0.0, "prefill_n": 0, "prefill_tokens": 0,
            "decode_s": 0.0, "decode_n": 0, "decode_tokens": 0,
            "dispatch_s": 0.0, "sync_s": 0.0,
            "overlap_s": 0.0, "step_wall_s": 0.0, "rollback_n": 0,
        }
        # two-deep pipelined step loop (config.async_scheduling): dispatch
        # step N+1 against speculatively-advanced scheduler state before
        # step N's tokens reach the host. Speculative decoding COMPOSES
        # with it (docs/36-speculative-decoding.md): verify dispatches are
        # in-flight work like decode windows, a verify CHAINS on an
        # in-flight verify (its fed proposals are host-known under full
        # acceptance; only the bonus token is spliced device-side), and a
        # partial acceptance is just another rollback.
        self._pipeline = config.async_scheduling
        self._inflight: _InflightStep | None = None
        # saturation telemetry (docs/29-saturation-slo.md): per-resolved-
        # step occupancy / padding / MFU accounting. The goodput LEDGER
        # lives on the scheduler (always on — its counters are part of the
        # metric contract); the meter is the togglable part
        # (--step-metering false) whose cost the bench's saturation phase
        # measures.
        self.meter = StepMeter(
            config.model, config.scheduler, enabled=config.step_metering
        )
        # structured output (docs/41-structured-output.md): compiled-
        # grammar LRU, lazily built on the first constrained request (the
        # engine is the only layer holding both tokenizer and model vocab
        # size — padding tokens past the tokenizer's range lift to "never
        # admissible"), plus the terminal-outcome counters behind
        # tpu:structured_requests_total
        self._grammar_cache = None
        self._structured_outcomes = {"valid": 0, "invalid": 0, "fallback": 0}
        # model_fingerprint (computed above, before the KV tiers): same
        # config + same checkpoint (or same random seed) => same KV bytes
        # for same tokens. KV adoption (disaggregated prefill) refuses
        # mismatched senders, and the remote KV store namespaces blocks by
        # it — same-shape-different-weights KV would silently corrupt
        # attention. The pool storage dtype is part of the identity:
        # adopting e.g. fp8-quantized pages into an exact bf16 cache would
        # silently mark lossy KV as byte-identical to locally computed KV.

    # -- structured output -------------------------------------------------

    @property
    def grammar_cache(self):
        """The engine's compiled-grammar LRU (grammar.GrammarCache),
        created on first use. The HTTP layer compiles specs through this
        so concurrent agent sessions sharing a schema share ONE automaton
        (and one set of device tables, keyed by grammar identity)."""
        if self._grammar_cache is None:
            from .grammar import GrammarCache

            self._grammar_cache = GrammarCache(
                self.tokenizer, self.config.model.vocab_size
            )
        return self._grammar_cache

    def count_structured(self, outcome: str) -> None:
        """Bump one tpu:structured_requests_total outcome — the engine
        counts terminal outcomes itself; the API layer calls this for
        requests that never reach the scheduler (compile-rejected =>
        invalid, constraints declined => fallback)."""
        self._structured_outcomes[outcome] = (
            self._structured_outcomes.get(outcome, 0) + 1
        )

    # -- request lifecycle -------------------------------------------------

    def add_request(
        self,
        request_id: str | None = None,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        sampling: SamplingParams | None = None,
        lora_name: str | None = None,
        deadline: float | None = None,
        tenant: TenantContext | None = None,
        kv_owner_hint: str | None = None,
    ) -> str:
        request_id = request_id or f"req-{next(self._req_counter)}"
        if prompt_token_ids is None:
            if prompt is None:
                raise ValueError("need prompt or prompt_token_ids")
            prompt_token_ids = self.tokenizer.encode(prompt)
        if lora_name is not None and lora_name not in self._lora_slots:
            # races with a concurrent unload land here too — a clear 4xx-able
            # error, not a KeyError 500
            raise ValueError(f"LoRA adapter {lora_name!r} is not loaded")
        tenant = tenant or TenantContext()
        req = Request(
            request_id=request_id,
            prompt_token_ids=list(prompt_token_ids),
            sampling=sampling or SamplingParams(),
            eos_token_id=self.tokenizer.eos_token_id,
            lora_index=self._lora_slots[lora_name] if lora_name else 0,
            lora_cache_salt=self._lora_salts[lora_name] if lora_name else 0,
            deadline=deadline,
            tenant_id=tenant.tenant_id,
            priority=tenant.priority,
            weight=tenant.weight,
            kv_owner_hint=kv_owner_hint,
        )
        if req.sampling.grammar is not None:
            from .grammar import GrammarState

            req.grammar = GrammarState(req.sampling.grammar)
        self.scheduler.add_request(req)
        self._states[request_id] = _RequestState(
            request=req, detok=IncrementalDetokenizer(self.tokenizer)
        )
        self._prompt_tokens += len(prompt_token_ids)
        return request_id

    def abort_request(self, request_id: str) -> bool:
        req = self.scheduler.abort_request(request_id)
        self._states.pop(request_id, None)
        return req is not None

    # -- LoRA adapters (reference contract: vLLM /v1/load_lora_adapter used
    #    by the LoRA controller, loraadapter_controller.go:582-611) ---------

    def load_lora(self, name: str, path: str) -> None:
        """Parse a PEFT adapter dir and install it into a free slot; serving
        `model=name` then computes base + (alpha/r)·B·A per request."""
        from ..models.lora_loader import load_lora_adapter

        if self.config.lora.max_loras == 0:
            raise RuntimeError(
                "LoRA is disabled (lora.max_loras=0); restart the engine "
                "with adapter slots to load adapters"
            )
        if name in self._lora_slots:
            raise ValueError(f"adapter {name!r} is already loaded")
        used = set(self._lora_slots.values())
        free = [
            s for s in range(1, self.config.lora.num_slots) if s not in used
        ]
        if not free:
            raise RuntimeError(
                f"all {self.config.lora.max_loras} adapter slots in use"
            )
        adapter = load_lora_adapter(path, self.config.model, self.config.lora)
        self.runner.install_lora(free[0], adapter)
        self._lora_slots[name] = free[0]
        self._lora_paths[name] = path
        # STABLE across engines serving the same adapter CONTENT — the LoRA
        # controller loads adapters under one name cluster-wide, and
        # cross-engine KV transfer needs the salted chains to line up, so
        # the seed is (name, file bytes) and deliberately NOT the local path
        # (per-node download dirs differ). The content digest also means
        # overwriting an adapter in place and reloading it can never
        # prefix-hit the old weights' cached KV. Chunked read: this runs
        # under the engine lock and adapters can be hundreds of MB
        import hashlib
        import os

        digest = hashlib.sha256(name.encode() + b"\0")
        sft = os.path.join(path, "adapter_model.safetensors")
        with open(sft, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                digest.update(chunk)
        # 63 bits: chain_hash packs tuple entries as signed 8-byte ints
        self._lora_salts[name] = (
            int.from_bytes(digest.digest()[:8], "little") >> 1
        ) or 1

    def unload_lora(self, name: str) -> None:
        slot = self._lora_slots.get(name)
        if slot is None:
            raise KeyError(f"adapter {name!r} is not loaded")
        # an in-flight request would silently continue on zeroed (or, after a
        # slot-reusing load, a DIFFERENT adapter's) weights — refuse instead
        busy = [
            r.request_id
            for r in (*self.scheduler.running, *self.scheduler.waiting)
            if r.lora_index == slot
        ]
        if busy:
            raise RuntimeError(
                f"adapter {name!r} is serving request(s) {busy[:3]}; drain "
                "or abort them before unloading"
            )
        del self._lora_slots[name]
        self._lora_paths.pop(name, None)
        self._lora_salts.pop(name, None)
        self.runner.remove_lora(slot)

    def list_loras(self) -> list[str]:
        return sorted(self._lora_slots)

    @property
    def lora_adapters(self) -> dict[str, str]:
        """name → source path of loaded adapters (the single registry — the
        server and /v1/models read this view)."""
        return dict(self._lora_paths)

    def _cache_root(self, lora_name: str | None) -> int:
        """Chain root for lookups/exports: salted when the name is a loaded
        adapter (its KV differs from base KV), the pool root otherwise."""
        from .kv_cache import chain_hash

        salt = self._lora_salts.get(lora_name or "")
        if salt:
            return chain_hash(self.scheduler.pool.root_hash(), (salt,))
        return self.scheduler.pool.root_hash()

    def embed(
        self,
        inputs: list[str] | list[list[int]],
    ) -> tuple[list[list[float]], int]:
        """OpenAI /v1/embeddings backend: last-token pooled, L2-normalized
        final hidden states (how decoder-only embedding serving works in the
        reference's engines). Returns (vectors, total prompt tokens)."""
        import numbers

        rows: list[list[int]] = []
        vocab = self.config.model.vocab_size
        for x in inputs:
            if isinstance(x, str):
                rows.append(self.tokenizer.encode(x))
            elif isinstance(x, list) and all(
                isinstance(t, numbers.Integral) and not isinstance(t, bool)
                for t in x
            ):
                bad = [int(t) for t in x if not 0 <= t < vocab]
                if bad:
                    # JAX gathers CLAMP out-of-range ids — that would be a
                    # silent wrong-answer, not an error
                    raise ValueError(
                        f"token id(s) {bad[:3]} out of range [0, {vocab})"
                    )
                rows.append([int(t) for t in x])
            else:
                raise ValueError(
                    "each embedding input must be a string or a list of "
                    "token ids"
                )
        max_t = self.config.model.max_model_len
        for r in rows:
            if not r:
                raise ValueError("empty embedding input")
            if len(r) > max_t:
                raise ValueError(
                    f"embedding input of {len(r)} tokens exceeds "
                    f"max_model_len ({max_t})"
                )
        vectors = self.runner.embed(rows).tolist()
        return vectors, sum(len(r) for r in rows)

    def warmup(self, scope: str = "full") -> int:
        """Compile the serving program set BEFORE traffic: without this the
        first request into each shape bucket eats a 10-40s XLA compile while
        holding the engine lock (VERDICT r1 weak #7).

        scope="coarse": compile only the DOMINATING shape lattice — full
        rows × the largest chunk bucket, walking the context-width ladder to
        its top, plus every pow2 decode window at full batch/width. With
        the runner's pad-up fallback (model_runner._pick_prefill_shape), a
        coarse-warmed engine serves with ZERO compile stalls from boot:
        any finer program key pads up to a coarse program while the exact
        one compiles in the background. Minutes, not tens of minutes.

        scope="full": the coarse pass plus the fine ladder — every
        reachable prefill bucket × pow2 row count, every decode bucket ×
        window, the width ladder, and the logprobs/min_tokens static
        variants. Fallback is disabled for the duration so every wave
        compiles its exact program (deterministic steady-state perf;
        compiles land in the persistent XLA cache, so "full" costs its
        wall time once per model/bucket-set). Returns warmup passes run."""
        import numpy as np

        sched = self.config.scheduler
        cfg = self.config.model
        usable_tokens = (
            self.scheduler.pool.num_usable * self.config.cache.block_size
        )
        passes = 0

        def wave(
            rows: int, prompt_len: int, max_tokens: int,
            logprobs: int | None = None, min_tokens: int = 0,
            row_lens: list[int] | None = None,
            seed_base: int | None = None,
        ) -> None:
            nonlocal passes
            lens = row_lens if row_lens is not None else [prompt_len] * rows
            base = seed_base if seed_base is not None else 7000 + passes * 131
            prompts = [
                list(
                    np.random.RandomState(base + i).randint(
                        1, cfg.vocab_size, size=n
                    )
                )
                for i, n in enumerate(lens)
            ]
            self.generate(
                prompts,
                SamplingParams(max_tokens=max_tokens, temperature=0.0,
                               ignore_eos=True, logprobs=logprobs,
                               min_tokens=min_tokens),
            )
            passes += 1

        # every wave must compile its EXACT program — padding a warmup wave
        # up to an earlier coarse program would silently skip the compile
        # the wave exists for
        self.runner.fallback_enabled = False
        try:
            # -- coarse dominating pass (both scopes): AOT-compile the
            # dominating lattice directly — no tokens generated, no pool
            # capacity needed, and the TOP width program exists even when
            # the pool cannot physically hold max_num_seqs × max_model_len
            # (generate-based waves could never reach that shape)
            passes += self.runner.precompile_dominating()
            if scope == "coarse":
                logger.info("coarse warmup compiled %d programs", passes)
                return passes
            # -- fine ladder (scope="full") ---------------------------------
            longest_chunk = min(
                sched.max_num_batched_tokens, cfg.max_model_len - 1
            )
            prev_bucket = 0
            for t in sorted(sched.prefill_buckets):
                # bucket t is reachable iff some chunk length in
                # (prev_bucket, longest_chunk] pads up to it (bucket_for picks
                # the smallest bucket >= the chunk)
                if prev_bucket >= longest_chunk:
                    break
                prompt_len = min(t, longest_chunk)
                per_seq = prompt_len + sched.decode_window + 1
                rows = max(1, min(sched.max_num_seqs, usable_tokens // per_seq))
                wave(rows, prompt_len, 1)
                # row-COUNT buckets: the prefill program key includes the pow2-
                # padded row count, and production batches mix one long chunk
                # with many short residuals — 1..max_num_seqs rows all occur.
                # Missing these was the live-stack collapse mode: every new
                # (rows, bucket) pair stalled serving for a 30-60s compile
                # while queued decoders starved. One mixed-length wave per pow2
                # row count covers them (lead row lands bucket t, 16-token
                # residuals fill the rows within the token budget).
                r = 1
                while r <= sched.max_num_seqs:
                    lead = min(
                        t, longest_chunk,
                        sched.max_num_batched_tokens - (r - 1) * 16,
                    )
                    if lead <= prev_bucket or r == rows:
                        r *= 2
                        continue  # combo unreachable or already warmed above
                    wave(r, lead, 1, row_lens=[lead] + [16] * (r - 1))
                    r *= 2
                prev_bucket = t
            w = 1
            while w <= sched.decode_window:
                for b in sched.decode_buckets:
                    if b > sched.max_num_seqs:
                        continue  # unreachable batch bucket
                    per_seq = 8 + w + 2
                    rows = max(1, min(b, usable_tokens // per_seq))
                    if rows == b or b == min(sched.decode_buckets):
                        # prefill emits the FIRST output token, so max_tokens
                        # w+1 leaves exactly w for the fused window — hitting
                        # window program w, not round_up_pow2(w-1)
                        wave(rows, 8, w + 1)
                w *= 2
            # block-table WIDTH buckets: the (floored) pow2 width of the
            # batch's longest context is part of every program key
            # (model_runner._block_table_array). Without these waves, a long
            # conversation's first crossing of each width boundary stalls
            # serving for a 30-60s compile — the measured live-stack collapse
            # mode. One 1-row wave per width above the 64-block floor walks a
            # request's context up the ladder (chunked prefill compiles the
            # prefill widths on the way; the trailing window compiles the
            # decode width).
            bs_tok = self.config.cache.block_size
            max_w = self.runner.max_blocks
            floor_w = sched.width_floor_blocks  # ladder starts above the floor
            width = floor_w * 2
            widths = []
            while width < max_w:
                widths.append(width)
                width *= 2
            if max_w > floor_w and max_w not in widths:
                widths.append(max_w)
            prev_len = 0
            for w_blocks in widths:
                prompt_len = min(
                    w_blocks * bs_tok, cfg.max_model_len, usable_tokens
                ) - sched.decode_window - 2
                if prompt_len <= prev_len:
                    break  # achievable context saturated: nothing new compiles
                wave(1, prompt_len, sched.decode_window + 1)
                prev_len = prompt_len
            # logprobs variants (want_logprobs is a static jit arg -> separate
            # programs): warm the largest prefill bucket and every decode bucket
            # at the full window — the common production hit. Smaller windows'
            # logprob variants still compile lazily (warming the full cross
            # product would double warmup time for a rarely-mixed dimension).
            for extra in ({"logprobs": 0}, {"min_tokens": 1}):
                # largest reachable prefill bucket: the common production hit
                wave(1, min(sorted(sched.prefill_buckets)[-1], longest_chunk), 1,
                     **extra)
                for b in sched.decode_buckets:
                    if b > sched.max_num_seqs:
                        continue
                    per_seq = 8 + sched.decode_window + 2
                    rows = max(1, min(b, usable_tokens // per_seq))
                    if rows == b or b == min(sched.decode_buckets):
                        wave(rows, 8, sched.decode_window + 1, **extra)
            logger.info("warmup ran %d bucket passes", passes)
            return passes
        finally:
            self.runner.fallback_enabled = True

    def kv_export(
        self,
        text: str | None = None,
        token_ids: list[int] | None = None,
        lora_name: str | None = None,
    ):
        """Disaggregated prefill: export the prompt's resident KV blocks
        (engine/kv_transfer.py). Called on the prefill engine."""
        from .kv_transfer import KVTransfer

        if token_ids is None:
            token_ids = self.tokenizer.encode(text or "")
        return KVTransfer(self.scheduler.pool, self.runner).export_prompt(
            list(token_ids), parent=self._cache_root(lora_name)
        )

    def kv_export_lazy(
        self,
        text: str | None = None,
        token_ids: list[int] | None = None,
        lora_name: str | None = None,
    ):
        """Streaming-sender variant of kv_export: dispatches the device→host
        copies and returns (hashes, per-block device slices) — resolution to
        numpy happens off the engine lock, per block, as frames go out."""
        from .kv_transfer import KVTransfer

        if token_ids is None:
            token_ids = self.tokenizer.encode(text or "")
        return KVTransfer(
            self.scheduler.pool, self.runner
        ).export_prompt_lazy(list(token_ids), parent=self._cache_root(lora_name))

    def kv_import(self, hashes, blocks, fingerprint: str = "") -> int:
        """Disaggregated prefill: adopt shipped KV blocks into this
        engine's pool. Called on the decode engine. Refuses KV from a sender
        with different weights (fingerprint mismatch)."""
        from .kv_transfer import KVTransfer

        if fingerprint != self.model_fingerprint:
            # empty counts as mismatch too: the wire format always carries a
            # fingerprint, so a missing one means a foreign/corrupt sender
            raise ValueError(
                f"KV fingerprint mismatch: sender {fingerprint!r} != this "
                f"engine {self.model_fingerprint!r} — refusing foreign KV"
            )
        return KVTransfer(self.scheduler.pool, self.runner).import_blocks(
            hashes, blocks
        )

    def kv_peer_replicate(self, owner: str, hashes: list[int]) -> int:
        """Flash-crowd replication target half (docs/39-device-peer-kv.md):
        fetch `hashes` from `owner` over the HTTP peer path and adopt them
        as parked evictable blocks. The wire fetch runs on the caller's
        thread OFF the step lock (seconds of wire time must not stall
        decode); only the adoption quiesces the step loop."""
        import numpy as np

        if self.peer_tier is None:
            return 0
        got = self.peer_tier.fetch_run(owner, list(hashes))
        if not got:
            return 0
        from .kv_codec import decode_block
        from .kv_transfer import KVTransfer

        blocks = np.stack([decode_block(g) for g in got])
        with self.step_lock:
            return KVTransfer(
                self.scheduler.pool, self.runner
            ).import_blocks(list(hashes)[: len(got)], blocks)

    def kv_lookup(self, text: str | None = None,
                  token_ids: list[int] | None = None,
                  lora_name: str | None = None) -> int:
        """Longest KV prefix (tokens) resident across HBM + host tiers —
        the probe behind KV-aware routing (reference: LMCache controller
        LookupMsg, routing_logic.py:264-344). `lora_name` (the request's
        model field) salts the chain for adapter traffic so routing doesn't
        chase base-model KV a LoRA request can't reuse."""
        if token_ids is None:
            token_ids = self.tokenizer.encode(text or "")
        return self.scheduler.pool.match_length(
            list(token_ids), parent=self._cache_root(lora_name)
        )

    def kv_peer_contains(self, hashes: list[int]) -> int:
        """Consecutive count of `hashes` this engine can serve from its
        local tiers — the /kv/peer_contains probe another engine's
        hydration planner issues before trusting the cluster index
        (docs/35-peer-kv-reuse.md). Safe WITHOUT the engine lock: pure
        GIL-atomic containment, and the probe is staleness-tolerant by
        design (fetch/adoption re-validate)."""
        from .kv_transfer import KVTransfer

        return KVTransfer(self.scheduler.pool, self.runner).contains_hashes(
            hashes
        )

    def kv_peer_export(self, hashes: list[int]):
        """(served, entries) for the consecutive locally-resident prefix
        of `hashes` — the /kv/peer_fetch sender half. Caller holds the
        engine lock; entries resolve to numpy OFF the lock (see
        KVTransfer.export_hashes)."""
        from .kv_transfer import KVTransfer

        return KVTransfer(self.scheduler.pool, self.runner).export_hashes(
            hashes
        )

    def kv_bytes_per_token(self) -> float:
        """Analytic KV bytes per token as they'd cross a migration link
        (block_bytes / block_size, divided by the at-rest codec's wire
        ratio when one is configured) — the tpu:kv_bytes_per_token gauge
        the router's route-vs-migrate scoring prices transfers with. WIRE
        bytes on purpose: a migrate under int4-at-rest moves codec
        payloads, so pricing logical bytes would overstate its cost and
        bias the router toward routing."""
        from .memory import kv_block_bytes

        dtype_name = self.config.cache.resolved_kv_dtype(
            self.config.model.dtype
        )
        logical = kv_block_bytes(
            self.config.model,
            self.config.cache.block_size,
            self.config.parallel.tensor_parallel_size,
            self.config.parallel.pipeline_parallel_size,
            kv_dtype=dtype_name,
        ) / self.config.cache.block_size
        return logical / self.kv_codec.wire_ratio(dtype_name)

    def has_request(self, request_id: str) -> bool:
        return request_id in self._states

    def validate_new_request(
        self, prompt_token_ids: list[int], lora_name: str | None = None
    ) -> None:
        """Admission checks that need NO engine lock (static config + GIL-
        atomic dict reads) — the async server validates before queueing so
        rejections stay synchronous 4xx errors even though admission itself
        is deferred to the step thread (the submit path must never contend
        with a running device step)."""
        n = len(prompt_token_ids)
        if n >= self.config.model.max_model_len:
            raise ValueError(
                f"prompt of {n} tokens exceeds "
                f"max_model_len={self.config.model.max_model_len}"
            )
        if (
            self.scheduler._blocks_needed(n + 1)
            > self.scheduler.pool.num_usable
        ):
            raise ValueError(
                f"prompt of {n} tokens cannot fit the KV pool "
                f"({self.scheduler.pool.num_usable} blocks of "
                f"{self.scheduler.block_size})"
            )
        if lora_name is not None and lora_name not in self._lora_slots:
            raise ValueError(f"LoRA adapter {lora_name!r} is not loaded")

    # -- admission control / load shedding ---------------------------------

    def observed_tokens_per_s(self) -> float:
        """Generation throughput — the denominator for Retry-After and
        queue-wait estimates. Decode-phase timing, NOT total step wall:
        warmup/lazy XLA compiles land in step_wall_s and would poison the
        estimate for the process's whole life (a 3 tok/s "observed rate"
        right after boot made the admission gate shed everything). 0.0
        before the first decode resolves (callers treat as "unknown")."""
        dec_s = float(self.timing["decode_s"])
        dec_t = float(self.timing["decode_tokens"])
        if dec_s > 0.0 and dec_t > 0:
            return dec_t / dec_s
        return 0.0

    def queue_depth(
        self, exclude_prefix: str | None = None
    ) -> tuple[int, int]:
        """(waiting requests, waiting prompt tokens still to prefill), read
        without the engine lock. Unlike len(), ITERATING a deque the step
        thread is mutating raises RuntimeError — retry the snapshot a few
        times and degrade to a request-count-only answer rather than turn
        an admission check or health probe into a 500. exclude_prefix
        drops a request's own sibling choices from the count (see
        check_admission)."""
        from .async_engine import _same_request

        for _ in range(5):
            waiting = self.scheduler.waiting
            try:
                snap = list(waiting)
            except RuntimeError:  # deque mutated during iteration
                continue
            if exclude_prefix is not None:
                snap = [
                    r for r in snap
                    if not _same_request(r.request_id, exclude_prefix)
                ]
            return len(snap), sum(
                max(0, r.prefill_target - r.num_computed_tokens)
                for r in snap
            )
        return len(self.scheduler.waiting), 0

    def estimate_retry_after_s(self, queued_tokens: int) -> float:
        """Seconds until the current backlog plausibly clears, from observed
        decode throughput — the Retry-After a 429 carries. Clamped to
        [1, 60]: never tell a client "retry now" while shedding, never park
        it for minutes on a stale estimate."""
        tps = self.observed_tokens_per_s()
        if tps <= 0.0:
            return 1.0
        return min(60.0, max(1.0, queued_tokens / tps))

    def check_admission(
        self,
        n_new_tokens: int,
        deadline: float | None = None,
        extra_waiting: int = 0,
        extra_tokens: int = 0,
        record: bool = True,
        exclude_prefix: str | None = None,
        tenant: TenantContext | None = None,
        evict: bool = False,
    ) -> None:
        """Load-shedding + deadline gate, run lock-free at submit time
        (extra_* carries the async server's not-yet-admitted pending queue).
        Raises EngineOverloadedError (→ 429 + Retry-After) when the bounded
        waiting queue / queued-token watermark is full, and
        DeadlineExceededError (→ 503) when the request would queue past its
        deadline — both strictly cheaper answered at the door than after
        burning prefill steps on a reply nobody will read. record=False is
        the would-this-shed probe (/ready, /health) — the shed counters
        must count refused REQUESTS, not probe polls. exclude_prefix keeps
        an n>1 request's own sibling choices out of its count — a request
        must never shed against itself."""
        cfg = self.config.scheduler
        n_waiting, queued_tokens = self.queue_depth(exclude_prefix)
        n_waiting += extra_waiting
        queued_tokens += extra_tokens

        def _shed(msg: str):
            if record:
                self.shed_requests += 1
                if tenant is not None:
                    self.scheduler.accounting.inc(tenant.tenant_id, "shed")
            raise EngineOverloadedError(
                msg, self.estimate_retry_after_s(queued_tokens)
            )

        # multi-tenant QoS: shedding is lowest-priority-first. A
        # higher-priority arrival at a full queue evicts the newest
        # strictly-lower-priority WAITING request (applied by the step
        # thread) instead of being refused itself. The victim is only
        # CLAIMED (mark_shed_victim) after every other refusal below has
        # passed — a token-watermark or deadline refusal of this arrival
        # must not also cost an already-queued request its slot — and only
        # by the submit-time check (evict=True); the pre-SSE check and
        # probe polls just peek, so one request can't evict twice.
        needs_eviction = False
        if cfg.max_waiting_requests > 0 and n_waiting >= cfg.max_waiting_requests:
            if tenant is not None and self.scheduler.has_shed_victim(
                tenant.priority
            ):
                needs_eviction = True
            else:
                _shed(
                    f"engine overloaded: {n_waiting} requests waiting "
                    f"(max_waiting_requests={cfg.max_waiting_requests})"
                )
        if cfg.max_queued_tokens > 0 and queued_tokens >= cfg.max_queued_tokens:
            _shed(
                f"engine overloaded: {queued_tokens} prompt tokens queued "
                f"(max_queued_tokens={cfg.max_queued_tokens})"
            )
        if deadline is not None:
            import time as _time

            now = _time.monotonic()
            if now > deadline:
                if record:
                    self.deadline_admission_rejects += 1
                raise DeadlineExceededError(
                    "request deadline already expired at admission"
                )
            tps = self.observed_tokens_per_s()
            if tps > 0.0:
                est_wait = (queued_tokens + n_new_tokens) / tps
                if now + est_wait > deadline:
                    if record:
                        self.deadline_admission_rejects += 1
                    raise DeadlineExceededError(
                        f"request would queue ~{est_wait:.1f}s past its "
                        "deadline; shed at admission"
                    )
        if needs_eviction and evict and record:
            # every other refusal passed: claim the lower-priority victim
            # now. The peek above and this mark race the step thread, so
            # the victim may have left the queue — then this arrival sheds
            # after all.
            if not self.scheduler.mark_shed_victim(tenant.priority):
                _shed(
                    f"engine overloaded: {n_waiting} requests waiting "
                    f"(max_waiting_requests={cfg.max_waiting_requests})"
                )

    def has_unfinished(self) -> bool:
        return self.scheduler.has_unfinished()

    # -- stepping ----------------------------------------------------------

    def step(self) -> list[RequestOutput]:
        """Schedule + execute one device step; returns per-request deltas.

        With async_scheduling (the default) this drives a TWO-DEEP
        PIPELINE: the step dispatched on the previous call is still
        executing on device while this call schedules and dispatches the
        next one against speculatively-advanced request state — decode
        inputs chain device-side from the in-flight step's output matrix —
        and only then resolves the previous step (one batched D2H sync),
        postprocesses it, and reconciles. When the reconcile shows the
        speculation was wrong (a mid-window stop token, max-tokens finish,
        stop-string hit, or abort), the just-dispatched step is discarded
        and rolled back, so the emitted token streams are bitwise identical
        to the serial loop. Outputs returned by one call therefore belong
        to the step dispatched on the PREVIOUS call (one step of latency,
        ~2x decode throughput when host and device times are comparable)."""
        if not self._pipeline:
            return self._step_sync()
        return self._step_pipelined()

    def _step_pipelined(self) -> list[RequestOutput]:
        t_enter = time.perf_counter()
        outputs: list[RequestOutput] = []
        inflight, self._inflight = self._inflight, None
        try:
            return self._step_pipelined_inner(inflight, outputs, t_enter)
        except Exception:
            if inflight is not None and not inflight.resolved:
                # the fault hit before the previous step was resolved (e.g.
                # a transient dispatch failure) — put it back so its
                # results aren't stranded; the next step (or the async
                # server's abort-all recovery) reconciles it
                self._inflight = inflight
            raise

    def _step_pipelined_inner(
        self,
        inflight: _InflightStep | None,
        outputs: list[RequestOutput],
        t_enter: float,
    ) -> list[RequestOutput]:
        t0 = time.perf_counter()
        work = self.scheduler.schedule(
            inflight=inflight.work if inflight else None
        )
        t1 = time.perf_counter()
        self.timing["sched_s"] += t1 - t0
        # requests the scheduler terminated outside a step (impossible-fit
        # re-admission abort, expired deadline) still need a terminal
        # output or streaming clients would hang forever
        for req in self.scheduler.take_finished_externally():
            outputs.append(
                self._make_output(
                    req, [], "", self._finish_reason(req) or "abort"
                )
            )
        nxt: _InflightStep | None = None
        pre_handle: StepHandle | None = None
        pre_seq = 0
        if isinstance(work, (DecodeWork, VerifyWork)):
            # a verify dispatch pipelines exactly like a decode window: its
            # rows advance speculatively by their fed length (full
            # acceptance) and the next step can chain a decode window off
            # the handle's on-device bonus-token vector
            handle = self.runner.execute_async(
                work, prev=inflight.handle if inflight else None
            )
            self.scheduler.begin_speculative(work)
            self.timing["dispatch_s"] += time.perf_counter() - t1
            nxt = _InflightStep(
                work=work, handle=handle, rec_seq=self._rec_dispatch(work)
            )
        elif isinstance(work, PrefillWork):
            # dispatched before resolving the in-flight decode so the host
            # array building overlaps device execution; resolved below in
            # this same call (prefill outputs are never speculated on)
            pre_handle = self.runner.execute_async(work)
            self.timing["dispatch_s"] += time.perf_counter() - t1
            pre_seq = self._rec_dispatch(work)
        if inflight is not None:
            # everything since step entry ran while the previous step was
            # still executing on device — the overlap the pipeline buys
            self.timing["overlap_s"] += time.perf_counter() - t_enter
            try:
                self._resolve_decode(inflight, outputs)
            except Exception:
                # the previous step's resolve faulted AFTER nxt was
                # dispatched: roll nxt back too, or its speculative window
                # would leak (rows stuck one window ahead with their
                # sampled tokens silently dropped)
                if nxt is not None:
                    self.scheduler.rollback_speculative(nxt.work)
                    nxt.handle.discard()
                    self.flightrec.discard(nxt.rec_seq)
                    self._ledger_rollback(nxt.work)
                raise
            if nxt is not None and not self.scheduler.speculation_valid(
                nxt.work
            ):
                # the reconciled state moved out from under the speculative
                # dispatch: discard it wholesale. The serial re-dispatch on
                # the next call reproduces the exact token stream (RNG is
                # rewound by discard()).
                self.scheduler.rollback_speculative(nxt.work)
                nxt.handle.discard()
                self.flightrec.discard(nxt.rec_seq)
                self.timing["rollback_n"] += 1
                self._ledger_rollback(nxt.work)
                nxt = None
        if work is None and inflight is not None and nxt is None:
            # a priority stall: the scheduler declined to chain because a
            # higher-priority waiter was blocked by in-flight victims. The
            # victims are resolved now — re-schedule IN THIS CALL so the
            # preempt-and-admit happens a full loop round-trip sooner
            # (the realtime arrival's TTFT is the point of the stall).
            t4 = time.perf_counter()
            work2 = self.scheduler.schedule()
            self.timing["sched_s"] += time.perf_counter() - t4
            for req in self.scheduler.take_finished_externally():
                outputs.append(
                    self._make_output(
                        req, [], "", self._finish_reason(req) or "abort"
                    )
                )
            if work2 is not None:
                self._execute_sync(work2, outputs, time.perf_counter())
        if (
            work is None
            and inflight is None
            and nxt is None
            and self.scheduler.hydration_parked()
        ):
            # the only schedulable work is parked at a pending hydration
            # fetch: yield a beat instead of busy-spinning step() — the
            # spin would contend the GIL with the very fetcher thread
            # whose landing we're waiting on, inflating the fetch latency
            # the planner priced
            time.sleep(0.001)
        if pre_handle is not None:
            t2 = time.perf_counter()
            rows = pre_handle.resolve()
            self.flightrec.resolve(pre_seq)
            t3 = time.perf_counter()
            self.timing["sync_s"] += pre_handle.sync_s
            self.timing["prefill_s"] += t3 - t2
            self.timing["prefill_n"] += 1
            self.timing["prefill_tokens"] += sum(
                len(t) for t in work.token_ids
            )
            results = self.scheduler.postprocess(work, rows)
            self._meter_prefill(work)
            self._emit_results(results, pre_handle.logprob_rows, outputs)
            self.timing["post_s"] += time.perf_counter() - t3
        self._inflight = nxt
        self.timing["step_wall_s"] += time.perf_counter() - t_enter
        self._drop_finished(outputs)
        return outputs

    def _resolve_decode(
        self, inflight: _InflightStep, outputs: list[RequestOutput]
    ) -> None:
        """Resolve the in-flight decode step — the decode hot path's single
        host sync — then reconcile its real results into the scheduler."""
        work, handle = inflight.work, inflight.handle
        t0 = time.perf_counter()
        rows = handle.resolve()
        inflight.resolved = True
        self.flightrec.resolve(inflight.rec_seq)
        t1 = time.perf_counter()
        self.timing["sync_s"] += handle.sync_s
        self.timing["decode_s"] += t1 - t0
        self.timing["decode_n"] += 1
        self.scheduler.end_speculative(work)
        results = self.scheduler.postprocess(work, rows)
        accepted = sum(len(t) for _, t in results)
        self.timing["decode_tokens"] += accepted
        self._meter_decode(work, accepted)
        self._emit_results(results, handle.logprob_rows, outputs)
        self.timing["post_s"] += time.perf_counter() - t1

    def _step_sync(self) -> list[RequestOutput]:
        """The serial fallback loop: schedule → execute → sync →
        postprocess, one step per call (async_scheduling=False)."""
        t0 = time.perf_counter()
        work = self.scheduler.schedule()
        t1 = time.perf_counter()
        self.timing["sched_s"] += t1 - t0
        outputs: list[RequestOutput] = []
        # requests the scheduler terminated outside a step still need a
        # terminal output or streaming clients would hang forever
        for req in self.scheduler.take_finished_externally():
            outputs.append(
                self._make_output(
                    req, [], "", self._finish_reason(req) or "abort"
                )
            )
        if work is None:
            if self.scheduler.hydration_parked():
                time.sleep(0.001)  # see the pipelined loop's parked note
            self._drop_finished(outputs)
            return outputs
        self._execute_sync(work, outputs, t1)
        self.timing["step_wall_s"] += time.perf_counter() - t0
        self._drop_finished(outputs)
        return outputs

    def _execute_sync(self, work, outputs: list[RequestOutput], t1: float):
        seq = self._rec_dispatch(work)
        sampled = self.runner.execute(work)
        self.flightrec.resolve(seq)
        t2 = time.perf_counter()
        kind = "prefill" if isinstance(work, PrefillWork) else "decode"
        self.timing[kind + "_s"] += t2 - t1
        self.timing[kind + "_n"] += 1
        self.timing["sync_s"] += self.runner.last_sync_s
        self.timing["dispatch_s"] += max(
            0.0, (t2 - t1) - self.runner.last_sync_s
        )
        lp_rows = self.runner.last_logprobs  # parallel to sampled rows
        results = self.scheduler.postprocess(work, sampled)
        self.timing[kind + "_tokens"] += (
            # chunk tokens PROCESSED (mid-prompt chunks emit none)
            sum(len(t) for t in work.token_ids)
            if kind == "prefill"
            # tokens actually ACCEPTED — exact for both the fused window
            # (mid-window stops discard the tail) and spec-decode verify
            # (1..k+1 accepted per row)
            else sum(len(toks) for _, toks in results)
        )
        if kind == "prefill":
            self._meter_prefill(work)
        else:
            self._meter_decode(work, sum(len(toks) for _, toks in results))
        self._emit_results(results, lp_rows, outputs)
        self.timing["post_s"] += time.perf_counter() - t2

    # -- flight recorder (docs/37-flight-recorder.md) ----------------------

    def _rec_dispatch(self, work) -> int:
        """One black-box record per device dispatch: batch shape + phase,
        the scheduler's decision summary, and queue/pool depths — what the
        engine was doing right before it (maybe) stopped doing anything.
        Also opens the dispatch/resolve cursor the watchdog's
        unresolved-step detection keys off (tracked even with recording
        disabled)."""
        sched = self.scheduler
        if isinstance(work, PrefillWork):
            kind, window = "prefill", 0
            tokens = sum(len(t) for t in work.token_ids)
        elif isinstance(work, VerifyWork):
            kind, window = "verify", 0
            tokens = sum(len(t) for t in work.token_ids)
        else:
            kind, window = "decode", work.window
            tokens = work.window * len(work.requests)
        if self.flightrec.enabled:
            # the O(batch) queue/pool summary is only worth computing
            # when a record will actually be written; the liveness
            # cursor needs none of it
            waiting, running = sched.num_waiting, sched.num_running
            pool_usage = sched.pool.usage_perc
        else:
            waiting = running = 0
            pool_usage = 0.0
        return self.flightrec.dispatch(
            kind, rows=len(work.requests), tokens=tokens, window=window,
            waiting=waiting, running=running, pool_usage=pool_usage,
        )

    # -- saturation & goodput telemetry (docs/29-saturation-slo.md) --------

    def _ledger_rollback(self, work: DecodeWork | VerifyWork) -> None:
        """A dispatched pipeline step was discarded: the device still
        executed it, sampling window × rows (or every verify row's fed
        positions) nobody will consume — sampled AND wasted in one motion
        (they never reach postprocess)."""
        if isinstance(work, VerifyWork):
            n = sum(len(t) for t in work.token_ids)
        else:
            n = work.window * len(work.requests)
        self.scheduler.ledger.rollback(n)

    def _meter_decode(self, work, accepted: int) -> None:
        """Record one resolved decode/verify dispatch with the meter. The
        context sum feeds the attention term of the FLOP estimate: row i's
        window tokens attend ~positions[i] + j each."""
        if not self.meter.enabled:
            return
        if isinstance(work, VerifyWork):
            rows = len(work.requests)
            fed = sum(len(t) for t in work.token_ids)
            self.meter.record_decode(
                rows=rows,
                window=max(1, -(-fed // max(1, rows))),
                accepted_tokens=accepted,
                sum_context=sum(
                    sum(p) + len(p) for p in work.positions
                ),
            )
            return
        rows = len(work.requests)
        w = work.window
        self.meter.record_decode(
            rows=rows,
            window=w,
            accepted_tokens=accepted,
            sum_context=w * sum(work.positions) + rows * (w * (w + 1) // 2),
        )

    def _meter_prefill(self, work: PrefillWork) -> None:
        if not self.meter.enabled:
            return
        chunk_tokens = sum(len(t) for t in work.token_ids)
        # each chunk token attends ~its absolute position: per row the sum
        # over [start, end) is len × (start + end) / 2
        sum_ctx = 0
        for ids, end in zip(work.token_ids, work.context_lens):
            n = len(ids)
            sum_ctx += n * (2 * end - n + 1) // 2
        self.meter.record_prefill(
            rows=len(work.requests),
            chunk_tokens=chunk_tokens,
            sum_context=sum_ctx,
            max_chunk=max(len(t) for t in work.token_ids),
        )

    def _kv_tier_usage(self) -> dict[str, float]:
        """Per-tier occupancy for tpu:engine_kv_tier_usage_perc. Remote is
        the store-reported fill fraction piggybacked on PUT acks
        (kvstore/client.py last_usage_perc) — 0 until the first ack."""
        tiers = {
            "hbm": self.scheduler.pool.usage_perc,
            "host": 0.0,
            "disk": 0.0,
            "remote": 0.0,
        }
        if self.host_tier is not None:
            tiers["host"] = self.host_tier.usage_perc
            disk = self.host_tier.disk
            if disk is not None and disk.max_bytes > 0:
                tiers["disk"] = min(
                    1.0, disk.total_bytes / disk.max_bytes
                )
        if self.remote_tier is not None:
            tiers["remote"] = getattr(
                self.remote_tier, "last_usage_perc", 0.0
            )
        return tiers

    def goodput_balance(self) -> dict:
        """Ledger balance check (delegates to the scheduler — the single
        definition of "live requests"): sampled == delivered + wasted +
        pending. At quiescence pending is 0, so delivered + wasted ==
        sampled EXACTLY — tests and the bench's saturation phase assert
        `balanced`."""
        return self.scheduler.goodput_balance()

    def hydration_signal(self) -> dict:
        """The compute-or-load planner's inputs (ROADMAP item 3,
        docs/30-kv-flow-telemetry.md): measured fetch bandwidth per tier
        alongside the analytic prefill FLOP/s. The planner's per-chunk
        decision is `block_bytes / fetch_bw` (load cost) vs
        `block_size_tokens × flops_per_token / prefill_flops_per_s`
        (recompute cost) — both denominators MEASURED here, not guessed.
        prefill_flops_per_s is the StepMeter's achieved-FLOP/s EWMA (0
        before any step resolves — fall back to a peak_flops_per_s
        fraction until traffic warms it); bandwidths are 0 for tiers that
        have never moved bytes."""
        from .kv_flow import TRANSFER_TIERS
        from .memory import kv_block_bytes
        from .saturation import matmul_params

        cfg = self.config.model

        bw = self.flow.bandwidth_bytes_per_s()
        meas = self.flow.bandwidth_measured()
        sat = self.meter.snapshot()
        block_bytes = kv_block_bytes(
            self.config.model,
            self.config.cache.block_size,
            self.config.parallel.tensor_parallel_size,
            self.config.parallel.pipeline_parallel_size,
            kv_dtype=self.config.cache.resolved_kv_dtype(
                self.config.model.dtype
            ),
        )
        # per-tier WIRE bytes per block: the encoded tiers (disk/remote/
        # peer — and the host ring under kv_at_rest_host_ring) move codec
        # payloads, so the planner must price fetches at compressed size;
        # this is what shifts recompute→load crossovers under int4
        wire = block_bytes
        if self.kv_codec.enabled:
            wire = self.kv_codec.wire_block_bytes(
                block_bytes,
                self.config.cache.resolved_kv_dtype(self.config.model.dtype),
            )
        ring_encoded = (
            self.host_tier is not None
            and getattr(self.host_tier, "encode_ring", False)
        )
        return {
            "fetch_bandwidth_bytes_per_s": {
                tier: bw[(tier, "in")] for tier in TRANSFER_TIERS
            },
            # sample-floor state per tier (TierBandwidth.measured): the
            # planner never trusts an estimate built from a single tiny
            # transfer — unmeasured tiers fall back (auto: sync load;
            # planner mode: recompute)
            "fetch_bandwidth_measured": {
                tier: meas[(tier, "in")] for tier in TRANSFER_TIERS
            },
            "store_bandwidth_bytes_per_s": {
                tier: bw[(tier, "out")] for tier in TRANSFER_TIERS
            },
            "prefill_flops_per_s": sat["achieved_flops_per_s"],
            "peak_flops_per_s": sat["peak_flops_per_s"],
            "flops_per_token": 2.0 * matmul_params(cfg),
            # attention score/value FLOPs per (token, attended-context-
            # position) pair — the same coefficient the StepMeter's
            # analytic model charges (saturation.step_flops), so the
            # planner prices long-context recompute with the context term
            # the achieved-FLOP/s denominator was measured against
            "attn_flops_per_token_ctx": (
                4.0 * cfg.num_heads * cfg.head_dim * cfg.num_layers
            ),
            "block_bytes": block_bytes,
            "wire_block_bytes": {
                "hbm": block_bytes,
                "host": wire if ring_encoded else block_bytes,
                "disk": wire,
                "remote": wire,
                "peer": wire,
                # the device path moves pool-precision pages over ICI/DCN
                # collectives — the at-rest codec never touches it, so a
                # device fetch prices at full logical block bytes
                # (compression ratio pinned at 1.0, docs/39)
                "device": block_bytes,
            },
            "block_size_tokens": self.config.cache.block_size,
        }

    def _device_peer_pull(self, owner: str, hashes: list[int]) -> int:
        """Pull a hash run from `owner` over device collectives (the
        Hydrator's device fetch lane, docs/39-device-peer-kv.md). Runs on
        the FETCHER thread: the HTTP trigger (POST /kv/peer_device_pull,
        split send/read so both sides join the collective concurrently)
        happens OUTSIDE the step lock — a stalled owner stalls only this
        thread, named "hydration_fetch" by the watchdog — and only the
        collective itself quiesces the step loop under `step_lock`.
        Returns run hashes resident after the pull (parked at refcount 0
        for adoption), 0 on any failure — which records a 0-byte
        device/in sample so the fault is visible in
        tpu:kv_transfer_seconds{tier="device"}."""
        import http.client
        import json as _json
        from urllib.parse import urlsplit

        from .kv_device_transfer import pull_kv_device_crossproc

        t0 = time.perf_counter()
        conn = None
        try:
            u = urlsplit(owner)
            body = _json.dumps(
                {"hashes": [int(h) for h in hashes]}
            ).encode()
            conn = http.client.HTTPConnection(
                u.hostname, u.port or 80,
                timeout=max(5.0, self.config.kv_peer_fetch_timeout_s),
            )
            # split trigger: send the full request, DON'T read the reply
            # yet — the owner parses and enters the collective while we
            # enter ours below; the reply lands after both sides finish
            conn.putrequest("POST", "/kv/peer_device_pull")
            conn.putheader("Content-Type", "application/json")
            conn.putheader("Content-Length", str(len(body)))
            conn.endheaders()
            conn.send(body)
        except Exception:
            # trigger never reached the owner: no collective exists on
            # either side — record the honest 0-byte failure sample
            logger.exception("device peer pull trigger to %s failed", owner)
            self.flow.record(
                "device", "in", 0, 0, time.perf_counter() - t0
            )
            if conn is not None:
                conn.close()
            return 0
        try:
            with self.step_lock:
                n = pull_kv_device_crossproc(self, False, list(hashes))
        except Exception:
            # the cooperative program aborts BOTH sides (fingerprint
            # allgather / go-no-go barrier); post-barrier failures
            # metered inside the transfer, pre-barrier ones here
            logger.exception("device peer pull from %s faulted", owner)
            self.flow.record(
                "device", "in", 0, 0, time.perf_counter() - t0
            )
            n = 0
        try:
            resp = conn.getresponse()
            resp.read()
            if n and resp.status != 200:
                logger.warning(
                    "device peer pull: owner %s answered %d after a "
                    "locally-successful transfer", owner, resp.status,
                )
        except Exception:  # noqa: BLE001 — the bytes already landed
            logger.warning(
                "device peer pull: reply read from %s failed", owner
            )
        finally:
            conn.close()
        return n

    def _emit_results(
        self, results, lp_rows, outputs: list[RequestOutput]
    ) -> None:
        for row_i, (req, toks) in enumerate(results):
            if not toks:  # mid-prompt prefill chunk: progress, no tokens
                continue
            new_lp = None
            if lp_rows is not None and req.sampling.logprobs is not None:
                # accepted tokens are a prefix of the dispatched row
                new_lp = lp_rows[row_i][: len(toks)]
            self._generation_tokens += len(toks)
            if req.first_token_time is None:
                req.first_token_time = time.monotonic()
            state = self._states.get(req.request_id)
            new_text = state.detok.push(toks) if state and state.detok else ""

            if state is not None and req.sampling.stop:
                state.pending_text += new_text
                # vLLM's stop checker skips ALL stop conditions below the
                # min_tokens floor — stop STRINGS included, not just the
                # token-id conditions the scheduler masks. Text still goes
                # through pending_text so a straddling match fires once the
                # floor is crossed.
                below_min = (
                    len(req.output_token_ids) < req.sampling.min_tokens
                )
                hit = (
                    None
                    if below_min
                    else self._find_stop(state.pending_text, req.sampling.stop)
                )
                if hit is not None:
                    emit = state.pending_text[:hit]
                    state.text += emit
                    state.pending_text = ""
                    if not req.status.finished:
                        self.scheduler.finish_request(
                            req, RequestStatus.FINISHED_STOPPED
                        )
                    outputs.append(
                        self._make_output(req, toks, emit, "stop", new_lp)
                    )
                    continue
                if req.status.finished:  # eos/length: flush held-back text
                    emit = state.pending_text
                    state.text += emit
                    state.pending_text = ""
                else:  # hold back text that could be a stop-string prefix
                    emit = self._emittable(state, req.sampling.stop)
                outputs.append(
                    self._make_output(
                        req, toks, emit, self._finish_reason(req), new_lp
                    )
                )
                continue

            if state is not None:
                state.text += new_text
            outputs.append(
                self._make_output(
                    req, toks, new_text, self._finish_reason(req), new_lp
                )
            )

    def _drop_finished(self, outputs: list[RequestOutput]) -> None:
        for out in outputs:
            if out.finished:
                self._states.pop(out.request_id, None)

    def _make_output(
        self, req: Request, toks: list[int], text: str,
        finish_reason: str | None, new_logprobs=None,
    ) -> RequestOutput:
        out = RequestOutput(
            new_logprobs=new_logprobs,
            request_id=req.request_id,
            new_token_ids=toks,
            finished=req.status.finished,
            finish_reason=finish_reason,
            num_prompt_tokens=req.num_prompt_tokens,
            num_output_tokens=len(req.output_token_ids),
            num_cached_prompt_tokens=req.num_cached_prompt_tokens,
        )
        out.text_delta = text
        if req.spec_window is not None:
            # this step resolved a verify window: hand its (proposed,
            # accepted, proposer) to the tracing spine's decode_window
            # event and clear the stamp (one window, one event)
            out.spec_window, req.spec_window = req.spec_window, None
        if out.finished:
            # lifecycle stamps for the tracing spine's phase attribution —
            # carried on the terminal output because the request state is
            # reaped (_drop_finished) before the HTTP layer sees it.
            # Rollback-safe by construction: outputs only ever describe
            # RESOLVED steps (a discarded speculative dispatch never
            # reaches postprocess, so no stamp can come from it).
            out.phase_times = {
                "arrival": req.arrival_time,
                "first_seat": req.first_seat_time,
                "first_token": req.first_token_time,
                "finish": req.finish_time or time.monotonic(),
                "prompt_tokens": req.num_prompt_tokens,
                "output_tokens": len(req.output_token_ids),
                "cached_prompt_tokens": req.num_cached_prompt_tokens,
                "preemptions": req.num_preemptions,
            }
            # hydration-source partition for the trace timeline's
            # kv_hydration event (docs/30-kv-flow-telemetry.md); None for
            # requests that never got a seat
            out.hydration = req.hydration
            # planner per-chunk outcomes (docs/31-hydration-planner.md):
            # the kv_hydration event's "plan" view
            out.hydration_chunks = req.hydration_outcomes
            # structured outcome (docs/41-structured-output.md), terminal
            # only: valid iff the automaton sits in an accepting state (the
            # body parses against the schema by construction); invalid when
            # generation was cut mid-structure (length cap / abort /
            # stop-string); counted once per constrained request
            if req.sampling.grammar is not None and req.grammar is not None:
                req.grammar.sync(req.output_token_ids)
                out.structured_outcome = (
                    "valid" if req.grammar.accepting else "invalid"
                )
                self.count_structured(out.structured_outcome)
            # mid-traffic compile stalls this request blocked on, for the
            # trace timeline (docs/42-compile-telemetry.md)
            out.compile_stalls = req.compile_stalls
        return out

    @staticmethod
    def _finish_reason(req: Request) -> str | None:
        return {
            RequestStatus.FINISHED_STOPPED: "stop",
            RequestStatus.FINISHED_LENGTH: "length",
            RequestStatus.FINISHED_ABORTED: "abort",
            RequestStatus.FINISHED_DEADLINE: "deadline",
            RequestStatus.FINISHED_SHED: "shed",
        }.get(req.status)

    @staticmethod
    def _find_stop(text: str, stops: tuple[str, ...]) -> int | None:
        """Earliest match position across ALL stop strings (not first-in-tuple:
        a later-listed stop can occur earlier in the stream)."""
        best: int | None = None
        for s in stops:
            idx = text.find(s)
            if idx != -1 and (best is None or idx < best):
                best = idx
        return best

    @staticmethod
    def _emittable(state: _RequestState, stops: tuple[str, ...]) -> str:
        """Emit pending text minus the longest tail that prefixes a stop."""
        pending = state.pending_text
        hold = 0
        for s in stops:
            for k in range(min(len(s) - 1, len(pending)), 0, -1):
                if s.startswith(pending[-k:]):
                    hold = max(hold, k)
                    break
        emit = pending[: len(pending) - hold] if hold else pending
        state.pending_text = pending[len(pending) - hold :] if hold else ""
        state.text += emit
        return emit

    # -- convenience (offline / bench) ------------------------------------

    def generate(
        self,
        prompts: list[str] | list[list[int]],
        sampling: SamplingParams,
        lora_name: str | None = None,
        kv_owner_hint: str | None = None,
    ) -> list[dict]:
        """Blocking batch generation; returns [{request_id, token_ids, text}]."""
        ids = []
        for p in prompts:
            if isinstance(p, str):
                ids.append(
                    self.add_request(
                        prompt=p, sampling=sampling, lora_name=lora_name,
                        kv_owner_hint=kv_owner_hint,
                    )
                )
            else:
                ids.append(
                    self.add_request(
                        prompt_token_ids=p, sampling=sampling,
                        lora_name=lora_name, kv_owner_hint=kv_owner_hint,
                    )
                )
        done: dict[str, dict] = {
            i: {"request_id": i, "token_ids": [], "text": ""} for i in ids
        }
        while self.has_unfinished():
            # step under the quiescence lock so a concurrent device-path
            # peer pull (Hydrator fetcher thread) never races the step's
            # kv_caches donation — same discipline as AsyncEngine._lock
            with self.step_lock:
                outs = self.step()
            if not outs:
                # nothing progressed (every request parked on hydration):
                # yield the lock for real — a tight reacquire loop can
                # starve the fetcher thread whose device-path pull needs
                # the same lock to run its collective
                time.sleep(0.001)
            for out in outs:
                d = done.get(out.request_id)
                if d is None:
                    continue
                d["token_ids"].extend(out.new_token_ids)
                d["text"] += out.text_delta
                if out.finished:
                    d["finish_reason"] = out.finish_reason
        return [done[i] for i in ids]

    # -- stats / control ---------------------------------------------------

    def stats(self) -> EngineStatsSnapshot:
        pool = self.scheduler.pool
        tenants, waits = self.scheduler.accounting.snapshot(drain_waits=True)
        saturation = self.meter.snapshot()
        saturation["goodput"] = self.scheduler.ledger.snapshot()
        saturation["kv_tiers"] = self._kv_tier_usage()
        disk = self.host_tier.disk if self.host_tier is not None else None
        return EngineStatsSnapshot(
            saturation=saturation,
            kv_flow=self.flow.snapshot(),
            disk_kv_stores=disk.stats.stores if disk is not None else 0,
            disk_kv_loads=disk.stats.loads if disk is not None else 0,
            kv_bytes_per_token=self.kv_bytes_per_token(),
            num_requests_running=self.scheduler.num_running,
            num_requests_waiting=self.scheduler.num_waiting,
            kv_usage_perc=pool.usage_perc,
            prefix_cache_hit_rate=pool.stats.hit_rate,
            prefix_cache_hits=pool.stats.hits,
            prefix_cache_queries=pool.stats.queries,
            num_preemptions=self.scheduler.total_preemptions,
            # queue evictions ARE load shedding (the victim got a 429-
            # shaped refusal, just after queueing instead of at the door)
            requests_shed=self.shed_requests + self.scheduler.shed_evictions,
            tenants=tenants,
            tenant_queue_waits=waits,
            requests_deadline_expired=(
                self.deadline_admission_rejects
                + self.scheduler.deadline_expired_total
            ),
            step_overlap_frac=(
                self.timing["overlap_s"] / self.timing["step_wall_s"]
                if self.timing["step_wall_s"] > 0
                else 0.0
            ),
            spec_draft_tokens=self.scheduler.spec_proposed_tokens,
            spec_accepted_tokens=self.scheduler.spec_accepted_tokens,
            spec_proposed_by=dict(self.scheduler.spec_proposed_by),
            spec_accepted_by=dict(self.scheduler.spec_accepted_by),
            generation_tokens=self._generation_tokens,
            prompt_tokens=self._prompt_tokens,
            host_kv_usage_perc=(
                self.host_tier.usage_perc if self.host_tier else 0.0
            ),
            host_kv_offloads=(
                self.host_tier.stats.offloads if self.host_tier else 0
            ),
            host_kv_reloads=(
                self.host_tier.stats.reloads if self.host_tier else 0
            ),
            remote_kv_stores=(
                self.remote_tier.stats.stores if self.remote_tier else 0
            ),
            remote_kv_fetched_blocks=(
                self.remote_tier.stats.fetched_blocks
                if self.remote_tier else 0
            ),
            structured_outcomes=dict(self._structured_outcomes),
            grammar_build_times=(
                self._grammar_cache.drain_build_times()
                if self._grammar_cache is not None
                else []
            ),
            compile=self.compile_watch.stats_snapshot(),
        )

    @property
    def is_sleeping(self) -> bool:
        return self.runner.is_sleeping

    def sleep(self, level: int = 1) -> None:
        if self.scheduler.has_unfinished():
            raise RuntimeError("cannot sleep with unfinished requests")
        self.runner.sleep(level)
        # the device pool is dropped; its content-addressed hashes would
        # otherwise match new requests against zeroed pages after wake
        self.scheduler.pool.clear_prefix_cache()

    def wake(self) -> None:
        self.runner.wake()
