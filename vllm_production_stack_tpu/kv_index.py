"""Cluster KV index: the subscriber half of the event-driven KV protocol.

One instance of `ClusterKVIndex` holds the cluster-wide view of which engine
has which KV chain hash locally resident (engine/kv_events.py is the
publisher half). Two processes embed it:

- the KV controller (engine/kv_controller.py): answers `/lookup` straight
  from the index — tokenize once, hash the chain once, walk sets — instead
  of fanning a probe out to every engine;
- the router in embedded-index mode (router/routing.py KvawarePolicy): the
  index lives in the router process itself, removing the controller hop
  from the request path entirely.

Consistency model: per-engine (epoch, seq) tracking. An event batch whose
seq_start is not exactly last_seq+1, or whose epoch changed (pool rebuild),
marks the engine STALE and the reply asks the publisher to resync with a
full snapshot. Stale engines drop out of indexed answers — callers fall back
to the legacy per-request fan-out for them — so a gap can cost probe
traffic, never a wrong answer sourced from a desynced index. A liveness TTL
(stale_after_s; publishers heartbeat when idle) does the same for engines
that die without deregistering.

Memory bound: an engine exceeding max_hashes_per_engine is reset to stale
(its set freed) rather than growing without limit — the same resync path
heals it.

Scope: the index tracks LOCAL residency only (HBM + host ring + disk). KV
held solely in a shared remote store (--remote-kv-url) is deliberately not
indexed — any engine can fetch it, so it carries no placement signal. Note
the asymmetry this buys: an engine-side /kv/lookup probe DOES count
remote-resident blocks (kv_cache.match_length continues into the store),
so in a mixed cluster with a remote store the fan-out answer for a
remote-warm prefix can exceed every indexed answer and routing leans
legacy for that prefix; acceptable, since placement is indifferent for
remote-reachable KV.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .engine.kv_cache import KVBlockPool, chain_hash_run
from .fleet import ConvergenceMeter
from .utils.logging import init_logger

logger = init_logger(__name__)

ROOT_HASH = KVBlockPool.root_hash()

# matches KVBlockPool defaults: ~2M 128-bit hashes ≈ tens of MB per engine,
# far above any realistic HBM+host-ring block count
DEFAULT_MAX_HASHES_PER_ENGINE = 2_000_000

# liveness TTL: an engine that stops posting (publisher flushes every
# ~0.5s and heartbeats every ~2s even when idle — kv_events.py) for this
# long is treated as dead and drops out of indexed answers. Without it a
# crashed/partitioned engine keeps winning lookups for every prefix it
# ever held. The slice is kept, not freed: a publisher that resumes with
# seq continuity heals instantly, no resync needed.
DEFAULT_STALE_AFTER_S = 10.0

# memory reclamation for engines that are GONE (scaled down, pod replaced
# under a new URL) rather than flapping: a slice silent this long is
# deleted outright. Deliberately much longer than the TTL — discovery
# flaps and rolling restarts must not free a multi-million-hash slice
# that would then need a full snapshot resync to rebuild.
DEFAULT_PURGE_AFTER_S = 600.0


def chain_hashes(
    token_ids: list[int], block_size: int, parent: int | None = None
) -> list[int]:
    """All full-block chain hashes of a prompt — byte-exact with the pool's
    matching by construction (same `chain_hash_run` the pool's `_chain`
    delegates to)."""
    return chain_hash_run(
        ROOT_HASH if parent is None else parent, token_ids, block_size
    )


def negotiate_transport(a: dict | None, b: dict | None) -> str:
    """Pick the peer-KV transport two engines can actually use (docs/39).

    "device" only when both advertise the SAME named mesh group AND form
    the exactly-supported collective shape: a 2-process jax.distributed
    program with the two engines on different process indices (the pairwise
    shard-flip program in kv_device_transfer handles exactly this shape).
    Anything else — either side silent, group mismatch, >2 processes, or
    the same process twice — is "http", the always-correct fallback."""
    if not a or not b:
        return "http"
    group = a.get("mesh_group") or ""
    if not group or group != (b.get("mesh_group") or ""):
        return "http"
    if a.get("process_count") != 2 or b.get("process_count") != 2:
        return "http"
    if a.get("process_index") == b.get("process_index"):
        return "http"
    return "device"


class LookupLatency:
    """Tiny fixed-bucket latency histogram, rendered in Prometheus text
    exposition. Shared by the controller's /metrics and the router's — both
    ends of the protocol report the same contract names
    (metrics_contract.CLUSTER_KV_LOOKUP_LATENCY) without dragging a
    prometheus_client registry into the index module."""

    BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
               0.1, 0.25, 0.5, 1.0, 2.5)

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: dict[str, list[int]] = {}
        self._sums: dict[str, float] = {}

    def observe(self, mode: str, seconds: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(
                mode, [0] * (len(self.BUCKETS) + 1)
            )
            for i, ub in enumerate(self.BUCKETS):
                if seconds <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[mode] = self._sums.get(mode, 0.0) + seconds

    def render(self, name: str) -> list[str]:
        lines = [f"# TYPE {name} histogram"]
        with self._lock:
            for mode, counts in sorted(self._counts.items()):
                acc = 0
                for ub, c in zip(self.BUCKETS, counts):
                    acc += c
                    lines.append(
                        f'{name}_bucket{{mode="{mode}",le="{ub}"}} {acc}'
                    )
                acc += counts[-1]
                lines.append(f'{name}_bucket{{mode="{mode}",le="+Inf"}} {acc}')
                lines.append(
                    f'{name}_sum{{mode="{mode}"}} {self._sums[mode]:.6f}'
                )
                lines.append(f'{name}_count{{mode="{mode}"}} {acc}')
        return lines


@dataclass
class _EngineView:
    """One publishing engine's slice of the index."""

    url: str
    epoch: str = ""
    seq: int = 0
    block_size: int = 0
    stale: bool = True  # no snapshot accepted yet, or a gap was detected
    hashes: set[int] = field(default_factory=set)
    last_event_t: float = 0.0


class ClusterKVIndex:
    """hash → engines view of the cluster's locally-resident KV prefixes.

    Thread-safe (a plain threading.Lock — every operation is pure dict/set
    work measured in microseconds; the subscriber loop and lookups may live
    on different threads in tests and bench harnesses).
    """

    def __init__(
        self,
        max_hashes_per_engine: int = DEFAULT_MAX_HASHES_PER_ENGINE,
        stale_after_s: float | None = DEFAULT_STALE_AFTER_S,
        purge_after_s: float | None = DEFAULT_PURGE_AFTER_S,
    ):
        self.max_hashes_per_engine = max_hashes_per_engine
        self.stale_after_s = stale_after_s  # None disables the liveness TTL
        self.purge_after_s = purge_after_s  # None disables dead-slice purge
        # publishers retry rejected snapshots every flush interval — warn
        # once a minute per engine, not once per retry
        self._reject_warn_t: dict[str, float] = {}
        self._lock = threading.Lock()
        self._engines: dict[str, _EngineView] = {}
        # counters for metrics
        self.events_applied = 0
        self.resyncs_requested = 0
        self.lookups = LookupLatency()
        # fleet-coherence telemetry (docs/32-fleet-telemetry.md): publish→
        # apply lag of event batches/snapshots as seen by THIS subscriber
        # (tpu:cluster_kv_convergence_lag_seconds). Publishers stamp the
        # oldest event's emit wall-time on each POST (kv_events.py "ts");
        # heartbeats apply nothing and are not observed.
        self.convergence = ConvergenceMeter()
        # device-transport identities (docs/39-device-peer-kv.md): engines
        # advertising a mesh group via /register land here; /peer_lookup
        # replies negotiate a per-pair transport hint from these. Kept
        # beside the event slices (not on _EngineView) — registration and
        # event publishing have independent lifecycles.
        self._transports: dict[str, dict] = {}

    # -- device-transport identities ---------------------------------------

    def set_transport(self, url: str, identity: dict | None) -> None:
        url = url.rstrip("/")
        with self._lock:
            if identity:
                self._transports[url] = dict(identity)
            else:
                self._transports.pop(url, None)

    def get_transport(self, url: str) -> dict | None:
        with self._lock:
            return self._transports.get(url.rstrip("/"))

    # -- event ingestion ---------------------------------------------------

    def apply(self, payload: dict) -> dict:
        """Apply one publisher POST body (snapshot or event batch). Returns
        the JSON-able reply: {"status": "ok"} or {"status": "resync",
        "resync": True} when the publisher must send a full snapshot."""
        url = (payload.get("engine") or "").rstrip("/")
        if not url:
            return {"status": "error", "error": "engine url is required"}
        epoch = payload.get("epoch") or ""
        snapshot_hashes: set[int] | None = None
        if payload.get("snapshot"):
            raw_hashes = payload.get("hashes", [])
            if len(raw_hashes) > self.max_hashes_per_engine:
                # enforce the memory bound on the snapshot path too, and
                # BEFORE parsing — otherwise an over-cap engine is accepted
                # here only to trip the cap on its next event batch,
                # re-shipping the same oversized snapshot forever
                now = time.monotonic()
                if now - self._reject_warn_t.get(url, -1e9) > 60.0:
                    self._reject_warn_t[url] = now
                    logger.warning(
                        "rejecting %d-hash snapshot from %s (cap %d)",
                        len(raw_hashes), url, self.max_hashes_per_engine,
                    )
                return {
                    "status": "error",
                    "error": f"snapshot of {len(raw_hashes)} hashes exceeds "
                             f"the per-engine cap {self.max_hashes_per_engine}",
                }
            # parse the (potentially huge) resync snapshot BEFORE taking the
            # lock — only the set swap happens under it, so concurrent
            # lookups never stall behind a hex-parse of a whole pool
            snapshot_hashes = {int(h, 16) for h in raw_hashes}
        with self._lock:
            self._purge_dead_locked(time.monotonic(), posting=url)
            view = self._engines.get(url)
            if view is None:
                view = self._engines[url] = _EngineView(url=url)
            view.block_size = int(
                payload.get("block_size") or view.block_size or 0
            )
            view.last_event_t = time.monotonic()
            # publish→apply lag: publishers stamp the oldest event's emit
            # wall-time; heartbeats (empty batches) apply nothing and are
            # skipped so idle traffic doesn't dilute the histogram
            publish_ts = payload.get("ts")
            if snapshot_hashes is not None:
                view.epoch = epoch
                view.seq = int(payload.get("seq") or 0)
                view.hashes = snapshot_hashes
                view.stale = False
                if publish_ts:
                    self.convergence.observe(time.time() - float(publish_ts))
                return {"status": "ok"}
            seq_start = int(payload.get("seq_start") or 0)
            events = payload.get("events") or []
            if view.stale or view.epoch != epoch or seq_start != view.seq + 1:
                view.stale = True
                self.resyncs_requested += 1
                return {"status": "resync", "resync": True}
            for ev in events:
                op = ev[0]
                if op == "a":
                    view.hashes.add(int(ev[1], 16))
                elif op == "e":
                    view.hashes.discard(int(ev[1], 16))
                elif op == "c":
                    view.hashes.clear()
                self.events_applied += 1
            view.seq = seq_start + len(events) - 1
            if events and publish_ts:
                self.convergence.observe(time.time() - float(publish_ts))
            if len(view.hashes) > self.max_hashes_per_engine:
                logger.warning(
                    "cluster KV index for %s exceeded %d hashes; resetting "
                    "to stale (publisher will resync)",
                    url, self.max_hashes_per_engine,
                )
                view.hashes = set()
                view.stale = True
                self.resyncs_requested += 1
                return {"status": "resync", "resync": True}
        return {"status": "ok"}

    def _purge_dead_locked(self, now: float, posting: str) -> None:
        """Delete slices of engines silent past purge_after_s — called
        opportunistically from apply() (O(engines), trivially cheap). A
        scaled-down or replaced pod must not hold millions of hashes
        forever; a flapping-but-publishing one never trips this (its
        heartbeats refresh last_event_t, and the engine currently posting
        is exempt by definition)."""
        if self.purge_after_s is None:
            return
        for u in [
            u for u, v in self._engines.items()
            if u != posting and now - v.last_event_t > self.purge_after_s
        ]:
            logger.info(
                "purging cluster KV index slice for %s (silent > %.0fs)",
                u, self.purge_after_s,
            )
            del self._engines[u]

    def remove_engine(self, url: str) -> None:
        """Drop an engine's slice NOW — for explicit /deregister only.
        Discovery churn must NOT call this: a health-probe flap would free
        a slice the publisher then has to rebuild with a full snapshot
        resync; lookups already restrict to available endpoints, the
        liveness TTL drops dead publishers from answers, and
        _purge_dead_locked reclaims the memory of truly-gone engines."""
        with self._lock:
            self._engines.pop(url.rstrip("/"), None)
            self._transports.pop(url.rstrip("/"), None)

    # -- queries -----------------------------------------------------------

    def _is_fresh(self, v: _EngineView, now: float) -> bool:
        return (
            not v.stale
            and v.block_size > 0
            and (
                self.stale_after_s is None
                or now - v.last_event_t <= self.stale_after_s
            )
        )

    def fresh_engines(self, urls: set[str] | None = None) -> set[str]:
        """Engines whose index slice is current (snapshot applied, no pending
        gap, publisher heard from within the liveness TTL) — the set indexed
        lookups may answer for."""
        now = time.monotonic()
        with self._lock:
            fresh = {
                u for u, v in self._engines.items() if self._is_fresh(v, now)
            }
        return fresh if urls is None else fresh & {
            u.rstrip("/") for u in urls
        }

    def lookup_token_ids(
        self, token_ids: list[int], urls: set[str] | None = None
    ) -> tuple[str | None, int]:
        """(engine url with the longest locally-resident prefix, matched
        tokens) over the fresh engines (optionally restricted to `urls`).
        Tokenizes nothing and probes nothing: one chain-hash pass per
        distinct block size, then pure set walks."""
        candidates = self.fresh_engines(urls)
        if not candidates:
            return None, 0
        with self._lock:
            views = [
                self._engines[u] for u in candidates if u in self._engines
            ]
            sizes = sorted({v.block_size for v in views})
        # hash OUTSIDE the lock: one pass per distinct block size (almost
        # always one); a long prompt must not serialize event ingestion
        hashes_by_bs = {bs: chain_hashes(token_ids, bs) for bs in sizes}
        with self._lock:
            best_url: str | None = None
            best_tokens = 0
            for bs in sizes:
                hashes = hashes_by_bs[bs]
                group = [v for v in views if v.block_size == bs]
                for v in group:
                    matched = 0
                    for h in hashes:
                        if h not in v.hashes:
                            break
                        matched += bs
                    # ties break on url order for determinism
                    if matched > best_tokens or (
                        matched == best_tokens
                        and best_url is not None
                        and matched > 0
                        and v.url < best_url
                    ):
                        best_url, best_tokens = v.url, matched
            if best_tokens == 0:
                # nothing resident anywhere: still a valid indexed answer
                return None, 0
            return best_url, best_tokens

    def lookup_hashes(
        self, hashes: list[int], block_size: int,
        urls: set[str] | None = None, exclude: str | None = None,
    ) -> tuple[str | None, int]:
        """(engine url with the longest consecutively-resident run of
        `hashes`, matched BLOCKS) over the fresh engines whose block size
        matches — the peer-tier rediscovery lookup (POST /peer_lookup,
        docs/35-peer-kv-reuse.md). The caller already chained its prompt
        (probe_prefix), so unlike lookup_token_ids nothing is hashed
        here: pure set walks. `exclude` drops the asking engine itself —
        its own residency is exactly what its probe already walked."""
        candidates = self.fresh_engines(urls)
        if exclude:
            candidates = candidates - {exclude.rstrip("/")}
        if not candidates or not hashes:
            return None, 0
        with self._lock:
            best_url: str | None = None
            best_blocks = 0
            for u in sorted(candidates):  # url order for determinism
                v = self._engines.get(u)
                if v is None or v.block_size != block_size:
                    continue
                matched = 0
                for h in hashes:
                    if h not in v.hashes:
                        break
                    matched += 1
                if matched > best_blocks:
                    best_url, best_blocks = u, matched
            return best_url, best_blocks

    def holders(
        self, hashes: list[int], block_size: int,
        urls: set[str] | None = None,
    ) -> list[str]:
        """Fresh engines (block size matching) whose slice shows the ENTIRE
        hash run resident — the replica count the proactive-replication
        loop and migration-aware eviction key off (docs/39). Sorted for
        determinism."""
        candidates = self.fresh_engines(urls)
        if not candidates or not hashes:
            return []
        out: list[str] = []
        with self._lock:
            for u in sorted(candidates):
                v = self._engines.get(u)
                if v is None or v.block_size != block_size:
                    continue
                if all(h in v.hashes for h in hashes):
                    out.append(u)
        return out

    def positions(self) -> dict[str, dict]:
        """Per-engine (epoch, seq) positions + slice sizes — the replica-
        coherence view /fleet and /debug/fleet expose, and the input to
        fleet.index_divergence_blocks (controller index vs an embedded
        replica's report)."""
        now = time.monotonic()
        with self._lock:
            return {
                u: {
                    "epoch": v.epoch,
                    "seq": v.seq,
                    "hashes": len(v.hashes),
                    "block_size": v.block_size,
                    "stale": not self._is_fresh(v, now),
                    "age_s": round(now - v.last_event_t, 3),
                }
                for u, v in self._engines.items()
            }

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "engines": len(self._engines),
                "stale_engines": sum(
                    1 for v in self._engines.values()
                    if not self._is_fresh(v, now)
                ),
                "hashes": sum(len(v.hashes) for v in self._engines.values()),
                "events_applied": self.events_applied,
                "resyncs_requested": self.resyncs_requested,
            }
