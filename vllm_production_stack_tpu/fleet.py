"""Fleet-coherence telemetry: the shared pieces (docs/32-fleet-telemetry.md).

ROADMAP 1 wants N router replicas to be *correct* — identical session
affinity, converged embedded KV indexes, globally-enforced tenant limits.
None of those three failure modes was measurable before this module
existed, so the multi-replica refactor had no acceptance signal. This is
the measurement layer, built the same way PR 6 (goodput → autoscaling
signal) and PR 7 (tier bandwidth → hydration planner signal) were: the
numbers first, on real wire traffic.

Three replica-coherence signals, one component each:

- `ConvergenceMeter` — publish→apply lag of KV events as seen by ONE
  subscriber (controller or embedded replica index). Each publisher batch
  carries the wall-clock emit time of its oldest event
  (engine/kv_events.py); the subscriber observes `now - ts` on apply.
  Cross-process wall clocks, so the number is honest only to NTP skew —
  fine at the ≥10 ms granularity replica convergence plays out on.
- `SessionStickinessAudit` — the engine-side detector for broken
  consistent-hash affinity. Routers stamp their replica id and the
  hashring-chosen owner on upstream requests
  (`x-router-replica-id` / `x-session-sticky-*`); the engine counts a
  violation when a session's consecutive requests carry different chosen
  owners, or when a request lands on an engine that is not its stamped
  owner (pre-byte failover moved it — affinity broke observably).
  With one replica and stable ring membership both counts are zero: the
  baseline the multi-replica refactor must preserve.
- `FleetView` — the controller-side aggregate. Router replicas POST
  periodic reports (router/fleet.FleetReporter): ring-membership hash,
  embedded-index positions, breaker states, per-tenant drained counters.
  The controller computes per-replica index divergence against its own
  authoritative index, flags ring-membership divergence, and rolls
  per-tenant spend up fleet-wide against the configured budget — the
  over-admission gauge is the "N split buckets overshoot the global
  limit N×" problem as a number.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

import xxhash

from .metrics_contract import STICKINESS_REASON_VALUES
from .utils.logging import init_logger

logger = init_logger(__name__)

# upstream stamp headers (router/request_service.py writes, engine/server.py
# reads). With no session policy the router is transparent to inbound
# copies, mirroring the tenant-stamp convention.
REPLICA_HEADER = "x-router-replica-id"
STICKY_SESSION_HEADER = "x-session-sticky-id"
STICKY_OWNER_HEADER = "x-session-sticky-owner"
RING_HASH_HEADER = "x-router-ring-hash"

# closed reason set for tpu:session_stickiness_violations_total — the
# single definition lives in the metrics contract (no imports there, so
# no cycle); aliased here for the audit's own bookkeeping
STICKINESS_REASONS = STICKINESS_REASON_VALUES


def membership_hash(nodes) -> str:
    """Stable 64-bit hex digest of a ring membership set. Two replicas
    whose session rings hold the same nodes — regardless of insertion
    order — report the same hash; any difference in membership shows up as
    a different value, which is exactly what the Prometheus
    `count(count by (hash)(tpu:router_ring_membership_hash)) > 1`
    divergence alert keys off."""
    return f"{xxhash.xxh64_intdigest(chr(10).join(sorted(nodes))):016x}"


class ConvergenceMeter:
    """Publish→apply lag histogram for ONE index subscriber.

    Fixed buckets, plain ints under a small lock (apply() runs on executor
    threads while /metrics scrapes from the loop). Two consumers, two
    shapes: `render()` emits cumulative Prometheus text for hand-rolled
    exporters (the KV controller), `drain()` hands raw observations to
    exactly one prometheus_client Histogram (the router's registry) so
    each lands in a real histogram exactly once."""

    BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
               1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
    MAX_PENDING = 10_000  # scrape stopped; stay bounded

    def __init__(self, buffer_pending: bool = True) -> None:
        # buffer_pending=False for render-only hosts (the KV controller):
        # nothing ever drains there, so buffering raw observations would
        # just pin MAX_PENDING floats for the process lifetime
        self.buffer_pending = buffer_pending
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.BUCKETS) + 1)
        self._sum = 0.0
        self._count = 0
        self._pending: list[float] = []

    def observe(self, seconds: float) -> None:
        seconds = max(0.0, seconds)  # NTP skew must not go negative
        with self._lock:
            for i, ub in enumerate(self.BUCKETS):
                if seconds <= ub:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1
            self._sum += seconds
            self._count += 1
            if self.buffer_pending and len(self._pending) < self.MAX_PENDING:
                self._pending.append(seconds)

    def drain(self) -> list[float]:
        """Observations since the last drain (for ONE prometheus
        Histogram consumer)."""
        with self._lock:
            out, self._pending = self._pending, []
        return out

    def stats(self) -> dict:
        """count / sum / p50 / p95 estimated from the bucket upper bounds
        (the honest resolution a fixed-bucket histogram has)."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            lag_sum = self._sum

        def pct(p: float) -> float | None:
            if total == 0:
                return None
            rank = p * total
            acc = 0
            for ub, c in zip(self.BUCKETS, counts):
                acc += c
                if acc >= rank:
                    return ub
            # overflow bucket: report the last finite bound (a lower
            # bound on the true percentile) — float('inf') would make
            # /fleet and /debug/fleet emit the invalid-JSON `Infinity`
            return float(self.BUCKETS[-1])

        return {
            "count": total,
            "sum_s": round(lag_sum, 6),
            "mean_s": round(lag_sum / total, 6) if total else None,
            "p50_s": pct(0.50),
            "p95_s": pct(0.95),
        }

    def render(self, name: str) -> list[str]:
        """Prometheus text-exposition lines (cumulative histogram)."""
        lines = [f"# TYPE {name} histogram"]
        with self._lock:
            acc = 0
            for ub, c in zip(self.BUCKETS, self._counts):
                acc += c
                lines.append(f'{name}_bucket{{le="{ub}"}} {acc}')
            acc += self._counts[-1]
            lines.append(f'{name}_bucket{{le="+Inf"}} {acc}')
            lines.append(f"{name}_sum {self._sum:.6f}")
            lines.append(f"{name}_count {acc}")
        return lines


class SessionStickinessAudit:
    """Engine-side session-affinity violation detector.

    Bounded LRU of session → (chosen owner, replica id, ring hash). Two
    violation classes (the closed STICKINESS_REASONS set):

    - ``owner_changed``: consecutive requests for one session reached this
      engine stamped with DIFFERENT ring-chosen owners — two replicas (or
      one replica across a membership change) disagreed about where the
      session lives.
    - ``non_owner_delivery``: the request landed here but its stamp names
      another engine as the ring-chosen owner — the routing layer moved a
      sticky session off its affinity target (pre-byte failover away from
      a dead/refusing owner is the common cause, and is exactly how a
      ring-membership mismatch between replicas becomes client-visible:
      the replica with the stale ring keeps choosing the gone engine).

    One replica with a stable ring produces zero of both by construction —
    the baseline number ROADMAP 1's refactor must preserve at N>1.

    Identity-scheme guard: non_owner_delivery only starts counting after
    this engine has seen its OWN advertised URL as an owner stamp at
    least once. Discovery may publish a different identity scheme than
    POD_IP:ENGINE_PORT (service-DNS names, a Service VIP) — comparing
    those against self_url would count a violation on 100% of perfectly
    sticky requests. An owner stamp that matches proves the schemes
    agree; until then mismatches are recorded as a scheme hint, not
    violations.
    """

    MAX_SESSIONS = 8192

    def __init__(self, self_url: str | None = None,
                 max_sessions: int = MAX_SESSIONS):
        # the engine's own advertised URL (http://POD_IP:ENGINE_PORT, the
        # same identity the KV event publisher uses). None = unknown →
        # non_owner_delivery detection is off, owner_changed still works.
        self.self_url = (self_url or "").rstrip("/") or None
        self.max_sessions = max_sessions
        self._lock = threading.Lock()
        self._sessions: OrderedDict[str, tuple[str, str, str]] = OrderedDict()
        self.violations: dict[str, int] = {r: 0 for r in STICKINESS_REASONS}
        self.observed = 0
        # identity-scheme proof: set the first time an owner stamp equals
        # self_url (routers address this engine the way it addresses
        # itself) — the gate that arms non_owner_delivery
        self.self_url_confirmed = False
        # ring hashes seen on stamps, for /debug introspection (replicas
        # whose hashes differ route the same session differently)
        self.ring_hashes_seen: dict[str, str] = {}  # hash -> last replica

    def observe_headers(self, headers) -> list[str]:
        """Convenience wrapper over the upstream stamp headers; a request
        without a sticky stamp is not session traffic and counts nothing."""
        session = headers.get(STICKY_SESSION_HEADER)
        if not session:
            return []
        return self.observe(
            session,
            owner=headers.get(STICKY_OWNER_HEADER, ""),
            replica=headers.get(REPLICA_HEADER, ""),
            ring_hash=headers.get(RING_HASH_HEADER, ""),
        )

    def observe(self, session_id: str, owner: str = "", replica: str = "",
                ring_hash: str = "") -> list[str]:
        """Record one session request; returns the violation reasons it
        tripped (empty for a clean sticky delivery)."""
        owner = owner.rstrip("/")
        reasons: list[str] = []
        with self._lock:
            self.observed += 1
            if ring_hash:
                self.ring_hashes_seen[ring_hash] = replica
                if len(self.ring_hashes_seen) > 64:  # stay bounded
                    self.ring_hashes_seen.pop(
                        next(iter(self.ring_hashes_seen))
                    )
            if self.self_url and owner:
                if owner == self.self_url:
                    self.self_url_confirmed = True
                elif self.self_url_confirmed:
                    # armed only after the schemes provably agree — see
                    # the class docstring's identity-scheme guard
                    reasons.append("non_owner_delivery")
            prev = self._sessions.get(session_id)
            if (
                prev is not None
                and owner and prev[0]
                and owner != prev[0]
            ):
                reasons.append("owner_changed")
            self._sessions[session_id] = (owner, replica, ring_hash)
            self._sessions.move_to_end(session_id)
            while len(self._sessions) > self.max_sessions:
                self._sessions.popitem(last=False)
            for r in reasons:
                self.violations[r] += 1
        return reasons

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self.violations)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "observed": self.observed,
                "sessions_tracked": len(self._sessions),
                "violations": dict(self.violations),
                "ring_hashes_seen": dict(self.ring_hashes_seen),
                "self_url": self.self_url,
                "self_url_confirmed": self.self_url_confirmed,
            }


def index_divergence_blocks(authoritative: dict, replica: dict) -> int:
    """Estimated blocks by which a replica's embedded index diverges from
    the authoritative (controller) index, from per-engine positions
    ({url: {"epoch", "seq", "hashes"}}).

    Same epoch → events are block mutations, so |seq gap| ≈ blocks of
    drift. Epoch mismatch or engine missing from the replica → the whole
    authoritative slice is divergent (a full snapshot resync is pending).
    Engines only the replica knows are ignored: the controller is the
    authority being compared against."""
    d = 0
    for url, a in authoritative.items():
        r = replica.get(url)
        if r is None or (r.get("epoch") or "") != (a.get("epoch") or ""):
            d += int(a.get("hashes", 0))
        else:
            d += abs(int(a.get("seq", 0)) - int(r.get("seq", 0)))
    return d


class _ReplicaState:
    """One router replica's latest report + a short rate-window history."""

    __slots__ = ("replica_id", "recv_t", "report_ts", "ring_hash",
                 "breakers", "has_index", "positions", "tenants", "history",
                 "divergence_blocks", "enforcing", "report_interval",
                 "pools")

    def __init__(self, replica_id: str):
        self.replica_id = replica_id
        self.recv_t = 0.0
        self.report_ts = 0.0
        # the cadence the replica says it reports at (0 = unknown):
        # enforcing_count sizes its per-replica liveness window from it
        self.report_interval = 0.0
        self.ring_hash = ""
        self.breakers: dict = {}
        # True when the replica hosts an embedded index at all — an EMPTY
        # positions dict from a cold embedded replica must still compute
        # divergence (= the whole authoritative slice), while a
        # controller-mode router (no index) must not
        self.has_index = False
        self.positions: dict = {}
        self.tenants: dict[str, dict[str, float]] = {}
        # (recv_t, {tenant: requests_total}) samples for rate computation
        self.history: deque = deque(maxlen=64)
        self.divergence_blocks: int | None = None
        # True when the replica runs a QoS gate (it admits tenant traffic
        # against local buckets) — only these count toward the budget-
        # scaling denominator M (docs/34-fleet-routing.md): a report-only
        # replica enforces nothing, so counting it would make the
        # enforcing ones admit BELOW the global budget forever
        self.enforcing = False
        # per-engine pool signals this replica scraped (url -> {role,
        # queue_wait_p95, seat_occupancy, load}) — the rebalancer's
        # imbalance input (docs/40-pool-rebalancing.md)
        self.pools: dict[str, dict] = {}


class FleetView:
    """Controller-side aggregate over router-replica reports.

    Owns no clock assumptions beyond monotonic receive times; replicas are
    expired from the view after `expire_after_s` silence (a scaled-down
    router must not pin a stale ring hash or tenant rate forever)."""

    def __init__(self, tenant_table=None, rate_window_s: float = 30.0,
                 expire_after_s: float = 120.0, live_within_s: float = 30.0):
        # qos.tenants.TenantTable (or None): the per-tenant budget the
        # fleet-wide utilization is measured against
        self.tenant_table = tenant_table
        self.rate_window_s = rate_window_s
        self.expire_after_s = expire_after_s
        # the budget-scaling denominator uses this TIGHTER liveness window
        # (not expire_after_s): a rolling restart mints new replica ids,
        # and counting a replaced pod for the full expiry would make the
        # live replicas enforce 1/(2M) shares for minutes per deploy
        self.live_within_s = live_within_s
        self._lock = threading.Lock()
        self._replicas: dict[str, _ReplicaState] = {}
        self.reports_applied = 0

    # -- ingestion ---------------------------------------------------------

    def apply_report(self, report: dict, authoritative_positions:
                     dict | None = None) -> dict:
        """Apply one replica report; returns the JSON-able reply the
        replica re-exports on its own /metrics (divergence vs the
        authoritative index, fleet-wide tenant utilization, ring-divergence
        flag) — so every replica can alert on the fleet view without an
        extra scrape target."""
        replica_id = str(report.get("replica") or "").strip()
        if not replica_id:
            return {"status": "error", "error": "replica id is required"}
        # coerce field shapes BEFORE mutating state: a malformed (but
        # JSON-valid) report must come back as the handler's 400 error
        # reply, not escape as a 500 every report interval
        try:
            report_ts = float(report.get("ts") or 0.0)
            report_interval = float(report.get("interval") or 0.0)
            ring_hash = str(report.get("ring_hash") or "")
            breakers = dict(report.get("breakers") or {})
            has_index = "index" in report
            enforcing = bool(report.get("enforcing"))
            positions = dict(report.get("index") or {})
            tenants = {
                str(t): {
                    str(k): float(v) for k, v in dict(c or {}).items()
                }
                for t, c in dict(report.get("tenants") or {}).items()
            }
            pools = {
                str(url): {
                    "role": str(dict(p or {}).get("role") or ""),
                    "queue_wait_p95": float(
                        dict(p or {}).get("queue_wait_p95") or 0.0
                    ),
                    "seat_occupancy": float(
                        dict(p or {}).get("seat_occupancy") or 0.0
                    ),
                    "load": float(dict(p or {}).get("load") or 0.0),
                }
                for url, p in dict(report.get("pools") or {}).items()
            }
        except (TypeError, ValueError) as e:
            return {"status": "error",
                    "error": f"malformed report field: {e}"}
        now = time.monotonic()
        with self._lock:
            self._expire_locked(now)
            st = self._replicas.get(replica_id)
            if st is None:
                st = self._replicas[replica_id] = _ReplicaState(replica_id)
            st.recv_t = now
            st.report_ts = report_ts
            st.report_interval = report_interval
            st.ring_hash = ring_hash
            st.breakers = breakers
            st.has_index = has_index
            st.enforcing = enforcing
            st.positions = positions
            st.tenants = tenants
            st.pools = pools
            st.history.append((
                now,
                {t: c.get("requests", 0.0) for t, c in tenants.items()},
            ))
            if authoritative_positions is not None and st.has_index:
                st.divergence_blocks = index_divergence_blocks(
                    authoritative_positions, st.positions
                )
            elif not st.has_index:
                st.divergence_blocks = None
            self.reports_applied += 1
            ring_divergent = self._ring_divergent_locked()
            divergence = st.divergence_blocks
        return {
            "status": "ok",
            "replicas": self.replica_count(),
            # the budget-scaling denominator: QoS-enforcing replicas heard
            # from within the tight liveness window (see enforcing_count)
            "enforcing_replicas": self.enforcing_count(),
            "divergence_blocks": divergence,
            "ring_divergent": ring_divergent,
            "tenants": self.tenant_rollup(),
        }

    def _expire_locked(self, now: float) -> None:
        for rid in [
            rid for rid, st in self._replicas.items()
            if now - st.recv_t > self.expire_after_s
        ]:
            del self._replicas[rid]

    def _ring_divergent_locked(self) -> bool:
        hashes = {
            st.ring_hash for st in self._replicas.values() if st.ring_hash
        }
        return len(hashes) > 1

    # -- queries -----------------------------------------------------------

    def replica_count(self) -> int:
        with self._lock:
            return len(self._replicas)

    def enforcing_count(self) -> int:
        """Replicas that run a QoS gate AND were heard from recently —
        the M fleet budget scaling divides by. 'Recently' is 3 of the
        replica's OWN reported intervals (the standard freshness rule),
        floored at live_within_s so sub-second test cadences don't flap;
        a fleet reporting slower than live_within_s/3 therefore still
        counts as live instead of silently collapsing scaling to 1.
        Excludes report-only replicas (nothing to scale there; their
        presence must not starve tenants below the global budget) and the
        ids a rolling restart leaves behind (they age out of this count
        in a few intervals, not the full expire_after_s)."""
        now = time.monotonic()
        with self._lock:
            return sum(
                1 for st in self._replicas.values()
                if st.enforcing
                and now - st.recv_t <= max(
                    self.live_within_s, 3 * st.report_interval
                )
            )

    def pool_stats(self, max_age_s: float | None = None) -> dict[str, dict]:
        """Merged per-engine pool signals across replica reports (url ->
        {role, queue_wait_p95, seat_occupancy, load}), freshest replica
        wins per engine — the rebalancer's one input query
        (docs/40-pool-rebalancing.md). `max_age_s` bounds how stale a
        report may be (defaults to the live_within_s liveness window):
        the rebalancer must not act on signals from before an outage."""
        if max_age_s is None:
            max_age_s = self.live_within_s
        now = time.monotonic()
        merged: dict[str, tuple[float, dict]] = {}
        with self._lock:
            for st in self._replicas.values():
                if now - st.recv_t > max_age_s:
                    continue
                for url, p in st.pools.items():
                    prev = merged.get(url)
                    if prev is None or st.recv_t > prev[0]:
                        merged[url] = (st.recv_t, p)
        return {url: dict(p) for url, (_, p) in merged.items()}

    def tenant_rollup(self) -> dict[str, dict]:
        """Fleet-wide per-tenant accounting: admitted request rate summed
        across replicas over the rate window, measured against the
        configured per-tenant budget. utilization 1.0 = the fleet admits
        exactly the global limit; over-admission ratio is how far PAST the
        limit the N per-replica buckets let traffic through (the N-way
        split problem as a number: N identical replicas each granting the
        full budget measure ≈ N-1)."""
        now = time.monotonic()
        with self._lock:
            # expire on EVERY read path, not just report ingestion — a
            # scaled-down router fleet must not freeze the utilization
            # gauges at their last busy values (the frozen-gauge failure
            # mode the StepMeter EWMAs were once fixed for)
            self._expire_locked(now)
            per_tenant_rate: dict[str, float] = {}
            totals: dict[str, dict[str, float]] = {}
            for st in self._replicas.values():
                # oldest sample inside the window (fall back to the oldest
                # held — a young view measures over what it has)
                base = None
                for t, counts in st.history:
                    if now - t <= self.rate_window_s:
                        base = (t, counts)
                        break
                if base is None and st.history:
                    base = st.history[0]
                latest = st.history[-1] if st.history else None
                if latest is not None and base is not None:
                    dt = max(1e-6, latest[0] - base[0])
                    for tenant, n in latest[1].items():
                        if dt < 0.5:
                            continue  # one sample: no honest rate yet
                        delta = n - base[1].get(tenant, 0.0)
                        per_tenant_rate[tenant] = (
                            per_tenant_rate.get(tenant, 0.0)
                            + max(0.0, delta) / dt
                        )
                for tenant, counts in st.tenants.items():
                    slot = totals.setdefault(
                        tenant, {"requests": 0.0, "prompt_tokens": 0.0,
                                 "throttled": 0.0}
                    )
                    for k in slot:
                        slot[k] += counts.get(k, 0.0)
        out: dict[str, dict] = {}
        for tenant in sorted(set(per_tenant_rate) | set(totals)):
            row: dict = {
                "requests_per_s": round(per_tenant_rate.get(tenant, 0.0), 3),
                **{k: v for k, v in (totals.get(tenant) or {}).items()},
            }
            limit = 0.0
            if self.tenant_table is not None:
                policy = self.tenant_table.get(tenant)
                if policy is not None:
                    limit = policy.requests_per_s
            if limit > 0:
                util = per_tenant_rate.get(tenant, 0.0) / limit
                row["limit_requests_per_s"] = limit
                row["limit_utilization"] = round(util, 3)
                row["overadmission_ratio"] = round(max(0.0, util - 1.0), 3)
            out[tenant] = row
        return out

    def snapshot(self, authoritative_positions: dict | None = None) -> dict:
        """The GET /fleet body: per-replica positions + divergence, ring
        membership agreement, fleet tenant rollup."""
        now = time.monotonic()
        with self._lock:
            self._expire_locked(now)
            replicas = []
            for st in sorted(self._replicas.values(),
                             key=lambda s: s.replica_id):
                if authoritative_positions is not None and st.has_index:
                    st.divergence_blocks = index_divergence_blocks(
                        authoritative_positions, st.positions
                    )
                replicas.append({
                    "replica": st.replica_id,
                    "age_s": round(now - st.recv_t, 3),
                    "ring_hash": st.ring_hash,
                    "breakers": st.breakers,
                    "index": st.positions or None,
                    "divergence_blocks": st.divergence_blocks,
                    "tenants": st.tenants,
                })
            ring_divergent = self._ring_divergent_locked()
        return {
            "replicas": replicas,
            "ring_divergent": ring_divergent,
            "tenants": self.tenant_rollup(),
            "reports_applied": self.reports_applied,
        }

    def divergence_by_replica(self) -> dict[str, int | None]:
        with self._lock:
            # same expiry rule as tenant_rollup: dead replicas must drop
            # out of the exported divergence gauges, not freeze in them
            self._expire_locked(time.monotonic())
            return {
                st.replica_id: st.divergence_blocks
                for st in self._replicas.values()
            }
