"""Per-tenant rate limiting: token buckets + a concurrency cap.

Router-side enforcement (qos/gate.py) runs BEFORE any endpoint is picked,
so a throttled tenant costs the cluster one bucket check — no tokenizer
work on the engines, no queue slot, no breaker state. The 429 carries a
per-tenant Retry-After computed from the bucket's own refill rate, which
is deliberately distinct from the engine's global-shed Retry-After
(that one is derived from observed decode throughput).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from .tenants import TenantPolicy, TenantTable


@dataclass(frozen=True)
class Throttled:
    """A refused admission: which limit tripped and when to come back."""

    tenant_id: str
    reason: str  # "requests_per_s" | "tokens_per_min" | "max_concurrent"
    retry_after_s: float
    detail: str = ""


class TokenBucket:
    """Classic token bucket on the monotonic clock. `rate` tokens accrue
    per second up to `burst`; try_take returns 0.0 on success or the
    seconds until `n` tokens will have accrued (the Retry-After)."""

    def __init__(self, rate: float, burst: float):
        self.rate = max(rate, 1e-9)
        self.burst = max(burst, 1.0)
        self._level = self.burst
        self._stamp = time.monotonic()

    def _refill(self, now: float) -> None:
        # clamp: a caller-supplied clock earlier than the last stamp must
        # never DRAIN the bucket (tests inject fake clocks; monotonic
        # itself never goes backwards)
        self._level = min(
            self.burst,
            self._level + max(0.0, now - self._stamp) * self.rate,
        )
        self._stamp = now

    def try_take(self, n: float = 1.0, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        self._refill(now)
        if self._level >= n:
            self._level -= n
            return 0.0
        return (n - self._level) / self.rate

    @property
    def level(self) -> float:
        return self._level


class _TenantState:
    def __init__(self, policy: TenantPolicy, scale: float = 1.0):
        self.policy = policy
        self.rps: TokenBucket | None = None
        self.tpm: TokenBucket | None = None
        self.in_flight = 0
        self._configure(policy, scale)

    def _configure(self, policy: TenantPolicy, scale: float) -> None:
        """`scale` is the fleet budget share this replica enforces
        (docs/34-fleet-routing.md): bucket RATES and bursts are the
        configured limits × scale, so M replicas at 1/M each admit ~the
        global budget — and every Retry-After derives from bucket.rate, so
        a scaled bucket advertises the scaled refill time, not the full-
        rate one. max_concurrent stays unscaled: concurrency guards this
        replica's own resources, not a fleet-wide rate."""
        if policy.requests_per_s > 0:
            # burst = one second's worth (>= 1): a tenant at 10 req/s may
            # legally arrive as a 10-request burst each second
            rate = policy.requests_per_s * scale
            if self.rps is None:
                self.rps = TokenBucket(rate, max(1.0, rate))
            else:
                self.rps.rate = max(rate, 1e-9)
                self.rps.burst = max(1.0, rate)
                self.rps._level = min(self.rps._level, self.rps.burst)
        else:
            self.rps = None
        if policy.tokens_per_min > 0:
            tpm = policy.tokens_per_min * scale
            if self.tpm is None:
                # same explicit >=1 burst floor as the update path below —
                # admission must not depend on whether the tenant predates
                # a table reload
                self.tpm = TokenBucket(tpm / 60.0, max(1.0, tpm))
            else:
                self.tpm.rate = max(tpm / 60.0, 1e-9)
                self.tpm.burst = max(1.0, tpm)
                self.tpm._level = min(self.tpm._level, self.tpm.burst)
        else:
            self.tpm = None

    def update(self, policy: TenantPolicy, scale: float = 1.0) -> None:
        """Refresh limits in place — bucket LEVELS survive a hot reload so
        a mid-traffic weight/limit change can't hand every tenant a fresh
        burst allowance."""
        self.policy = policy
        self._configure(policy, scale)


class TenantLimiter:
    """Thread-safe per-tenant enforcement over a (swappable) TenantTable."""

    def __init__(self, table: TenantTable):
        self._lock = threading.Lock()
        self._states: dict[str, _TenantState] = {}
        # fleet budget share (docs/34-fleet-routing.md): 1.0 = full local
        # budget; 1/M when the fleet reporter learns M replicas are live
        self._scale = 1.0
        self.update_table(table)

    def update_table(self, table: TenantTable) -> None:
        with self._lock:
            fresh: dict[str, _TenantState] = {}
            for policy in [*table.policies(), table.default_policy]:
                prev = self._states.get(policy.tenant_id)
                if prev is not None:
                    prev.update(policy, self._scale)
                    fresh[policy.tenant_id] = prev
                else:
                    fresh[policy.tenant_id] = _TenantState(
                        policy, self._scale
                    )
            self._states = fresh

    @property
    def rate_scale(self) -> float:
        return self._scale

    def set_rate_scale(self, scale: float) -> None:
        """Re-rate every tenant's buckets in place to `scale` × the
        configured limits (levels survive, clamped to the new burst).
        Clamped to (0, 1]: scaling can only tighten toward a fleet share,
        never loosen past the configured budget."""
        scale = min(1.0, max(1e-6, scale))
        with self._lock:
            if scale == self._scale:
                return
            self._scale = scale
            for st in self._states.values():
                st.update(st.policy, scale)

    def _state(self, tenant_id: str) -> _TenantState | None:
        return self._states.get(tenant_id)

    def try_admit(
        self, policy: TenantPolicy, n_tokens: int, now: float | None = None
    ) -> Throttled | None:
        """One request carrying `n_tokens` prompt tokens asks in. Returns
        None and holds a concurrency slot on success (caller MUST release),
        or a Throttled refusal. Checks are ordered cheapest-first and only
        the first trip is charged — a refused request consumes nothing."""
        with self._lock:
            st = self._state(policy.tenant_id)
            if st is None:  # tenant removed mid-flight: treat as default
                return None
            p = st.policy
            if p.max_concurrent > 0 and st.in_flight >= p.max_concurrent:
                return Throttled(
                    p.tenant_id, "max_concurrent", 1.0,
                    f"{st.in_flight} requests already in flight "
                    f"(max_concurrent={p.max_concurrent})",
                )
            if st.rps is not None:
                wait = st.rps.try_take(1.0, now)
                if wait > 0.0:
                    return Throttled(
                        p.tenant_id, "requests_per_s",
                        min(60.0, max(wait, 0.05)),
                        f"request rate above {p.requests_per_s}/s",
                    )
            if st.tpm is not None and n_tokens > 0:
                wait = st.tpm.try_take(float(n_tokens), now)
                if wait > 0.0:
                    # un-charge the request bucket: this admission failed
                    if st.rps is not None:
                        st.rps._level = min(
                            st.rps.burst, st.rps._level + 1.0
                        )
                    return Throttled(
                        p.tenant_id, "tokens_per_min",
                        min(60.0, max(wait, 0.05)),
                        f"prompt-token rate above {p.tokens_per_min}/min",
                    )
            st.in_flight += 1
            return None

    def release(self, tenant_id: str) -> None:
        with self._lock:
            st = self._states.get(tenant_id)
            if st is not None and st.in_flight > 0:
                st.in_flight -= 1

    def in_flight(self, tenant_id: str) -> int:
        with self._lock:
            st = self._states.get(tenant_id)
            return st.in_flight if st else 0
