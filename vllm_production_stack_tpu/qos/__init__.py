"""Multi-tenant QoS: tenant identity, quotas, priority classes, and
weighted fair-share scheduling (docs/27-multitenancy.md).

The subsystem has two halves sharing one vocabulary:

- **Router side** (qos/gate.py): the auth middleware resolves the caller's
  bearer key to a `TenantPolicy` from a hot-reloadable `TenantTable`
  (qos/tenants.py); the `QoSGate` enforces per-tenant token-bucket rate
  limits and concurrency caps (qos/limiter.py) BEFORE any endpoint is
  picked, and stamps `x-tenant-id` / `x-priority` / `x-tenant-weight` on
  the upstream request.
- **Engine side**: the scheduler turns those stamps into a weighted
  fair-share admission pick (qos/fairshare.py virtual token counter),
  lowest-priority-first preemption/shedding, and per-tenant accounting
  (qos/accounting.py) exported through the tpu:tenant_* metric contract.

Traffic with no stamps collapses to the single `default` tenant and the
pre-QoS FIFO behavior — an unconfigured stack pays nothing for this layer.
"""

from .accounting import TenantAccounting
from .fairshare import FairShareClock
from .limiter import TenantLimiter, Throttled, TokenBucket
from .tenants import (
    DEFAULT_TENANT_ID,
    PRIORITY_BATCH,
    PRIORITY_CLASSES,
    PRIORITY_RANK,
    PRIORITY_REALTIME,
    PRIORITY_STANDARD,
    RANK_TO_CLASS,
    TENANT_HEADER,
    TENANT_PRIORITY_HEADER,
    TENANT_WEIGHT_HEADER,
    TenantContext,
    TenantPolicy,
    TenantTable,
    tenant_from_headers,
)

__all__ = [
    "DEFAULT_TENANT_ID",
    "FairShareClock",
    "PRIORITY_BATCH",
    "PRIORITY_CLASSES",
    "PRIORITY_RANK",
    "PRIORITY_REALTIME",
    "PRIORITY_STANDARD",
    "RANK_TO_CLASS",
    "TENANT_HEADER",
    "TENANT_PRIORITY_HEADER",
    "TENANT_WEIGHT_HEADER",
    "TenantAccounting",
    "TenantContext",
    "TenantLimiter",
    "TenantPolicy",
    "TenantTable",
    "Throttled",
    "TokenBucket",
    "tenant_from_headers",
]
