"""Per-tenant observability counters, shared by scheduler and HTTP threads.

The scheduler (step thread) counts admitted requests, generated tokens,
and queue-wait seconds; the admission gate (HTTP executor threads) counts
sheds. One lock, tiny critical sections. Label cardinality is bounded by
MAX_TENANTS — tenant ids arrive in request headers, so an abusive client
must not be able to mint unbounded Prometheus label values; overflow
traffic aggregates under the ``_overflow`` tenant.
"""

from __future__ import annotations

import threading


class TenantAccounting:
    MAX_TENANTS = 64
    MAX_PENDING_WAITS = 10_000

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, dict[str, float]] = {}
        self._waits: list[tuple[str, float]] = []

    def _slot(self, tenant_id: str) -> dict[str, float]:
        c = self._counters.get(tenant_id)
        if c is None:
            if len(self._counters) >= self.MAX_TENANTS:
                tenant_id = "_overflow"
                c = self._counters.get(tenant_id)
                if c is None:
                    c = self._counters[tenant_id] = {}
            else:
                c = self._counters[tenant_id] = {}
        return c

    def inc(self, tenant_id: str, key: str, n: float = 1) -> None:
        with self._lock:
            c = self._slot(tenant_id)
            c[key] = c.get(key, 0) + n

    def observe_wait(self, tenant_id: str, seconds: float) -> None:
        with self._lock:
            c = self._slot(tenant_id)
            c["queue_wait_sum"] = c.get("queue_wait_sum", 0.0) + seconds
            c["queue_wait_count"] = c.get("queue_wait_count", 0) + 1
            if len(self._waits) < self.MAX_PENDING_WAITS:
                self._waits.append((tenant_id, seconds))

    def snapshot(
        self, drain_waits: bool = False
    ) -> tuple[dict[str, dict[str, float]], list[tuple[str, float]]]:
        """(cumulative counters copy, queue-wait observations). Draining
        hands the raw observations to exactly one consumer (the metrics
        exporter's histogram); non-draining callers still see the
        cumulative sum/count in the counters."""
        with self._lock:
            counters = {t: dict(c) for t, c in self._counters.items()}
            if drain_waits:
                waits, self._waits = self._waits, []
            else:
                waits = list(self._waits)
        return counters, waits
