"""Tenant identity: the policy table and the per-request context.

The `TenantTable` is the operator-facing artifact (a YAML/JSON file, helm
`routerSpec.tenantTable`, hot-reloaded through the router's dynamic-config
watcher). A row maps an API key to a tenant with a priority class, a
fair-share weight, and rate/concurrency limits. The router resolves the
caller to a row and stamps the request; everything downstream (engine
scheduler, metrics) works from the stamped `TenantContext` — the engine
never needs the table or the keys.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from pathlib import Path

# priority classes, most-latency-critical first. RANK is the scheduler's
# ordering key: LOWER rank wins admission ties, HIGHER rank is shed /
# preempted first.
PRIORITY_REALTIME = 0
PRIORITY_STANDARD = 1
PRIORITY_BATCH = 2
PRIORITY_RANK = {
    "realtime": PRIORITY_REALTIME,
    "standard": PRIORITY_STANDARD,
    "batch": PRIORITY_BATCH,
}
RANK_TO_CLASS = {v: k for k, v in PRIORITY_RANK.items()}
PRIORITY_CLASSES = tuple(PRIORITY_RANK)

DEFAULT_TENANT_ID = "default"

# stamped by the router on upstream requests (and stripped from inbound
# ones when QoS is active — clients must not spoof their class)
TENANT_HEADER = "x-tenant-id"
TENANT_PRIORITY_HEADER = "x-priority"
TENANT_WEIGHT_HEADER = "x-tenant-weight"

# tenant ids become Prometheus label values and header values — keep them
# boring. Same charset the id validation below enforces.
_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# fair-share weight sanity bounds: a 0 weight divides by zero in the
# virtual clock; an absurd one is a fat-fingered table entry
_MIN_WEIGHT, _MAX_WEIGHT = 1e-3, 1e6


@dataclass(frozen=True)
class TenantPolicy:
    """One table row. Limits of 0 mean unlimited (that knob is off)."""

    tenant_id: str
    api_key: str = ""  # empty = not key-resolvable (header-trusted only)
    priority: str = "standard"  # realtime | standard | batch
    weight: float = 1.0
    requests_per_s: float = 0.0
    tokens_per_min: float = 0.0
    max_concurrent: int = 0

    @property
    def priority_rank(self) -> int:
        return PRIORITY_RANK[self.priority]

    def context(self) -> "TenantContext":
        return TenantContext(
            tenant_id=self.tenant_id,
            priority=self.priority_rank,
            weight=self.weight,
        )


@dataclass(frozen=True)
class TenantContext:
    """What the engine needs to know about a request's tenant — carried in
    the stamped headers, never the table itself."""

    tenant_id: str = DEFAULT_TENANT_ID
    priority: int = PRIORITY_STANDARD  # rank (0 realtime .. 2 batch)
    weight: float = 1.0

    @property
    def is_default(self) -> bool:
        return (
            self.tenant_id == DEFAULT_TENANT_ID
            and self.priority == PRIORITY_STANDARD
            and self.weight == 1.0
        )


DEFAULT_CONTEXT = TenantContext()


def _parse_policy(tenant_id: str, row: dict) -> TenantPolicy:
    if not isinstance(row, dict):
        raise ValueError(f"tenant {tenant_id!r}: entry must be a mapping")
    if not _ID_RE.match(tenant_id):
        raise ValueError(
            f"tenant id {tenant_id!r} invalid: 1-64 chars of [A-Za-z0-9._-]"
        )
    unknown = set(row) - {
        "api_key", "priority", "weight", "requests_per_s",
        "tokens_per_min", "max_concurrent",
    }
    if unknown:
        raise ValueError(
            f"tenant {tenant_id!r}: unknown keys {sorted(unknown)}"
        )
    priority = row.get("priority", "standard")
    if priority not in PRIORITY_RANK:
        raise ValueError(
            f"tenant {tenant_id!r}: priority {priority!r} not in "
            f"{sorted(PRIORITY_RANK)}"
        )
    weight = float(row.get("weight", 1.0))
    if not _MIN_WEIGHT <= weight <= _MAX_WEIGHT:
        raise ValueError(
            f"tenant {tenant_id!r}: weight {weight} outside "
            f"[{_MIN_WEIGHT}, {_MAX_WEIGHT}]"
        )
    rps = float(row.get("requests_per_s", 0.0))
    tpm = float(row.get("tokens_per_min", 0.0))
    conc = int(row.get("max_concurrent", 0))
    if rps < 0 or tpm < 0 or conc < 0:
        raise ValueError(f"tenant {tenant_id!r}: limits must be >= 0")
    api_key = row.get("api_key", "") or ""
    if not isinstance(api_key, str):
        raise ValueError(f"tenant {tenant_id!r}: api_key must be a string")
    return TenantPolicy(
        tenant_id=tenant_id,
        api_key=api_key,
        priority=priority,
        weight=weight,
        requests_per_s=rps,
        tokens_per_min=tpm,
        max_concurrent=conc,
    )


class TenantTable:
    """Validated, immutable-after-construction tenant policy set. A
    malformed input raises during construction — the caller (dynamic-config
    reload) keeps serving the previous table."""

    def __init__(self, policies: list[TenantPolicy]):
        ids = [p.tenant_id for p in policies]
        dup = {i for i in ids if ids.count(i) > 1}
        if dup:
            raise ValueError(f"duplicate tenant ids: {sorted(dup)}")
        keys = [p.api_key for p in policies if p.api_key]
        dupk = {k for k in keys if keys.count(k) > 1}
        if dupk:
            raise ValueError(
                f"{len(dupk)} api key(s) are shared by multiple tenants"
            )
        self._by_id: dict[str, TenantPolicy] = {
            p.tenant_id: p for p in policies
        }

    # -- construction ------------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict) -> "TenantTable":
        """Accepts {"tenants": {id: {...}}} or a bare {id: {...}} mapping.
        An optional "default" id customizes the policy unmatched traffic
        falls back to."""
        if not isinstance(data, dict):
            raise ValueError("tenant table must be a mapping")
        rows = data.get("tenants", data)
        if not isinstance(rows, dict):
            raise ValueError("'tenants' must map tenant id -> policy")
        extra = set(data) - {"tenants"} if "tenants" in data else set()
        if extra:
            raise ValueError(f"unknown tenant-table keys: {sorted(extra)}")
        return cls([_parse_policy(tid, row or {}) for tid, row in rows.items()])

    @classmethod
    def loads(cls, text: str, fmt: str = "yaml") -> "TenantTable":
        import json

        import yaml

        data = json.loads(text) if fmt == "json" else yaml.safe_load(text)
        return cls.from_dict(data or {})

    @classmethod
    def load(cls, path: str | Path) -> "TenantTable":
        p = Path(path)
        fmt = "json" if p.suffix == ".json" else "yaml"
        return cls.loads(p.read_text(), fmt=fmt)

    # -- lookup ------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._by_id)

    def __contains__(self, tenant_id: str) -> bool:
        return tenant_id in self._by_id

    def policies(self) -> list[TenantPolicy]:
        return list(self._by_id.values())

    def get(self, tenant_id: str) -> TenantPolicy | None:
        return self._by_id.get(tenant_id)

    @property
    def default_policy(self) -> TenantPolicy:
        return self._by_id.get(DEFAULT_TENANT_ID) or TenantPolicy(
            tenant_id=DEFAULT_TENANT_ID
        )

    def has_keys(self) -> bool:
        return any(p.api_key for p in self._by_id.values())

    def resolve_key(self, token: str | None) -> TenantPolicy | None:
        """Bearer token → tenant. Scans EVERY row with a constant-time
        comparison so the match position (or absence) is not observable
        through timing — the same reason the router's global key check
        uses hmac.compare_digest."""
        import hmac

        if not token:
            return None
        # compare bytes: compare_digest raises TypeError on non-ASCII str
        # inputs, and a weird client token must 401, not 500
        tok = token.encode("utf-8", "surrogateescape")
        found: TenantPolicy | None = None
        for p in self._by_id.values():
            if p.api_key and hmac.compare_digest(
                p.api_key.encode("utf-8", "surrogateescape"), tok
            ):
                found = p  # keep scanning: constant work per call
        return found


def tenant_from_headers(headers) -> TenantContext:
    """Parse the stamped tenant headers into a context; anything absent or
    malformed falls back to the default-tenant value (a bad header must
    degrade service class, never 500 the request)."""
    tid = headers.get(TENANT_HEADER, "") or DEFAULT_TENANT_ID
    if not _ID_RE.match(tid):
        tid = DEFAULT_TENANT_ID
    rank = PRIORITY_RANK.get(
        (headers.get(TENANT_PRIORITY_HEADER) or "standard").lower(),
        PRIORITY_STANDARD,
    )
    try:
        weight = float(headers.get(TENANT_WEIGHT_HEADER, "") or 1.0)
    except (TypeError, ValueError):
        weight = 1.0
    if not _MIN_WEIGHT <= weight <= _MAX_WEIGHT:
        weight = 1.0
    return TenantContext(tenant_id=tid, priority=rank, weight=weight)
