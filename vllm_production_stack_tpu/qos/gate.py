"""Router-side QoS enforcement: resolve the tenant, meter it, stamp it.

One `QoSGate` hangs off RouterState when --tenant-table-file is set (or a
dynamic-config reload supplies a `tenants` mapping). The request path is:

  auth middleware  -> resolve_tenant(bearer token, headers)  (identity)
  request_service  -> try_admit(policy, body)                (quota)
                   -> stamp(headers, policy)                 (propagation)
                   -> release(policy) when the proxy attempt ends

Enforcement runs BEFORE any endpoint is picked, composing with (not
bypassing) the endpoint breakers and the engines' own load shedding: a
tenant inside its quota can still get the engine's global 429, and a
tenant outside it never costs an engine anything.
"""

from __future__ import annotations

import threading

from ..utils.logging import init_logger
from .limiter import TenantLimiter, Throttled
from .tenants import (
    TENANT_HEADER,
    TENANT_PRIORITY_HEADER,
    TENANT_WEIGHT_HEADER,
    TenantPolicy,
    TenantTable,
)

logger = init_logger(__name__)

# inbound copies of the stamp headers are ALWAYS dropped while QoS is
# active — a client must not pick its own priority class
STAMP_HEADERS = (TENANT_HEADER, TENANT_PRIORITY_HEADER, TENANT_WEIGHT_HEADER)

# slot on the aiohttp request where the auth middleware parks the resolved
# TenantPolicy for the proxy path (router/app.py sets, request_service reads)
TENANT_REQUEST_KEY = "tpu_tenant_policy"


def count_prompt_tokens(body: dict, tokenizer) -> int:
    """Prompt tokens of an OpenAI-shaped request body, for the
    tokens-per-minute bucket. Token-id prompts count exactly; text routes
    through the gate's tokenizer (the same plumbing KV-aware routing uses,
    utils.tokenizer.hashing_tokenizer). No tokenizer -> requests-only
    metering (the token bucket charges 0)."""
    prompt = body.get("prompt")
    if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
        return len(prompt)
    if tokenizer is None:
        return 0
    parts: list[str] = []
    if "messages" in body:
        for msg in body.get("messages", []):
            content = msg.get("content", "")
            if isinstance(content, list):
                parts.extend(
                    p.get("text", "")
                    for p in content
                    if isinstance(p, dict)
                )
            elif content:
                parts.append(str(content))
    elif isinstance(prompt, list):
        parts.extend(str(p) for p in prompt)
    elif prompt:
        parts.append(str(prompt))
    text = "\n".join(parts)
    if not text:
        return 0
    try:
        return len(tokenizer.encode(text))
    except Exception:  # metering must never fail the request
        return max(1, len(text) // 4)


class QoSGate:
    def __init__(self, table: TenantTable, tokenizer=None):
        self.table = table
        self.tokenizer = tokenizer
        self.limiter = TenantLimiter(table)
        # monotonic per-tenant counters, drained as deltas by the /metrics
        # renderer (router/metrics.py) into real prometheus counters;
        # _totals accumulates the same bumps WITHOUT draining, for the
        # fleet reporter (router/fleet.py) — two consumers, two stores, so
        # neither steals the other's increments
        self._mlock = threading.Lock()
        self._pending: dict[tuple[str, str], float] = {}
        self._totals: dict[tuple[str, str], float] = {}
        self.reloads = 0
        # fleet budget scaling (docs/34-fleet-routing.md): the share of
        # each tenant's GLOBAL budget this replica's buckets enforce.
        # Driven by the fleet reporter from the controller's replica
        # count; exported as tpu:router_tenant_budget_scale.
        self.budget_scale = 1.0
        self.budget_replicas = 1

    # -- table lifecycle ---------------------------------------------------

    def update_table(self, table: TenantTable) -> None:
        """Hot-swap the policy table (dynamic-config reload). Limiter state
        for surviving tenants is preserved; the caller validates BEFORE
        calling, so a malformed file never reaches here."""
        self.table = table
        self.limiter.update_table(table)
        self.reloads += 1
        logger.info(
            "tenant table reloaded (#%d): %d tenant(s)",
            self.reloads, len(table),
        )

    def set_fleet_scale(self, replicas: int) -> None:
        """Scale local buckets to a 1/M share of each tenant's global
        budget, M = live router replica count from the controller's
        /fleet/report reply — N replicas each granting the full budget
        over-admit ≈ N-1×; N at 1/N each admit ~the global limit with no
        synchronous hop on the admission path. replicas <= 1 (single
        replica, or the fleet reporter's controller-outage degradation)
        restores the full local budget: fail open toward availability,
        never stricter."""
        m = max(1, int(replicas))
        scale = 1.0 / m
        if scale == self.budget_scale:
            return
        self.budget_scale = scale
        self.budget_replicas = m
        self.limiter.set_rate_scale(scale)
        logger.info(
            "fleet budget scaling: %d live replica(s) -> local share %.3f",
            m, scale,
        )

    # -- identity ----------------------------------------------------------

    def resolve_tenant(self, token: str | None, headers) -> TenantPolicy | None:
        """Caller identity: bearer-key row match first; then the trusted
        x-tenant-id header for KEYLESS rows (internal/mTLS deployments that
        authenticate upstream of the router — a row with an api_key can
        never be claimed by header). None when the token matches no tenant
        (the middleware then falls back to the global key check)."""
        policy = self.table.resolve_key(token)
        if policy is not None:
            return policy
        claimed = headers.get(TENANT_HEADER)
        if claimed:
            row = self.table.get(claimed)
            if row is not None and not row.api_key:
                return row
        return None

    # -- quota -------------------------------------------------------------

    def try_admit(self, policy: TenantPolicy, body: dict) -> Throttled | None:
        n_tokens = count_prompt_tokens(body, self.tokenizer)
        verdict = self.limiter.try_admit(policy, n_tokens)
        if verdict is None:
            self._bump(policy.tenant_id, "requests")
            if n_tokens:
                self._bump(policy.tenant_id, "prompt_tokens", n_tokens)
        else:
            self._bump(policy.tenant_id, "throttled")
        return verdict

    def release(self, policy: TenantPolicy) -> None:
        self.limiter.release(policy.tenant_id)

    # -- propagation -------------------------------------------------------

    def stamp(self, headers: dict[str, str], policy: TenantPolicy) -> None:
        """Stamp the resolved tenant onto upstream headers (inbound copies
        were already stripped — see request_service._upstream_headers)."""
        headers[TENANT_HEADER] = policy.tenant_id
        headers[TENANT_PRIORITY_HEADER] = policy.priority
        headers[TENANT_WEIGHT_HEADER] = repr(policy.weight)

    # -- metrics -----------------------------------------------------------

    def _bump(self, tenant_id: str, key: str, n: float = 1) -> None:
        with self._mlock:
            k = (tenant_id, key)
            self._pending[k] = self._pending.get(k, 0) + n
            self._totals[k] = self._totals.get(k, 0) + n

    def drain_counter_deltas(self) -> dict[tuple[str, str], float]:
        """(tenant, kind) -> increment since the last scrape."""
        with self._mlock:
            out, self._pending = self._pending, {}
        return out

    def totals(self) -> dict[str, dict[str, float]]:
        """Monotonic per-tenant totals ({tenant: {kind: count}}) — the
        fleet report's tenant accounting payload. Reading never drains, so
        it composes with the /metrics delta consumer."""
        with self._mlock:
            out: dict[str, dict[str, float]] = {}
            for (tenant, kind), n in self._totals.items():
                out.setdefault(tenant, {})[kind] = n
        return out
