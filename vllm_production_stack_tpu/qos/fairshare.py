"""Weighted fair share via a per-tenant virtual token counter.

Start-time weighted fair queueing, reduced to what an admission pick
needs: each tenant carries a virtual time that advances by
``cost / weight`` per admitted request, and the scheduler admits the
waiting head of the tenant with the smallest virtual time (within the
winning priority tier). A tenant with weight 3 accrues virtual time a
third as fast as a weight-1 tenant, so under saturation it is admitted —
and therefore holds decode seats — 3x as often: decode-token share tracks
weight without any per-step bookkeeping.

The clamp to the global virtual clock on reactivation is the classic WFQ
fix for banked credit: an idle tenant rejoins AT the current clock
instead of monopolizing admissions until its stale counter catches up.
"""

from __future__ import annotations


class FairShareClock:
    """Single-threaded (scheduler-owned) virtual-time bookkeeping."""

    def __init__(self) -> None:
        self._vtime: dict[str, float] = {}
        self._vclock = 0.0

    def key(self, tenant_id: str) -> float:
        """Ordering key for the admission pick: the tenant's start tag if
        it were admitted now (idle tenants clamp up to the clock)."""
        return max(self._vtime.get(tenant_id, 0.0), self._vclock)

    def charge(self, tenant_id: str, cost: float, weight: float) -> None:
        """Account one admitted request of `cost` tokens."""
        start = self.key(tenant_id)
        self._vtime[tenant_id] = start + cost / max(weight, 1e-6)
        self._vclock = start
        # bound the map: tenants come from request headers, so an abusive
        # client could otherwise grow it without limit. Far-behind entries
        # are equivalent to the clamp anyway.
        if len(self._vtime) > 4096:
            self._vtime = {
                t: v for t, v in self._vtime.items() if v > self._vclock
            }

    def forget(self, tenant_id: str) -> None:
        self._vtime.pop(tenant_id, None)
