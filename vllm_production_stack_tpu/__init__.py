"""TPU-native production LLM-serving stack.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the vLLM
Production Stack (reference: /root/reference): an OpenAI-compatible request
router with pluggable routing algorithms, a TPU serving engine with paged KV
cache in HBM and continuous batching, a KV offload fabric, and the
deployment/observability assets around them.

Subpackages
-----------
- ``engine``   — the TPU serving engine (the part the reference outsources to
  vLLM images): scheduler, paged KV pool, model runner, OpenAI HTTP server.
- ``models``   — model definitions (pure-functional JAX) + weight loading.
- ``ops``      — attention and other core ops (XLA reference + Pallas kernels).
- ``parallel`` — device mesh construction and sharding specs (TP/PP/DP).
- ``router``   — the OpenAI-compatible request router (reference:
  src/vllm_router/).
- ``utils``    — logging, singletons, misc.
"""

__version__ = "0.1.0"
