"""Paged-KV attention ops — XLA reference implementation.

The reference stack delegates attention to vLLM's CUDA paged-attention
kernels inside the engine image (external to the repo). Here the KV cache is
a preallocated paged pool in HBM and attention is expressed so XLA can fuse
and tile it onto the MXU; `ops/paged_attention_pallas.py` provides the
hand-written TPU kernel for the decode hot path, with this module as the
always-available fallback (and the CPU-test path).

Conventions
-----------
- A KV page pool for ONE layer is `kv` with shape
  ``(2, num_blocks, block_size, num_kv_heads, head_dim)`` (index 0 = K, 1 = V).
- Block 0 is the reserved *null* page: padding tokens write there and
  page-table padding points there; masks keep it out of every softmax.
- A "slot" is ``block_id * block_size + offset`` — the flat position of a
  token's KV in the pool.
- Logical cache position j of a sequence lives at slot
  ``block_table[j // block_size] * block_size + j % block_size``, so a gather
  of `block_table` pages yields the sequence's KV ordered by token position.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _scale_inv_freq(inv_freq: jax.Array, scaling: tuple) -> jax.Array:
    """RoPE frequency scaling (HF rope_scaling semantics).

    "linear": positional interpolation — every frequency divided by the
    factor. "llama3" (transformers modeling_rope_utils
    _compute_llama3_parameters, the Llama-3.1 recipe): frequencies whose
    wavelength exceeds original_max_position/low_freq_factor are divided
    by the factor, wavelengths under original_max_position/
    high_freq_factor stay unscaled, and the band between interpolates
    smoothly — long-range position signal compresses while local
    ordering stays exact."""
    kind, factor, low, high, orig = scaling
    if kind == "linear":
        return inv_freq / factor
    if kind != "llama3":
        raise ValueError(f"unsupported rope_scaling type {kind!r}")
    low_wl = orig / low
    high_wl = orig / high
    wavelen = 2.0 * jnp.pi / inv_freq
    smooth = (orig / wavelen - low) / (high - low)
    smoothed = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    return jnp.where(
        wavelen > low_wl,
        inv_freq / factor,
        jnp.where(wavelen < high_wl, inv_freq, smoothed),
    )


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float = 10000.0,
    scaling: tuple | None = None,
) -> jax.Array:
    """Rotary position embedding, non-interleaved (HF Llama convention).

    x: (..., T, heads, head_dim), positions: (..., T) int32;
    scaling: ModelConfig.rope_scaling tuple (llama3 / linear) or None.
    """
    head_dim = x.shape[-1]
    half = head_dim // 2
    freqs = jnp.arange(half, dtype=jnp.float32) / half
    inv_freq = theta**-freqs  # (half,)
    if scaling is not None:
        inv_freq = _scale_inv_freq(inv_freq, scaling)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., T, half)
    cos = jnp.cos(angles)[..., None, :]  # (..., T, 1, half)
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rx1 = x1 * cos - x2 * sin
    rx2 = x2 * cos + x1 * sin
    return jnp.concatenate([rx1, rx2], axis=-1).astype(x.dtype)



def _to_compute(x: jax.Array, like: jax.Array) -> jax.Array:
    """Convert quantized (e.g. float8 pool) K/V to the compute dtype as it
    streams into attention. XLA fuses the convert into the consumer, so the
    HBM read stays at the storage width — the whole point of a quantized
    cache."""
    return x if x.dtype == like.dtype else x.astype(like.dtype)

def write_kv_pages(
    kv: jax.Array, k: jax.Array, v: jax.Array, slot_mapping: jax.Array
) -> jax.Array:
    """Scatter new K/V rows into the paged pool.

    kv: (2, num_blocks, block_size, kvH, D); k, v: (N, kvH, D);
    slot_mapping: (N,) flat slot per token (padding rows point at block 0).
    """
    num_blocks, block_size = kv.shape[1], kv.shape[2]
    flat = kv.reshape(2, num_blocks * block_size, *kv.shape[3:])
    flat = flat.at[0, slot_mapping].set(k.astype(kv.dtype))
    flat = flat.at[1, slot_mapping].set(v.astype(kv.dtype))
    return flat.reshape(kv.shape)


def write_kv_pages_blockwise(
    kv: jax.Array,  # (2, num_blocks, bs, kvH, D)
    k: jax.Array,  # (B, T, kvH, D) — the chunk's new K rows
    v: jax.Array,  # (B, T, kvH, D)
    write_block_ids: jax.Array,  # (B, NBW) pool blocks covering the chunk's
    #   written span, in order (padding rows/slots -> 0, the null page)
    start_off: jax.Array,  # (B,) chunk's first token offset in its 1st block
    chunk_lens: jax.Array,  # (B,) real tokens in the chunk per row
) -> jax.Array:
    """Chunk K/V write at BLOCK granularity: read-modify-write whole pages
    instead of scattering one row per token. A token-row scatter of a 256x128
    prefill wave issues 32K scatter descriptors per layer and measured
    ~160 ms/wave on a v5e chip; page-granular .at[ids].set with 16x fewer,
    16x larger units cuts that to ~50 ms including the merge gather.

    The merge keeps pool content outside [start_off, start_off+chunk_len)
    (earlier chunks of the same sequence living in the first page), so
    chunked prefill continuation is exact. Rows may start mid-block; padding
    rows point every id at the null page."""
    b, t, kvh, d = k.shape
    nbw = write_block_ids.shape[1]
    bs = kv.shape[2]
    s = nbw * bs
    # chunk-token index of each (row, span-position); clamp for the gather,
    # mask decides validity
    tok = jnp.arange(s, dtype=jnp.int32)[None, :] - start_off[:, None]
    mask = (tok >= 0) & (tok < chunk_lens[:, None])  # (B, S)
    tok_c = jnp.clip(tok, 0, t - 1)
    rows = jnp.arange(b)[:, None]
    k_sp = k[rows, tok_c].reshape(b, nbw, bs, kvh, d).astype(kv.dtype)
    v_sp = v[rows, tok_c].reshape(b, nbw, bs, kvh, d).astype(kv.dtype)
    m = mask.reshape(b, nbw, bs, 1, 1)
    ids = write_block_ids.reshape(-1)
    old = kv[:, ids].reshape(2, b, nbw, bs, kvh, d)
    kv = kv.at[0, ids].set(
        jnp.where(m, k_sp, old[0]).reshape(b * nbw, bs, kvh, d)
    )
    kv = kv.at[1, ids].set(
        jnp.where(m, v_sp, old[1]).reshape(b * nbw, bs, kvh, d)
    )
    return kv


def gather_pages(kv: jax.Array, block_tables: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Gather each sequence's pages into contiguous (B, S_ctx, kvH, D) K and V.

    block_tables: (B, max_blocks) int32 (padding entries = 0, the null page).
    """
    b, max_blocks = block_tables.shape
    block_size, kvh, d = kv.shape[2], kv.shape[3], kv.shape[4]
    pages = kv[:, block_tables]  # (2, B, max_blocks, block_size, kvH, D)
    seq = pages.reshape(2, b, max_blocks * block_size, kvh, d)
    return seq[0], seq[1]


def causal_page_mask(
    q_positions: jax.Array, context_lens: jax.Array, s: int,
    window: int = 0,
) -> jax.Array:
    """(B, T, S) mask: gathered-context position j is attendable by the query
    at logical position p iff j < context_len and j <= p — and, when
    `window` > 0 (sliding-window attention: Mistral-v0.1 all-layer SWA,
    Gemma-2 alternating layers), additionally j > p - window. Build once
    per step per window kind and reuse across same-kind layers.

    q_positions: (B, T); context_lens: (B,); s: gathered context length.
    """
    ctx_pos = jnp.arange(s, dtype=jnp.int32)[None, :]  # (1, S)
    valid = ctx_pos < context_lens[:, None]  # (B, S)
    causal = ctx_pos[:, None, :] <= q_positions[..., None]  # (B, T, S)
    mask = valid[:, None, :] & causal
    if window > 0:
        mask &= ctx_pos[:, None, :] > q_positions[..., None] - window
    return mask


# context length above which masked_attention switches to the chunked
# flash path: the direct path materializes (B, kvH, qpk, T, S) f32 scores,
# which at long context is GBs per layer (e.g. B=16, T=512 chunk, S=8192:
# 2.1 GiB) — the chunked path holds one S-chunk of scores at a time
FLASH_CHUNK = 2048


def _softcap(scores: jax.Array, cap: float) -> jax.Array:
    """Gemma-2 attention-logit softcapping: cap * tanh(scores / cap),
    applied AFTER the scale, BEFORE the mask (HF eager_attention_forward
    order)."""
    return jnp.tanh(scores / cap) * cap if cap else scores


def masked_attention(
    q: jax.Array,
    keys: jax.Array,
    values: jax.Array,
    mask: jax.Array,
    *,
    scale: float,
    softcap: float = 0.0,
) -> jax.Array:
    """GQA attention over already-contiguous keys/values.

    q: (B, T, num_heads, D); keys/values: (B, S, kvH, D); mask: (B, T, S).
    returns: (B, T, num_heads, D)
    """
    b, t, num_heads, d = q.shape
    s = keys.shape[1]
    kvh = keys.shape[2]
    qpk = num_heads // kvh
    keys, values = _to_compute(keys, q), _to_compute(values, q)
    qg = q.reshape(b, t, kvh, qpk, d)
    if s > FLASH_CHUNK:
        pad = (-s) % FLASH_CHUNK
        if pad:
            # pad to a chunk multiple; masked-off padding contributes zero
            # weight, so the result is unchanged
            keys = jnp.pad(keys, ((0, 0), (0, pad), (0, 0), (0, 0)))
            values = jnp.pad(values, ((0, 0), (0, pad), (0, 0), (0, 0)))
            mask = jnp.pad(mask, ((0, 0), (0, 0), (0, pad)))
        return _flash_masked_attention(qg, keys, values, mask, scale=scale,
                                       softcap=softcap)
    # scores accumulate in f32 but Q/K stream through the MXU in their native
    # dtype — casting bf16 operands to f32 first would double the HBM traffic
    # of the K read AND fall off the bf16 systolic path (f32 models, i.e. the
    # CPU parity tests, are unchanged: preferred_element_type is f32 either
    # way). Scores: (B, kvH, qpk, T, S)
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, keys, preferred_element_type=jnp.float32
    )
    scores *= scale
    scores = _softcap(scores, softcap)
    scores = jnp.where(mask[:, None, None], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    # P·V: probs stream in the value dtype (bf16 on TPU), f32 accumulation
    out = jnp.einsum(
        "bkgts,bskd->btkgd", probs.astype(values.dtype), values,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, num_heads, d).astype(q.dtype)


def _flash_masked_attention(
    qg: jax.Array,  # (B, T, kvH, qpk, D)
    keys: jax.Array,  # (B, S, kvH, D)
    values: jax.Array,  # (B, S, kvH, D)
    mask: jax.Array,  # (B, T, S)
    *,
    scale: float,
    softcap: float = 0.0,
) -> jax.Array:
    """Online-softmax over S chunks (lax.scan): peak score memory is one
    (B, kvH, qpk, T, FLASH_CHUNK) block instead of the full S axis. Same
    math as the direct path up to float associativity."""
    b, t, kvh, qpk, d = qg.shape
    s = keys.shape[1]
    n = s // FLASH_CHUNK
    # chunk-major stacks for scan
    k_c = keys.reshape(b, n, FLASH_CHUNK, kvh, d).transpose(1, 0, 2, 3, 4)
    v_c = values.reshape(b, n, FLASH_CHUNK, kvh, d).transpose(1, 0, 2, 3, 4)
    m_c = mask.reshape(b, t, n, FLASH_CHUNK).transpose(2, 0, 1, 3)

    def body(carry, inputs):
        m_prev, l_prev, acc = carry
        k, v, msk = inputs
        # native-dtype Q/K/V through the MXU, f32 accumulation (see
        # masked_attention)
        scores = jnp.einsum(
            "btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32
        ) * scale
        scores = _softcap(scores, softcap)
        scores = jnp.where(msk[:, None, None], scores, NEG_INF)
        m_cur = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bkgts,bskd->bkgtd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l_new, acc), None

    init = (
        jnp.full((b, kvh, qpk, t), NEG_INF, jnp.float32),
        jnp.zeros((b, kvh, qpk, t), jnp.float32),
        jnp.zeros((b, kvh, qpk, t, d), jnp.float32),
    )
    (m, l, acc), _ = jax.lax.scan(body, init, (k_c, v_c, m_c))
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # (B, kvH, qpk, T, D)
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, t, kvh * qpk, d)
    return out.astype(qg.dtype)


def paged_attention_xla(
    q: jax.Array,
    kv: jax.Array,
    block_tables: jax.Array,
    mask: jax.Array,
    *,
    scale: float,
    softcap: float = 0.0,
) -> jax.Array:
    """Causal attention of queries against the paged KV cache.

    Covers prefill, chunked prefill, and decode with one einsum-shaped
    program (decode is T=1): the chunk's own K/V are written to the pool
    *before* calling this, so causality is purely positional masking.

    q: (B, T, num_heads, D)
    kv: (2, num_blocks, block_size, kvH, D) for this layer
    block_tables: (B, max_blocks)
    mask: (B, T, S) from causal_page_mask
    returns: (B, T, num_heads, D)
    """
    keys, values = gather_pages(kv, block_tables)  # (B, S, kvH, D)
    return masked_attention(q, keys, values, mask, scale=scale,
                            softcap=softcap)


def paged_attention_with_staged(
    q: jax.Array,
    kv: jax.Array,
    block_tables: jax.Array,
    hist_mask: jax.Array,
    staged_k: jax.Array,
    staged_v: jax.Array,
    staged_mask: jax.Array,
    *,
    scale: float,
    softcap: float = 0.0,
) -> jax.Array:
    """Decode-window attention: pooled history + this window's staged KV.

    Inside the fused decode window the pool is LOOP-INVARIANT (read-only):
    the window's new K/V live in a small staging buffer and are committed to
    the pool once after the loop. Carrying the full pool through the
    lax.fori_loop instead ping-pongs it — two extra full-pool buffers of
    compile-time temp (measured: 2.0 GiB pool → 4.28 GiB temp), which is what
    capped pool sizes well below HBM.

    q: (B, 1, num_heads, D) — decode queries
    kv: (2, num_blocks, block_size, kvH, D), read-only
    hist_mask: (B, S) — pool positions < this row's history length
    staged_k/staged_v: (W, B, kvH, D) — this window's K/V so far
    staged_mask: (W,) — staged slots valid at this iteration (w <= k)
    returns: (B, 1, num_heads, D)
    """
    hist_k, hist_v = gather_pages(kv, block_tables)  # (B, S, kvH, D)
    return attention_with_hist(
        q, hist_k, hist_v, hist_mask, staged_k, staged_v, staged_mask,
        scale=scale, softcap=softcap,
    )


def attention_with_hist(
    q: jax.Array,
    hist_k: jax.Array,
    hist_v: jax.Array,
    hist_mask: jax.Array,
    staged_k: jax.Array,
    staged_v: jax.Array,
    staged_mask: jax.Array,
    *,
    scale: float,
    softcap: float = 0.0,
) -> jax.Array:
    """Decode-window attention against ALREADY-CONTIGUOUS history + staged
    window KV. The pool gather that produces hist_k/hist_v is loop-invariant
    across a fused decode window, so the runner hoists it OUT of the window
    loop when HBM headroom allows (one gather per layer per window instead of
    per iteration — measured 20→12 ms/iter at B=256, S=256 on a v5e chip,
    42→16 at S=512; the per-iteration gather's cost tracks gathered bytes,
    not page count).

    q: (B, 1, num_heads, D); hist_k/hist_v: (B, S, kvH, D);
    hist_mask: (B, S); staged_k/staged_v: (W, B, kvH, D); staged_mask: (W,).
    """
    b, t, num_heads, d = q.shape
    kvh = hist_k.shape[2]
    qpk = num_heads // kvh
    hist_k, hist_v = _to_compute(hist_k, q), _to_compute(hist_v, q)
    qg = q.reshape(b, t, kvh, qpk, d)
    # score the two regions separately and concatenate SCORES (small, f32)
    # rather than keys/values — concatenating K and V materializes a fresh
    # (B, S+W, kvH, D) copy of the gathered history per layer per iteration.
    # Native-dtype Q/K/V stream through the MXU with f32 accumulation
    # (bf16 history read at bf16 width — the decode loop's dominant traffic)
    hist_scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, hist_k, preferred_element_type=jnp.float32
    )
    st_scores = jnp.einsum(
        "btkgd,wbkd->bkgtw", qg, staged_k.astype(q.dtype),
        preferred_element_type=jnp.float32,
    )
    scores = jnp.concatenate([hist_scores, st_scores], axis=-1) * scale
    scores = _softcap(scores, softcap)
    s = hist_k.shape[1]
    mask = jnp.concatenate(
        [
            jnp.broadcast_to(hist_mask[:, None, :], (b, t, s)),
            jnp.broadcast_to(staged_mask[None, None, :], (b, t, staged_mask.shape[0])),
        ],
        axis=-1,
    )
    scores = jnp.where(mask[:, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgts,bskd->btkgd", probs[..., :s].astype(hist_v.dtype), hist_v,
        preferred_element_type=jnp.float32,
    )
    out += jnp.einsum(
        "bkgtw,wbkd->btkgd",
        probs[..., s:].astype(staged_v.dtype), staged_v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, num_heads, d).astype(q.dtype)
