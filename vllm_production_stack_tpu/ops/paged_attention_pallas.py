"""Pallas TPU kernel: paged decode attention over the HBM KV pool.

The XLA fallback (ops/attention.py:paged_attention_with_staged) materializes
each row's gathered history — (B, S, kvH, D) per layer per window iteration —
in HBM before attending. This kernel never materializes the gather: the
Pallas pipeline streams KV pages HBM→VMEM directly from the paged pool, with
the page id for each grid step read from the scalar-prefetched block table
(the "gather" IS the pipeline's index_map), and a flash-style online softmax
accumulates across pages in VMEM scratch. The window's staged K/V (see
models/llama.py:decode_window_step) are folded in by a final grid step so the
kernel computes the complete decode attention output.

Reference parity: the reference stack delegates this op to vLLM's CUDA
paged-attention kernels inside its engine images (external to its repo);
SURVEY §7.3 ranks a TPU-native equivalent as hard part #1.

Layout notes (pallas_guide.md): last dim 128 lanes — head_dim (64/128) maps
onto lanes; token-position and head axes map onto sublanes. All matmuls are
(≤heads × D) @ (D × page) — small for the MXU, but decode is HBM-bandwidth
bound, so the win is streaming pages once, not MXU utilization.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    tables_ref,  # (B, nb) int32 — page id per (row, page-slot)
    hist_ref,  # (B,) int32 — pool history length per row
    step_ref,  # (1,) int32 — iteration index inside the fused window
    # pipeline inputs
    q_ref,  # (1, nh, D)
    kv_ref,  # (2, 1, bs, kvh, D) — this grid step's pool page (K and V)
    staged_k_ref,  # (W, 1, kvh, D) — this row's staged window K
    staged_v_ref,  # (W, 1, kvh, D)
    # output
    out_ref,  # (1, nh, D)
    # scratch
    m_ref,  # (nh, 1) f32 running max
    l_ref,  # (nh, 1) f32 running denominator
    acc_ref,  # (nh, D) f32 running numerator
    *,
    scale: float,
    block_size: int,
    num_kv_heads: int,
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    num_pages = pl.num_programs(1) - 1  # last step handles the staged window

    nh, d = q_ref.shape[1], q_ref.shape[2]
    qpk = nh // num_kv_heads
    q = q_ref[0].astype(jnp.float32)  # (nh, D)

    @pl.when(j == 0)
    def _init_acc():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def flash_update(scores, values):
        """scores: (nh, S) f32 already masked; values: (S, kvh, D)."""
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_cur = jnp.max(scores, axis=1, keepdims=True)  # (nh, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # (nh, 1)
        p = jnp.exp(scores - m_new)  # (nh, S)
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        # per-kv-head GQA: the qpk query heads of group g attend values[:, g].
        # Built by concatenation — value-level .at[].add lowers to scatter-add,
        # which the Pallas TPU lowering doesn't implement
        v_f = values.astype(jnp.float32)
        acc = acc_ref[:] * alpha  # (nh, D)
        acc_ref[:] = jnp.concatenate(
            [
                acc[g * qpk : (g + 1) * qpk]
                + jax.lax.dot(
                    p[g * qpk : (g + 1) * qpk],
                    v_f[:, g, :],
                    preferred_element_type=jnp.float32,
                )
                for g in range(num_kv_heads)
            ],
            axis=0,
        )

    @pl.when(j < num_pages)
    def _visit_page():
        k_page = kv_ref[0, 0].astype(jnp.float32)  # (bs, kvh, D)
        v_page = kv_ref[1, 0]
        # token positions covered by this page slot
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        valid = pos < hist_ref[b]  # (1, bs)
        scores = jnp.concatenate(
            [
                jax.lax.dot_general(
                    q[g * qpk : (g + 1) * qpk],
                    k_page[:, g, :],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for g in range(num_kv_heads)
            ],
            axis=0,
        )  # (nh, bs)
        scores = jnp.where(valid, scores * scale, NEG_INF)
        flash_update(scores, v_page)

    @pl.when(j == num_pages)
    def _finalize():
        w = staged_k_ref.shape[0]
        k_st = staged_k_ref[:, 0].astype(jnp.float32)  # (W, kvh, D)
        v_st = staged_v_ref[:, 0]
        widx = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
        valid = widx <= step_ref[0]  # staged slot written iff w <= k
        scores = jnp.concatenate(
            [
                jax.lax.dot_general(
                    q[g * qpk : (g + 1) * qpk],
                    k_st[:, g, :],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for g in range(num_kv_heads)
            ],
            axis=0,
        )  # (nh, W)
        scores = jnp.where(valid, scores * scale, NEG_INF)
        flash_update(scores, v_st)
        out_ref[0] = (acc_ref[:] / l_ref[:]).astype(out_ref.dtype)


def _hist_kernel(
    # scalar prefetch
    hist_ref,  # (B,) int32 — history length per row
    step_ref,  # (1,) int32 — iteration index inside the fused window
    # pipeline inputs
    q_ref,  # (1, nh, D)
    k_ref,  # (1, C, kvh, D) — this grid step's contiguous history chunk
    v_ref,  # (1, C, kvh, D)
    staged_k_ref,  # (W, 1, kvh, D)
    staged_v_ref,  # (W, 1, kvh, D)
    # output
    out_ref,  # (1, nh, D)
    # scratch
    m_ref,  # (nh, 1) f32
    l_ref,  # (nh, 1) f32
    acc_ref,  # (nh, D) f32
    *,
    scale: float,
    chunk: int,
    num_kv_heads: int,
):
    """Flash decode over HOISTED CONTIGUOUS history (B, S, kvh, D): large
    sequential chunks instead of 16-token pool pages, so each HBM→VMEM DMA
    moves C·kvh·D elements (e.g. 512 tokens ≈ 0.5 MB) — the regime where the
    pipeline reaches memory bandwidth. Same flash recurrence as
    _decode_kernel; the staged window folds in on the final grid step."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    num_chunks = pl.num_programs(1) - 1

    nh, d = q_ref.shape[1], q_ref.shape[2]
    qpk = nh // num_kv_heads
    q = q_ref[0]  # (nh, D) native dtype; dots accumulate f32

    @pl.when(j == 0)
    def _init_acc():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def flash_update(scores, values):
        """scores: (nh, S) f32 already masked; values: (S, kvh, D)."""
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        p = p.astype(values.dtype)
        acc = acc_ref[:] * alpha
        acc_ref[:] = jnp.concatenate(
            [
                acc[g * qpk : (g + 1) * qpk]
                + jax.lax.dot(
                    p[g * qpk : (g + 1) * qpk],
                    values[:, g, :],
                    preferred_element_type=jnp.float32,
                )
                for g in range(num_kv_heads)
            ],
            axis=0,
        )

    @pl.when(j < num_chunks)
    def _visit_chunk():
        k_chunk = k_ref[0]  # (C, kvh, D)
        v_chunk = v_ref[0]
        pos = j * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        valid = pos < hist_ref[b]  # (1, C)
        scores = jnp.concatenate(
            [
                jax.lax.dot_general(
                    q[g * qpk : (g + 1) * qpk],
                    k_chunk[:, g, :],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for g in range(num_kv_heads)
            ],
            axis=0,
        )  # (nh, C)
        scores = jnp.where(valid, scores * scale, NEG_INF)
        flash_update(scores, v_chunk)

    @pl.when(j == num_chunks)
    def _finalize():
        w = staged_k_ref.shape[0]
        k_st = staged_k_ref[:, 0]  # (W, kvh, D)
        v_st = staged_v_ref[:, 0]
        widx = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
        valid = widx <= step_ref[0]
        scores = jnp.concatenate(
            [
                jax.lax.dot_general(
                    q[g * qpk : (g + 1) * qpk],
                    k_st[:, g, :],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for g in range(num_kv_heads)
            ],
            axis=0,
        )  # (nh, W)
        scores = jnp.where(valid, scores * scale, NEG_INF)
        flash_update(scores, v_st)
        out_ref[0] = (acc_ref[:] / l_ref[:]).astype(out_ref.dtype)


# --------------------------------------------------------------------------
# Paged PREFILL (chunked-prefill-aware) flash attention
# --------------------------------------------------------------------------

# query-tile length: q rows resident in VMEM across the page stream. 256
# keeps (nh, Tt, D) q + f32 (nh*Tt, D) acc under ~3 MB for llama head
# shapes, leaving room for double-buffered page DMAs
PREFILL_Q_TILE = 256


def _prefill_kernel(
    # scalar prefetch
    tables_ref,  # (B, nb) int32 — page id per (row, page-slot)
    ctx_ref,  # (B,) int32 — resident tokens AFTER this chunk (incl. chunk)
    start_ref,  # (B,) int32 — logical position of the chunk's first token
    # pipeline inputs
    q_ref,  # (1, nh, Tt, D) — head-major so per-head slices are static 2D
    kv_ref,  # (2, 1, bs, kvh, D) — this grid step's pool page
    # output
    out_ref,  # (1, nh, Tt, D)
    # scratch
    m_ref,  # (nh*Tt, 1) f32 running max, head-major rows
    l_ref,  # (nh*Tt, 1) f32 running denominator
    acc_ref,  # (nh*Tt, D) f32 running numerator
    *,
    scale: float,
    block_size: int,
    num_kv_heads: int,
):
    """Flash prefill over the paged pool: the page id for each grid step
    comes from the scalar-prefetched block table (the gather IS the
    pipeline's index_map — same trick as _decode_kernel), the query tile
    stays in VMEM, and causality is computed from iotas alone: the serving
    scheduler feeds chunks with CONTIGUOUS positions (scheduler.py
    work.positions = range(start, start+len)), so q position = chunk_start
    + tile offset + row. Chunked prefill needs no special casing — resident
    pages hold earlier chunks AND this chunk's freshly-written KV (forward
    writes before attending), and `pos_k <= pos_q` masks the not-yet-valid
    tail of the chunk's own pages."""
    b = pl.program_id(0)
    qt = pl.program_id(1)
    j = pl.program_id(2)
    num_pages = pl.num_programs(2)

    nh, tt, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    qpk = nh // num_kv_heads

    @pl.when(j == 0)
    def _init_acc():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # (Tt, bs) mask — identical for every head, built once per grid step
    q_pos = (
        start_ref[b]
        + qt * tt
        + jax.lax.broadcasted_iota(jnp.int32, (tt, block_size), 0)
    )
    pos_k = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (tt, block_size), 1
    )
    valid = (pos_k < ctx_ref[b]) & (pos_k <= q_pos)

    # skip pages fully outside this tile's attendable range: beyond the
    # row's residency, or entirely after the tile's last query position.
    # The DMA still lands (static block spec) but the 2*nh dots don't run
    page_live = (j * block_size < ctx_ref[b]) & (
        j * block_size <= start_ref[b] + qt * tt + tt - 1
    )

    @pl.when(page_live)
    def _visit_live_page():
        k_page = kv_ref[0, 0].astype(jnp.float32)  # (bs, kvh, D)
        v_page = kv_ref[1, 0].astype(jnp.float32)
        for h in range(nh):
            g = h // qpk
            q_h = q_ref[0, h].astype(jnp.float32)  # (Tt, D)
            scores = jax.lax.dot_general(
                q_h, k_page[:, g, :],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (Tt, bs)
            scores = jnp.where(valid, scores * scale, NEG_INF)
            r0, r1 = h * tt, (h + 1) * tt
            m_prev, l_prev = m_ref[r0:r1], l_ref[r0:r1]
            m_cur = jnp.max(scores, axis=1, keepdims=True)  # (Tt, 1)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(scores - m_new)  # (Tt, bs)
            l_ref[r0:r1] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
            m_ref[r0:r1] = m_new
            acc_ref[r0:r1] = acc_ref[r0:r1] * alpha + jax.lax.dot(
                p, v_page[:, g, :], preferred_element_type=jnp.float32
            )

    @pl.when(j == num_pages - 1)
    def _finalize():
        for h in range(nh):
            r0, r1 = h * tt, (h + 1) * tt
            # padding rows attend nothing (ctx 0) — l stays 0; the max
            # keeps them finite (their outputs are never read)
            out_ref[0, h] = (
                acc_ref[r0:r1] / jnp.maximum(l_ref[r0:r1], 1e-30)
            ).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_prefill_attention(
    q: jax.Array,  # (B, T, nh, D) — the chunk's queries
    kv: jax.Array,  # (2, num_blocks, bs, kvh, D) — pool, chunk KV already in
    block_tables: jax.Array,  # (B, nb) int32
    context_lens: jax.Array,  # (B,) int32 — resident incl. this chunk
    chunk_start: jax.Array,  # (B,) int32 — logical position of q[:, 0]
    *,
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """Complete chunked-prefill attention (resident pages + the chunk's own
    freshly-written pages) without materializing the per-row gather OR the
    (B, T, S) mask the XLA path builds. Returns (B, T, nh, D).

    Reference parity: covers what the reference's engine images get from
    vLLM's CUDA flash-prefill over paged KV; SURVEY §7.1 names paged
    attention kernels as the TPU-native hard part."""
    b, t, nh, d = q.shape
    kvh, bs = kv.shape[3], kv.shape[2]
    nb = block_tables.shape[1]
    tt = min(t, PREFILL_Q_TILE)
    assert t % tt == 0, (t, tt)  # T is a power-of-two bucket

    # head-major q so the kernel's per-head slices are static 2D views
    q_hm = q.transpose(0, 2, 1, 3)  # (B, nh, T, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # tables, context_lens, chunk_start
        grid=(b, t // tt, nb),
        in_specs=[
            pl.BlockSpec((1, nh, tt, d), lambda i, qt, j, tb, c, st: (i, 0, qt, 0)),
            # the paged "gather": page id for grid step (i, qt, j) comes
            # straight from the prefetched block table
            pl.BlockSpec(
                (2, 1, bs, kvh, d),
                lambda i, qt, j, tb, c, st: (0, tb[i, j], 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, nh, tt, d), lambda i, qt, j, tb, c, st: (i, 0, qt, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((nh * tt, 1), jnp.float32),
            pltpu.VMEM((nh * tt, 1), jnp.float32),
            pltpu.VMEM((nh * tt, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _prefill_kernel, scale=scale, block_size=bs, num_kv_heads=kvh
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, t, d), q.dtype),
        interpret=interpret,
    )(block_tables, context_lens, chunk_start, q_hm, kv)
    return out.transpose(0, 2, 1, 3)  # back to (B, T, nh, D)


def paged_prefill_attention_sharded(
    mesh,
    q: jax.Array,  # (B, T, nh, D) — batch over dp, heads over tp
    kv: jax.Array,  # (2, num_blocks, bs, kvh, D) — kv heads over tp
    block_tables: jax.Array,  # (B, nb)
    context_lens: jax.Array,  # (B,)
    chunk_start: jax.Array,  # (B,)
    *,
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """The prefill kernel under tensor/data parallelism — same shard_map
    placement as paged_decode_attention_sharded: prefill attention is
    embarrassingly parallel over (row, head) once KV pages are head-sharded
    (kv_cache_spec's layout), so no collective is needed."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DP_AXIS, TP_AXIS

    tp = mesh.shape[TP_AXIS]
    nh, kvh = q.shape[2], kv.shape[3]
    if nh % tp or kvh % tp:
        raise ValueError(
            f"pallas prefill under tp={tp} needs heads divisible by tp "
            f"(num_heads={nh}, num_kv_heads={kvh})"
        )
    fn = shard_map(
        functools.partial(
            paged_prefill_attention, scale=scale, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(
            P(DP_AXIS, None, TP_AXIS, None),  # q
            P(None, None, None, TP_AXIS, None),  # kv pool
            P(DP_AXIS, None),  # block tables
            P(DP_AXIS),  # context_lens
            P(DP_AXIS),  # chunk_start
        ),
        out_specs=P(DP_AXIS, None, TP_AXIS, None),
        check_rep=False,
    )
    return fn(q, kv, block_tables, context_lens, chunk_start)


HIST_CHUNK = 512


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def hist_decode_attention(
    q: jax.Array,  # (B, nh, D) — decode queries, one token per row
    hist_k: jax.Array,  # (B, S, kvh, D) — hoisted contiguous history
    hist_v: jax.Array,  # (B, S, kvh, D)
    hist_len: jax.Array,  # (B,) int32
    staged_k: jax.Array,  # (W, B, kvh, D)
    staged_v: jax.Array,  # (W, B, kvh, D)
    step_k: jax.Array,  # scalar int32
    *,
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """Flash decode attention over hoisted contiguous history + the staged
    window. Returns (B, nh, D). S must divide into HIST_CHUNK-or-smaller
    power-of-two chunks (the runner's context buckets are powers of two).

    Status: opt-in experiment, NOT wired into the serving path. Measured on a
    v5e chip (llama-1b decode, B=256, W=64, S=256): 20.2 ms/iter vs 12.1 for
    the XLA formulation over the same hoisted history
    (ops/attention.py:attention_with_hist) — at these shapes XLA's fused
    einsum pipeline beats the kernel's per-(row, chunk) grid; revisit for
    long-context shapes where the score matrix stops fitting fused form."""
    b, nh, d = q.shape
    kvh = hist_k.shape[2]
    s = hist_k.shape[1]
    chunk = min(s, HIST_CHUNK)
    assert s % chunk == 0, (s, chunk)
    n_chunks = s // chunk
    w = staged_k.shape[0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # hist_len, step_k
        grid=(b, n_chunks + 1),
        in_specs=[
            pl.BlockSpec((1, nh, d), lambda i, j, h, st: (i, 0, 0)),
            pl.BlockSpec(
                (1, chunk, kvh, d),
                lambda i, j, h, st: (i, jnp.minimum(j, (s // chunk) - 1), 0, 0),
            ),
            pl.BlockSpec(
                (1, chunk, kvh, d),
                lambda i, j, h, st: (i, jnp.minimum(j, (s // chunk) - 1), 0, 0),
            ),
            pl.BlockSpec((w, 1, kvh, d), lambda i, j, h, st: (0, i, 0, 0)),
            pl.BlockSpec((w, 1, kvh, d), lambda i, j, h, st: (0, i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, d), lambda i, j, h, st: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _hist_kernel, scale=scale, chunk=chunk, num_kv_heads=kvh
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, d), q.dtype),
        interpret=interpret,
    )(hist_len, jnp.reshape(step_k, (1,)), q, hist_k, hist_v, staged_k, staged_v)


def paged_decode_attention_sharded(
    mesh,
    q: jax.Array,  # (B, nh, D) — batch sharded over dp, heads over tp
    kv: jax.Array,  # (2, num_blocks, bs, kvh, D) — kv heads over tp
    block_tables: jax.Array,  # (B, nb)
    hist_len: jax.Array,  # (B,)
    staged_k: jax.Array,  # (W, B, kvh, D)
    staged_v: jax.Array,  # (W, B, kvh, D)
    step_k: jax.Array,
    *,
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """The kernel under tensor/data parallelism: pallas_call has no GSPMD
    partition rule, so shard_map places one kernel instance per device —
    each computes its own tp-shard of the heads (KV heads shard cleanly:
    q head g attends kv head g//q_per_kv, and megatron sharding keeps whole
    GQA groups per shard) over its own dp-shard of the rows. No collective
    is needed: decode attention is embarrassingly parallel over (row, head)
    once KV pages are head-sharded, which is exactly kv_cache_spec's layout
    (parallel/sharding.py — 'each chip only ever touches its own heads'
    pages')."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DP_AXIS, TP_AXIS

    tp = mesh.shape[TP_AXIS]
    nh, kvh = q.shape[1], kv.shape[3]
    if nh % tp or kvh % tp:
        raise ValueError(
            f"pallas under tp={tp} needs heads divisible by tp "
            f"(num_heads={nh}, num_kv_heads={kvh})"
        )
    fn = shard_map(
        functools.partial(
            paged_decode_attention, scale=scale, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(
            P(DP_AXIS, TP_AXIS, None),  # q
            P(None, None, None, TP_AXIS, None),  # kv pool
            P(DP_AXIS, None),  # block tables
            P(DP_AXIS),  # hist_len
            P(None, DP_AXIS, TP_AXIS, None),  # staged k
            P(None, DP_AXIS, TP_AXIS, None),  # staged v
            P(),  # step_k scalar
        ),
        out_specs=P(DP_AXIS, TP_AXIS, None),
        check_rep=False,
    )
    return fn(q, kv, block_tables, hist_len, staged_k, staged_v, step_k)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention(
    q: jax.Array,  # (B, nh, D) — decode queries, one token per row
    kv: jax.Array,  # (2, num_blocks, bs, kvh, D) — the pool, read-only
    block_tables: jax.Array,  # (B, nb) int32
    hist_len: jax.Array,  # (B,) int32 — pool positions < hist_len are valid
    staged_k: jax.Array,  # (W, B, kvh, D) — fused-window staged keys
    staged_v: jax.Array,  # (W, B, kvh, D)
    step_k: jax.Array,  # scalar int32 — current iteration in the window
    *,
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """Complete decode attention (pooled history + staged window) without
    materializing the per-row gather. Returns (B, nh, D)."""
    b, nh, d = q.shape
    kvh, bs = kv.shape[3], kv.shape[2]
    nb = block_tables.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # tables, hist_len, step_k
        grid=(b, nb + 1),
        in_specs=[
            pl.BlockSpec((1, nh, d), lambda i, j, t, h, s: (i, 0, 0)),
            # the paged "gather": page id for grid step (i, j) comes straight
            # from the prefetched block table (clamped on the final step,
            # whose fetch is unused)
            pl.BlockSpec(
                (2, 1, bs, kvh, d),
                lambda i, j, t, h, s: (
                    0,
                    t[i, jnp.minimum(j, t.shape[1] - 1)],
                    0,
                    0,
                    0,
                ),
            ),
            pl.BlockSpec(
                (staged_k.shape[0], 1, kvh, d), lambda i, j, t, h, s: (0, i, 0, 0)
            ),
            pl.BlockSpec(
                (staged_v.shape[0], 1, kvh, d), lambda i, j, t, h, s: (0, i, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec((1, nh, d), lambda i, j, t, h, s: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, scale=scale, block_size=bs, num_kv_heads=kvh
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, d), q.dtype),
        interpret=interpret,
    )(block_tables, hist_len, jnp.reshape(step_k, (1,)), q, kv, staged_k, staged_v)
