"""The engine↔router metrics contract.

The reference router scrapes vLLM metric names from each engine's /metrics
(`vllm:num_requests_running`, `vllm:gpu_cache_usage_perc`,
`vllm:gpu_prefix_cache_hit_rate`, ... — src/vllm_router/stats/
engine_stats.py:63-76) and the observability stack / KEDA autoscaling key off
them (observability/prom-adapter.yaml:19-31). This module is the single
source of truth for the TPU equivalents: HBM paged-KV metrics instead of GPU
KV metrics. Both the engine exporter (engine/metrics.py) and the router
scraper (router/stats/engine_stats.py) import these names.
"""

# gauges
NUM_REQUESTS_RUNNING = "tpu:num_requests_running"
NUM_REQUESTS_WAITING = "tpu:num_requests_waiting"
HBM_KV_USAGE_PERC = "tpu:hbm_kv_usage_perc"
PREFIX_CACHE_HIT_RATE = "tpu:hbm_prefix_cache_hit_rate"
# host-RAM offload tier (LMCache CPU-offload equivalent)
HOST_KV_USAGE_PERC = "tpu:host_kv_usage_perc"
# pipelined step loop (engine/engine.py async_scheduling): fraction of
# step-loop wall time in which host scheduling/postprocess overlapped an
# in-flight device step — 0.0 on the serial fallback path
STEP_OVERLAP_FRAC = "tpu:engine_step_overlap_frac"

# counters
PREFIX_CACHE_HITS = "tpu:hbm_prefix_cache_hits_total"
PREFIX_CACHE_QUERIES = "tpu:hbm_prefix_cache_queries_total"
NUM_PREEMPTIONS = "tpu:num_preemptions_total"
PROMPT_TOKENS = "tpu:prompt_tokens_total"
GENERATION_TOKENS = "tpu:generation_tokens_total"
HOST_KV_OFFLOADS = "tpu:host_kv_offloaded_blocks_total"
HOST_KV_RELOADS = "tpu:host_kv_reloaded_blocks_total"
# remote KV store tier (LMCache remote-server equivalent, kvstore/)
REMOTE_KV_STORES = "tpu:remote_kv_stored_blocks_total"
REMOTE_KV_FETCHES = "tpu:remote_kv_fetched_blocks_total"
# speculative decoding (vLLM parity: vllm:spec_decode_num_*_tokens) —
# aggregate totals across proposers
SPEC_DRAFT_TOKENS = "tpu:spec_decode_num_draft_tokens_total"
SPEC_ACCEPTED_TOKENS = "tpu:spec_decode_num_accepted_tokens_total"
# per-proposer acceptance accounting (docs/36-speculative-decoding.md):
# proposer= is a CLOSED label set (ngram = prompt lookup, draft = the
# draft-model proposer), exporter-seeded at zero. The acceptance-rate
# recording rule tpu:spec_decode_acceptance:rate5m divides these.
SPEC_PROPOSED_TOKENS = "tpu:spec_decode_proposed_tokens_total"
SPEC_ACCEPTED_BY_PROPOSER = "tpu:spec_decode_accepted_tokens_total"
SPEC_PROPOSER_VALUES = ("ngram", "draft")

# -- request-lifecycle robustness (docs/26-robustness.md) --------------------
# admission control: requests refused with 429 + Retry-After because the
# waiting queue / queued-token watermark was full (load shedding)
REQUESTS_SHED = "tpu:requests_shed_total"
# deadline enforcement: requests rejected at admission ("would queue past
# deadline") or aborted mid-decode after their deadline expired
REQUESTS_DEADLINE_EXPIRED = "tpu:requests_deadline_expired_total"
# 1 while the engine is draining (admissions stopped, in-flight finishing)
ENGINE_DRAINING = "tpu:engine_draining"

# -- multi-tenant QoS (docs/27-multitenancy.md). All labeled tenant= with
# cardinality bounded by the tenant table size (qos.TenantAccounting caps
# engine-side ids minted from headers; overflow aggregates under
# tenant="_overflow"). The engine exports requests/tokens/shed and the
# queue-wait histogram; the router exports requests/prompt-tokens admitted
# through the QoS gate plus per-tenant throttles (429s that never reached
# an engine). Shared names: dashboards key off one series wherever the
# enforcement happened.
TENANT_REQUESTS = "tpu:tenant_requests_total"
TENANT_PROMPT_TOKENS = "tpu:tenant_prompt_tokens_total"
TENANT_GENERATION_TOKENS = "tpu:tenant_generation_tokens_total"
# engine-side: admission refusals + queue evictions, lowest-priority-first
TENANT_SHED = "tpu:tenant_shed_total"
# router-side: per-tenant token-bucket / concurrency refusals (429 +
# per-tenant Retry-After, distinct from the engine's global-shed path)
TENANT_THROTTLED = "tpu:tenant_throttled_total"
# engine-side histogram: seconds from submission to first scheduler seat
TENANT_QUEUE_WAIT = "tpu:tenant_queue_wait_seconds"

TENANT_ENGINE_COUNTERS = (
    TENANT_REQUESTS,
    TENANT_GENERATION_TOKENS,
    TENANT_SHED,
)
TENANT_ROUTER_COUNTERS = (
    TENANT_REQUESTS,
    TENANT_PROMPT_TOKENS,
    TENANT_THROTTLED,
)

# -- per-request latency histograms (docs/28-request-tracing.md) ------------
# Observed at request finish from the tracing spine's phase attribution,
# with trace-id exemplars (visible under the OpenMetrics exposition —
# GET /metrics?format=openmetrics; deliberately a query param, not Accept
# negotiation, because OpenMetrics rewrites the `tpu:` prefix to `tpu_`
# and honoring Prometheus's default Accept would rename the whole scrape
# contract). The ENGINE exports
# all five (its clock sees the whole lifecycle: admission → first seat →
# first token → finish); the ROUTER exports TTFT and E2E from its own
# vantage (client-visible latency, including routing + proxy overhead).
REQUEST_TTFT = "tpu:request_ttft_seconds"
REQUEST_E2E = "tpu:request_e2e_seconds"
REQUEST_QUEUE_WAIT = "tpu:request_queue_wait_seconds"
REQUEST_PREFILL = "tpu:request_prefill_seconds"
REQUEST_DECODE = "tpu:request_decode_seconds"

REQUEST_PHASE_HISTOGRAMS = (
    REQUEST_TTFT,
    REQUEST_E2E,
    REQUEST_QUEUE_WAIT,
    REQUEST_PREFILL,
    REQUEST_DECODE,
)
# shared boundaries wherever a phase histogram lives (router and engine
# export the same names; dashboards aggregate across both)
REQUEST_PHASE_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)
# inter-token latency (TPOT), ROUTER vantage only: the gap between
# consecutive streamed chunks as the client sees them — the client-visible
# SLO the engine-side decode histogram cannot capture (proxy + network
# included). NOT in REQUEST_PHASE_HISTOGRAMS: that tuple is the per-engine
# scrape set; this one lives on the router like its TTFT/E2E.
REQUEST_ITL = "tpu:request_itl_seconds"

# -- saturation & goodput (docs/29-saturation-slo.md) -----------------------
# Per-step utilization accounting from the engine step loop
# (engine/saturation.StepMeter) — the "why isn't the chip full" signals the
# SLO rule pack (observability/rules/) and the KEDA/prom-adapter autoscaling
# path key off. Gauges are resolve-cadence EWMAs (~10 s time constant).
ENGINE_DECODE_SEAT_OCCUPANCY = "tpu:engine_decode_seat_occupancy"
ENGINE_PADDING_WASTE_FRAC = "tpu:engine_padding_waste_frac"
# analytic-model achieved FLOP/s and the MFU estimate (achieved / chip peak;
# 0 when the peak is unknown — CPU backend or unrecognized device kind)
ENGINE_ACHIEVED_FLOPS = "tpu:engine_achieved_flops_per_s"
ENGINE_MFU = "tpu:engine_mfu"
# per-tier KV occupancy, labeled tier="hbm"|"host"|"disk"|"remote" (remote
# is the store-reported fill fraction piggybacked on PUT acks; 0 until the
# first ack lands)
ENGINE_KV_TIER_USAGE = "tpu:engine_kv_tier_usage_perc"
# token split + padding accounting, labeled phase="prefill"|"decode":
# step tokens are USEFUL tokens processed (prefill chunk tokens / decode
# host-accepted tokens); padded tokens are the device-computed token slots
# including bucket padding — padding-waste rate = 1 - step/padded by rule
ENGINE_STEP_TOKENS = "tpu:engine_step_tokens_total"
ENGINE_PADDED_TOKENS = "tpu:engine_padded_tokens_total"
# cumulative analytic forward-pass FLOPs (rate() ÷ chip peak = MFU by rule)
ENGINE_MODEL_FLOPS = "tpu:engine_model_flops_total"
# per-resolved-step distributions (histograms): decode-seat occupancy
# fraction, and the resolve-cadence wall per step labeled phase=
ENGINE_STEP_OCCUPANCY = "tpu:engine_step_occupancy"
ENGINE_STEP_WALL = "tpu:engine_step_wall_seconds"
# goodput ledger (engine/saturation.GoodputLedger): every device-sampled
# token classified exactly once — delivered + wasted == sampled at
# quiescence. reason= is the CLOSED WASTE_REASON_VALUES set below, the
# single definition saturation.WASTE_REASONS aliases (semantics of each
# reason are documented there).
GOODPUT_TOKENS = "tpu:goodput_tokens_total"
WASTED_TOKENS = "tpu:wasted_tokens_total"
WASTE_REASON_VALUES = (
    "rollback",
    "preempted_recompute",
    "deadline_expired",
    "severed",
    "shed_evicted",
    "overshoot",
)
# router-side: streams severed after headers (engine died mid-stream; the
# truncated transfer is the client's only honest signal). Request-level —
# the router can't see token boundaries; the engine-side ledger carries the
# token cost of torn streams under wasted{reason="severed"}.
ROUTER_SEVERED_STREAMS = "tpu:router_severed_streams_total"

SATURATION_GAUGES = (
    ENGINE_DECODE_SEAT_OCCUPANCY,
    ENGINE_PADDING_WASTE_FRAC,
    ENGINE_ACHIEVED_FLOPS,
    ENGINE_MFU,
    ENGINE_KV_TIER_USAGE,
)
SATURATION_COUNTERS = (
    ENGINE_STEP_TOKENS,
    ENGINE_PADDED_TOKENS,
    ENGINE_MODEL_FLOPS,
    GOODPUT_TOKENS,
    WASTED_TOKENS,
)
SATURATION_HISTOGRAMS = (
    ENGINE_STEP_OCCUPANCY,
    ENGINE_STEP_WALL,
)

# -- KV-hierarchy flow telemetry (docs/30-kv-flow-telemetry.md) -------------
# Per-tier transfer meters (engine/kv_flow.KVFlowMeter): every tier move —
# host-ring offload/reload, disk store/load, remote put/fetch, device-path
# PD transfer, peer-engine fetch — records bytes, blocks and wall latency.
# Labels are CLOSED sets (cardinality bounded by construction, series
# seeded at zero): tier= names the NON-HBM side of the hop, direction= is
# relative to HBM ("in" = toward the device pool / hydration, "out" =
# away / offload). "peer" is another engine's HBM/host tiers reached over
# /kv/peer_fetch (docs/35-peer-kv-reuse.md): "in" = blocks pulled FROM a
# peer, "out" = blocks this engine served TO a peer.
KV_TRANSFER_TIERS = ("host", "disk", "remote", "device", "peer")
KV_TRANSFER_DIRECTIONS = ("in", "out")
KV_TRANSFER_BYTES = "tpu:kv_transfer_bytes_total"
KV_TRANSFER_BLOCKS = "tpu:kv_transfer_blocks_total"
# at-rest KV quantization (docs/38-kv-quantization.md): KV_TRANSFER_BYTES
# counts WIRE bytes (what actually crossed the hop — int4+scales / fp8
# payloads under --kv-at-rest-codec); this pair exposes the logical side.
# logical_bytes = the decoded fp16/bf16 bytes those transfers represent;
# the gauge is their time-decayed ratio logical/wire per (tier, direction)
# (1.0 with no codec — the exporter seeds every combination).
KV_TRANSFER_LOGICAL_BYTES = "tpu:kv_transfer_logical_bytes_total"
KV_TIER_COMPRESSION_RATIO = "tpu:kv_tier_compression_ratio"
# histogram: wall seconds per transfer batch, labeled tier=/direction=
KV_TRANSFER_SECONDS = "tpu:kv_transfer_seconds"
# gauge: time-decayed recent-mean transfer bandwidth per (tier, direction)
# (engine/kv_flow.TierBandwidth) — the measured fetch-GB/s half of the
# compute-or-load hydration signal (LLMEngine.hydration_signal, ROADMAP 3)
KV_TIER_BANDWIDTH = "tpu:kv_tier_bandwidth_bytes_per_s"
# per-request hydration attribution: every admitted request's prompt
# tokens classified EXACTLY once by where their KV came from —
# hbm_hit + host_reload + disk_load + remote_fetch + peer_fetch +
# recomputed == prompt_tokens (same audited-partition discipline as the
# goodput ledger)
# ("peer_fetch" = blocks pulled from another engine's tiers over the
# peer-fetch path, docs/35-peer-kv-reuse.md)
KV_HYDRATION_SOURCES = (
    "hbm_hit", "host_reload", "disk_load", "remote_fetch", "peer_fetch",
    "recomputed",
)
REQUEST_PREFIX_TOKENS = "tpu:request_prefix_tokens_total"
# disk-tier block counters (the host ring has HOST_KV_*, the remote store
# REMOTE_KV_* — the disk rung was dark before this pair existed)
DISK_KV_STORES = "tpu:disk_kv_stored_blocks_total"
DISK_KV_LOADS = "tpu:disk_kv_loaded_blocks_total"
# compute-or-load hydration planner (docs/31-hydration-planner.md):
# per-CHUNK decisions over lower-tier-resident prefix runs. "load" =
# async tier fetch pipelined with prefill of the recomputed head;
# "recompute" = the chunk's measured fetch cost lost to prefill FLOP/s
# (or its tier sits below the TierBandwidth sample floor in forced
# mode); "fallback_recompute" = a load chunk that missed its deadline or
# whose fetch failed, flipped back to compute at the prefill boundary —
# an ADDITIONAL event on top of that chunk's plan-time "load" count, so
# share-of-plan rules must use {choice=~"load|recompute"} as their
# denominator (tpu:kv_hydration_load_share:rate5m does).
KV_HYDRATION_DECISIONS = "tpu:kv_hydration_decision_total"
KV_HYDRATION_CHOICES = ("load", "recompute", "fallback_recompute")

# -- peer-engine KV tier (docs/35-peer-kv-reuse.md) -------------------------
# gauge: analytic KV bytes per token of this engine's pool (block_bytes /
# block_size — a per-config constant). The router's priced route-vs-migrate
# scoring multiplies it by the matched prefix length and divides by the
# fleet-reported peer fetch bandwidth (tpu:kv_tier_bandwidth_bytes_per_s
# {tier="peer",direction="in"}) to price a migration in seconds without
# knowing the model.
KV_BYTES_PER_TOKEN = "tpu:kv_bytes_per_token"
# router counter labeled decision=: how the KV-aware policy resolved each
# owner-found request under --kv-migrate-scoring priced. "owner" = follow
# the prefix owner (affinity); "migrate" = route to the least-loaded engine
# and stamp the owner hint upstream so the target's hydration planner pulls
# the prefix over the peer tier instead of recomputing it.
ROUTER_KV_MIGRATE_DECISIONS = "tpu:router_kv_migrate_decisions_total"
KV_MIGRATE_DECISION_VALUES = ("owner", "migrate")

# Closed label sets per metric, the single source of truth the exporters
# seed from and tools/check_metrics_contract.py validates BOTH ways: the
# exporter registries must render exactly these values, and any literal
# label matcher in the dashboard / rule pack must name one of them (a
# typo'd tier="dsk" used to pass the checker silently). Open-but-bounded
# labels (tenant=, model_name=) are deliberately absent.
METRIC_LABEL_VALUES: dict[str, dict[str, tuple[str, ...]]] = {
    KV_TRANSFER_BYTES: {
        "tier": KV_TRANSFER_TIERS, "direction": KV_TRANSFER_DIRECTIONS,
    },
    KV_TRANSFER_BLOCKS: {
        "tier": KV_TRANSFER_TIERS, "direction": KV_TRANSFER_DIRECTIONS,
    },
    KV_TRANSFER_LOGICAL_BYTES: {
        "tier": KV_TRANSFER_TIERS, "direction": KV_TRANSFER_DIRECTIONS,
    },
    KV_TIER_COMPRESSION_RATIO: {
        "tier": KV_TRANSFER_TIERS, "direction": KV_TRANSFER_DIRECTIONS,
    },
    KV_TRANSFER_SECONDS: {
        "tier": KV_TRANSFER_TIERS, "direction": KV_TRANSFER_DIRECTIONS,
    },
    KV_TIER_BANDWIDTH: {
        "tier": KV_TRANSFER_TIERS, "direction": KV_TRANSFER_DIRECTIONS,
    },
    REQUEST_PREFIX_TOKENS: {"source": KV_HYDRATION_SOURCES},
    KV_HYDRATION_DECISIONS: {"choice": KV_HYDRATION_CHOICES},
    ROUTER_KV_MIGRATE_DECISIONS: {"decision": KV_MIGRATE_DECISION_VALUES},
    ENGINE_KV_TIER_USAGE: {"tier": ("hbm", "host", "disk", "remote")},
    ENGINE_STEP_TOKENS: {"phase": ("prefill", "decode")},
    ENGINE_PADDED_TOKENS: {"phase": ("prefill", "decode")},
    ENGINE_STEP_WALL: {"phase": ("prefill", "decode")},
    WASTED_TOKENS: {"reason": WASTE_REASON_VALUES},
    SPEC_PROPOSED_TOKENS: {"proposer": SPEC_PROPOSER_VALUES},
    SPEC_ACCEPTED_BY_PROPOSER: {"proposer": SPEC_PROPOSER_VALUES},
}

KV_FLOW_COUNTERS = (
    KV_TRANSFER_BYTES,
    KV_TRANSFER_BLOCKS,
    KV_TRANSFER_LOGICAL_BYTES,
    REQUEST_PREFIX_TOKENS,
    DISK_KV_STORES,
    DISK_KV_LOADS,
    KV_HYDRATION_DECISIONS,
)

# -- cluster KV index (event-driven KV-aware routing) -----------------------
# Exported by the KV controller's /metrics and re-exported by the router in
# embedded-index mode (router/metrics.py). NOT part of the per-engine scrape
# contract below — these describe the cluster-level index, not one engine.
CLUSTER_KV_INDEX_HASHES = "tpu:cluster_kv_index_hashes"
CLUSTER_KV_INDEX_ENGINES = "tpu:cluster_kv_index_engines"
CLUSTER_KV_INDEX_STALE_ENGINES = "tpu:cluster_kv_index_stale_engines"
CLUSTER_KV_EVENTS = "tpu:cluster_kv_events_total"
CLUSTER_KV_RESYNCS = "tpu:cluster_kv_resyncs_total"
# counter labeled mode=. The controller observes "indexed"|"fanout"|"mixed"
# (mixed = index for fresh engines + fan-out for the rest in one lookup)
# plus "peer" (/peer_lookup rediscovery calls from engines' peer tiers,
# docs/35-peer-kv-reuse.md — not a routed request); the router observes
# "indexed"|"controller"|"mixed" (controller = pure controller hop, mixed
# = non-authoritative index attempt escalated to the controller hop).
# Each ROUTED request is counted under exactly one of the routed modes.
CLUSTER_KV_LOOKUPS = "tpu:cluster_kv_lookups_total"
# histogram labeled mode= (kv_index.LookupLatency renders it)
CLUSTER_KV_LOOKUP_LATENCY = "tpu:cluster_kv_lookup_latency_seconds"
# counter: proactive flash-crowd replications the controller ordered AND
# saw adopted (docs/39-device-peer-kv.md — the BanaServe push-replication
# half; 0 while --replicate-threshold is unset/0)
CLUSTER_KV_REPLICATIONS = "tpu:cluster_kv_replications_total"

# -- fleet-coherence telemetry (docs/32-fleet-telemetry.md) ------------------
# The measurement layer ROADMAP 1's multi-replica router refactor builds
# against: replica index convergence, session-stickiness audit, and
# cluster-wide tenant accounting. Subscriber-side names are exported by
# every index host (KV controller /metrics AND the router registry in
# embedded mode) under the same names, like the CLUSTER_KV_* set above.
#
# histogram: publish-timestamp → apply-timestamp lag of KV event batches
# as seen by ONE subscriber (wall clocks across processes — honest to NTP
# skew, which is far below the ≥10ms replica-convergence granularity)
CLUSTER_KV_CONVERGENCE_LAG = "tpu:cluster_kv_convergence_lag_seconds"
# gauge labeled engine=: the subscriber's applied event-sequence position
# per publishing engine (cardinality bounded by the engine count; compare
# across replicas to see who lags whom)
CLUSTER_KV_ENGINE_SEQ = "tpu:cluster_kv_engine_seq"
# gauge: estimated blocks by which a replica's embedded index diverges
# from the controller's authoritative one (|seq gap| same-epoch, full
# slice on epoch mismatch / missing engine — fleet.index_divergence_blocks).
# The controller exports it per replica (labeled replica=); each router
# re-exports its OWN value unlabeled from the /fleet/report reply.
CLUSTER_KV_INDEX_DIVERGENCE = "tpu:cluster_kv_index_divergence_blocks"
# engine-side counter labeled reason= (closed set, fleet.STICKINESS_REASONS):
# "owner_changed" = consecutive requests of one session stamped with
# different ring-chosen owners; "non_owner_delivery" = a session request
# delivered to an engine that is not its stamped owner (failover moved it).
# Zero with 1 replica and STABLE membership — the baseline ROADMAP 1 must
# preserve. Endpoint churn legitimately remaps sessions at any N (the
# consistent-hash minimal-remap property bounds how many), so transient
# owner_changed blips during scaling are expected; a SUSTAINED rate on a
# stable fleet is the multi-replica affinity break.
SESSION_STICKINESS_VIOLATIONS = "tpu:session_stickiness_violations_total"
# gauges labeled tenant= (cardinality bounded by the tenant table):
# fleet-wide admitted request rate over the configured per-tenant budget
# (1.0 = the fleet admits exactly the global limit), and how far past the
# limit N per-replica buckets over-admit (≈ N-1 when every replica grants
# the full budget). Computed by the controller's FleetView from periodic
# router reports; each router re-exports the reply so the fleet view is
# scrapeable at every replica.
FLEET_TENANT_UTILIZATION = "tpu:fleet_tenant_limit_utilization"
FLEET_TENANT_OVERADMISSION = "tpu:fleet_tenant_overadmission_ratio"
# info-style gauge labeled hash= (value 1): the router's session-ring
# membership hash. Replicas whose hashes differ route the same session to
# different engines — `count(count by (hash)(...)) > 1` is the
# TpuRouterRingDivergence alert.
ROUTER_RING_MEMBERSHIP_HASH = "tpu:router_ring_membership_hash"
# router gauges the 10k-connection bench (ROADMAP 1) reads: in-flight
# proxied streams and the endpoint count discovery currently publishes
ROUTER_ACTIVE_STREAMS = "tpu:router_active_streams"
ROUTER_DISCOVERY_ENDPOINTS = "tpu:router_discovery_endpoints"
# engine-side KV event publisher health (engine/kv_events.py): batches
# POSTed (incl. heartbeats/snapshots), failed publish rounds, and the
# events buffered awaiting flush — the PUBLISHER vantage on a failing
# event path (before this pair, a dying publisher was only visible as
# controller-side resync storms, the wrong place to alert on)
KV_EVENT_PUBLISH_BATCHES = "tpu:kv_event_publish_batches_total"
KV_EVENT_PUBLISH_FAILURES = "tpu:kv_event_publish_failures_total"
KV_EVENT_QUEUE_DEPTH = "tpu:kv_event_pending_queue_depth"
# gauge: subscribers the engine's KV event publisher fans batches out to
# (KV_CONTROLLER_URL is a comma-separated list — the controller, embedded-
# index router replicas, or both; each keeps its own cursor/resync state).
# 0 = no publisher configured (docs/34-fleet-routing.md).
KV_EVENT_SUBSCRIBERS = "tpu:kv_event_subscribers"
# router gauge: the share of each tenant's GLOBAL budget this replica's
# local token buckets enforce (fleet budget scaling, 1/M for M live
# replicas per the controller's /fleet/report reply). 1.0 = full local
# budget — either a single replica, scaling off, or the controller-outage
# degradation (fail open toward availability, never stricter).
ROUTER_TENANT_BUDGET_SCALE = "tpu:router_tenant_budget_scale"

# closed reason set — the SINGLE definition (fleet.STICKINESS_REASONS
# aliases it, so the audit and the exporter can never drift). Registered
# into METRIC_LABEL_VALUES below — the dict literal predates this section.
STICKINESS_REASON_VALUES = ("owner_changed", "non_owner_delivery")
METRIC_LABEL_VALUES[SESSION_STICKINESS_VIOLATIONS] = {
    "reason": STICKINESS_REASON_VALUES,
}

# -- flight recorder & thread-liveness watchdog (docs/37-flight-recorder.md)
# A wedged engine produces no requests and therefore no request-vantage
# telemetry; these names turn SILENCE into signal. Every long-lived loop in
# the engine process beats a heartbeat into engine/flightrec.ThreadRegistry;
# the gauge below is each loop's seconds-since-last-beat at scrape time
# (0 for loops not running in this deployment — e.g. no hydration fetcher
# without a disk/remote tier). thread= is a CLOSED set:
#   step               the AsyncEngine step-loop thread (drives the device)
#   hydration_fetch    the compute-or-load planner's chunk fetcher thread
#   kv_event_publisher the cluster-KV-index event publisher task
#   kv_writer          the remote KV store's async PUT writer thread
#   bg_compile         background XLA compile jobs (busy only mid-compile —
#                      a beat older than its threshold while busy is the
#                      "XLA compiles forever" wedge)
#   watchdog           the watchdog thread itself (its age is computed by
#                      the exporter, not the watchdog, so a dead watchdog
#                      is visible here rather than self-reported)
#   rebalancer         the KV controller's pool-rebalancer tick loop
#                      (docs/40-pool-rebalancing.md) — hand-rendered on
#                      the CONTROLLER's /metrics; the engine exporter
#                      seeds it 0 like every loop not running locally
THREAD_HEARTBEAT_AGE = "tpu:thread_heartbeat_age_seconds"
THREAD_NAME_VALUES = (
    "step", "hydration_fetch", "kv_event_publisher", "kv_writer",
    "bg_compile", "watchdog", "rebalancer",
)
# watchdog trips, by kind (closed set):
#   stale_heartbeat  a registered loop stopped beating while busy
#   unresolved_step  a device step was dispatched and never resolved (the
#                    collective-stall / wedged-tunnel shape: the host is
#                    alive, the device work never comes back)
# Counted once per stall EPISODE (a 10-minute wedge is one trip, not 600).
ENGINE_STEP_STALLS = "tpu:engine_step_stalls_total"
STALL_KIND_VALUES = ("stale_heartbeat", "unresolved_step")
METRIC_LABEL_VALUES[THREAD_HEARTBEAT_AGE] = {"thread": THREAD_NAME_VALUES}
METRIC_LABEL_VALUES[ENGINE_STEP_STALLS] = {"kind": STALL_KIND_VALUES}
# router/controller asyncio event-loop lag (engine/flightrec.
# EventLoopLagProbe): decaying peak of how far a short sleep overshot its
# deadline — a router whose loop is starved (blocking call, CPU overload)
# serves nothing while every request-vantage metric just goes quiet.
# Exported by the router registry AND hand-rendered by the KV controller,
# like the CLUSTER_KV_* names.
ROUTER_EVENT_LOOP_LAG = "tpu:router_event_loop_lag_seconds"

# -- prefill/decode pool rebalancing (docs/40-pool-rebalancing.md) -----------
# The role-flip actuator that closes the TpuSeatStarvation loop: the KV
# controller watches per-pool queue-wait p95 vs decode-seat occupancy and
# flips the least-loaded engine of the rich pool into the starved one.
#
# engine-side gauge labeled role= (closed set): 1 on the engine's CURRENT
# pool role, 0 on the other. Both series render 0 on engines that are not
# part of a disaggregated deployment — the absence of a 1 is itself the
# "this engine has no pool role" signal, and keeps the closed set seeded.
# The router's stats scraper reads this to follow live-advertised roles
# instead of frozen helm labels.
POOL_ROLE = "tpu:pool_role"
POOL_ROLE_VALUES = ("prefill", "decode")
# controller-side counter labeled outcome= (closed set): one increment per
# finished rebalance EPISODE.  completed = flip verified and kept;
# rolled_back = the verify window judged the imbalance worse and the flip
# was undone once; abandoned = the target engine went unreachable mid-
# episode (its restart restores the static role, so abandoning is safe).
# Hand-rendered live by the controller's /metrics; the router registry
# zero-seeds the same name so the contract check has one exporter home
# (the CLUSTER_KV_REPLICATIONS convention).
POOL_REBALANCE_FLIPS = "tpu:pool_rebalance_flips_total"
POOL_REBALANCE_OUTCOME_VALUES = ("completed", "rolled_back", "abandoned")
# controller-side gauge labeled phase= (closed set): 1 on the state
# machine's current phase, 0 elsewhere. "observe" = idle/watching,
# "cooldown" = post-episode hold-off; drain/flip/rejoin/verify are the
# transitional phases of an active episode — a transitional phase pinned
# at 1 for many minutes is the TpuRebalanceStuck alert.
POOL_REBALANCE_PHASE = "tpu:pool_rebalance_phase"
POOL_REBALANCE_PHASE_VALUES = (
    "observe", "cooldown", "drain", "flip", "rejoin", "verify",
)
METRIC_LABEL_VALUES[POOL_ROLE] = {"role": POOL_ROLE_VALUES}
METRIC_LABEL_VALUES[POOL_REBALANCE_FLIPS] = {
    "outcome": POOL_REBALANCE_OUTCOME_VALUES,
}
METRIC_LABEL_VALUES[POOL_REBALANCE_PHASE] = {
    "phase": POOL_REBALANCE_PHASE_VALUES,
}

# -- structured output (docs/41-structured-output.md) ------------------------
# Grammar-constrained decoding: requests carrying response_format /
# guided_json / a forced tool_choice decode under a token-class automaton
# whose mask is applied on device inside the jitted sampling path.
#
# engine-side counter labeled outcome= (closed set): one increment per
# FINISHED structured request. valid = the terminal automaton state was
# accepting (the body parses under the declared schema); invalid = it was
# not (length cap, client stop sequence, or a compile-rejected schema
# counted at the API layer); fallback = the schema compiled but the engine
# runs with structured_output=fallback, so constraints were declined and
# the request decoded free-form.
STRUCTURED_REQUESTS = "tpu:structured_requests_total"
STRUCTURED_OUTCOME_VALUES = ("valid", "invalid", "fallback")
# histogram: wall seconds to compile one grammar (schema -> byte-DFA ->
# token-class tables). Cache hits do not observe; a hot p99 here means the
# schema corpus is churning faster than the grammar cache can hold it.
GRAMMAR_BUILD_TIME = "tpu:grammar_build_time_seconds"
METRIC_LABEL_VALUES[STRUCTURED_REQUESTS] = {
    "outcome": STRUCTURED_OUTCOME_VALUES,
}

# -- XLA compile telemetry (docs/42-compile-telemetry.md) --------------------
# The TPU stack's third failure axis (after requests and pods): a shape
# that escapes the pad-up bucket ladder stalls every stream for a
# synchronous XLA compile. CompileWatch (engine/compile_watch.py) records
# every program build; these series make compile hygiene a standing
# production guarantee instead of a bench-time assertion.
#
# counter labeled (phase, trigger), both closed sets: one increment per
# program (or grammar-table) build. trigger=warmup is planned (boot
# waves / precompile_dominating), bg is the background AOT thread
# absorbing a pad-up fallback, mid_traffic is a synchronous compile ON
# the dispatch path after warmup — the stall the ladder exists to prevent.
ENGINE_COMPILES = "tpu:engine_compiles_total"
COMPILE_PHASE_VALUES = ("prefill", "decode", "verify", "grammar")
COMPILE_TRIGGER_VALUES = ("warmup", "bg", "mid_traffic")
METRIC_LABEL_VALUES[ENGINE_COMPILES] = {
    "phase": COMPILE_PHASE_VALUES,
    "trigger": COMPILE_TRIGGER_VALUES,
}
# histogram: wall seconds per program build (all triggers; the rules
# group records its p95). XLA compiles run 30-60s on real models — the
# boundaries stretch far past the request-phase buckets.
ENGINE_COMPILE_SECONDS = "tpu:engine_compile_seconds"
COMPILE_SECONDS_BUCKETS = (
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 30.0,
    60.0, 120.0, 300.0,
)
# gauge: programs in the CompileWatch inventory (compiled and retained);
# counters: dispatches whose EXACT program key was already compiled (hit)
# vs pad-up fallbacks and sync compiles (miss)
ENGINE_PROGRAM_CACHE_PROGRAMS = "tpu:engine_program_cache_programs"
ENGINE_PROGRAM_CACHE_HITS = "tpu:engine_program_cache_hits_total"
ENGINE_PROGRAM_CACHE_MISSES = "tpu:engine_program_cache_misses_total"
# recompile-storm episodes (--compile-storm-threshold/-window): counted
# once per EPISODE like ENGINE_STEP_STALLS — the report names the shapes
ENGINE_COMPILE_STORMS = "tpu:engine_compile_storms_total"

CLUSTER_KV_GAUGES = (
    CLUSTER_KV_INDEX_HASHES,
    CLUSTER_KV_INDEX_ENGINES,
    CLUSTER_KV_INDEX_STALE_ENGINES,
)
CLUSTER_KV_COUNTERS = (
    CLUSTER_KV_EVENTS,
    CLUSTER_KV_RESYNCS,
    CLUSTER_KV_LOOKUPS,
    CLUSTER_KV_REPLICATIONS,
)

# -- router-side robustness (NOT part of the per-engine scrape contract:
# these describe the router's view of its upstreams). Exported by
# router/metrics.py; the breaker state/open counts follow the same
# value-owned-by-component gauge convention as CLUSTER_KV_EVENTS.
ROUTER_BREAKER_STATE = "tpu:router_breaker_state"  # 0 closed / 1 half / 2 open
ROUTER_BREAKER_OPENS = "tpu:router_breaker_opens_total"
ROUTER_UPSTREAM_FAILURES = "tpu:router_upstream_failures_total"

ROUTER_BREAKER_GAUGES = (
    ROUTER_BREAKER_STATE,
    ROUTER_BREAKER_OPENS,
    ROUTER_UPSTREAM_FAILURES,
)

ALL_GAUGES = (
    NUM_REQUESTS_RUNNING,
    NUM_REQUESTS_WAITING,
    HBM_KV_USAGE_PERC,
    PREFIX_CACHE_HIT_RATE,
    HOST_KV_USAGE_PERC,
    STEP_OVERLAP_FRAC,
    ENGINE_DRAINING,
    # saturation (docs/29-saturation-slo.md)
    ENGINE_DECODE_SEAT_OCCUPANCY,
    ENGINE_PADDING_WASTE_FRAC,
    ENGINE_ACHIEVED_FLOPS,
    ENGINE_MFU,
    ENGINE_KV_TIER_USAGE,
    # KV flow telemetry (docs/30-kv-flow-telemetry.md)
    KV_TIER_BANDWIDTH,
    # at-rest codec effectiveness (docs/38-kv-quantization.md):
    # logical/wire per (tier, direction), 1.0 with no codec
    KV_TIER_COMPRESSION_RATIO,
    # peer-engine KV tier (docs/35-peer-kv-reuse.md): the migrate-pricing
    # constant the router reads off each engine's scrape
    KV_BYTES_PER_TOKEN,
    # fleet-coherence telemetry (docs/32-fleet-telemetry.md): engine-side
    # KV event publisher backlog + fan-out subscriber count
    KV_EVENT_QUEUE_DEPTH,
    KV_EVENT_SUBSCRIBERS,
    # thread-liveness watchdog (docs/37-flight-recorder.md): per-loop
    # heartbeat age (thread= closed set) — the signal a wedged engine
    # still emits when it serves nothing
    THREAD_HEARTBEAT_AGE,
    # pool rebalancing (docs/40-pool-rebalancing.md): the engine's live
    # pool role (role= closed set, 1 on the current role)
    POOL_ROLE,
    # compile telemetry (docs/42-compile-telemetry.md): programs in the
    # CompileWatch inventory
    ENGINE_PROGRAM_CACHE_PROGRAMS,
)
ALL_COUNTERS = (
    PREFIX_CACHE_HITS,
    PREFIX_CACHE_QUERIES,
    NUM_PREEMPTIONS,
    PROMPT_TOKENS,
    GENERATION_TOKENS,
    HOST_KV_OFFLOADS,
    HOST_KV_RELOADS,
    REMOTE_KV_STORES,
    REMOTE_KV_FETCHES,
    SPEC_DRAFT_TOKENS,
    SPEC_ACCEPTED_TOKENS,
    # per-proposer split (proposer= closed set, docs/36)
    SPEC_PROPOSED_TOKENS,
    SPEC_ACCEPTED_BY_PROPOSER,
    REQUESTS_SHED,
    REQUESTS_DEADLINE_EXPIRED,
    # tenant-labeled (cardinality bounded by the tenant table); rendered
    # by the engine exporter even before any stamped traffic arrives
    TENANT_REQUESTS,
    TENANT_GENERATION_TOKENS,
    TENANT_SHED,
    # saturation & goodput (docs/29-saturation-slo.md); phase=/reason=
    # labels are closed sets, so cardinality is bounded by construction
    ENGINE_STEP_TOKENS,
    ENGINE_PADDED_TOKENS,
    ENGINE_MODEL_FLOPS,
    GOODPUT_TOKENS,
    WASTED_TOKENS,
    # KV flow telemetry (docs/30-kv-flow-telemetry.md); tier=/direction=/
    # source= labels are closed sets (METRIC_LABEL_VALUES)
    KV_TRANSFER_BYTES,
    KV_TRANSFER_BLOCKS,
    KV_TRANSFER_LOGICAL_BYTES,
    REQUEST_PREFIX_TOKENS,
    DISK_KV_STORES,
    DISK_KV_LOADS,
    KV_HYDRATION_DECISIONS,
    # fleet-coherence telemetry (docs/32-fleet-telemetry.md): stickiness
    # audit (reason= is the closed STICKINESS_REASON_VALUES set) and the
    # KV event publisher's own health counters
    SESSION_STICKINESS_VIOLATIONS,
    KV_EVENT_PUBLISH_BATCHES,
    KV_EVENT_PUBLISH_FAILURES,
    # thread-liveness watchdog (docs/37-flight-recorder.md): stall
    # episodes by kind (closed STALL_KIND_VALUES set)
    ENGINE_STEP_STALLS,
    # structured output (docs/41-structured-output.md): finished
    # constrained requests by outcome (closed STRUCTURED_OUTCOME_VALUES)
    STRUCTURED_REQUESTS,
    # compile telemetry (docs/42-compile-telemetry.md): program builds by
    # (phase, trigger), program-cache hit/miss dispatches, and storm
    # episodes (counted once per episode, like ENGINE_STEP_STALLS)
    ENGINE_COMPILES,
    ENGINE_PROGRAM_CACHE_HITS,
    ENGINE_PROGRAM_CACHE_MISSES,
    ENGINE_COMPILE_STORMS,
)
