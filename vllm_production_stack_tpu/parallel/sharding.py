"""PartitionSpecs for model params, KV cache, and activations.

Megatron-style tensor parallelism expressed as named shardings — the TPU
equivalent of the reference's `--tensor-parallel-size` (SURVEY §2.4):

  - attention q/k/v projections: column-parallel (shard the head axis)
  - attention output projection: row-parallel (XLA inserts the psum)
  - MLP gate/up: column-parallel; down: row-parallel
  - embedding + lm_head: vocab-sharded (logits psum/all-gathered by XLA)
  - KV cache pages: sharded over kv-heads on the tp axis, so each chip only
    ever touches its own heads' pages (no cross-chip KV traffic in decode)

Param trees are "stacked": every per-layer leaf carries a leading num_layers
dimension and the model scans over it, so specs below include that axis first.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..engine.config import ModelConfig
from .mesh import DP_AXIS, EP_AXIS, PP_AXIS, TP_AXIS


def llama_param_specs(cfg: ModelConfig) -> dict:
    """PartitionSpec tree structurally matching init_params(cfg)'s tree
    (optional leaves — attention biases, untied lm_head — included only when
    the config produces them).

    Pipeline parallelism is GSPMD stage sharding: every per-layer leaf's
    leading L axis is sharded over the pp mesh axis, so each stage holds a
    contiguous block of layers and XLA moves the activations between stages
    (the TPU replacement for the reference's Ray-deployed
    --pipeline-parallel-size, ray-cluster.yaml:556-566). On a pp=1 mesh the
    axis is size 1 and the specs degrade to pure tp."""
    attn = {
        # [L, hidden, heads*head_dim] — L over pp stages, head axis over tp
        "wq": P(PP_AXIS, None, TP_AXIS),
        "wk": P(PP_AXIS, None, TP_AXIS),
        "wv": P(PP_AXIS, None, TP_AXIS),
        # [L, heads*head_dim, hidden] — shard input (head) axis; psum after
        "wo": P(PP_AXIS, TP_AXIS, None),
    }
    if cfg.attention_bias:
        attn |= {
            "bq": P(PP_AXIS, TP_AXIS),
            "bk": P(PP_AXIS, TP_AXIS),
            "bv": P(PP_AXIS, TP_AXIS),
        }
    if cfg.qk_norm or cfg.qk_norm_flat:
        # replicated in both scopes: per-head (qwen3, [L, head_dim]) norm
        # weights are head-invariant, and the flat scope (olmo2,
        # [L, heads*head_dim]) needs the WHOLE axis for its mean-square —
        # a tp shard cannot compute it locally, and the vectors are tiny
        attn |= {
            "q_norm": P(PP_AXIS, None),
            "k_norm": P(PP_AXIS, None),
        }
    if cfg.num_experts:
        # Mixtral MoE: expert axis over ep (each device holds E/ep experts —
        # the reason ep exists: 8x7B expert weights don't fit one chip),
        # inner axis over tp; GSPMD psums the combine over ep and tp
        mlp = {
            "router": P(PP_AXIS, None, None),  # [L, hidden, E] tiny
            "gate": P(PP_AXIS, EP_AXIS, None, TP_AXIS),  # [L, E, h, inter]
            "up": P(PP_AXIS, EP_AXIS, None, TP_AXIS),
            "down": P(PP_AXIS, EP_AXIS, TP_AXIS, None),  # [L, E, inter, h]
        }
        mlp_key = "moe"
    else:
        mlp = {
            "gate": P(PP_AXIS, None, TP_AXIS),  # [L, hidden, inter]
            "up": P(PP_AXIS, None, TP_AXIS),
            "down": P(PP_AXIS, TP_AXIS, None),  # [L, inter, hidden]
        }
        mlp_key = "mlp"
    specs = {
        "embed": P(TP_AXIS, None),  # [vocab, hidden] vocab-sharded
        "layers": {
            "attn": attn,
            mlp_key: mlp,
            **(
                {}
                if cfg.post_norms_only
                else {"input_norm": P(PP_AXIS, None),
                      "post_attn_norm": P(PP_AXIS, None)}
            ),
            **(
                {"attn_out_norm": P(PP_AXIS, None),
                 "ffw_out_norm": P(PP_AXIS, None)}
                if cfg.sandwich_norms or cfg.post_norms_only
                else {}
            ),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, TP_AXIS)  # [hidden, vocab] vocab-sharded
    return specs


def lora_param_specs(cfg: ModelConfig, lora_cfg) -> dict:
    """Spec tree matching init_lora_params. Column-parallel modules shard
    B's out axis (like their base weight); row-parallel modules shard A's in
    axis; the rank axis stays replicated (it's ≤ max_lora_rank)."""
    from ..models.llama import lora_module_dims

    row_parallel = {"o_proj", "down_proj"}
    # same module filter as init_lora_params, so the spec tree and the param
    # tree can never diverge structurally; L (axis 1) shards over pp stages
    # alongside the base weights
    names = [m for m in lora_cfg.target_modules if m in lora_module_dims(cfg)]
    specs: dict = {"scale": P()}
    for name in names:
        if name in row_parallel:
            specs[name] = {
                "A": P(None, PP_AXIS, TP_AXIS, None),  # (n, L, in, r)
                "B": P(None, PP_AXIS, None, None),  # (n, L, r, out)
            }
        else:
            specs[name] = {
                "A": P(None, PP_AXIS, None, None),
                "B": P(None, PP_AXIS, None, TP_AXIS),
            }
    return specs


def kv_cache_spec() -> P:
    """Per-layer leaf [2, num_blocks, block_size, kv_heads, head_dim] — kv
    heads shard over tp; the block axis shards over pp so each stage holds
    1/pp of the pool (per-layer leaves can't be placed per-stage with one
    prefix spec, but block-sharding splits the memory the same way).
    Applies to every leaf of the per-layer KV tuple (jit/`device_put` treat
    a single spec as a pytree prefix)."""
    return P(None, PP_AXIS, None, TP_AXIS, None)


def decode_tokens_spec() -> P:
    """[B] token ids — shard batch over dp."""
    return P(DP_AXIS)


def prefill_tokens_spec() -> P:
    """[T] a single sequence's chunk — replicated (prefill batches one seq)."""
    return P()
