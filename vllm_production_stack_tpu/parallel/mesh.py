"""Device mesh construction.

TPU-first replacement for the reference's process-group plumbing: instead of
NCCL groups (vLLM) / Ray placement groups (ray-cluster.yaml), parallelism is a
`jax.sharding.Mesh` with named axes; XLA inserts the collectives (psum /
all-gather / reduce-scatter) over ICI within a slice and DCN across slices.

Axis convention (used by every PartitionSpec in this package):
  - "dp": data parallel (request batch replicas)
  - "tp": tensor parallel (megatron-style weight sharding; rides ICI)
  - "pp": pipeline stages (multi-slice / DCN)  [stage meshes, later rounds]
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
TP_AXIS = "tp"
PP_AXIS = "pp"


def make_mesh(
    tensor_parallel_size: int = 1,
    data_parallel_size: int = 1,
    pipeline_parallel_size: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a (dp, pp, tp) mesh over the available devices.

    TP is the innermost axis so that its collectives map onto
    nearest-neighbour ICI links (the same reason the reference pins TP within
    a node via /dev/shm + NVLink, deployment-vllm-multi.yaml:424-431); pp
    sits between dp and tp so each stage is a contiguous tp group — on
    multi-host deployments stage boundaries are the host/DCN boundaries
    (the RayCluster replacement, ray-cluster.yaml:556-566).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    want = tensor_parallel_size * data_parallel_size * pipeline_parallel_size
    if want > len(devices):
        raise ValueError(
            f"mesh needs {want} devices (tp={tensor_parallel_size} x "
            f"dp={data_parallel_size} x pp={pipeline_parallel_size}) "
            f"but only {len(devices)} available"
        )
    grid = np.array(devices[:want]).reshape(
        data_parallel_size, pipeline_parallel_size, tensor_parallel_size
    )
    return Mesh(grid, (DP_AXIS, PP_AXIS, TP_AXIS))


def single_device_mesh() -> Mesh:
    return make_mesh(1, 1)


def shard(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
