"""Device mesh construction.

TPU-first replacement for the reference's process-group plumbing: instead of
NCCL groups (vLLM) / Ray placement groups (ray-cluster.yaml), parallelism is a
`jax.sharding.Mesh` with named axes; XLA inserts the collectives (psum /
all-gather / reduce-scatter) over ICI within a slice and DCN across slices.

Axis convention (used by every PartitionSpec in this package):
  - "dp": data parallel (request batch replicas)
  - "tp": tensor parallel (megatron-style weight sharding; rides ICI)
  - "sp": sequence/context parallel (ring attention over sequence shards
          for long-context prefill; rides ICI next to tp)
  - "pp": pipeline stages (multi-slice / DCN)  [stage meshes, later rounds]
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
TP_AXIS = "tp"
PP_AXIS = "pp"
SP_AXIS = "sp"
EP_AXIS = "ep"


def make_mesh(
    tensor_parallel_size: int = 1,
    data_parallel_size: int = 1,
    pipeline_parallel_size: int = 1,
    devices: list | None = None,
    sequence_parallel_size: int = 1,
    expert_parallel_size: int = 1,
) -> Mesh:
    """Build a (dp, pp, ep, sp, tp) mesh over the available devices.

    TP is the innermost axis so that its collectives map onto
    nearest-neighbour ICI links (the same reason the reference pins TP within
    a node via /dev/shm + NVLink, deployment-vllm-multi.yaml:424-431); sp sits
    directly outside tp so the ring-attention ppermute hops are also
    single-ICI-hop neighbours; pp is outermost-but-one so each stage is a
    contiguous sp×tp group — on multi-host deployments stage boundaries are
    the host/DCN boundaries (the RayCluster replacement,
    ray-cluster.yaml:556-566).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    want = (
        tensor_parallel_size
        * data_parallel_size
        * pipeline_parallel_size
        * sequence_parallel_size
        * expert_parallel_size
    )
    if want > len(devices):
        raise ValueError(
            f"mesh needs {want} devices (tp={tensor_parallel_size} x "
            f"dp={data_parallel_size} x pp={pipeline_parallel_size} x "
            f"sp={sequence_parallel_size} x ep={expert_parallel_size}) "
            f"but only {len(devices)} available"
        )
    # sp stays adjacent to tp (innermost-but-one) so ring-attention ppermute
    # hops are single-ICI-hop neighbours; the latency-tolerant ep psum sits
    # outside both
    grid = np.array(devices[:want]).reshape(
        data_parallel_size,
        pipeline_parallel_size,
        expert_parallel_size,
        sequence_parallel_size,
        tensor_parallel_size,
    )
    return Mesh(grid, (DP_AXIS, PP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS))


def single_device_mesh() -> Mesh:
    return make_mesh(1, 1)


def shard(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
