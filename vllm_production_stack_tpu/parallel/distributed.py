"""Multi-host process bootstrap: the executable half of the helm env contract.

The multi-host StatefulSet template (helm/templates/statefulset-multihost.yaml)
exports `JAX_COORDINATOR_ADDRESS` / `JAX_NUM_PROCESSES` / `JAX_PROCESS_ID`
into every pod of a slice — one engine process per TPU host, pod 0 doubling
as the coordinator. This module consumes that contract: `maybe_initialize()`
turns it into a live `jax.distributed` service so `jax.devices()` spans every
host's chips and one GSPMD mesh (parallel/mesh.py) can cover a v5e-16's four
hosts. Reference equivalent: the RayCluster head gating on EXPECTED_NODES
before launching vLLM with pipeline parallelism
(/root/reference/helm/templates/ray-cluster.yaml:44-46,556-566) — here the
coordination service is JAX's own, not Ray.

Also provides the multi-PROCESS dryrun used by `__graft_entry__.
dryrun_multichip`: N real OS processes, each owning one virtual CPU device,
form one mesh through this exact code path and run a collective + a sharded
model forward — validating the statefulset contract end-to-end without TPU
hardware (`python -m vllm_production_stack_tpu.parallel.distributed --worker`
is the per-process entry).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

from ..utils.logging import init_logger

logger = init_logger(__name__)

ENV_COORDINATOR = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"


def distributed_env() -> tuple[str, int, int] | None:
    """(coordinator_address, num_processes, process_id) from the helm env
    contract, or None when the pod is not part of a multi-host slice."""
    addr = os.environ.get(ENV_COORDINATOR)
    if not addr:
        return None
    try:
        n = int(os.environ.get(ENV_NUM_PROCESSES, "1"))
        pid = int(os.environ.get(ENV_PROCESS_ID, "0"))
    except ValueError as e:
        raise ValueError(
            f"malformed {ENV_NUM_PROCESSES}/{ENV_PROCESS_ID}: {e}"
        ) from e
    if not 0 <= pid < max(n, 1):
        raise ValueError(
            f"{ENV_PROCESS_ID}={pid} out of range for "
            f"{ENV_NUM_PROCESSES}={n}"
        )
    return addr, n, pid


def maybe_initialize(mode: str = "auto") -> bool:
    """Call `jax.distributed.initialize` from the env contract.

    mode: "auto" initializes iff the contract names >1 process; "on"
    requires the contract (raises if absent); "off" never initializes.
    Must run before the first JAX backend touch. Returns True when the
    distributed service was started."""
    if mode == "off":
        return False
    env = distributed_env()
    if env is None or env[1] <= 1:
        if mode == "on":
            raise RuntimeError(
                f"--distributed on, but {ENV_COORDINATOR} is unset (or "
                f"{ENV_NUM_PROCESSES} <= 1); the multi-host statefulset "
                "exports these — see helm/templates/statefulset-multihost.yaml"
            )
        return False
    addr, n, pid = env
    import jax

    # the CPU PJRT client ships without cross-process collectives
    # ("Multiprocess computations aren't implemented on the CPU
    # backend"); gloo turns them on. Every cross-process transfer
    # program in this repo (PD ship, device-path peer pulls) and the
    # multiprocess dryruns need this on the cpu backend; it's a no-op
    # for TPU (the option only shapes the CPU client). Must land before
    # the first backend touch, which is why it lives here.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — jax without the knob: keep going
        pass

    logger.info(
        "initializing jax.distributed: coordinator=%s processes=%d "
        "process_id=%d", addr, n, pid,
    )
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=n, process_id=pid
    )
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


# -- multi-process dryrun ----------------------------------------------------


def _worker() -> None:
    """One process of the multi-process dryrun (spawned with the helm env
    contract set): initialize, form a dp mesh spanning every process, run a
    cross-process collective and a dp-sharded model forward."""
    import numpy as np

    ok = maybe_initialize("on")
    assert ok
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = jax.process_count()
    pid = jax.process_index()
    assert len(jax.devices()) == n * jax.local_device_count()

    from ..engine.config import ModelConfig
    from ..models import llama
    from . import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(data_parallel_size=jax.device_count())

    # 1) collective across PROCESS boundaries: global sum of per-process
    # contributions through the mesh
    local = np.full((jax.local_device_count(), 1), pid + 1, np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(mesh_lib.DP_AXIS, None)), local
    )
    total = jax.jit(jnp.sum)(garr)
    # every process holds the replicated global result
    want = sum(p + 1 for p in range(n)) * (jax.device_count() // n)
    assert float(total) == want, (float(total), want)

    # 2) dp-sharded model forward: identical params on every process (same
    # PRNGKey), batch rows sharded one per device across processes
    cfg = ModelConfig(
        model="dryrun-mp-llama", vocab_size=128, hidden_size=32,
        intermediate_size=64, num_layers=2, num_heads=2, num_kv_heads=2,
        head_dim=16, max_model_len=32, dtype="float32",
    )
    params = jax.jit(llama.init_params, static_argnums=0)(
        cfg, jax.random.PRNGKey(0)
    )
    t = 8
    rows_per_proc = jax.device_count() // n
    rng = np.random.RandomState(100 + pid)
    ids_local = rng.randint(1, cfg.vocab_size, size=(rows_per_proc, t))
    ids = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(mesh_lib.DP_AXIS, None)),
        ids_local.astype(np.int32),
    )
    lens = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(mesh_lib.DP_AXIS)),
        np.full((rows_per_proc,), t, np.int32),
    )

    @jax.jit
    def fwd(p, ids, lens):
        vecs = llama.embed_encode(cfg, p, ids, lens)
        return llama.compute_logits(cfg, p, vecs)

    logits = fwd(params, ids, lens)
    jax.block_until_ready(logits)
    for shard in logits.addressable_shards:
        assert np.all(np.isfinite(np.asarray(shard.data)))
    print(f"MP_DRYRUN_OK process={pid}/{n}", flush=True)


def _pd_worker() -> None:
    """One process of the cross-process PD dryrun: process 0 is a
    prefill-role engine, process 1 a decode-role engine — DIFFERENT
    jax.distributed processes, each with its own single-device mesh (the
    multi-host PD deployment shape, one engine pod per host). The prefill
    side computes the prompt's KV; `ship_kv_device_crossproc` moves the
    pages device-to-device (a cooperative shard-flip program — the DCN
    hop); the decode side adopts them and its continuation must be
    IDENTICAL to a from-scratch engine's (bit-identical pages ⇒ identical
    greedy tokens; a fresh same-seed engine recomputing the prompt is the
    oracle)."""
    import numpy as np

    ok = maybe_initialize("on")
    assert ok
    import jax
    from jax.experimental import multihost_utils

    n = jax.process_count()
    pid = jax.process_index()
    assert n == 2, f"PD dryrun is a 2-process shape, got {n}"
    tp = int(os.environ.get("PD_DRYRUN_TP", "1"))

    from ..engine.config import (
        CacheConfig, EngineConfig, ModelConfig, ParallelConfig,
        SchedulerConfig,
    )
    from ..engine.engine import LLMEngine
    from ..engine.kv_device_transfer import ship_kv_device_crossproc
    from ..engine.request import SamplingParams
    from . import mesh as mesh_lib

    local_mesh = mesh_lib.make_mesh(
        tensor_parallel_size=tp, devices=jax.local_devices()[:tp]
    )
    config = EngineConfig(
        model=ModelConfig(
            model="dryrun-pd-llama", vocab_size=128, hidden_size=32,
            intermediate_size=64, num_layers=2, num_heads=2, num_kv_heads=2,
            head_dim=16, max_model_len=64, dtype="float32",
        ),
        cache=CacheConfig(block_size=8, num_blocks=32),
        scheduler=SchedulerConfig(
            max_num_seqs=2, max_num_batched_tokens=32,
            prefill_buckets=(32,), decode_buckets=(2,), decode_window=4,
        ),
        parallel=ParallelConfig(tensor_parallel_size=tp),
    )
    engine = LLMEngine(config, mesh=local_mesh)
    rng = np.random.RandomState(7)
    prompt = [int(x) for x in rng.randint(1, 128, size=24)]
    sampling = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)

    if pid == 0:
        # PD prefill convention (router request_service): max_tokens=1
        engine.generate([prompt], SamplingParams(
            max_tokens=1, temperature=0.0, ignore_eos=True,
        ))
    multihost_utils.sync_global_devices("pd-prefill-done")

    adopted = ship_kv_device_crossproc(
        engine, role="prefill" if pid == 0 else "decode", token_ids=prompt,
    )
    if pid == 1:
        assert adopted > 0, "decode side adopted nothing"
        s0 = engine.stats()
        out = engine.generate([prompt], sampling)[0]["token_ids"]
        s1 = engine.stats()
        assert s1.prefix_cache_hits > s0.prefix_cache_hits, (
            "continuation did not hit the adopted blocks"
        )
        # oracle: a fresh same-seed engine that computes the prompt's KV
        # itself — identical continuation proves the shipped pages carry
        # the exact bytes
        oracle = LLMEngine(config, mesh=local_mesh)
        want = oracle.generate([prompt], sampling)[0]["token_ids"]
        assert out == want, (out, want)
    # re-ship the SAME prompt: everything is already resident on the
    # decode side, so adoption must be 0 AND must not leak the pins
    # stage_adoption takes on resident chain members (the empty-ship
    # abort path — a leak makes blocks unevictable over repeat ships)
    refs_before = dict(engine.scheduler.pool._ref)
    adopted2 = ship_kv_device_crossproc(
        engine, role="prefill" if pid == 0 else "decode", token_ids=prompt,
    )
    if pid == 1:
        assert adopted2 == 0, adopted2
        assert engine.scheduler.pool._ref == refs_before, (
            "re-ship leaked block pins",
            refs_before, engine.scheduler.pool._ref,
        )
        print(
            f"PD_DRYRUN_OK adopted={adopted} continuation={out[:4]}...",
            flush=True,
        )
    else:
        print("PD_DRYRUN_OK role=prefill", flush=True)
    multihost_utils.sync_global_devices("pd-done")


def _device_peer_worker() -> None:
    """One process of the device-path PEER KV dryrun (docs/39): process 0
    is an OWNER engine serving the real HTTP app (EngineServer — its
    AsyncEngine step loop shares the quiescence lock the device serve
    takes); process 1 is a PULLER whose hydration planner labels the
    prompt's continuation tier "device" through the owner-hint contains
    probe, and whose fetcher thread pulls the pages over the cooperative
    shard-flip collective instead of HTTP. The puller asserts the bytes
    moved on (device, in) — NOT (peer, in) — that the admitted prompt's
    tokens attribute to peer_fetch, and that the continuation is
    token-identical to a from-scratch oracle engine (bit-identical pages
    ⇒ identical greedy tokens)."""
    import json
    import threading
    import time

    import numpy as np

    ok = maybe_initialize("on")
    assert ok
    import jax

    n = jax.process_count()
    pid = jax.process_index()
    assert n == 2, f"device-peer dryrun is a 2-process shape, got {n}"
    assert os.environ.get("KV_MESH_GROUP"), (
        "spawner must export KV_MESH_GROUP — it is the transport identity"
    )

    from ..engine.config import (
        CacheConfig, EngineConfig, ModelConfig, ParallelConfig,
        SchedulerConfig,
    )
    from ..engine.engine import LLMEngine
    from ..engine.request import SamplingParams
    from . import mesh as mesh_lib

    local_mesh = mesh_lib.make_mesh(
        tensor_parallel_size=1, devices=jax.local_devices()[:1]
    )
    config = EngineConfig(
        model=ModelConfig(
            model="dryrun-devpeer-llama", vocab_size=128, hidden_size=32,
            intermediate_size=64, num_layers=2, num_heads=2, num_kv_heads=2,
            head_dim=16, max_model_len=64, dtype="float32",
        ),
        cache=CacheConfig(
            block_size=8, num_blocks=32, num_host_blocks=16,
        ),
        scheduler=SchedulerConfig(
            max_num_seqs=2, max_num_batched_tokens=32,
            prefill_buckets=(32,), decode_buckets=(2,), decode_window=4,
        ),
        parallel=ParallelConfig(tensor_parallel_size=1),
        kv_peer_fetch=True,
        kv_peer_transport="device",
        # generous plan deadline: the first pull cold-compiles the
        # shard-flip program, which would blow the 0.5s auto floor and
        # flip the chunk to fallback_recompute before the bytes land
        kv_hydration_timeout_s=120.0,
    )
    engine = LLMEngine(config, mesh=local_mesh)
    assert engine.peer_tier is not None
    assert engine.peer_tier.transport_identity is not None, (
        "no mesh identity — KV_MESH_GROUP + jax.distributed should have "
        "produced one"
    )
    rng = np.random.RandomState(7)
    prompt = [int(x) for x in rng.randint(1, 128, size=24)]
    sampling = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)

    if pid == 0:
        # ---- owner: compute the prompt's KV, then serve the real app.
        # Prefill BEFORE the server starts: once AsyncEngine's step loop
        # owns the engine, a second sync generate loop would race it for
        # this request's outputs.
        engine.generate([prompt], SamplingParams(
            max_tokens=1, temperature=0.0, ignore_eos=True,
        ))
        import asyncio
        import http.client

        from aiohttp import web

        from ..engine.server import EngineServer

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        srv = EngineServer(engine, watchdog=False)
        done = threading.Event()
        loop = asyncio.new_event_loop()

        def _serve() -> None:
            asyncio.set_event_loop(loop)

            async def _done(request):
                done.set()
                return web.json_response({"ok": True})

            app = srv.build_app()
            app.router.add_post("/dryrun_done", _done)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            loop.run_until_complete(
                web.TCPSite(runner, "127.0.0.1", port).start()
            )
            loop.run_forever()

        # tpulint: allow(thread-lifecycle) — dryrun-subprocess serve loop;
        # the worker OS process exits (and reclaims the daemon thread)
        # right after the puller signals /dryrun_done
        threading.Thread(
            target=_serve, daemon=True, name="devpeer-serve"
        ).start()
        deadline = time.monotonic() + 60.0
        while True:  # publish the port only once the app answers
            try:
                c = http.client.HTTPConnection("127.0.0.1", port, timeout=1)
                c.request("GET", "/health")
                c.getresponse().read()
                c.close()
                break
            except OSError:
                assert time.monotonic() < deadline, "engine app never came up"
                time.sleep(0.05)
        # publish the port through the coordination service's KV store —
        # NOT an XLA collective (the CPU backend refuses whole-mesh
        # multiprocess computations; the pairwise shard-flip transfer
        # program is the only collective this dryrun should run)
        from jax._src.distributed import global_state

        global_state.client.key_value_set("devpeer_dryrun/port", str(port))
        assert done.wait(timeout=240.0), "puller never signalled completion"
        served = engine.flow.bytes[("device", "out")]
        assert served > 0, "owner served no device-path bytes"
        time.sleep(0.2)  # let the /dryrun_done reply flush before exit
        print(f"DEVPEER_DRYRUN_OK role=owner served_bytes={served}",
              flush=True)
    else:
        from jax._src.distributed import global_state

        port = int(global_state.client.blocking_key_value_get(
            "devpeer_dryrun/port", 120_000
        ))
        owner_url = f"http://127.0.0.1:{port}"
        # warm the compute estimator: plan_decisions cannot engage on a
        # cold engine (no achieved FLOP/s and no chip peak on CPU), and a
        # declined plan recomputes everything — the device lane would
        # never fire. A throwaway generate (disjoint tokens, no prefix
        # collision with the real prompt) gives the StepMeter its
        # sample, exactly like the peer tests' _warm helper. It must run
        # several dispatches: the meter's wall clock starts at the FIRST
        # record call (which reads wall=0 and cannot update the EWMA), so
        # only the decode steps after the prefill feed achieved-FLOP/s.
        engine.generate([[9] * 8], SamplingParams(
            max_tokens=4, temperature=0.0, ignore_eos=True,
        ))
        # seed the device estimator past the TierBandwidth sample floor:
        # an unmeasured device tier prices recompute (never declines, but
        # never loads either) — in production the Hydrator's bootstrap
        # pulls cross the floor; here we pin a fast estimate so the plan
        # deterministically decides "load" and the pull happens
        now = time.perf_counter()
        est = engine.flow.bandwidth[("device", "in")]
        est.record(1 << 20, 1e-3, now)
        est.record(1 << 20, 1e-3, now + 1e-3)
        base_peer = engine.flow.bytes[("peer", "in")]
        t0 = time.perf_counter()
        out = engine.generate(
            [prompt], sampling, kv_owner_hint=owner_url
        )[0]["token_ids"]
        latency = time.perf_counter() - t0
        dev_bytes = engine.flow.bytes[("device", "in")]
        assert dev_bytes > 0, "no bytes moved on the device path"
        assert engine.flow.transfers[("device", "in")] >= 1
        assert engine.flow.bytes[("peer", "in")] == base_peer, (
            "puller fell back to HTTP peer fetch"
        )
        assert engine.flow.hydration["peer_fetch"] > 0, (
            "admitted prompt attributed no tokens to peer_fetch"
        )
        # oracle: a fresh same-seed engine computing every token itself —
        # identical continuation proves the pulled pages carry the exact
        # bytes the owner's prefill produced
        oracle = LLMEngine(config, mesh=local_mesh)
        want = oracle.generate([prompt], sampling)[0]["token_ids"]
        assert out == want, (out, want)
        import http.client

        try:
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            c.request("POST", "/dryrun_done", body=json.dumps({}))
            c.getresponse().read()
            c.close()
        except OSError:
            pass  # owner may already be tearing down
        print(
            f"DEVPEER_DRYRUN_OK pulled_bytes={dev_bytes} "
            f"latency_s={latency:.3f} continuation={out[:4]}...",
            flush=True,
        )


def _spawn_workers(
    n_processes: int, flag: str, timeout_s: float, ok_marker: str,
    devices_per_proc: int = 1, extra_env: dict | None = None,
):
    """Spawn n real OS processes that form ONE jax.distributed runtime via
    the helm env contract (each process = one TPU host stand-in with 1 CPU
    device). Raises on any failure; returns the per-process outputs."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    # workers must import this package regardless of the caller's cwd
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    procs = []
    for pid in range(n_processes):
        env = dict(os.environ)
        # a TPU-plugin sitecustomize (keyed on PALLAS_AXON_POOL_IPS in the
        # dev image) must NOT register in the CPU workers: with the remote
        # device service unreachable, plugin registration hangs the worker
        # before jax.distributed ever initializes
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update({
            ENV_COORDINATOR: f"127.0.0.1:{port}",
            ENV_NUM_PROCESSES: str(n_processes),
            ENV_PROCESS_ID: str(pid),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count="
                         f"{devices_per_proc}",
            "PYTHONPATH": pkg_root + os.pathsep + env.get("PYTHONPATH", ""),
            **(extra_env or {}),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-m",
             "vllm_production_stack_tpu.parallel.distributed", flag],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        ))
    outputs = []
    failed = []
    for pid, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            failed.append((pid, "timeout", out))
            continue
        outputs.append(out)
        if proc.returncode != 0 or ok_marker not in out:
            failed.append((pid, f"rc={proc.returncode}", out))
    if failed:
        detail = "\n".join(
            f"--- process {pid} ({why}):\n{out[-2000:]}"
            for pid, why, out in failed
        )
        raise RuntimeError(
            f"multi-process dryrun failed in {len(failed)}/{n_processes} "
            f"processes:\n{detail}"
        )
    return outputs


def run_multiprocess_dryrun(n_processes: int = 2, timeout_s: float = 300.0):
    """N processes form one mesh and run a collective + dp-sharded forward
    (the multi-host statefulset contract, executable)."""
    return _spawn_workers(n_processes, "--worker", timeout_s, "MP_DRYRUN_OK")


def run_multiprocess_pd_dryrun(timeout_s: float = 300.0, tp: int = 1):
    """2 processes: prefill engine + decode engine in DIFFERENT
    jax.distributed processes, device-path KV ship across them,
    bit-identical continuation asserted (VERDICT r4 #5). tp>1 gives each
    role a tp-sharded mesh (tp devices per process) and ships each kvh
    chunk over its own pairwise flip."""
    return _spawn_workers(
        2, "--pd-worker", timeout_s, "PD_DRYRUN_OK",
        devices_per_proc=tp, extra_env={"PD_DRYRUN_TP": str(tp)},
    )


def run_multiprocess_device_peer_dryrun(timeout_s: float = 300.0):
    """2 processes: an owner engine serving the real HTTP app and a puller
    whose hydration fetch lane pulls the prompt's KV over the device
    collective path (docs/39-device-peer-kv.md) — transport negotiated
    through the owner-hint contains probe, bytes metered under
    (device, in), continuation bit-identical to a from-scratch oracle."""
    return _spawn_workers(
        2, "--device-peer-worker", timeout_s, "DEVPEER_DRYRUN_OK",
        extra_env={"KV_MESH_GROUP": "devpeer-dryrun"},
    )


def main() -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--worker", action="store_true",
                   help="run as one process of the multi-process dryrun")
    p.add_argument("--pd-worker", action="store_true",
                   help="run as one process of the cross-process PD dryrun")
    p.add_argument("--device-peer-worker", action="store_true",
                   help="run as one process of the device-path peer KV "
                        "dryrun")
    p.add_argument("--processes", type=int, default=2)
    args = p.parse_args()
    if args.worker:
        _worker()
    elif args.pd_worker:
        _pd_worker()
    elif args.device_peer_worker:
        _device_peer_worker()
    else:
        run_multiprocess_dryrun(args.processes)
        run_multiprocess_pd_dryrun()
        run_multiprocess_device_peer_dryrun()
        print(f"multi-process dryrun OK ({args.processes} processes + PD "
              "+ device-peer)")


if __name__ == "__main__":
    main()
