"""Multi-host process bootstrap: the executable half of the helm env contract.

The multi-host StatefulSet template (helm/templates/statefulset-multihost.yaml)
exports `JAX_COORDINATOR_ADDRESS` / `JAX_NUM_PROCESSES` / `JAX_PROCESS_ID`
into every pod of a slice — one engine process per TPU host, pod 0 doubling
as the coordinator. This module consumes that contract: `maybe_initialize()`
turns it into a live `jax.distributed` service so `jax.devices()` spans every
host's chips and one GSPMD mesh (parallel/mesh.py) can cover a v5e-16's four
hosts. Reference equivalent: the RayCluster head gating on EXPECTED_NODES
before launching vLLM with pipeline parallelism
(/root/reference/helm/templates/ray-cluster.yaml:44-46,556-566) — here the
coordination service is JAX's own, not Ray.

Also provides the multi-PROCESS dryrun used by `__graft_entry__.
dryrun_multichip`: N real OS processes, each owning one virtual CPU device,
form one mesh through this exact code path and run a collective + a sharded
model forward — validating the statefulset contract end-to-end without TPU
hardware (`python -m vllm_production_stack_tpu.parallel.distributed --worker`
is the per-process entry).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

from ..utils.logging import init_logger

logger = init_logger(__name__)

ENV_COORDINATOR = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"


def distributed_env() -> tuple[str, int, int] | None:
    """(coordinator_address, num_processes, process_id) from the helm env
    contract, or None when the pod is not part of a multi-host slice."""
    addr = os.environ.get(ENV_COORDINATOR)
    if not addr:
        return None
    try:
        n = int(os.environ.get(ENV_NUM_PROCESSES, "1"))
        pid = int(os.environ.get(ENV_PROCESS_ID, "0"))
    except ValueError as e:
        raise ValueError(
            f"malformed {ENV_NUM_PROCESSES}/{ENV_PROCESS_ID}: {e}"
        ) from e
    if not 0 <= pid < max(n, 1):
        raise ValueError(
            f"{ENV_PROCESS_ID}={pid} out of range for "
            f"{ENV_NUM_PROCESSES}={n}"
        )
    return addr, n, pid


def maybe_initialize(mode: str = "auto") -> bool:
    """Call `jax.distributed.initialize` from the env contract.

    mode: "auto" initializes iff the contract names >1 process; "on"
    requires the contract (raises if absent); "off" never initializes.
    Must run before the first JAX backend touch. Returns True when the
    distributed service was started."""
    if mode == "off":
        return False
    env = distributed_env()
    if env is None or env[1] <= 1:
        if mode == "on":
            raise RuntimeError(
                f"--distributed on, but {ENV_COORDINATOR} is unset (or "
                f"{ENV_NUM_PROCESSES} <= 1); the multi-host statefulset "
                "exports these — see helm/templates/statefulset-multihost.yaml"
            )
        return False
    addr, n, pid = env
    import jax

    logger.info(
        "initializing jax.distributed: coordinator=%s processes=%d "
        "process_id=%d", addr, n, pid,
    )
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=n, process_id=pid
    )
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


# -- multi-process dryrun ----------------------------------------------------


def _worker() -> None:
    """One process of the multi-process dryrun (spawned with the helm env
    contract set): initialize, form a dp mesh spanning every process, run a
    cross-process collective and a dp-sharded model forward."""
    import numpy as np

    ok = maybe_initialize("on")
    assert ok
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = jax.process_count()
    pid = jax.process_index()
    assert len(jax.devices()) == n * jax.local_device_count()

    from ..engine.config import ModelConfig
    from ..models import llama
    from . import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(data_parallel_size=jax.device_count())

    # 1) collective across PROCESS boundaries: global sum of per-process
    # contributions through the mesh
    local = np.full((jax.local_device_count(), 1), pid + 1, np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(mesh_lib.DP_AXIS, None)), local
    )
    total = jax.jit(jnp.sum)(garr)
    # every process holds the replicated global result
    want = sum(p + 1 for p in range(n)) * (jax.device_count() // n)
    assert float(total) == want, (float(total), want)

    # 2) dp-sharded model forward: identical params on every process (same
    # PRNGKey), batch rows sharded one per device across processes
    cfg = ModelConfig(
        model="dryrun-mp-llama", vocab_size=128, hidden_size=32,
        intermediate_size=64, num_layers=2, num_heads=2, num_kv_heads=2,
        head_dim=16, max_model_len=32, dtype="float32",
    )
    params = jax.jit(llama.init_params, static_argnums=0)(
        cfg, jax.random.PRNGKey(0)
    )
    t = 8
    rows_per_proc = jax.device_count() // n
    rng = np.random.RandomState(100 + pid)
    ids_local = rng.randint(1, cfg.vocab_size, size=(rows_per_proc, t))
    ids = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(mesh_lib.DP_AXIS, None)),
        ids_local.astype(np.int32),
    )
    lens = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(mesh_lib.DP_AXIS)),
        np.full((rows_per_proc,), t, np.int32),
    )

    @jax.jit
    def fwd(p, ids, lens):
        vecs = llama.embed_encode(cfg, p, ids, lens)
        return llama.compute_logits(cfg, p, vecs)

    logits = fwd(params, ids, lens)
    jax.block_until_ready(logits)
    for shard in logits.addressable_shards:
        assert np.all(np.isfinite(np.asarray(shard.data)))
    print(f"MP_DRYRUN_OK process={pid}/{n}", flush=True)


def _pd_worker() -> None:
    """One process of the cross-process PD dryrun: process 0 is a
    prefill-role engine, process 1 a decode-role engine — DIFFERENT
    jax.distributed processes, each with its own single-device mesh (the
    multi-host PD deployment shape, one engine pod per host). The prefill
    side computes the prompt's KV; `ship_kv_device_crossproc` moves the
    pages device-to-device (a cooperative shard-flip program — the DCN
    hop); the decode side adopts them and its continuation must be
    IDENTICAL to a from-scratch engine's (bit-identical pages ⇒ identical
    greedy tokens; a fresh same-seed engine recomputing the prompt is the
    oracle)."""
    import numpy as np

    ok = maybe_initialize("on")
    assert ok
    import jax
    from jax.experimental import multihost_utils

    n = jax.process_count()
    pid = jax.process_index()
    assert n == 2, f"PD dryrun is a 2-process shape, got {n}"
    tp = int(os.environ.get("PD_DRYRUN_TP", "1"))

    from ..engine.config import (
        CacheConfig, EngineConfig, ModelConfig, ParallelConfig,
        SchedulerConfig,
    )
    from ..engine.engine import LLMEngine
    from ..engine.kv_device_transfer import ship_kv_device_crossproc
    from ..engine.request import SamplingParams
    from . import mesh as mesh_lib

    local_mesh = mesh_lib.make_mesh(
        tensor_parallel_size=tp, devices=jax.local_devices()[:tp]
    )
    config = EngineConfig(
        model=ModelConfig(
            model="dryrun-pd-llama", vocab_size=128, hidden_size=32,
            intermediate_size=64, num_layers=2, num_heads=2, num_kv_heads=2,
            head_dim=16, max_model_len=64, dtype="float32",
        ),
        cache=CacheConfig(block_size=8, num_blocks=32),
        scheduler=SchedulerConfig(
            max_num_seqs=2, max_num_batched_tokens=32,
            prefill_buckets=(32,), decode_buckets=(2,), decode_window=4,
        ),
        parallel=ParallelConfig(tensor_parallel_size=tp),
    )
    engine = LLMEngine(config, mesh=local_mesh)
    rng = np.random.RandomState(7)
    prompt = [int(x) for x in rng.randint(1, 128, size=24)]
    sampling = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)

    if pid == 0:
        # PD prefill convention (router request_service): max_tokens=1
        engine.generate([prompt], SamplingParams(
            max_tokens=1, temperature=0.0, ignore_eos=True,
        ))
    multihost_utils.sync_global_devices("pd-prefill-done")

    adopted = ship_kv_device_crossproc(
        engine, role="prefill" if pid == 0 else "decode", token_ids=prompt,
    )
    if pid == 1:
        assert adopted > 0, "decode side adopted nothing"
        s0 = engine.stats()
        out = engine.generate([prompt], sampling)[0]["token_ids"]
        s1 = engine.stats()
        assert s1.prefix_cache_hits > s0.prefix_cache_hits, (
            "continuation did not hit the adopted blocks"
        )
        # oracle: a fresh same-seed engine that computes the prompt's KV
        # itself — identical continuation proves the shipped pages carry
        # the exact bytes
        oracle = LLMEngine(config, mesh=local_mesh)
        want = oracle.generate([prompt], sampling)[0]["token_ids"]
        assert out == want, (out, want)
    # re-ship the SAME prompt: everything is already resident on the
    # decode side, so adoption must be 0 AND must not leak the pins
    # stage_adoption takes on resident chain members (the empty-ship
    # abort path — a leak makes blocks unevictable over repeat ships)
    refs_before = dict(engine.scheduler.pool._ref)
    adopted2 = ship_kv_device_crossproc(
        engine, role="prefill" if pid == 0 else "decode", token_ids=prompt,
    )
    if pid == 1:
        assert adopted2 == 0, adopted2
        assert engine.scheduler.pool._ref == refs_before, (
            "re-ship leaked block pins",
            refs_before, engine.scheduler.pool._ref,
        )
        print(
            f"PD_DRYRUN_OK adopted={adopted} continuation={out[:4]}...",
            flush=True,
        )
    else:
        print("PD_DRYRUN_OK role=prefill", flush=True)
    multihost_utils.sync_global_devices("pd-done")


def _spawn_workers(
    n_processes: int, flag: str, timeout_s: float, ok_marker: str,
    devices_per_proc: int = 1, extra_env: dict | None = None,
):
    """Spawn n real OS processes that form ONE jax.distributed runtime via
    the helm env contract (each process = one TPU host stand-in with 1 CPU
    device). Raises on any failure; returns the per-process outputs."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    # workers must import this package regardless of the caller's cwd
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    procs = []
    for pid in range(n_processes):
        env = dict(os.environ)
        # a TPU-plugin sitecustomize (keyed on PALLAS_AXON_POOL_IPS in the
        # dev image) must NOT register in the CPU workers: with the remote
        # device service unreachable, plugin registration hangs the worker
        # before jax.distributed ever initializes
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update({
            ENV_COORDINATOR: f"127.0.0.1:{port}",
            ENV_NUM_PROCESSES: str(n_processes),
            ENV_PROCESS_ID: str(pid),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count="
                         f"{devices_per_proc}",
            "PYTHONPATH": pkg_root + os.pathsep + env.get("PYTHONPATH", ""),
            **(extra_env or {}),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-m",
             "vllm_production_stack_tpu.parallel.distributed", flag],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        ))
    outputs = []
    failed = []
    for pid, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            failed.append((pid, "timeout", out))
            continue
        outputs.append(out)
        if proc.returncode != 0 or ok_marker not in out:
            failed.append((pid, f"rc={proc.returncode}", out))
    if failed:
        detail = "\n".join(
            f"--- process {pid} ({why}):\n{out[-2000:]}"
            for pid, why, out in failed
        )
        raise RuntimeError(
            f"multi-process dryrun failed in {len(failed)}/{n_processes} "
            f"processes:\n{detail}"
        )
    return outputs


def run_multiprocess_dryrun(n_processes: int = 2, timeout_s: float = 300.0):
    """N processes form one mesh and run a collective + dp-sharded forward
    (the multi-host statefulset contract, executable)."""
    return _spawn_workers(n_processes, "--worker", timeout_s, "MP_DRYRUN_OK")


def run_multiprocess_pd_dryrun(timeout_s: float = 300.0, tp: int = 1):
    """2 processes: prefill engine + decode engine in DIFFERENT
    jax.distributed processes, device-path KV ship across them,
    bit-identical continuation asserted (VERDICT r4 #5). tp>1 gives each
    role a tp-sharded mesh (tp devices per process) and ships each kvh
    chunk over its own pairwise flip."""
    return _spawn_workers(
        2, "--pd-worker", timeout_s, "PD_DRYRUN_OK",
        devices_per_proc=tp, extra_env={"PD_DRYRUN_TP": str(tp)},
    )


def main() -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--worker", action="store_true",
                   help="run as one process of the multi-process dryrun")
    p.add_argument("--pd-worker", action="store_true",
                   help="run as one process of the cross-process PD dryrun")
    p.add_argument("--processes", type=int, default=2)
    args = p.parse_args()
    if args.worker:
        _worker()
    elif args.pd_worker:
        _pd_worker()
    else:
        run_multiprocess_dryrun(args.processes)
        run_multiprocess_pd_dryrun()
        print(f"multi-process dryrun OK ({args.processes} processes + PD)")


if __name__ == "__main__":
    main()
