"""Ring attention: sequence/context parallelism for long-context prefill.

The reference stack inherits long-context support from its engines (vLLM
context-parallel / chunked prefill); on TPU the idiomatic construction is a
ring over the "sp" mesh axis (SURVEY §2.4 parallelism map):

  - the sequence axis of Q, K and V is sharded over sp devices;
  - each device computes flash-style (online-softmax) attention of its LOCAL
    query shard against the K/V shard it currently holds, then rotates the
    K/V shard to its ring neighbour with `lax.ppermute`;
  - after sp-1 hops every query shard has seen every K/V shard, and the
    online softmax has combined the partials exactly as one softmax would.

Peak memory per device is O(T/sp) for K/V and one (Tq_local, Tkv_local)
score block — never the (T, T) score matrix — and the ppermute rides
nearest-neighbour ICI because sp is adjacent to tp in the mesh grid
(parallel/mesh.py). Composes with tp: heads shard over tp inside the same
shard_map, and the only collective over sp is the ppermute itself.

For CHUNKED prefill (continuing a partially-computed sequence) the ring also
seeds its online softmax with a pooled-history block: every query shard
attends the sequence's already-resident paged KV (positions < hist_len)
before the ring starts — so the engine's sp path supports the same
chunk-by-chunk prefill contract as the paged XLA path.

No counterpart file exists in the reference (it ships no model/engine code);
behaviourally this replaces the NCCL context-parallel path of its served
engines with XLA collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 promotes shard_map to the top level (check_vma kwarg)
    _shard_map = jax.shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
except AttributeError:  # 0.4/0.5: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = "check_rep"

from .mesh import DP_AXIS, SP_AXIS, TP_AXIS

NEG_INF = -1e30


def _ring_attention_local(
    q: jax.Array,  # (B, Tq, nh_local, D) this device's query shard
    k: jax.Array,  # (B, Tkv, kvh_local, D) the K shard currently held
    v: jax.Array,  # (B, Tkv, kvh_local, D)
    q_pos: jax.Array,  # (B, Tq) int32 GLOBAL positions of local queries
    kv_pos: jax.Array,  # (B, Tkv) int32 global positions of held K/V
    kv_valid: jax.Array,  # (B, Tkv) bool: held K/V is a real token
    hist_k: jax.Array | None,  # (B, S, kvh_local, D) pooled history, or None
    hist_v: jax.Array | None,
    hist_len: jax.Array | None,  # (B,) pool positions < hist_len are history
    *,
    axis_name: str,
    scale: float,
) -> jax.Array:
    """Per-device body (runs under shard_map). Causality is evaluated on
    GLOBAL positions carried alongside the K/V shard, so any contiguous or
    striped sequence layout is correct — the ring never needs to know which
    shard "came first"."""
    axis_size = jax.lax.psum(1, axis_name)
    b, tq, nh, d = q.shape
    kvh = k.shape[2]
    qpk = nh // kvh
    qg = q.reshape(b, tq, kvh, qpk, d).astype(jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def attend_block(k, v, mask, m_prev, l_prev, acc):
        # (B, kvH, qpk, Tq, Tkv) one shard-pair score block
        scores = (
            jnp.einsum("btkgd,bskd->bkgts", qg, k.astype(jnp.float32)) * scale
        )
        scores = jnp.where(mask[:, None, None], scores, NEG_INF)
        m_cur = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bkgts,bskd->bkgtd", p, v.astype(jnp.float32)
        )
        return m_new, l_new, acc

    state = (
        jnp.full((b, kvh, qpk, tq), NEG_INF, jnp.float32),
        jnp.zeros((b, kvh, qpk, tq), jnp.float32),
        jnp.zeros((b, kvh, qpk, tq, d), jnp.float32),
    )
    if hist_k is not None:
        # chunked prefill: every query attends ALL already-resident history
        # (history position j < hist_len, no causal test needed — history is
        # strictly before this chunk)
        s = hist_k.shape[1]
        hist_mask = jnp.broadcast_to(
            (jnp.arange(s, dtype=jnp.int32)[None, :] < hist_len[:, None])[
                :, None, :
            ],
            (b, tq, s),
        )  # (B, Tq, S) — attend_block's mask contract is always rank 3
        state = attend_block(hist_k, hist_v, hist_mask, *state)

    def chunk_mask(kv_pos, kv_valid):
        return kv_valid[:, None, :] & (
            kv_pos[:, None, :] <= q_pos[:, :, None]
        )  # (B, Tq, Tkv)

    # local block first, then rotate-then-attend (axis_size - 1) times: the
    # ring does exactly axis_size - 1 ppermute hops — the last shard is not
    # rotated onward just to be dropped
    state = attend_block(k, v, chunk_mask(kv_pos, kv_valid), *state)

    def body(carry, _):
        k, v, kv_pos, kv_valid, m, l, acc = carry
        # rotate the K/V shard (and its position metadata) around the ring
        k, v, kv_pos, kv_valid = (
            jax.lax.ppermute(x, axis_name, perm)
            for x in (k, v, kv_pos, kv_valid)
        )
        m, l, acc = attend_block(k, v, chunk_mask(kv_pos, kv_valid), m, l, acc)
        return (k, v, kv_pos, kv_valid, m, l, acc), None

    (_, _, _, _, _, l, acc), _ = jax.lax.scan(
        body, (k, v, kv_pos, kv_valid, *state), None, length=axis_size - 1
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, tq, nh, d).astype(q.dtype)


def ring_attention(
    mesh: Mesh,
    q: jax.Array,  # (B, T, num_heads, D), T sharded over sp
    k: jax.Array,  # (B, T, kvH, D)
    v: jax.Array,  # (B, T, kvH, D)
    q_pos: jax.Array,  # (B, T) int32 global positions
    kv_valid: jax.Array,  # (B, T) bool real-token mask
    *,
    scale: float,
    hist_k: jax.Array | None = None,  # (B, S, kvH, D) pooled history
    hist_v: jax.Array | None = None,
    hist_len: jax.Array | None = None,  # (B,) history length per row
) -> jax.Array:
    """Causal GQA attention with the sequence axis sharded over the mesh's
    sp axis (batch over dp, heads over tp). With hist_* given, queries also
    attend an already-computed paged-history block (replicated over sp —
    every query shard needs all history; O(S/tp) per device like the paged
    path). Numerically equivalent to ops.attention.masked_attention over the
    concatenated context, up to float associativity. On an sp=1 mesh it
    degrades to one local flash block."""
    qspec = P(DP_AXIS, SP_AXIS, TP_AXIS, None)
    pspec = P(DP_AXIS, SP_AXIS)
    hspec = P(DP_AXIS, None, TP_AXIS, None)
    fn = functools.partial(
        _ring_attention_local, axis_name=SP_AXIS, scale=scale
    )
    if hist_k is None:
        in_specs = (qspec, qspec, qspec, pspec, pspec, pspec, None, None, None)
        args = (q, k, v, q_pos, q_pos, kv_valid, None, None, None)
    else:
        in_specs = (
            qspec, qspec, qspec, pspec, pspec, pspec,
            hspec, hspec, P(DP_AXIS),
        )
        args = (q, k, v, q_pos, q_pos, kv_valid, hist_k, hist_v, hist_len)
    return _shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=qspec,
        **{_SHARD_MAP_CHECK_KW: False},
    )(*args)
