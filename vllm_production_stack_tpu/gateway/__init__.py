"""Gateway integration: the Envoy ext-proc Endpoint Picker (EPP)."""
