"""Endpoint Picker (EPP): an Envoy ext-proc gRPC service that picks the
serving engine for each request and hands it back to the gateway as the
`x-gateway-destination-endpoint` header.

This is the TPU stack's equivalent of the reference's Go gateway inference
extension (src/gateway_inference_extension/*.go): a Gateway-API
InferencePool's extensionRef points at this service; Envoy/kgateway streams
each request through `ExternalProcessor.Process`, the EPP parses the
OpenAI-format body, consults the SAME routing policies the router uses
(router/routing.py — session / prefix-aware / kv-aware / round-robin), and
mutates the request headers so the gateway forwards to the chosen engine.

The protocol subset lives in gateway/protos/ext_proc_min.proto — message and
field numbering are wire-compatible with envoy.service.ext_proc.v3, compiled
with the system protoc at import time into the same user-private cache the
native C++ components use (no grpc_tools in this image).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys

import grpc

from ..router.routing import RoutingContext, make_policy
from ..utils.jsonio import loads_off_loop
from ..utils.logging import init_logger
from ..utils.native import _build_dir

logger = init_logger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_PROTO = os.path.join(_REPO_ROOT, "gateway", "protos", "ext_proc_min.proto")

ENDPOINT_HEADER = "x-gateway-destination-endpoint"


def endpoint_address(url: str) -> str:
    """`host:port` socket address for the destination header.

    Gateway-API inference-extension data planes (Envoy original_dst /
    kgateway, as consumed by the reference's Go pickers via the upstream EPP
    framework) treat `x-gateway-destination-endpoint` as an ip:port address,
    not a URL — a scheme-prefixed value would not route. The URL form stays
    internal (policies, discovery); only the header gets the address."""
    from urllib.parse import urlsplit

    parts = urlsplit(url if "//" in url else f"//{url}")
    host = parts.hostname or ""
    if ":" in host:  # IPv6 literal: keep the bracket form Envoy expects
        host = f"[{host}]"
    port = parts.port or (443 if parts.scheme == "https" else 80)
    return f"{host}:{port}"


def _load_pb2():
    """protoc-compile the minimal ext-proc proto into the private cache and
    import the generated module (cache key = source content hash)."""
    import hashlib

    build_dir = _build_dir()
    if build_dir is None:
        raise RuntimeError("no private cache dir for generated protos")
    with open(_PROTO, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:12]
    out_dir = os.path.join(build_dir, f"extproc_pb2_{tag}")
    marker = os.path.join(out_dir, "ext_proc_min_pb2.py")
    if not os.path.exists(marker):
        # generate into a per-pid temp dir and os.replace into place, so a
        # concurrent first start can never import a half-written module
        # (same discipline as utils/native._compile)
        import shutil

        tmp_dir = f"{out_dir}.tmp{os.getpid()}"
        os.makedirs(tmp_dir, exist_ok=True)
        subprocess.run(
            [
                "protoc",
                f"-I{os.path.dirname(_PROTO)}",
                f"--python_out={tmp_dir}",
                os.path.basename(_PROTO),
            ],
            check=True,
            capture_output=True,
        )
        try:
            os.replace(tmp_dir, out_dir)
        except OSError:
            pass  # a concurrent start won the rename race
        if os.path.exists(marker):
            # the canonical dir is complete (ours or the race winner's)
            shutil.rmtree(tmp_dir, ignore_errors=True)
        else:
            # out_dir is stale debris from a killed run — NEVER delete it
            # (a concurrent starter may be importing from it); import from
            # our own private tmp_dir instead
            out_dir = tmp_dir
    if out_dir not in sys.path:
        sys.path.insert(0, out_dir)
    import ext_proc_min_pb2  # noqa: E402

    return ext_proc_min_pb2


pb2 = _load_pb2()

_SERVICE = "envoy.service.ext_proc.v3.ExternalProcessor"


class EppService:
    """One ext-proc stream per request: buffer request headers, pick the
    endpoint when the body (or end-of-stream headers) arrives, reply with a
    header mutation. Response-phase messages pass through untouched."""

    def __init__(self, policy, endpoints_fn):
        self.policy = policy
        self.endpoints_fn = endpoints_fn  # () -> list[Endpoint]

    async def _pick(self, headers: dict[str, str], body: dict) -> str | None:
        endpoints = [e for e in self.endpoints_fn() if e.healthy and not e.sleeping]
        # model filtering mirrors the router's _eligible_endpoints
        # (router/request_service.py): only engines actually serving the
        # requested model are candidates; the only fallback is engines with
        # NO published model list (not yet probed) — never engines that
        # advertise a different model
        model = body.get("model")
        if model:
            by_model = [e for e in endpoints if e.has_model(model)]
            endpoints = by_model or [e for e in endpoints if not e.model_names]
        if not endpoints:
            return None
        ctx = RoutingContext(endpoints=endpoints, headers=headers, body=body)
        return await self.policy.route(ctx)

    @staticmethod
    def _header_dict(http_headers) -> dict[str, str]:
        out = {}
        for hv in http_headers.headers.headers:
            out[hv.key.lower()] = hv.value or hv.raw_value.decode(
                "utf-8", "replace"
            )
        return out

    def _mutation_response(self, kind: str, url: str):
        mut = pb2.HeaderMutation(
            set_headers=[
                pb2.HeaderValueOption(
                    header=pb2.HeaderValue(
                        key=ENDPOINT_HEADER,
                        raw_value=endpoint_address(url).encode(),
                    )
                )
            ]
        )
        common = pb2.CommonResponse(
            status=pb2.CommonResponse.CONTINUE, header_mutation=mut
        )
        if kind == "headers":
            return pb2.ProcessingResponse(
                request_headers=pb2.HeadersResponse(response=common)
            )
        return pb2.ProcessingResponse(
            request_body=pb2.BodyResponse(response=common)
        )

    @staticmethod
    def _immediate(code: int, message: str):
        return pb2.ProcessingResponse(
            immediate_response=pb2.ImmediateResponse(
                status=pb2.HttpStatus(code=code),
                body=json.dumps({"error": message}).encode(),
                details=message,
            )
        )

    async def Process(self, request_iterator, context):
        headers: dict[str, str] = {}
        body_chunks: list[bytes] = []
        async for req in request_iterator:
            which = req.WhichOneof("request")
            if which == "request_headers":
                headers = self._header_dict(req.request_headers)
                if req.request_headers.end_of_stream:
                    # bodyless request: route on headers alone
                    url = await self._pick(headers, {})
                    if url is None:
                        yield self._immediate(503, "no healthy endpoints")
                        return
                    yield self._mutation_response("headers", url)
                    continue
                yield pb2.ProcessingResponse(
                    request_headers=pb2.HeadersResponse(
                        response=pb2.CommonResponse(
                            status=pb2.CommonResponse.CONTINUE
                        )
                    )
                )
            elif which == "request_body":
                # STREAMED mode delivers the body in chunks: buffer until
                # end_of_stream so routing sees the complete JSON exactly
                # once (each chunk still gets its protocol-mandated reply)
                body_chunks.append(req.request_body.body)
                if not req.request_body.end_of_stream:
                    yield pb2.ProcessingResponse(
                        request_body=pb2.BodyResponse(
                            response=pb2.CommonResponse(
                                status=pb2.CommonResponse.CONTINUE
                            )
                        )
                    )
                    continue
                try:
                    # large prompt bodies parse off the gRPC event loop —
                    # a multi-MB json.loads here stalls every concurrent
                    # ext-proc stream (the PR 2 resync-body bug class)
                    body = await loads_off_loop(b"".join(body_chunks) or b"{}")
                except json.JSONDecodeError:
                    body = {}
                if not isinstance(body, dict):
                    # valid JSON but not an object (array/string/number):
                    # policies index into it — route as bodyless instead of
                    # crashing the stream
                    body = {}
                body_chunks = []
                url = await self._pick(headers, body)
                if url is None:
                    yield self._immediate(503, "no healthy endpoints")
                    return
                yield self._mutation_response("body", url)
            elif which == "response_headers":
                yield pb2.ProcessingResponse(
                    response_headers=pb2.HeadersResponse(
                        response=pb2.CommonResponse(
                            status=pb2.CommonResponse.CONTINUE
                        )
                    )
                )
            elif which == "response_body":
                yield pb2.ProcessingResponse(
                    response_body=pb2.BodyResponse(
                        response=pb2.CommonResponse(
                            status=pb2.CommonResponse.CONTINUE
                        )
                    )
                )
            elif which == "request_trailers":
                yield pb2.ProcessingResponse(
                    request_trailers=pb2.TrailersResponse()
                )
            elif which == "response_trailers":
                yield pb2.ProcessingResponse(
                    response_trailers=pb2.TrailersResponse()
                )


def make_server(service: EppService, port: int = 0) -> tuple[grpc.aio.Server, int]:
    """grpc.aio server with a hand-wired generic handler (no grpc_tools
    codegen in this image — serializers come straight from the pb2 classes).
    Returns (server, bound_port)."""
    server = grpc.aio.server()
    handler = grpc.method_handlers_generic_handler(
        _SERVICE,
        {
            "Process": grpc.stream_stream_rpc_method_handler(
                service.Process,
                request_deserializer=pb2.ProcessingRequest.FromString,
                response_serializer=pb2.ProcessingResponse.SerializeToString,
            )
        },
    )
    server.add_generic_rpc_handlers((handler,))
    bound = server.add_insecure_port(f"[::]:{port}")
    return server, bound


async def _amain(args) -> None:
    from ..router.discovery import StaticDiscovery

    urls = [u.strip() for u in args.static_backends.split(",")]
    models = None
    if args.static_models:
        # the router's convention (router/app.py): ';' separates per-backend
        # groups, ',' separates models within a group
        models = [
            [m.strip() for m in group.split(",") if m.strip()]
            for group in args.static_models.split(";")
        ]
        if len(models) == 1 and len(urls) > 1:
            models = models * len(urls)  # one group: applies to every backend
        if len(models) != len(urls):
            raise SystemExit(
                f"--static-models has {len(models)} group(s) for "
                f"{len(urls)} backend(s)"
            )
    discovery = StaticDiscovery(urls=urls, models=models)
    await discovery.start()
    policy = make_policy(args.routing_policy, **(
        {"session_key": args.session_key} if args.routing_policy == "session"
        else {"kv_controller_url": args.kv_controller_url}
        if args.routing_policy == "kvaware" else {}
    ))
    service = EppService(policy, discovery.endpoints)
    server, port = make_server(service, args.port)
    await server.start()
    logger.info("EPP listening on :%d (policy=%s)", port, args.routing_policy)
    await server.wait_for_termination()


def main() -> None:
    p = argparse.ArgumentParser(description="ext-proc endpoint picker")
    p.add_argument("--port", type=int, default=9002)
    p.add_argument("--routing-policy", default="prefixaware",
                   choices=["roundrobin", "session", "prefixaware", "kvaware"])
    p.add_argument("--session-key", default="x-session-id")
    p.add_argument("--kv-controller-url", default="http://localhost:9100")
    p.add_argument("--static-backends", required=True,
                   help="comma-separated engine base URLs")
    p.add_argument("--static-models", default="",
                   help="';'-separated per-backend groups of ','-separated "
                        "model names (one group applies to all backends)")
    args = p.parse_args()
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
