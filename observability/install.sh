#!/usr/bin/env bash
# Install the observability stack: kube-prometheus-stack + the TPU dashboard
# + prometheus-adapter custom metrics (reference observability/install.sh).
set -euo pipefail
NS="${NS:-monitoring}"

helm repo add prometheus-community \
    https://prometheus-community.github.io/helm-charts
helm repo update
helm upgrade --install kube-prom-stack \
    prometheus-community/kube-prometheus-stack -n "$NS" --create-namespace

kubectl -n "$NS" create configmap tpu-dashboard \
    --from-file=tpu-dashboard.json="$(dirname "$0")/tpu-dashboard.json" \
    --dry-run=client -o yaml | kubectl apply -f -
kubectl -n "$NS" label configmap tpu-dashboard grafana_dashboard=1 --overwrite

helm upgrade --install prom-adapter \
    prometheus-community/prometheus-adapter -n "$NS" \
    -f "$(dirname "$0")/prom-adapter.yaml"

kubectl apply -f "$(dirname "$0")/podmonitor.yaml"

# SLO rule pack (docs/29-saturation-slo.md): ship the recording rules +
# burn-rate alerts as a PrometheusRule so the operator-managed Prometheus
# picks them up (the file's `groups:` body is the standard rule format)
kubectl -n "$NS" apply -f - <<EOF
apiVersion: monitoring.coreos.com/v1
kind: PrometheusRule
metadata:
  name: tpu-slo-rules
  labels:
    release: kube-prom-stack
spec:
$(sed 's/^/  /' "$(dirname "$0")/rules/tpu-slo-rules.yaml" | grep -v '^  #')
EOF

# Fleet-coherence rule pack (docs/32-fleet-telemetry.md): convergence-lag
# percentiles, stickiness-violation rates, tenant over-admission, and the
# router ring-divergence alert
kubectl -n "$NS" apply -f - <<EOF
apiVersion: monitoring.coreos.com/v1
kind: PrometheusRule
metadata:
  name: tpu-fleet-rules
  labels:
    release: kube-prom-stack
spec:
$(sed 's/^/  /' "$(dirname "$0")/rules/tpu-fleet-rules.yaml" | grep -v '^  #')
EOF
echo "observability stack installed in namespace $NS"
