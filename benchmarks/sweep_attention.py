"""Decode-attention backend sweep: Pallas paged kernel vs XLA gather.

Measures one layer's decode attention per (block_size, context, batch)
config on the real chip — the evidence behind ModelRunner's
`_resolve_attention_backend` policy (VERDICT r2 #7: the shipped default
must be the measured winner at the shipped config).

    python benchmarks/sweep_attention.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


LOOP_ITERS = 64


def time_fn(fn, q, *args) -> float:
    """Per-iteration time of `fn(q, *args)` measured as ONE device
    dispatch running LOOP_ITERS dependent iterations in a lax.fori_loop
    (output feeds back into q). Host-side chained timing through the dev
    tunnel is unusable: the dispatch layer pipelines/caches so
    aggressively that 20-op chains reported multi-TB/s 'bandwidth'. One
    fused loop leaves only ~RTT/LOOP_ITERS (~1.5 ms/64) of attribution
    error, identical for both backends."""

    @jax.jit
    def run(q0, *a):
        def body(_, qq):
            out = fn(qq, *a)
            return qq + 0.1 * out.reshape(qq.shape)

        return jax.lax.fori_loop(0, LOOP_ITERS, body, q0)

    run(q, *args).block_until_ready()  # compile
    best = float("inf")
    for i in range(3):
        # DIFFERENT input values each timed run: the dev tunnel's dispatch
        # layer serves cached results for (executable, identical inputs)
        # pairs, which turns repeat timings into no-ops
        qi = (q * (1.125 + 0.125 * i)).block_until_ready()
        t0 = time.perf_counter()
        # np.asarray forces a host readback — through the dev tunnel,
        # block_until_ready alone returns before remote execution finishes
        np.asarray(run(qi, *args))
        best = min(best, time.perf_counter() - t0)
    return best / LOOP_ITERS * 1000.0  # ms


def bench_config(
    batch: int, ctx: int, block_size: int, nh: int, kvh: int, d: int,
    window: int = 16, dtype=jnp.bfloat16, kv_dtype=None,
) -> dict:
    from vllm_production_stack_tpu.ops.attention import (
        paged_attention_with_staged,
    )
    from vllm_production_stack_tpu.ops.paged_attention_pallas import (
        paged_decode_attention,
    )

    rng = np.random.RandomState(0)
    nb = ctx // block_size
    num_blocks = batch * nb + 2
    scale = d ** -0.5
    # fp8 pools: pages + staged window store in the pool dtype, queries
    # stay bf16 — matching the engine's fused-window layout
    kvd = kv_dtype if kv_dtype is not None else dtype

    q = jnp.asarray(rng.randn(batch, nh, d), dtype)
    kv = jnp.asarray(
        rng.randn(2, num_blocks, block_size, kvh, d), kvd
    )
    tables = jnp.asarray(
        rng.randint(1, num_blocks, size=(batch, nb)), jnp.int32
    )
    hist_len = jnp.full((batch,), ctx, jnp.int32)
    staged_k = jnp.asarray(rng.randn(window, batch, kvh, d), kvd)
    staged_v = jnp.asarray(rng.randn(window, batch, kvh, d), kvd)
    step_k = jnp.int32(window - 1)
    hist_mask = jnp.ones((batch, ctx), bool)
    staged_mask = jnp.ones((window,), bool)

    pallas_fn = jax.jit(
        lambda *a: paged_decode_attention(*a, scale=scale)
    )
    pallas_ms = time_fn(
        pallas_fn, q, kv, tables, hist_len, staged_k, staged_v, step_k,
    )

    xla_fn = jax.jit(
        lambda q4, *a: paged_attention_with_staged(q4, *a, scale=scale)
    )
    xla_ms = time_fn(
        xla_fn, q[:, None], kv, tables, hist_mask, staged_k, staged_v,
        staged_mask,
    )
    return {
        "batch": batch, "ctx": ctx, "block_size": block_size,
        "kv_dtype": jnp.dtype(kvd).name,
        "pallas_ms": round(pallas_ms, 3), "xla_ms": round(xla_ms, 3),
        "winner": "pallas" if pallas_ms < xla_ms else "xla",
        "ratio": round(pallas_ms / xla_ms, 2),
    }


def bench_prefill_config(
    batch: int, ctx: int, block_size: int, nh: int, kvh: int, d: int,
    chunk: int = 512, dtype=jnp.bfloat16, kv_dtype=None,
) -> dict:
    """One layer's CHUNKED-PREFILL attention: a chunk-token query tile
    attending [resident history + the chunk's own pages] — the paged
    flash-prefill kernel vs the XLA gather+mask formulation. `ctx` is the
    residency INCLUDING the chunk (the chunk is written before attending),
    so the XLA path gathers ctx tokens and builds a (B, chunk, ctx) mask."""
    from vllm_production_stack_tpu.ops.attention import (
        causal_page_mask, paged_attention_xla,
    )
    from vllm_production_stack_tpu.ops.paged_attention_pallas import (
        paged_prefill_attention,
    )

    rng = np.random.RandomState(0)
    nb = ctx // block_size
    num_blocks = batch * nb + 2
    scale = d ** -0.5
    kvd = kv_dtype if kv_dtype is not None else dtype

    q = jnp.asarray(rng.randn(batch, chunk, nh, d), dtype)
    kv = jnp.asarray(rng.randn(2, num_blocks, block_size, kvh, d), kvd)
    tables = jnp.asarray(
        rng.randint(1, num_blocks, size=(batch, nb)), jnp.int32
    )
    ctx_lens = jnp.full((batch,), ctx, jnp.int32)
    start = jnp.full((batch,), ctx - chunk, jnp.int32)

    pallas_fn = jax.jit(
        lambda qq, *a: paged_prefill_attention(qq, *a, scale=scale)
    )
    pallas_ms = time_fn(pallas_fn, q, kv, tables, ctx_lens, start)

    positions = start[:, None] + jnp.arange(chunk, dtype=jnp.int32)[None, :]
    mask = causal_page_mask(positions, ctx_lens, nb * block_size)
    xla_fn = jax.jit(
        lambda qq, *a: paged_attention_xla(qq, *a, scale=scale)
    )
    xla_ms = time_fn(xla_fn, q, kv, tables, mask)
    return {
        "phase": "prefill", "batch": batch, "ctx": ctx, "chunk": chunk,
        "block_size": block_size, "kv_dtype": jnp.dtype(kvd).name,
        "pallas_ms": round(pallas_ms, 3), "xla_ms": round(xla_ms, 3),
        "winner": "pallas" if pallas_ms < xla_ms else "xla",
        "ratio": round(pallas_ms / xla_ms, 2),
    }


def main() -> None:
    import ml_dtypes

    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    p.add_argument("--fp8", action="store_true",
                   help="fp8 (e4m3) KV pool rows — the north-star pool "
                        "config (VERDICT r3 #5: auto must have fp8 "
                        "measurements)")
    p.add_argument("--prefill", action="store_true",
                   help="sweep chunked-prefill attention instead of decode "
                        "(evidence for resolve_auto_prefill_backend)")
    args = p.parse_args()
    # llama-1b decode head shape
    nh, kvh, d = 32, 8, 64
    kvd = jnp.dtype(ml_dtypes.float8_e4m3fn) if args.fp8 else None
    if args.prefill:
        configs = [
            (4, 1024, 16), (4, 1024, 32),
            (4, 4096, 16), (4, 4096, 32), (4, 4096, 64),
        ]
        if not args.quick:
            configs += [(16, 4096, 32), (1, 8192, 32), (1, 8192, 64)]
        for batch, ctx, bs in configs:
            print(json.dumps(bench_prefill_config(
                batch, ctx, bs, nh, kvh, d, kv_dtype=kvd
            )), flush=True)
        return
    configs = [
        (16, 1024, 16), (16, 1024, 32), (16, 1024, 64),
        (16, 4096, 16), (16, 4096, 32), (16, 4096, 64),
    ]
    if not args.quick:
        configs += [(64, 1024, 16), (64, 1024, 64), (64, 4096, 64)]
    for batch, ctx, bs in configs:
        print(json.dumps(bench_config(
            batch, ctx, bs, nh, kvh, d, kv_dtype=kvd
        )), flush=True)


if __name__ == "__main__":
    main()
