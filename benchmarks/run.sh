#!/usr/bin/env bash
# Multi-round QA benchmark recipe (mirrors the reference's
# benchmarks/multi-round-qa/run.sh: warmup with long histories, then a QPS
# sweep against the deployed router).
#
#   ./benchmarks/run.sh <router-url> <model-name>
set -euo pipefail

URL="${1:-http://localhost:8000}"
MODEL="${2:-llama-3-8b}"
OUT_DIR="${OUT_DIR:-bench-results}"
mkdir -p "$OUT_DIR"

# Warmup: populate prefix caches with the shared system prompt + user
# histories (the reference warms 400 users with 20k-token histories).
python benchmarks/multi_round_qa.py \
    --base-url "$URL" --model "$MODEL" \
    --num-users "${WARMUP_USERS:-40}" --qps "${WARMUP_QPS:-2}" \
    --num-rounds 2 --system-prompt-len 1000 --user-info-len 2000 \
    --answer-len 100 --duration "${WARMUP_S:-60}" \
    --output "$OUT_DIR/warmup.csv"

# QPS sweep (reference sweeps 0.1 -> 4.1). SHAREGPT=<file> switches the
# question source to real ShareGPT conversation turns.
SHAREGPT_ARG=""
if [ -n "${SHAREGPT:-}" ]; then
    SHAREGPT_ARG="--sharegpt $SHAREGPT"
fi
: > "$OUT_DIR/results.jsonl"
for QPS in ${QPS_SWEEP:-0.5 1.0 2.0 4.0}; do
    echo "=== qps=$QPS ==="
    python benchmarks/multi_round_qa.py \
        --base-url "$URL" --model "$MODEL" \
        --num-users "${NUM_USERS:-320}" --qps "$QPS" \
        --num-rounds "${NUM_ROUNDS:-10}" \
        --system-prompt-len "${SYS_LEN:-1000}" \
        --user-info-len "${USER_LEN:-20000}" \
        --answer-len "${ANSWER_LEN:-100}" \
        --duration "${DURATION_S:-120}" \
        $SHAREGPT_ARG \
        --output "$OUT_DIR/sweep-qps$QPS.csv" \
        | tee "$OUT_DIR/summary-qps$QPS.json"
    # one aggregate row per QPS, machine-readable across the whole sweep
    cat "$OUT_DIR/summary-qps$QPS.json" >> "$OUT_DIR/results.jsonl"
done

python benchmarks/plot.py "$OUT_DIR" || true
echo "sweep complete: $OUT_DIR/results.jsonl"
