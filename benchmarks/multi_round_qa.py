"""Multi-round QA serving benchmark — the stack's north-star workload.

Re-implementation of the reference harness's workload semantics
(benchmarks/multi-round-qa/multi-round-qa.py:18-534): N concurrent simulated
users share a long system prompt, each carries a per-user history, and every
round appends a question + the model's answer — so a serving stack with
prefix caching / KV-aware routing re-uses the shared and per-user context
instead of recomputing it. Users ramp up with gap = num_users/qps, leave
after num_rounds, and are replaced to hold concurrency steady.

Speaks plain OpenAI chat completions over aiohttp (works against the TPU
router, a single engine, or any OpenAI endpoint — the reference harness
only needs the API too). Emits a per-request CSV and a summary with QPS,
prompt/generation throughput, and TTFT percentiles (README.md:80-86 of the
reference benchmark).

Usage:
    python benchmarks/multi_round_qa.py --base-url http://localhost:8000 \
        --model llama-3-8b --num-users 320 --qps 2.0 --num-rounds 10 \
        --system-prompt-len 1000 --user-info-len 2000 --answer-len 100
"""

from __future__ import annotations

import argparse
import asyncio
import csv
import json
import random
import statistics
import sys
import time
from dataclasses import dataclass, field

import aiohttp

_WORDS = (
    "alpha beta gamma delta epsilon zeta eta theta iota kappa lambda mu nu "
    "xi omicron pi rho sigma tau upsilon phi chi psi omega"
).split()


def filler_text(n_tokens: int, seed: int = 0) -> str:
    """~n_tokens of deterministic filler (1 word ≈ 1 token is close enough
    for load shaping; the reference uses dummy-token text the same way)."""
    rng = random.Random(seed)
    return " ".join(rng.choice(_WORDS) for _ in range(max(1, n_tokens)))


@dataclass
class WorkloadConfig:
    num_users: int = 10
    system_prompt_len: int = 1000
    user_info_len: int = 2000
    answer_len: int = 100
    num_rounds: int = 5
    qps: float = 1.0
    model: str = "tiny-llama"
    base_url: str = "http://localhost:8000"
    duration_s: float = 60.0
    enable_user_id: bool = False
    temperature: float = 0.0
    # ShareGPT mode (reference data_preprocessing.py + run.sh --sharegpt):
    # real conversation turns replace the synthetic filler questions
    sharegpt_conversations: list[list[str]] | None = None


def load_sharegpt(path: str) -> list[list[str]]:
    """ShareGPT JSON -> per-conversation human turns. Accepts the common
    dump format: [{"conversations": [{"from": "human"|"gpt", "value": ...},
    ...]}, ...]; conversations with no human turns are dropped."""
    with open(path) as f:
        data = json.load(f)
    out: list[list[str]] = []
    for entry in data:
        turns = [
            t.get("value", "")
            for t in entry.get("conversations", [])
            if t.get("from") in ("human", "user") and t.get("value")
        ]
        if turns:
            out.append(turns)
    if not out:
        raise ValueError(f"no usable conversations in {path}")
    return out


@dataclass
class RequestRecord:
    user_id: int
    round_idx: int
    launch_time: float
    ttft: float | None = None
    finish_time: float | None = None
    prompt_tokens: int = 0
    completion_tokens: int = 0
    error: str | None = None

    @property
    def latency(self) -> float | None:
        if self.finish_time is None:
            return None
        return self.finish_time - self.launch_time


class UserSession:
    """One simulated user's conversation state machine."""

    def __init__(self, cfg: WorkloadConfig, user_id: int, system_prompt: str):
        self.cfg = cfg
        self.user_id = user_id
        self.system_prompt = system_prompt
        self.user_info = filler_text(cfg.user_info_len, seed=1000 + user_id)
        self.history: list[dict] = []
        self.round_idx = 0
        self.inflight = False

    @property
    def done(self) -> bool:
        return self.round_idx >= self.cfg.num_rounds and not self.inflight

    def build_messages(self) -> list[dict]:
        convs = self.cfg.sharegpt_conversations
        if convs:
            turns = convs[self.user_id % len(convs)]
            q = turns[self.round_idx % len(turns)]
            return [
                {"role": "system", "content": self.system_prompt},
                *self.history,
                {"role": "user", "content": q},
            ]
        q = (
            f"Question {self.round_idx} from user {self.user_id}: "
            + filler_text(16, seed=self.user_id * 97 + self.round_idx)
        )
        return [
            {"role": "system", "content": self.system_prompt},
            {"role": "user", "content": f"My notes: {self.user_info}"},
            *self.history,
            {"role": "user", "content": q},
        ]

    async def launch_round(
        self, session: aiohttp.ClientSession, records: list[RequestRecord]
    ) -> None:
        cfg = self.cfg
        rec = RequestRecord(self.user_id, self.round_idx, time.time())
        records.append(rec)
        self.inflight = True
        messages = self.build_messages()
        body = {
            "model": cfg.model,
            "messages": messages,
            "max_tokens": cfg.answer_len,
            "temperature": cfg.temperature,
            "stream": True,
            "stream_options": {"include_usage": True},
        }
        headers = {}
        if cfg.enable_user_id:
            headers["x-user-id"] = str(self.user_id)
        answer_parts: list[str] = []
        try:
            async with session.post(
                cfg.base_url + "/v1/chat/completions", json=body,
                headers=headers,
            ) as resp:
                if resp.status != 200:
                    rec.error = f"HTTP {resp.status}"
                    return
                async for raw in resp.content:
                    line = raw.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    payload = line[len("data: "):]
                    if payload == "[DONE]":
                        break
                    try:
                        chunk = json.loads(payload)
                    except json.JSONDecodeError:
                        # a truncated/garbage SSE line is a failed request,
                        # not a vanished one
                        rec.error = f"malformed SSE line: {payload[:80]!r}"
                        return
                    if chunk.get("error"):
                        # engines surface post-header failures (e.g. prompt
                        # too long) as SSE error events on a 200 stream
                        rec.error = str(chunk["error"].get("message", "error"))
                        return
                    if chunk.get("choices"):
                        choice = chunk["choices"][0]
                        delta = choice.get("delta", {})
                        text = delta.get("content")
                        # first generated-token signal: a content delta (even
                        # one held back to "" by incremental detokenization
                        # of partial UTF-8) or the finish marker
                        if rec.ttft is None and (
                            text is not None or choice.get("finish_reason")
                        ):
                            rec.ttft = time.time() - rec.launch_time
                        if text:
                            answer_parts.append(text)
                    if chunk.get("usage"):
                        rec.prompt_tokens = chunk["usage"].get(
                            "prompt_tokens", 0
                        )
                        rec.completion_tokens = chunk["usage"].get(
                            "completion_tokens", 0
                        )
            rec.finish_time = time.time()
            if rec.completion_tokens == 0 and answer_parts:
                # endpoint sent no usage chunk; approximate from the stream
                rec.completion_tokens = len(answer_parts)
            self.history.append(messages[-1])
            self.history.append(
                {"role": "assistant", "content": "".join(answer_parts)}
            )
            self.round_idx += 1
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            rec.error = str(e)
        finally:
            self.inflight = False


class UserSessionManager:
    """Ramps users up at gap = num_users/qps, holds concurrency at
    num_users (a finished user is replaced by a fresh one), and launches
    one round per user per scheduling opportunity at the target QPS."""

    def __init__(self, cfg: WorkloadConfig):
        self.cfg = cfg
        self.system_prompt = (
            "You are a helpful assistant. "
            + filler_text(cfg.system_prompt_len, seed=42)
        )
        self.sessions: list[UserSession] = []
        self.records: list[RequestRecord] = []
        self._next_user_id = 0
        self._gap = 1.0 / cfg.qps if cfg.qps > 0 else 0.1
        # absolute schedule: launches catch up after slow ticks instead of
        # drifting below the target QPS by up to a poll interval per request
        self._next_launch: float | None = None

    def _spawn(self) -> UserSession:
        s = UserSession(self.cfg, self._next_user_id, self.system_prompt)
        self._next_user_id += 1
        self.sessions.append(s)
        return s

    def step(self, now: float, session: aiohttp.ClientSession,
             tasks: set) -> None:
        # replace finished users; ramp until num_users live
        self.sessions = [s for s in self.sessions if not s.done]
        while len(self.sessions) < self.cfg.num_users:
            self._spawn()
        if self._next_launch is None:
            self._next_launch = now
        while now >= self._next_launch:
            # round-robin the launch opportunity over idle users
            idle = [
                s for s in self.sessions
                if not s.inflight and s.round_idx < self.cfg.num_rounds
            ]
            if not idle:
                # nobody to launch: don't accrue an unbounded backlog
                self._next_launch = now + self._gap
                return
            user = min(idle, key=lambda s: s.round_idx)
            self._next_launch += self._gap
            t = asyncio.ensure_future(user.launch_round(session, self.records))
            tasks.add(t)
            t.add_done_callback(tasks.discard)

    # -- reporting --------------------------------------------------------

    def summary(self, elapsed: float) -> dict:
        ok = [r for r in self.records if r.finish_time is not None]
        ttfts = sorted(r.ttft for r in ok if r.ttft is not None)

        def pct(p):
            return ttfts[int(p * (len(ttfts) - 1))] if ttfts else None

        return {
            "target_qps": self.cfg.qps,
            "requests_completed": len(ok),
            "requests_failed": sum(1 for r in self.records if r.error),
            "qps": round(len(ok) / elapsed, 3) if elapsed else 0,
            "prompt_tok_per_s": round(
                sum(r.prompt_tokens for r in ok) / elapsed, 1
            ),
            "gen_tok_per_s": round(
                sum(r.completion_tokens for r in ok) / elapsed, 1
            ),
            "avg_ttft_s": round(statistics.mean(ttfts), 4) if ttfts else None,
            "p50_ttft_s": round(pct(0.50), 4) if ttfts else None,
            "p90_ttft_s": round(pct(0.90), 4) if ttfts else None,
            "elapsed_s": round(elapsed, 1),
        }

    def write_csv(self, path: str) -> None:
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow([
                "user_id", "round", "launch_time", "ttft", "latency",
                "prompt_tokens", "completion_tokens", "error",
            ])
            for r in self.records:
                w.writerow([
                    r.user_id, r.round_idx, f"{r.launch_time:.3f}",
                    f"{r.ttft:.4f}" if r.ttft is not None else "",
                    f"{r.latency:.4f}" if r.latency is not None else "",
                    r.prompt_tokens, r.completion_tokens, r.error or "",
                ])


async def run_benchmark(cfg: WorkloadConfig) -> tuple[dict, UserSessionManager]:
    manager = UserSessionManager(cfg)
    tasks: set = set()
    timeout = aiohttp.ClientTimeout(total=300)
    start = time.time()
    async with aiohttp.ClientSession(timeout=timeout) as session:
        while time.time() - start < cfg.duration_s:
            manager.step(time.time(), session, tasks)
            await asyncio.sleep(0.02)
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
    return manager.summary(time.time() - start), manager


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--base-url", default="http://localhost:8000")
    p.add_argument("--model", default="tiny-llama")
    p.add_argument("--num-users", type=int, default=10)
    p.add_argument("--qps", type=float, default=1.0)
    p.add_argument("--num-rounds", type=int, default=5)
    p.add_argument("--system-prompt-len", type=int, default=1000)
    p.add_argument("--user-info-len", type=int, default=2000)
    p.add_argument("--answer-len", type=int, default=100)
    p.add_argument("--duration", type=float, default=60.0)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--enable-user-id", action="store_true",
                   help="send x-user-id (exercises session-sticky routing)")
    p.add_argument("--sharegpt", default=None, metavar="FILE",
                   help="ShareGPT JSON dump: real conversation turns "
                        "replace the synthetic questions (reference "
                        "data_preprocessing.py mode)")
    p.add_argument("--output", default="summary.csv")
    args = p.parse_args(argv)
    cfg = WorkloadConfig(
        num_users=args.num_users, system_prompt_len=args.system_prompt_len,
        user_info_len=args.user_info_len, answer_len=args.answer_len,
        num_rounds=args.num_rounds, qps=args.qps, model=args.model,
        base_url=args.base_url.rstrip("/"), duration_s=args.duration,
        enable_user_id=args.enable_user_id, temperature=args.temperature,
        sharegpt_conversations=(
            load_sharegpt(args.sharegpt) if args.sharegpt else None
        ),
    )
    summary, manager = asyncio.run(run_benchmark(cfg))
    manager.write_csv(args.output)
    print(json.dumps(summary))
    return 0 if summary["requests_completed"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
