"""Plot a QPS sweep (reference benchmarks/multi-round-qa/plot.py).

Reads the per-QPS summary JSONs run.sh writes and draws the two headline
curves: p50 TTFT vs offered QPS and generation throughput vs offered QPS.

    python benchmarks/plot.py bench-results/ [-o bench-results/sweep.png]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_summaries(results_dir: str) -> list[dict]:
    rows = []
    for path in sorted(glob.glob(os.path.join(results_dir, "summary-qps*.json"))):
        with open(path) as f:
            rows.append(json.load(f))
    rows.sort(key=lambda r: r.get("target_qps", 0))
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("results_dir")
    p.add_argument("-o", "--output", default=None,
                   help="output PNG (default: <results_dir>/sweep.png)")
    args = p.parse_args(argv)
    rows = load_summaries(args.results_dir)
    if not rows:
        print(f"no summary-qps*.json under {args.results_dir}",
              file=sys.stderr)
        return 1

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        # keep the data usable even without the plotting dep: emit one
        # aggregate row per QPS (the reference's CSV summary role)
        for r in rows:
            print(json.dumps(r))
        print("matplotlib unavailable; printed rows instead",
              file=sys.stderr)
        return 0

    qps = [r.get("target_qps") for r in rows]
    ttft = [r.get("p50_ttft_s") for r in rows]
    tput = [r.get("gen_tok_per_s") for r in rows]

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(10, 4))
    ax1.plot(qps, ttft, marker="o")
    ax1.set_xlabel("offered QPS")
    ax1.set_ylabel("p50 TTFT (s)")
    ax1.set_title("TTFT vs load")
    ax1.grid(True, alpha=0.3)
    ax2.plot(qps, tput, marker="o", color="tab:green")
    ax2.set_xlabel("offered QPS")
    ax2.set_ylabel("generation throughput (tok/s)")
    ax2.set_title("Throughput vs load")
    ax2.grid(True, alpha=0.3)
    fig.tight_layout()
    out = args.output or os.path.join(args.results_dir, "sweep.png")
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
