"""Fixed-QPS load generator for the router perf rig.

Counterpart of the reference's src/tests/perftest/request_generator.py: fire
chat completions at a target QPS against the router (backed by fake engines,
vllm_production_stack_tpu/testing/fake_engine.py) and report achieved
QPS/latency — the router-only throughput gate used in CI
(router-e2e-test.yml:51-66; 4 fake engines @ 500 tok/s, --qps 10).

    python benchmarks/request_generator.py --base-url http://localhost:8000 \
        --model fake-model --qps 10 --duration 30
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import time

import aiohttp


async def fire(session, base_url, model, results):
    t0 = time.time()
    try:
        async with session.post(
            base_url + "/v1/chat/completions",
            json={
                "model": model,
                "messages": [{"role": "user", "content": "ping"}],
                "max_tokens": 16,
            },
        ) as resp:
            await resp.read()
            results.append((resp.status, time.time() - t0))
    except (aiohttp.ClientError, asyncio.TimeoutError):
        results.append((0, time.time() - t0))


async def run(base_url, model, qps, duration) -> dict:
    results: list[tuple[int, float]] = []
    tasks: set = set()
    gap = 1.0 / qps
    start = time.time()
    async with aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=60)
    ) as session:
        nxt = start
        while time.time() - start < duration:
            now = time.time()
            if now >= nxt:
                t = asyncio.ensure_future(
                    fire(session, base_url, model, results)
                )
                tasks.add(t)
                t.add_done_callback(tasks.discard)
                nxt += gap
            await asyncio.sleep(min(0.005, max(0.0, nxt - time.time())))
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
    elapsed = time.time() - start
    ok = [lat for status, lat in results if status == 200]
    return {
        "target_qps": qps,
        "achieved_qps": round(len(ok) / elapsed, 2),
        "errors": sum(1 for s, _ in results if s != 200),
        "avg_latency_s": round(statistics.mean(ok), 4) if ok else None,
        "p99_latency_s": (
            round(sorted(ok)[int(0.99 * (len(ok) - 1))], 4) if ok else None
        ),
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--base-url", default="http://localhost:8000")
    p.add_argument("--model", default="fake-model")
    p.add_argument("--qps", type=float, default=10.0)
    p.add_argument("--duration", type=float, default=30.0)
    args = p.parse_args(argv)
    print(json.dumps(asyncio.run(
        run(args.base_url.rstrip("/"), args.model, args.qps, args.duration)
    )))


if __name__ == "__main__":
    main()
