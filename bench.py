"""Headline benchmark: offline serving throughput of the TPU engine.

Runs the flagship Llama-class engine (llama-1b preset, bf16, random weights —
zero-egress container) on the real chip: 16 concurrent requests, 128-token
prompts, 128 greedy output tokens each, continuous batching with chunked
prefill over the paged HBM KV pool.

Prints ONE JSON line: generation throughput in tok/s. vs_baseline is measured
against 500 tok/s — the per-engine emission rate the reference stack uses in
its router perf rig (src/tests/perftest/fake-openai-server.py; the repo
publishes no absolute engine numbers, BASELINE.md).
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_TOK_S = 500.0


def main() -> None:
    from vllm_production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.models.registry import resolve_model_config

    n_seqs, prompt_len, gen_len = 16, 128, 128
    model_cfg = resolve_model_config("llama-1b", max_model_len=1024,
                                     dtype="bfloat16")
    config = EngineConfig(
        model=model_cfg,
        cache=CacheConfig(block_size=16, num_blocks=400),
        scheduler=SchedulerConfig(
            max_num_seqs=n_seqs,
            max_num_batched_tokens=prompt_len,
            decode_buckets=(n_seqs,),
            prefill_buckets=(prompt_len,),
        ),
        parallel=ParallelConfig(tensor_parallel_size=1),
    )
    engine = LLMEngine(config)
    sampling = SamplingParams(max_tokens=gen_len, temperature=0.0)

    def make_prompts(seed0: int) -> list[list[int]]:
        return [
            list(
                np.random.RandomState(seed0 + i).randint(
                    1, model_cfg.vocab_size, size=prompt_len
                )
            )
            for i in range(n_seqs)
        ]

    # warmup: compile the prefill/decode buckets
    engine.generate(
        make_prompts(10_000),
        SamplingParams(max_tokens=4, temperature=0.0),
    )

    t0 = time.perf_counter()
    outs = engine.generate(make_prompts(0), sampling)
    elapsed = time.perf_counter() - t0

    gen_tokens = sum(len(o["token_ids"]) for o in outs)
    assert gen_tokens == n_seqs * gen_len, (gen_tokens, n_seqs * gen_len)
    tok_s = gen_tokens / elapsed

    print(
        json.dumps(
            {
                "metric": "engine_generation_throughput",
                "value": round(tok_s, 1),
                "unit": "tok/s",
                "vs_baseline": round(tok_s / BASELINE_TOK_S, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
