"""Headline benchmark: the SERVED stack, measured end to end.

VERDICT r3 #1: the driver-captured number must BE the live-stack run —
router + engine as real OS processes, driven over HTTP/SSE with the
north-star multi-round-QA workload (BASELINE.md; reference
benchmarks/multi-round-qa/run.sh). VERDICT r4 #1: the bench must FINISH
inside the driver budget and must not lose completed sections to a late
timeout. Hence the structure here:

- every phase runs as a SUBPROCESS with its own wall-clock cap (the TPU
  tunnel grants one process at a time, so the orchestrator itself never
  touches JAX — a wedged phase dies alone and the chip frees for the
  next);
- every phase's JSON is printed AND FLUSHED the moment it completes, so
  a driver timeout preserves everything already measured (the driver
  keeps the output tail);
- phases are ordered cheapest-first; each checks the remaining global
  budget (BENCH_BUDGET_S, default 3300 s) before starting and reports
  itself as skipped rather than overrunning;
- engine boots reuse the persistent XLA compilation cache
  (/tmp/vllm-tpu-xla-cache — populated by prior local runs on this box),
  falling back to --warmup-scope coarse when cold.

Phases, in order:

1. microbench: offline batch generation throughput (256 x 128+128) — the
   raw chip number tracked since round 1 (vs the 500 tok/s per-engine
   rate of the reference's router perf rig,
   src/tests/perftest/fake-openai-server.py)
2. livestack: THE HEADLINE — real router + engine processes over
   HTTP/SSE; closed-loop saturation throughput plus an open-loop
   offered-QPS wave (the reference's run.sh QPS-sweep shape, where the
   p50-TTFT bar is defined)
3. northstar: the same workload in-process (no HTTP) — the engine's
   ceiling, for attribution of serving overhead
4. int8_8b: Llama-3-8B with int8 weight quantization on ONE 16 GiB v5e
   chip (the reference's headline model, model.yaml:1-28) — req/s, TTFT
   percentiles, HBM accounting

CPU-only pre-preflight phases (routing, robustness, fairness, tracing,
saturation, kvflow, hydration) run BEFORE the chip preflight so their
evidence survives a wedged TPU tunnel.

The final line is the ONE driver-parsed JSON: headline = served
closed-loop req/s vs the >=2.0 req/s bar, with every phase attached.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
SERVED_BASELINE_REQ_S = 2.0  # VERDICT r3 "done" bar for the served stack
TOTAL_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "3300"))

_t_start = time.monotonic()


def _remaining() -> float:
    return TOTAL_BUDGET_S - (time.monotonic() - _t_start)


def _emit(section: str, data: dict) -> None:
    """Print-and-flush one section's result the moment it exists — a
    later timeout cannot lose it (the driver keeps the tail)."""
    print(json.dumps({"bench_section": section, **data}), flush=True)


def _run_phase(section: str, argv: list[str], timeout_s: float,
               key: str | None = None, min_needed_s: float = 120.0) -> dict:
    """Run one phase as a subprocess; parse the last JSON line of its
    stdout. Returns {"error"/"skipped": ...} instead of raising so a bad
    phase never takes down the phases after it.

    The phase runs in its OWN process group: a timeout signals the whole
    group, so the engine/router grandchildren a wedged bench_livestack
    would otherwise orphan (holding the single-grant TPU tunnel and
    starving every later phase) die with it.
    """
    budget = min(timeout_s, _remaining() - 30.0)
    if budget < min_needed_s:
        result = {"skipped": f"budget: {_remaining():.0f}s left, "
                             f"need >={min_needed_s:.0f}s"}
        _emit(section, result)
        return result
    t0 = time.monotonic()
    proc = None
    try:
        proc = subprocess.Popen(
            [sys.executable, *argv], cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        try:
            out_b, _ = proc.communicate(timeout=budget)
            out = out_b.decode(errors="replace")
            result = _parse_phase_json(out, proc.returncode, key)
        except subprocess.TimeoutExpired:
            _kill_group(proc)
            out_b, _ = proc.communicate(timeout=30)
            out = (out_b or b"").decode(errors="replace")
            result = {"error": f"timeout after {budget:.0f}s",
                      "tail": out[-800:]}
    except Exception as e:  # noqa: BLE001 — phase isolation is the point
        if proc is not None and proc.poll() is None:
            _kill_group(proc)
        result = {"error": f"{type(e).__name__}: {e}"}
    result["phase_elapsed_s"] = round(time.monotonic() - t0, 1)
    _emit(section, result)
    return result


def _kill_group(proc: subprocess.Popen) -> None:
    """SIGTERM, a short grace, then ALWAYS SIGKILL the phase's whole
    process group — engine and router grandchildren included (they hold
    the TPU grant). The direct child dying is NOT enough to stop: a
    grandchild wedged in a native compile ignores SIGTERM and would
    otherwise keep the single-grant tunnel and the stdout pipe."""
    import signal

    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and proc.poll() is None:
        time.sleep(0.5)
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def _parse_phase_json(out: str, rc: int, key: str | None) -> dict:
    last_json = None
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                last_json = json.loads(line)
            except json.JSONDecodeError:
                pass
    if last_json is None:
        return {"error": f"no JSON in output (rc={rc})", "tail": out[-800:]}
    result = last_json.get(key, last_json) if key else last_json
    if rc != 0:
        result.setdefault("rc", rc)
    return result


# -- bench regression gate ---------------------------------------------------
# Every BENCH_r*.json the driver archives is a full phase tree; comparing
# the current run against the newest USABLE one turns the trajectory into
# a gate: a phase metric drifting past tolerance is named in the output
# instead of waiting for a human to diff two JSON blobs. Advisory by
# design — the gate never fails the run (a wedged-tunnel baseline like
# r04/r05 would otherwise poison every later run).
_REGRESSION_TOL_PCT = 15.0

# direction heuristics by metric-name markers; HIGHER-better is checked
# first because throughput names like req_per_s/tok_s also end in the
# lower-better "_s" suffix. Unknown direction -> not compared (counts,
# config echoes, booleans-as-ints).
_HIGHER_BETTER = ("req_per_s", "tok_s", "per_s", "throughput", "rate",
                  "qps", "goodput", "value", "hit")
_LOWER_BETTER = ("ttft", "latency", "overhead_pct", "lag", "stall",
                 "wait", "_ms", "_s")
_NEVER_COMPARED = ("elapsed_s", "rc", "n", "timeout", "budget")


def _metric_direction(path: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 unknown (skip)."""
    leaf = path.rsplit(".", 1)[-1]
    if any(leaf == m or leaf.endswith(m) for m in _NEVER_COMPARED):
        return 0
    if any(m in leaf for m in _HIGHER_BETTER):
        return 1
    if any(m in leaf for m in _LOWER_BETTER):
        return -1
    return 0


def _numeric_leaves(tree, prefix: str = "") -> dict[str, float]:
    """Flatten a phase tree to dotted-path -> numeric leaf (bools are
    NOT numbers here; lists are opaque — per-rep samples, not metrics)."""
    out: dict[str, float] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_numeric_leaves(v, f"{prefix}.{k}" if prefix else k))
    elif isinstance(tree, bool):
        pass
    elif isinstance(tree, (int, float)):
        out[prefix] = float(tree)
    return out


def _load_bench_baseline() -> tuple[str | None, dict | None]:
    """Newest BENCH_r*.json whose driver-parsed tree is usable. r04
    archived parsed=None (inspection crash) — skipped, older history
    still serves as the baseline."""
    import glob

    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")),
                       reverse=True):
        try:
            doc = json.loads(open(path, encoding="utf-8").read())
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and parsed:
            return os.path.basename(path), parsed
    return None, None


def _regression_gate(current: dict) -> dict:
    """Compare every shared numeric leaf against the newest usable
    baseline at ±_REGRESSION_TOL_PCT. Absent history -> baseline: none.
    NEVER raises and never fails the run — the flagged list is evidence
    in the trajectory, not a verdict."""
    try:
        fname, base = _load_bench_baseline()
        if base is None:
            return {"baseline": "none",
                    "tolerance_pct": _REGRESSION_TOL_PCT}
        b, c = _numeric_leaves(base), _numeric_leaves(current)
        flagged: list[dict] = []
        compared = 0
        for path, bv in sorted(b.items()):
            cv = c.get(path)
            if cv is None or bv == 0.0:
                continue  # metric absent this run / no baseline signal
            direction = _metric_direction(path)
            if direction == 0:
                continue
            compared += 1
            delta_pct = (cv - bv) / abs(bv) * 100.0
            if direction * delta_pct < -_REGRESSION_TOL_PCT:
                flagged.append({
                    "metric": path,
                    "baseline": bv,
                    "current": cv,
                    "delta_pct": round(delta_pct, 1),
                })
        flagged.sort(key=lambda r: -abs(r["delta_pct"]))
        return {
            "baseline": fname,
            "tolerance_pct": _REGRESSION_TOL_PCT,
            "compared": compared,
            "flagged": flagged[:40],
        }
    except Exception as e:  # noqa: BLE001 — advisory gate, never fatal
        return {"baseline": "none",
                "error": f"{type(e).__name__}: {e}"}


def run_microbench() -> dict:
    """Offline throughput: 256 concurrent 128-token prompts, 128 greedy
    tokens each, continuous batching over the paged fp8-capable pool.
    (Runs inside the `--phase micro` subprocess.)"""
    import numpy as np

    from vllm_production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.models.registry import resolve_model_config

    n_seqs, prompt_len, gen_len = 256, 128, 128
    model_cfg = resolve_model_config("llama-1b", max_model_len=1024,
                                     dtype="bfloat16")
    config = EngineConfig(
        model=model_cfg,
        cache=CacheConfig(block_size=16, num_blocks=None,
                          hbm_utilization=0.78),
        scheduler=SchedulerConfig(
            max_num_seqs=n_seqs,
            max_num_batched_tokens=n_seqs * prompt_len,
            decode_buckets=(n_seqs,),
            prefill_buckets=(32, prompt_len, 2048, n_seqs * prompt_len),
            decode_window=128,
            width_floor_blocks=1,
        ),
        parallel=ParallelConfig(tensor_parallel_size=1),
    )
    engine = LLMEngine(config)
    sampling = SamplingParams(max_tokens=gen_len, temperature=0.0,
                              ignore_eos=True)

    def make_prompts(seed0: int) -> list[list[int]]:
        return [
            list(np.random.RandomState(seed0 + i).randint(
                1, model_cfg.vocab_size, size=prompt_len))
            for i in range(n_seqs)
        ]

    # warmup compiles every program the measured wave hits
    engine.generate(make_prompts(10_000), sampling)
    elapsed = None
    for wave_seed in (0, 20_000):  # best of two: tunnel hiccup tolerance
        t0 = time.perf_counter()
        outs = engine.generate(make_prompts(wave_seed), sampling)
        wave = time.perf_counter() - t0
        gen = sum(len(o["token_ids"]) for o in outs)
        assert gen == n_seqs * gen_len, (gen, n_seqs * gen_len)
        elapsed = wave if elapsed is None else min(elapsed, wave)
    return {
        "tok_s": round(n_seqs * gen_len / elapsed, 1),
        "total_s": round(elapsed, 3),
        "vs_fake_engine_rate": round(n_seqs * gen_len / elapsed / 500.0, 2),
    }


def run_steploop_bench() -> dict:
    """Decode step-loop attribution (pipelined-engine PR): steps/s and
    host-sync fraction (sync_s / step wall) for the serial loop vs the
    two-deep pipelined loop, same model/workload. The pipelined loop
    dispatches window N+1 against speculatively-advanced state before
    window N's tokens reach the host, so the host-sync fraction is the
    direct measure of what the overlap buys. Small wave — the microbench
    above owns the headline throughput number."""
    import numpy as np

    from vllm_production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        SchedulerConfig,
    )
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.models.registry import resolve_model_config

    import gc

    n_seqs, prompt_len, gen_len = 64, 64, 128
    model_cfg = resolve_model_config("llama-1b", max_model_len=512,
                                     dtype="bfloat16")
    out: dict = {}
    engine = None
    for mode, async_on in (("sync", False), ("pipelined", True)):
        # free the previous mode's weights + KV pool BEFORE building the
        # next engine — two live pools would OOM the chip
        del engine
        gc.collect()
        config = EngineConfig(
            model=model_cfg,
            cache=CacheConfig(block_size=16, num_blocks=None,
                              hbm_utilization=0.70),
            scheduler=SchedulerConfig(
                max_num_seqs=n_seqs,
                max_num_batched_tokens=n_seqs * prompt_len,
                decode_buckets=(n_seqs,),
                prefill_buckets=(prompt_len, n_seqs * prompt_len),
                decode_window=8,  # many short windows: the step-loop regime
                width_floor_blocks=1,
            ),
            async_scheduling=async_on,
        )
        engine = LLMEngine(config)
        sampling = SamplingParams(max_tokens=gen_len, temperature=0.0,
                                  ignore_eos=True)
        prompts = [
            list(np.random.RandomState(5000 + i).randint(
                1, model_cfg.vocab_size, size=prompt_len))
            for i in range(n_seqs)
        ]
        engine.generate(prompts, sampling)  # warmup: compile the wave
        t_before = dict(engine.timing)
        t0 = time.perf_counter()
        outs = engine.generate(prompts, sampling)
        wall = time.perf_counter() - t0
        gen = sum(len(o["token_ids"]) for o in outs)
        dt = {k: engine.timing[k] - t_before[k] for k in t_before}
        out[mode] = {
            "tok_s": round(gen / wall, 1),
            "steps_s": round(dt["decode_n"] / wall, 2),
            "sync_frac": round(dt["sync_s"] / wall, 3),
            "overlap_frac": round(
                dt["overlap_s"] / dt["step_wall_s"], 3
            ) if dt["step_wall_s"] else 0.0,
            "rollbacks": dt["rollback_n"],
            "wall_s": round(wall, 3),
        }
    if out["sync"]["tok_s"]:
        out["pipeline_speedup"] = round(
            out["pipelined"]["tok_s"] / out["sync"]["tok_s"], 3
        )
    del engine
    gc.collect()
    try:
        out["speculative"] = run_steploop_spec_arms()
    except Exception as e:  # never lose the loop numbers to the rider
        out["speculative"] = {"error": str(e)}
    return out


def run_steploop_spec_arms() -> dict:
    """Speculative decoding × step loop (docs/36-speculative-decoding.md):
    speculative+pipelined vs pipelined-only vs speculative-serial on a
    repetition-friendly decode workload — decode tok/s, acceptance rate,
    host-sync fraction. Asserts the composed arm strictly beats BOTH
    baselines, that its streams are bitwise identical to the serial
    speculative loop, and that the goodput-ledger partition is exact.

    Workload: random prompts into a CYCLIC-decode fixture model — the
    tiny-llama preset with attention-output and MLP-down projections
    zeroed, so the hidden state is a function of the current token alone
    and greedy decode iterates a fixed token→token map into a short cycle
    (the same crafted-fixture idiom the identical-weights draft tests
    lean on). Perfectly periodic output is the n-gram proposer's home
    regime: acceptance approaches 1 and the verify dispatch (ONE forward
    over k+1 positions) replaces a decode window's w sequential forwards,
    which is exactly the economics speculation is supposed to buy. The
    fixture keeps the cycle shorter than the proposer's lookback on any
    vocab; STEPLOOP_SPEC_MODEL overrides the model on a chip big enough
    to amortize a larger vocab's longer cycles."""
    import gc
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from vllm_production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        SchedulerConfig,
    )
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.models.registry import resolve_model_config

    n_seqs, prompt_len, gen_len, spec_k = 8, 32, 256, 8
    model_cfg = resolve_model_config(
        os.environ.get("STEPLOOP_SPEC_MODEL", "tiny-llama"),
        max_model_len=512,
    )

    def fixture_params(params):
        def zero_mixing(path, x):
            name = "/".join(str(getattr(k, "key", k)) for k in path)
            if name.endswith("attn/wo") or name.endswith("mlp/down"):
                return jnp.zeros_like(x)
            return x

        return jax.tree_util.tree_map_with_path(zero_mixing, params)

    prompts = [
        list(np.random.RandomState(900 + i).randint(
            1, model_cfg.vocab_size, size=prompt_len))
        for i in range(n_seqs)
    ]
    sampling = SamplingParams(max_tokens=gen_len, temperature=0.0,
                              ignore_eos=True)
    out: dict = {}
    streams: dict[str, list[list[int]]] = {}
    engine = None
    for mode, async_on, k in (
        ("pipelined_only", True, 0),
        ("spec_serial", False, spec_k),
        ("spec_pipelined", True, spec_k),
    ):
        del engine
        gc.collect()
        engine = LLMEngine(EngineConfig(
            model=model_cfg,
            cache=CacheConfig(block_size=16, num_blocks=1024),
            scheduler=SchedulerConfig(
                max_num_seqs=n_seqs,
                max_num_batched_tokens=n_seqs * prompt_len,
                decode_buckets=(n_seqs,),
                prefill_buckets=(prompt_len, n_seqs * prompt_len),
                decode_window=8,
                width_floor_blocks=1,
                num_speculative_tokens=k,
            ),
            async_scheduling=async_on,
        ))
        engine.runner.params = fixture_params(engine.runner.params)
        engine.generate(prompts, sampling)  # warmup: compile the wave
        sched = engine.scheduler
        best = None
        for _ in range(2):  # best of two: scheduler-noise tolerance
            t_before = dict(engine.timing)
            prop0 = sched.spec_proposed_tokens
            acc0 = sched.spec_accepted_tokens
            t0 = time.perf_counter()
            outs = engine.generate(prompts, sampling)
            wall = time.perf_counter() - t0
            gen = sum(len(o["token_ids"]) for o in outs)
            assert gen == n_seqs * gen_len, (gen, n_seqs * gen_len)
            dt = {kk: engine.timing[kk] - t_before[kk] for kk in t_before}
            proposed = sched.spec_proposed_tokens - prop0
            accepted = sched.spec_accepted_tokens - acc0
            balance = engine.goodput_balance()
            streams[mode] = [o["token_ids"] for o in outs]
            wave = {
                "tok_s": round(gen / wall, 1),
                "acceptance_rate": round(accepted / proposed, 3)
                if proposed else 0.0,
                "proposed": proposed,
                "sync_frac": round(dt["sync_s"] / wall, 3),
                "overlap_frac": round(
                    dt["overlap_s"] / dt["step_wall_s"], 3
                ) if dt["step_wall_s"] else 0.0,
                "rollbacks": dt["rollback_n"],
                "ledger_balanced": bool(balance["balanced"]),
                "wall_s": round(wall, 3),
            }
            if best is None or wave["tok_s"] > best["tok_s"]:
                best = wave
        out[mode] = best
        engine.runner.shutdown(wait=True)
    # the PR 1 equivalence bar, speculation active: composing with the
    # pipeline must not move a single token
    out["streams_bitwise_equal"] = (
        streams["spec_serial"] == streams["spec_pipelined"]
    )
    out["composed_beats_pipelined_only"] = (
        out["spec_pipelined"]["tok_s"] > out["pipelined_only"]["tok_s"]
    )
    out["composed_beats_spec_serial"] = (
        out["spec_pipelined"]["tok_s"] > out["spec_serial"]["tok_s"]
    )
    assert out["streams_bitwise_equal"], "spec streams diverged across loops"
    assert all(out[m]["ledger_balanced"] for m in streams), out
    assert out["composed_beats_pipelined_only"], out
    # the composed-beats-serial claim is the PIPELINE's contribution —
    # host work hidden behind device compute. On the cpu backend the
    # "device" IS the host (same cores execute both), so there is nothing
    # to hide behind and chaining's extra dispatch shows up as pure
    # overhead; `host_cores` rides the JSON (the fleet-bench honesty-note
    # idiom) so a serialized CPU result reads as what it is. Asserted on
    # a real accelerator, reported otherwise.
    out["host_cores"] = os.cpu_count()
    out["backend"] = jax.default_backend()
    if out["backend"] != "cpu":
        assert out["composed_beats_spec_serial"], out
    return out


async def _routing_bench() -> dict:
    """KV-aware routing lookup cost, fan-out vs indexed (CPU-only — pure
    host-side code, so this number survives even when TPU preflight fails).

    Old request path: router → controller → /kv/lookup probe on EVERY
    engine (each probe walking the hash chain server-side) — measured here
    as real aiohttp servers. New path: the event-driven cluster KV index
    embedded in the router process (kv_index.ClusterKVIndex fed from each
    pool's KVEventLog) answered in-process with zero network hops. The same
    probe set runs through both; answers must MATCH (same pool state ⇒ same
    matched_tokens) and indexed must be >=10x fan-out lookups/s."""
    import asyncio

    import numpy as np
    from aiohttp import web

    from vllm_production_stack_tpu.engine.kv_cache import KVBlockPool
    from vllm_production_stack_tpu.engine.kv_controller import KVController
    from vllm_production_stack_tpu.kv_index import ClusterKVIndex

    N_ENGINES, BLOCK, N_PROMPTS, PROMPT_TOKENS = 4, 16, 32, 512
    N_LOOKUPS = 300
    pools = [KVBlockPool(2048, BLOCK) for _ in range(N_ENGINES)]

    # index first sees each pool EMPTY via snapshot, then ingests the
    # admissions through the real event stream — the push protocol under
    # measurement, not a shortcut bulk load. Liveness TTL off: these
    # simulated engines never heartbeat, and the fan-out phase runs
    # between the feed and the indexed lookups
    index = ClusterKVIndex(stale_after_s=None)
    urls = [None] * N_ENGINES  # filled once servers bind

    rng = np.random.RandomState(7)
    prompts = [
        [int(t) for t in rng.randint(1, 30000, size=PROMPT_TOKENS)]
        for _ in range(N_PROMPTS)
    ]

    def admit(pool: KVBlockPool, ids: list[int]) -> None:
        parent = pool.root_hash()
        for i in range(len(ids) // BLOCK):
            blk = pool.allocate()
            assert blk is not None, "routing bench pool sized too small"
            parent = pool.register_full_block(
                blk, parent, tuple(ids[i * BLOCK : (i + 1) * BLOCK])
            )

    # fan-out side: each engine is a real aiohttp server whose /kv/lookup
    # walks its pool's chain — the per-probe server-side work the old path
    # pays on every routed request
    def engine_app(pool: KVBlockPool) -> web.Application:
        async def kv_lookup(request):
            body = await request.json()
            n = pool.match_length(list(body["token_ids"]))
            return web.json_response({"matched_tokens": n})

        app = web.Application()
        app.router.add_post("/kv/lookup", kv_lookup)
        return app

    runners = []
    try:
        for i, pool in enumerate(pools):
            runner = web.AppRunner(engine_app(pool))
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = runner.addresses[0][1]
            urls[i] = f"http://127.0.0.1:{port}"
            runners.append(runner)

        # snapshot (empty), admit, then drain the REAL event logs into the
        # index — each prompt lands on one engine; every third prompt's
        # first half is also resident on the next engine
        for i, pool in enumerate(pools):
            epoch, seq, hashes = pool.snapshot_events()
            index.apply({
                "engine": urls[i], "epoch": epoch, "block_size": BLOCK,
                "snapshot": True, "seq": seq,
                "hashes": [f"{h:x}" for h in hashes],
            })
        for j, pr in enumerate(prompts):
            k = j % N_ENGINES
            admit(pools[k], pr)
            if j % 3 == 0:
                admit(pools[(k + 1) % N_ENGINES], pr[: PROMPT_TOKENS // 2])
        for i, pool in enumerate(pools):
            while True:
                seq_start, events = pool.events.drain()
                if not events:
                    break
                reply = index.apply({
                    "engine": urls[i], "epoch": pool.events.epoch,
                    "block_size": BLOCK, "seq_start": seq_start,
                    "events": events,
                })
                assert reply.get("status") == "ok", reply

        controller = KVController(urls, mode="fanout")
        c_runner = web.AppRunner(controller.build_app())
        await c_runner.setup()
        c_site = web.TCPSite(c_runner, "127.0.0.1", 0)
        await c_site.start()
        runners.append(c_runner)
        c_url = f"http://127.0.0.1:{c_runner.addresses[0][1]}"

        # probe set: hits (full prompt), partial hits (prompt + junk tail),
        # misses (fresh random) — identical for both paths
        probes = []
        for i in range(N_LOOKUPS):
            pr = prompts[i % N_PROMPTS]
            kind = i % 3
            if kind == 0:
                probes.append(pr)
            elif kind == 1:
                probes.append(
                    pr + [int(t) for t in rng.randint(1, 30000, size=64)]
                )
            else:
                probes.append(
                    [int(t) for t in rng.randint(1, 30000, size=256)]
                )

        import aiohttp

        fanout_lat, fanout_ans = [], []
        async with aiohttp.ClientSession() as sess:
            for ids in probes:
                t0 = time.perf_counter()
                async with sess.post(
                    c_url + "/lookup", json={"token_ids": ids}
                ) as resp:
                    data = await resp.json()
                fanout_lat.append(time.perf_counter() - t0)
                fanout_ans.append(int(data["matched_tokens"]))

        indexed_lat, indexed_ans = [], []
        for ids in probes:
            t0 = time.perf_counter()
            _, n = index.lookup_token_ids(ids)
            indexed_lat.append(time.perf_counter() - t0)
            indexed_ans.append(n)
    finally:
        for runner in runners:
            await runner.cleanup()

    mismatches = sum(1 for a, b in zip(fanout_ans, indexed_ans) if a != b)

    def pct(lat, p):
        return round(sorted(lat)[min(len(lat) - 1, int(p * len(lat)))] * 1e3, 3)

    fanout_lps = round(N_LOOKUPS / sum(fanout_lat), 1)
    indexed_lps = round(N_LOOKUPS / sum(indexed_lat), 1)
    return {
        "engines": N_ENGINES,
        "lookups": N_LOOKUPS,
        "probes_per_fanout_lookup": controller.probes_sent / N_LOOKUPS,
        "fanout": {"lookups_s": fanout_lps,
                   "p50_ms": pct(fanout_lat, 0.50),
                   "p99_ms": pct(fanout_lat, 0.99)},
        "indexed": {"lookups_s": indexed_lps,
                    "p50_ms": pct(indexed_lat, 0.50),
                    "p99_ms": pct(indexed_lat, 0.99)},
        "speedup": round(indexed_lps / fanout_lps, 1) if fanout_lps else None,
        "answers_match": mismatches == 0,
        "mismatches": mismatches,
    }


def _phase_routing_main() -> None:
    """Subprocess entry for the CPU-only routing lookup bench."""
    import asyncio

    result = asyncio.run(_routing_bench())
    print(json.dumps({"routing": result}), flush=True)


async def _robustness_bench() -> dict:
    """Request-lifecycle robustness numbers (docs/26-robustness.md), on a
    CPU tiny engine behind its real HTTP server so the section survives a
    wedged TPU tunnel:

    - **shed latency** — how fast an overloaded engine turns a request
      away (429 + Retry-After) under a flood that overruns
      max_waiting_requests. Slow shedding is no shedding: the 429 must
      come back orders of magnitude faster than serving the request.
    - **drain time** — how long POST /drain?wait=true takes to pass the
      drain barrier with a stream in flight — the bound helm's preStop
      hook + terminationGracePeriodSeconds rely on.
    """
    import asyncio
    from dataclasses import replace

    from aiohttp.test_utils import TestClient, TestServer

    from vllm_production_stack_tpu.engine.config import EngineConfig
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.server import EngineServer

    N_FLOOD = 32
    cfg = EngineConfig.tiny()
    cfg = cfg.replace(
        scheduler=replace(cfg.scheduler, max_waiting_requests=4)
    )
    srv = EngineServer(
        LLMEngine(cfg), served_model_name="tiny", drain_timeout_s=30.0
    )
    client = TestClient(TestServer(srv.build_app()))
    await client.start_server()
    try:
        body = {"model": "tiny", "prompt": [5, 6, 7, 8],
                "temperature": 0.0, "max_tokens": 24, "ignore_eos": True}
        # warm up: the flood must measure shedding, not XLA compiles
        r = await client.post("/v1/completions", json=dict(body, max_tokens=4))
        assert r.status == 200, await r.text()

        async def one():
            t0 = time.monotonic()
            r = await client.post("/v1/completions", json=body)
            await r.read()
            return r.status, time.monotonic() - t0, r.headers.get("Retry-After")

        results = await asyncio.gather(*[one() for _ in range(N_FLOOD)])
        shed_lat = sorted(lat for st, lat, _ in results if st == 429)
        served_lat = sorted(lat for st, lat, _ in results if st == 200)
        retry_after = [float(ra) for st, _, ra in results if st == 429 and ra]

        def pct(lat, p):
            if not lat:
                return None
            return round(lat[min(len(lat) - 1, int(p * len(lat)))] * 1e3, 2)

        # drain with a stream in flight (one-way — runs LAST)
        stream_task = asyncio.ensure_future(
            client.post("/v1/completions",
                        json=dict(body, max_tokens=48, stream=True))
        )
        await asyncio.sleep(0.05)
        t0 = time.monotonic()
        r = await client.post("/drain?wait=true")
        drain_s = time.monotonic() - t0
        drained = (await r.json()).get("drained")
        stream_resp = await stream_task
        stream_text = await stream_resp.text()
        return {
            "flood_requests": N_FLOOD,
            "served": len(served_lat),
            "shed": len(shed_lat),
            "shed_latency_p50_ms": pct(shed_lat, 0.50),
            "shed_latency_p99_ms": pct(shed_lat, 0.99),
            "served_latency_p50_ms": pct(served_lat, 0.50),
            "retry_after_s": retry_after[0] if retry_after else None,
            "drain_s": round(drain_s, 3),
            "drained": bool(drained),
            "drained_stream_clean": "data: [DONE]" in stream_text,
        }
    finally:
        await client.close()


async def _tracing_bench() -> dict:
    """Request-tracing spine overhead (docs/28-request-tracing.md), on a
    CPU tiny engine behind its real HTTP server — the same flood shape as
    the robustness phase, run twice: --request-tracing false, then true.
    The spine's cost must be MEASURED, not asserted (acceptance bar:
    ≤2% p50 latency with tracing enabled). One engine serves both modes
    (the server rebuilds around it), so XLA compiles are paid once and
    the comparison is compile-noise-free."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from vllm_production_stack_tpu.engine.config import EngineConfig
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.server import EngineServer

    N_CLIENTS = 8
    N_PER_CLIENT = 8
    REPS = 6  # alternate off/on and keep each mode's BEST rep: a CPU
    # box's scheduling jitter (tens of ms on a shared host) dwarfs the
    # spine's per-request cost, and min-of-reps is the standard
    # noise-robust estimator — 3 reps were measured insufficient here
    body = {"model": "tiny", "prompt": [5, 6, 7, 8], "temperature": 0.0,
            "max_tokens": 12, "ignore_eos": True}
    engine = LLMEngine(EngineConfig.tiny())

    def pct(lat, p):
        if not lat:
            return None
        return round(lat[min(len(lat) - 1, int(p * len(lat)))] * 1e3, 2)

    last_buffered = 0

    async def flood(tracing: bool) -> list[float]:
        nonlocal last_buffered
        srv = EngineServer(
            engine, served_model_name="tiny", request_tracing=tracing
        )
        client = TestClient(TestServer(srv.build_app()))
        await client.start_server()
        try:
            lat: list[float] = []

            async def one_client(n: int):
                for _ in range(n):
                    t0 = time.monotonic()
                    r = await client.post("/v1/completions", json=body)
                    await r.read()
                    assert r.status == 200, await r.text()
                    lat.append(time.monotonic() - t0)

            await asyncio.gather(
                *[one_client(N_PER_CLIENT) for _ in range(N_CLIENTS)]
            )
            dbg = await (await client.get("/debug/requests")).json()
            last_buffered = dbg.get("finished_buffered", 0)
            return lat
        finally:
            await client.close()

    async def settle_compiles(timeout_s=60.0):
        """Wait until no background XLA compile is queued or running —
        the compiler's idle gate fires exactly when a flood stops, i.e.
        right inside the next measurement window, and the CPU it steals
        dwarfs the spine cost being measured."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            with engine.runner._bg_lock:
                if not engine.runner._bg_inflight:
                    return
            await asyncio.sleep(0.25)

    # untimed warmup floods: the measured passes must compare the spine's
    # cost, not who paid the XLA compiles for the flood's batch shapes —
    # and one more after the compile settle so both modes start from the
    # same steady state (flood latency drifts downward while warming)
    for _ in range(2):
        await flood(False)
    await settle_compiles()
    await flood(False)
    # POOL the latencies of all reps per mode (alternating order, so
    # box-level drift lands evenly in both pools): percentiles over the
    # pooled distribution are far more stable than any single flood's —
    # one flood's p50 swings several percent with queue-phase alignment,
    # which would drown the spine's tens-of-µs per-request cost
    pools: dict[bool, list[float]] = {False: [], True: []}
    buffered = {False: 0, True: 0}
    for _ in range(REPS):
        for mode in (False, True):
            pools[mode].extend(await flood(mode))
            buffered[mode] = last_buffered

    def summarize(mode: bool) -> dict:
        lat = sorted(pools[mode])
        return {
            "tracing": mode,
            # fastest single request: the tightest bound on per-request
            # added cost (immune to queue-phase jitter entirely)
            "min_ms": round(lat[0] * 1e3, 2),
            "p50_ms": pct(lat, 0.50),
            "p99_ms": pct(lat, 0.99),
            "mean_ms": round(sum(lat) / len(lat) * 1e3, 2),
            "buffered_traces": buffered[mode],
        }

    off, on = summarize(False), summarize(True)
    return {
        "requests_per_mode": N_CLIENTS * N_PER_CLIENT * REPS,
        "reps": REPS,
        "off": off,
        "on": on,
        "p50_overhead_pct": round(
            (on["p50_ms"] / off["p50_ms"] - 1.0) * 100.0, 2
        ),
        "mean_overhead_pct": round(
            (on["mean_ms"] / off["mean_ms"] - 1.0) * 100.0, 2
        ),
        "min_overhead_pct": round(
            (on["min_ms"] / off["min_ms"] - 1.0) * 100.0, 2
        ),
    }


async def _structured_bench() -> dict:
    """Structured-output serving (docs/41-structured-output.md), CPU-only
    and pre-preflight: an agent swarm — concurrent chat sessions sharing
    ONE system+tools prefix — where every turn is a forced
    schema-constrained tool call (`tool_choice: "required"`).

    Evidence in the BENCH trajectory:
    - valid tool-call rate 1.0 with enforcement vs ~0 without (same
      model, same prompts — the grammar is the only difference)
    - constrained-vs-unconstrained decode overhead at MATCHED decode
      length (the unconstrained arm replays the constrained arm's median
      completion length with ignore_eos, so both arms run the same
      number of decode steps and the delta prices the mask alone)
    - TTFT under the swarm (streamed probes)
    - shared-prefix hit rate (every agent rides the same system+tools
      prefill)
    - ZERO new compiled programs after warmup — the mask is data, not
      shape, so constrained traffic must never recompile
    - bitwise serial-vs-pipelined identity of a constrained stream
    """
    import asyncio
    import dataclasses

    from aiohttp.test_utils import TestClient, TestServer

    from vllm_production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, ModelConfig, SchedulerConfig,
    )
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.engine.server import EngineServer

    N_AGENTS = 16
    REPS = 3
    N_TTFT = 8

    TOOLS = [{"type": "function", "function": {
        "name": "record_result",
        "description": "Record one benchmark observation.",
        "parameters": {"type": "object", "properties": {
            "status": {"enum": ["pass", "fail", "flaky"]},
            "cached": {"type": "boolean"},
            "tier": {"enum": [0, 1, 2]},
        }},
    }}]
    SYSTEM = ("You are one recorder in a swarm of benchmark agents. "
              "Observe the run named in the user turn and record exactly "
              "one observation by calling the tool.")

    # the tool-steering preamble alone outgrows the 256-token tiny
    # context, so the swarm engine gets a longer one
    engine = LLMEngine(EngineConfig(
        model=ModelConfig.tiny(max_model_len=1024),
        cache=CacheConfig(block_size=8, num_blocks=1536),
        scheduler=SchedulerConfig(
            max_num_seqs=8, max_num_batched_tokens=1024,
            decode_buckets=(4, 8), prefill_buckets=(256, 512, 1024),
        ),
    ))
    srv = EngineServer(engine, served_model_name="tiny")
    client = TestClient(TestServer(srv.build_app()))
    await client.start_server()

    def body(i: int, constrained: bool, max_tokens: int) -> dict:
        return {
            "model": "tiny",
            "messages": [
                {"role": "system", "content": SYSTEM},
                {"role": "user", "content": f"run #{i}: record it"},
            ],
            "tools": TOOLS,
            "tool_choice": "required" if constrained else "auto",
            # unconstrained replays the constrained arm's decode length
            "ignore_eos": not constrained,
            "temperature": 0.0, "max_tokens": max_tokens,
        }

    async def one(i: int, constrained: bool, max_tokens: int):
        t0 = time.monotonic()
        r = await client.post("/v1/chat/completions",
                              json=body(i, constrained, max_tokens))
        doc = await r.json()
        lat = time.monotonic() - t0
        assert r.status == 200, doc
        calls = doc["choices"][0]["message"].get("tool_calls") or []
        ok = False
        if len(calls) == 1 and calls[0]["function"]["name"] == "record_result":
            try:
                json.loads(calls[0]["function"]["arguments"])
                ok = True
            except ValueError:
                ok = False
        n_out = (doc.get("usage") or {}).get("completion_tokens") or 0
        return lat, ok, n_out

    async def flood(constrained: bool, max_tokens: int):
        return await asyncio.gather(
            *[one(i, constrained, max_tokens) for i in range(N_AGENTS)]
        )

    async def settle_compiles(timeout_s=60.0):
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            with engine.runner._bg_lock:
                if not engine.runner._bg_inflight:
                    return
            await asyncio.sleep(0.25)

    def pct(lat, p):
        if not lat:
            return None
        return round(lat[min(len(lat) - 1, int(p * len(lat)))] * 1e3, 2)

    try:
        # untimed warmup: pay XLA compiles + the grammar build, and learn
        # the constrained arm's decode length for the matched replay
        warm = await flood(True, 192)
        n_toks = sorted(n for _, _, n in warm)
        matched = max(8, n_toks[len(n_toks) // 2])
        await flood(False, matched)
        await settle_compiles()
        grammar_builds = list(engine.stats().grammar_build_times)
        programs0 = len(engine.runner._aot_exec)
        bg0 = engine.runner.bg_compiles

        pools: dict[bool, list[float]] = {True: [], False: []}
        valid = {True: 0, False: 0}
        total = {True: 0, False: 0}
        for _ in range(REPS):
            for mode in (True, False):
                # constrained stops naturally at its accepting EOS (cap is
                # slack); unconstrained replays the matched median length
                for lat, ok, _n in await flood(mode, 192 if mode else matched):
                    pools[mode].append(lat)
                    valid[mode] += int(ok)
                    total[mode] += 1

        # streamed TTFT probes, constrained: time to the first SSE chunk
        async def ttft_one(i: int) -> float:
            t0 = time.monotonic()
            r = await client.post(
                "/v1/chat/completions",
                json=dict(body(i, True, 192), stream=True),
            )
            assert r.status == 200
            async for line in r.content:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    t = time.monotonic() - t0
                    r.close()
                    return t
            raise AssertionError("stream produced no chunk")

        ttft = sorted(await asyncio.gather(
            *[ttft_one(i) for i in range(N_TTFT)]
        ))

        snap = engine.stats()
        con = sorted(pools[True])
        unc = sorted(pools[False])
        result = {
            "agents": N_AGENTS,
            "requests_per_arm": total[True],
            "matched_decode_tokens": matched,
            "valid_rate_constrained": round(valid[True] / total[True], 3),
            "valid_rate_unconstrained": round(valid[False] / total[False], 3),
            "constrained_p50_ms": pct(con, 0.50),
            "constrained_p99_ms": pct(con, 0.99),
            "unconstrained_p50_ms": pct(unc, 0.50),
            "p50_overhead_pct": round(
                (pct(con, 0.50) / pct(unc, 0.50) - 1.0) * 100.0, 2
            ),
            "ttft_p50_ms": pct(ttft, 0.50),
            "ttft_p99_ms": pct(ttft, 0.99),
            "prefix_cache_hit_rate": round(snap.prefix_cache_hit_rate, 3),
            "grammar_builds": len(grammar_builds),
            "grammar_build_ms": [round(s * 1e3, 1) for s in grammar_builds],
            "new_programs_after_warmup":
                len(engine.runner._aot_exec) - programs0,
            "bg_compiles_after_warmup": engine.runner.bg_compiles - bg0,
            "structured_outcomes": dict(snap.structured_outcomes or {}),
        }
    finally:
        await client.close()
        engine.runner.shutdown(wait=True)

    # rider: serial vs pipelined constrained streams must be bitwise
    # identical (the async step loop may not change one masked token)
    spec = {"kind": "json_schema", "schema": {
        "type": "object", "properties": {
            "ok": {"type": "boolean"},
            "mode": {"enum": ["fast", "slow"]},
        },
    }}
    prompts = [list(range(5, 12)), list(range(40, 52))]
    streams = []
    for async_on in (True, False):
        eng = LLMEngine(EngineConfig.tiny().replace(async_scheduling=async_on))
        try:
            sp = SamplingParams(max_tokens=48, temperature=0.0)
            sp = dataclasses.replace(
                sp, grammar=eng.grammar_cache.get(spec)[0]
            )
            outs = eng.generate(prompts, sp)
            streams.append([o["token_ids"] for o in outs])
        finally:
            eng.runner.shutdown(wait=True)
    result["bitwise_serial_eq_pipelined"] = streams[0] == streams[1]
    return result


def _phase_structured_main() -> None:
    """Subprocess entry for the CPU-only structured-output bench. Forces
    CPU before anything touches jax — runs pre-preflight, so the
    grammar-enforcement evidence survives a wedged TPU tunnel."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_structured_bench())
    print(json.dumps({"structured": result}), flush=True)


async def _compile_bench() -> dict:
    """XLA compile telemetry (docs/42-compile-telemetry.md), CPU-only and
    pre-preflight: proves the pad-up guarantee the CompileWatch exists to
    police, on a mixed workload that walks every program-key dimension.

    Evidence in the BENCH trajectory:
    - ZERO mid-traffic compiles after coarse warmup across repeated mixed
      waves (bucket-ladder sweep + three grammar schemas + ngram spec
      decode) — the serving-path guarantee, now measured, not assumed
    - GET /debug/programs serves a non-empty inventory with per-program
      compile walls and dispatch counts
    - the storm arm feeds a cold engine unpadded shapes with a threshold
      of 3: the detector trips, and the ONE structured report names the
      offending shapes
    - watch-off vs watch-on p50 at the ≤2% noise floor (the dispatch-path
      bookkeeping must be free)
    """
    import asyncio
    import dataclasses

    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer

    from vllm_production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, ModelConfig, SchedulerConfig,
    )
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.engine.server import EngineServer

    def make_config(**overrides) -> EngineConfig:
        return EngineConfig(
            model=ModelConfig.tiny(max_model_len=512),
            cache=CacheConfig(block_size=8, num_blocks=320),
            scheduler=SchedulerConfig(
                max_num_seqs=4, max_num_batched_tokens=128,
                decode_buckets=(2, 4), prefill_buckets=(32, 64, 128),
                decode_window=4, num_speculative_tokens=2,
            ),
        ).replace(**overrides)

    rng = np.random.RandomState(17)
    # the spec-decode arm uses FIXED prompts, one row per call: verify
    # program shapes derive from proposal lengths and batch composition,
    # so fresh random tokens each wave would compile fresh verify
    # programs forever and the steady-state assertion could never hold
    spec_rng = np.random.RandomState(23)
    SPEC_PROMPTS = [
        [int(t) for t in spec_rng.randint(1, 500, size=6)] * 4,
        [int(t) for t in spec_rng.randint(1, 500, size=6)] * 3,
    ]
    SCHEMAS = [
        {"kind": "json_schema", "schema": {
            "type": "object", "properties": {"ok": {"type": "boolean"}},
        }},
        {"kind": "json_schema", "schema": {
            "type": "object",
            "properties": {"mode": {"enum": ["fast", "slow"]}},
        }},
        {"kind": "json_schema", "schema": {
            "type": "object", "properties": {
                "tier": {"enum": [0, 1, 2]},
                "cached": {"type": "boolean"},
            },
        }},
    ]

    def mixed_wave(engine: LLMEngine) -> None:
        """One pass over every program-key dimension: prefill bucket
        ladder, grammar-keyed decode programs, spec-decode verify."""
        vocab = engine.config.model.vocab_size
        greedy = SamplingParams(
            max_tokens=6, temperature=0.0, ignore_eos=True
        )
        for plen in (20, 56, 120):  # pads to buckets 32 / 64 / 128
            engine.generate(
                [[int(t) for t in rng.randint(1, vocab, size=plen)]
                 for _ in range(3)],
                greedy,
            )
        for spec in SCHEMAS:
            sp = dataclasses.replace(
                SamplingParams(max_tokens=24, temperature=0.0),
                grammar=engine.grammar_cache.get(spec)[0],
            )
            engine.generate(
                [[int(t) for t in rng.randint(1, vocab, size=12)]], sp
            )
        # repeated tail -> the ngram proposer fires -> verify dispatches
        for prompt in SPEC_PROMPTS:
            engine.generate(
                [prompt],
                SamplingParams(max_tokens=12, temperature=0.0,
                               ignore_eos=True),
            )

    async def settle(engine: LLMEngine, timeout_s: float = 60.0) -> None:
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            with engine.runner._bg_lock:
                if not engine.runner._bg_inflight:
                    return
            await asyncio.sleep(0.25)

    def by_trigger(compiles: dict) -> dict:
        out: dict[str, int] = {}
        for k, v in compiles.items():
            trig = k.rsplit("/", 1)[-1]
            out[trig] = out.get(trig, 0) + v
        return out

    def watch_overhead(engine: LLMEngine) -> dict:
        """Watch-on vs watch-off decode-wave p50 — the blackbox/
        saturation estimator (12 alternating reps, within-pair order
        flipped, step loop driven directly to dodge aiohttp jitter)."""
        vocab = engine.config.model.vocab_size
        prompts = [
            [int(t) for t in rng.randint(1, vocab, size=16)]
            for _ in range(8)
        ]
        sp = SamplingParams(max_tokens=24, temperature=0.0, ignore_eos=True)
        for _ in range(3):  # pay any straggler compile before measuring
            engine.generate(prompts, sp)
        REPS = 12
        times: dict[bool, list[float]] = {False: [], True: []}
        for rep in range(REPS):
            order = (False, True) if rep % 2 == 0 else (True, False)
            for watching in order:
                engine.compile_watch.enabled = watching
                t0 = time.perf_counter()
                outs = engine.generate(prompts, sp)
                times[watching].append(time.perf_counter() - t0)
                lens = [len(o["token_ids"]) for o in outs]
                assert sum(lens) == 8 * 24, lens
        engine.compile_watch.enabled = True

        def p50(xs):
            return sorted(xs)[len(xs) // 2]

        off_p50, on_p50 = p50(times[False]), p50(times[True])
        result = {
            "reps": REPS,
            "off_p50_ms": round(off_p50 * 1e3, 2),
            "on_p50_ms": round(on_p50 * 1e3, 2),
            "p50_overhead_pct": round((on_p50 / off_p50 - 1.0) * 100.0, 2),
        }
        assert result["p50_overhead_pct"] <= 2.0, result
        result["overhead_ok"] = True
        return result

    # -- main arm: warmed engine, mixed traffic, zero mid-traffic compiles
    # storm threshold lifted way above the lazy shapes a tiny test engine
    # legitimately compiles on its first wave (verify + grammar-keyed
    # decode programs are not in the coarse lattice) — the storm DETECTOR
    # is exercised by the dedicated cold arm below
    engine = LLMEngine(make_config(compile_storm_threshold=50))
    try:
        t0 = time.monotonic()
        warm_passes = engine.warmup(scope="coarse")
        warm_s = time.monotonic() - t0
        # two untimed waves: pay the lazy shapes coarse warmup leaves
        # (grammar tables, grammar-keyed decode programs, verify) so the
        # measured waves run against a fully-populated program cache
        mixed_wave(engine)
        mixed_wave(engine)
        await settle(engine)
        base_snap = engine.compile_watch.stats_snapshot()
        mid0 = base_snap["mid_traffic"]

        for _ in range(3):
            mixed_wave(engine)
        await settle(engine)
        snap = engine.compile_watch.stats_snapshot()
        mid_traffic_after = snap["mid_traffic"] - mid0
        assert mid_traffic_after == 0, (
            f"{mid_traffic_after} mid-traffic compiles in steady state "
            f"(pad-up guarantee broken): {snap['compiles']}"
        )
        overhead = watch_overhead(engine)

        # server starts AFTER all blocking-generate traffic: _on_startup
        # spins the AsyncEngine step loop, which would co-drive step()
        # and steal outputs from engine.generate's collector (the
        # inventory read is the only thing that needs HTTP)
        srv = EngineServer(engine, served_model_name="tiny")
        client = TestClient(TestServer(srv.build_app()))
        await client.start_server()
        try:
            r = await client.get("/debug/programs")
            payload = await r.json()
        finally:
            await client.close()
        assert r.status == 200, payload
        assert payload["programs"], "empty inventory after mixed traffic"
        hits, misses = snap["hits"], snap["misses"]
        result = {
            "coarse_warmup_programs": warm_passes,
            "coarse_warmup_s": round(warm_s, 1),
            "inventory_programs": len(payload["programs"]),
            "compiles_by_trigger": by_trigger(snap["compiles"]),
            "mid_traffic_compiles": mid_traffic_after,
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_rate": round(hits / max(1, hits + misses), 3),
            "grammar_builds": sum(
                v for k, v in snap["compiles"].items()
                if k.startswith("grammar/")
            ),
            "watch_overhead": overhead,
        }
    finally:
        engine.runner.shutdown(wait=True)

    # -- storm arm: a COLD engine fed unpadded shapes, threshold 3 — the
    # detector must trip once and the report must name the shapes
    storm_engine = LLMEngine(make_config(
        compile_storm_threshold=3, compile_storm_window_s=60.0,
    ))
    try:
        vocab = storm_engine.config.model.vocab_size
        for plen in (20, 56, 120):  # three cold sync compiles, no warmup
            storm_engine.generate(
                [[int(t) for t in rng.randint(1, vocab, size=plen)]],
                SamplingParams(max_tokens=4, temperature=0.0,
                               ignore_eos=True),
            )
        watch = storm_engine.compile_watch
        report = watch.last_storm_report
        assert watch.storms_total >= 1, dict(watch.compiles)
        assert report and report["shapes"], report
        named = [s["key"] for s in report["shapes"]]
        assert any("'prefill'" in k for k in named), named
        result["storm"] = {
            "storms": watch.storms_total,
            "threshold": 3,
            "window_s": 60.0,
            "mid_traffic_compiles": report["mid_traffic_compiles"],
            "shapes_named": named[:4],
        }
    finally:
        storm_engine.runner.shutdown(wait=True)
    return result


def _phase_compile_main() -> None:
    """Subprocess entry for the CPU-only compile-telemetry bench (pad-up
    guarantee + storm detector + watch overhead, docs/42-compile-
    telemetry.md). Forces CPU before anything touches jax — this phase
    watches compiles, so its evidence must survive a wedged chip."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_compile_bench())
    print(json.dumps({"compile": result}), flush=True)


async def _blackbox_bench() -> dict:
    """Flight recorder / watchdog / postmortem evidence (docs/37-flight-
    recorder.md), CPU-only and pre-preflight — the phase exists precisely
    because a wedged chip produces no request-vantage evidence, so its own
    evidence must survive a wedged TPU tunnel.

    Three DISTINCT wedges injected with the chaos harness
    (testing/faults.py), each individually NAMED by the watchdog
    (correct thread=/kind=) with a postmortem JSON written for each:

    1. **stalled fetcher** — the hydration fetcher blocks under the
       disk-tier lock (faults.hold_lock): stale_heartbeat,
       thread=hydration_fetch;
    2. **blackholed publisher** — the KV-event publisher's resync POST
       lands in a black hole (faults.black_hole): stale_heartbeat,
       thread=kv_event_publisher;
    3. **frozen step loop** — engine.step blocks mid-request
       (faults.frozen_step_loop) behind the REAL HTTP server:
       stale_heartbeat, thread=step, /ready flips 503 while /health stays
       green, and the stall counter + heartbeat age render on /metrics.

    Plus the noise-floor bar: a recorder-on vs recorder-off decode-wave
    flood (alternating reps, p50 of wave wall times — the saturation
    phase's proven estimator) must show ≤2% p50 overhead — same bar as
    the StepMeter.
    """
    import asyncio
    import tempfile
    from dataclasses import replace as _dc_replace

    from aiohttp.test_utils import TestClient, TestServer

    from vllm_production_stack_tpu.engine.config import EngineConfig
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.flightrec import (
        PostmortemDumper,
        ThreadRegistry,
        Watchdog,
    )
    from vllm_production_stack_tpu.engine.server import EngineServer
    from vllm_production_stack_tpu.testing import faults

    pm_dir = tempfile.mkdtemp(prefix="tpu-blackbox-pm-")
    wedges: dict = {}

    async def _check_dump(dumper: PostmortemDumper, thread: str) -> dict:
        # the watchdog sets `stalled` BEFORE its report/dump finishes —
        # await the episode's dump WHILE the wedge is still held, so the
        # captured heartbeat table shows the stall, not the recovery
        deadline = time.monotonic() + 5.0
        while dumper.dumps_written < 1 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert dumper.dumps_written >= 1, f"no postmortem for {thread}"
        doc = json.loads(open(dumper.last_path, encoding="utf-8").read())
        assert doc["trigger"] == "watchdog"
        hb = doc["heartbeats"][thread]
        assert hb["stale"] is True, hb
        return {"path": dumper.last_path, "age_s": hb["age_s"]}

    async def _await_stall(wd: Watchdog, timeout_s: float = 8.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if wd.stalled is not None:
                return wd.stalled
            await asyncio.sleep(0.05)
        raise AssertionError("watchdog never named the stall")

    # -- wedge 1: fetcher stalled under the disk-tier lock -----------------
    async def wedge_fetcher() -> dict:
        from vllm_production_stack_tpu.engine.hydration import (
            HydrationChunk,
            HydrationPlan,
        )

        cfg = EngineConfig.tiny()
        cfg = cfg.replace(cache=_dc_replace(
            cfg.cache, disk_kv_dir=tempfile.mkdtemp(prefix="bb-disk-"),
            disk_kv_gib=0.05,
        ))
        engine = LLMEngine(cfg)
        engine.threads.register("hydration_fetch", stall_after_s=0.3)
        dumper = PostmortemDumper(
            pm_dir, recorder=engine.flightrec, registry=engine.threads
        )
        wd = Watchdog(
            engine.threads, recorder=engine.flightrec, interval_s=0.05,
            on_stall=lambda r: dumper.dump("watchdog", "fetcher wedge"),
        )
        wd.start()
        chunk = HydrationChunk(index=0, start_block=0, hashes=[7],
                               tiers=["disk"], decision="load")
        plan = HydrationPlan("bb-req", [chunk], block_size=8,
                             deadline=time.monotonic() + 60.0, estimates={})
        t0 = time.monotonic()
        with faults.hold_lock(engine.host_tier.disk._mu):
            engine.hydrator._ensure_thread()
            engine.hydrator._q.put((plan, chunk))
            stall = await _await_stall(wd)
            detect_s = time.monotonic() - t0
            pm = await _check_dump(dumper, "hydration_fetch")
        threads = {f["thread"] for f in stall["findings"]}
        kinds = {f["kind"] for f in stall["findings"]}
        assert threads == {"hydration_fetch"}, stall
        assert kinds == {"stale_heartbeat"}, stall
        out = {"named": sorted(threads), "kinds": sorted(kinds),
               "detect_s": round(detect_s, 2), "postmortem": pm}
        wd.stop()
        engine.hydrator.close()
        return out

    # -- wedge 2: publisher blackholed mid-resync --------------------------
    async def wedge_publisher() -> dict:
        import aiohttp

        from vllm_production_stack_tpu.engine.kv_events import (
            KVEventLog,
            KVEventPublisher,
        )

        server, port = await faults.black_hole()
        reg = ThreadRegistry()
        hb = reg.register("kv_event_publisher", stall_after_s=0.3)
        dumper = PostmortemDumper(pm_dir, registry=reg)
        wd = Watchdog(
            reg, interval_s=0.05,
            on_stall=lambda r: dumper.dump("watchdog", "publisher wedge"),
        )
        wd.start()
        log = KVEventLog()
        log.emit_admit(1, 0)

        async def snapshot():
            return log.epoch, log.snapshot_mark(), [1]

        session = aiohttp.ClientSession()
        pub = KVEventPublisher(
            [f"http://127.0.0.1:{port}"], "http://bb:8000", log, snapshot,
            16, lambda: session, interval_s=0.05, send_timeout_s=30.0,
            heartbeat=hb,
        )
        t0 = time.monotonic()
        pub.start()
        try:
            stall = await _await_stall(wd)
            detect_s = time.monotonic() - t0
            pm = await _check_dump(dumper, "kv_event_publisher")
            threads = {f["thread"] for f in stall["findings"]}
            kinds = {f["kind"] for f in stall["findings"]}
            assert threads == {"kv_event_publisher"}, stall
            assert kinds == {"stale_heartbeat"}, stall
            return {"named": sorted(threads), "kinds": sorted(kinds),
                    "detect_s": round(detect_s, 2), "postmortem": pm}
        finally:
            wd.stop()
            await pub.stop()
            await session.close()
            server.close()
            await server.wait_closed()

    # -- wedge 3: frozen step loop, through the real HTTP server -----------
    async def wedge_step(engine: LLMEngine) -> dict:
        srv = EngineServer(
            engine, served_model_name="tiny",
            watchdog_interval_s=0.05, watchdog_stall_s=0.4,
            postmortem_dir=pm_dir,
        )
        client = TestClient(TestServer(srv.build_app()))
        await client.start_server()
        try:
            t0 = time.monotonic()
            with faults.frozen_step_loop(engine):
                resp = await client.post("/v1/completions", json={
                    "model": "tiny", "prompt": [3, 4, 5],
                    "max_tokens": 64, "temperature": 0.0, "stream": True,
                })
                assert resp.status == 200
                stall = None
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    ready = await client.get("/ready")
                    if ready.status == 503:
                        body = await ready.json()
                        if body.get("reason") == "stalled":
                            stall = body["stall"]
                            break
                    await asyncio.sleep(0.05)
                assert stall is not None, "/ready never flipped on stall"
                detect_s = time.monotonic() - t0
                health = await client.get("/health")
                assert health.status == 200  # liveness never flips
                metrics = await (await client.get("/debug/flight")).json()
                resp.close()
            # recovery: the wedge released, /ready must come back
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if (await client.get("/ready")).status == 200:
                    break
                await asyncio.sleep(0.05)
            scrape = await (await client.get("/metrics")).text()
            threads = {f["thread"] for f in stall["findings"]}
            kinds = {f["kind"] for f in stall["findings"]}
            assert "step" in threads, stall
            assert "stale_heartbeat" in kinds, stall
            from vllm_production_stack_tpu import metrics_contract as mc

            stall_line = [
                line for line in scrape.splitlines()
                if line.startswith(mc.ENGINE_STEP_STALLS)
                and 'kind="stale_heartbeat"' in line
            ]
            assert stall_line and float(stall_line[0].split()[-1]) >= 1, \
                stall_line
            assert metrics["postmortems"]["written"] >= 1
            return {
                "named": sorted(threads), "kinds": sorted(kinds),
                "detect_s": round(detect_s, 2),
                "ready_flipped": True, "health_stayed_green": True,
                "postmortem": {"path": metrics["postmortems"]["last_path"]},
            }
        finally:
            await client.close()

    # -- noise floor: recorder-on vs recorder-off flood --------------------
    def overhead(engine: LLMEngine) -> dict:
        """Decode-wave flood on ONE warm engine, flight recording off vs
        on (the flag gates every ring append), 12 alternating reps, p50
        of wave wall times — the saturation phase's proven estimator.
        The recorder's cost lives entirely in the step loop, so driving
        step() directly measures it without aiohttp scheduling jitter
        (which was measured to swing an HTTP flood's p50 ±10% on a
        shared CPU box — two orders of magnitude above the signal)."""
        import numpy as np

        from vllm_production_stack_tpu.engine.request import SamplingParams

        rng = np.random.RandomState(11)
        vocab = engine.config.model.vocab_size
        prompts = [
            [int(t) for t in rng.randint(1, vocab, size=16)]
            for _ in range(8)
        ]
        wave_sampling = SamplingParams(
            max_tokens=24, temperature=0.0, ignore_eos=True
        )
        for _ in range(3):  # pay every XLA compile before measuring
            engine.generate(prompts, wave_sampling)
        REPS = 12
        times: dict[bool, list[float]] = {False: [], True: []}
        for rep in range(REPS):
            # alternate within-pair order too: a monotone box-level
            # drift must not always land on the same mode's slot
            order = (False, True) if rep % 2 == 0 else (True, False)
            for recording in order:
                engine.flightrec.enabled = recording
                t0 = time.perf_counter()
                outs = engine.generate(prompts, wave_sampling)
                times[recording].append(time.perf_counter() - t0)
                assert sum(len(o["token_ids"]) for o in outs) == 8 * 24
        engine.flightrec.enabled = True

        def p50(xs):
            return sorted(xs)[len(xs) // 2]

        off_p50, on_p50 = p50(times[False]), p50(times[True])
        result = {
            "reps": REPS,
            "wave_requests": 8,
            "off_p50_ms": round(off_p50 * 1e3, 2),
            "on_p50_ms": round(on_p50 * 1e3, 2),
            "off_min_ms": round(min(times[False]) * 1e3, 2),
            "on_min_ms": round(min(times[True]) * 1e3, 2),
            "p50_overhead_pct": round(
                (on_p50 / off_p50 - 1.0) * 100.0, 2
            ),
            "min_overhead_pct": round(
                (min(times[True]) / min(times[False]) - 1.0) * 100.0, 2
            ),
        }
        # the acceptance bar: same ≤2% p50 ceiling as the StepMeter
        assert result["p50_overhead_pct"] <= 2.0, result
        result["overhead_ok"] = True
        return result

    wedges["fetcher_disk_lock"] = await wedge_fetcher()
    wedges["publisher_blackholed"] = await wedge_publisher()
    engine = LLMEngine(EngineConfig.tiny())
    wedges["step_loop_frozen"] = await wedge_step(engine)
    flood_overhead = overhead(engine)
    named = {w["named"][0] if len(w["named"]) == 1 else tuple(w["named"])
             for w in wedges.values()}
    return {
        "wedges": wedges,
        "all_three_named": len(wedges) == 3 and all(
            w.get("postmortem") for w in wedges.values()
        ),
        "distinct_threads_named": sorted(
            t for w in wedges.values() for t in w["named"]
        ),
        "postmortem_dir": pm_dir,
        "overhead": flood_overhead,
        "_named_set_size": len(named),
    }


async def _fairness_bench() -> dict:
    """Multi-tenant QoS numbers (docs/27-multitenancy.md), on a CPU tiny
    engine behind its real HTTP server (stamped headers, the engines' own
    trust model — the router's stamping is exercised by tests/test_qos.py):

    - **qos-off throughput** — the same flood UNSTAMPED, run first (the
      fair-share path latches on the first stamped request): the QoS layer
      must cost nothing when unused.
    - **weighted share** — two batch-class tenants weighted 3:1, both
      saturating a deliberately small seat count: achieved decode-token
      share must track 75/25.
    - **probe TTFT** — a realtime-class probe under the batch flood must
      preempt a seat instead of queueing behind it: p50 TTFT bounded by a
      small multiple of its unloaded TTFT.
    """
    import asyncio
    from dataclasses import replace

    from aiohttp.test_utils import TestClient, TestServer

    from vllm_production_stack_tpu.engine.config import EngineConfig
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.server import EngineServer

    FLOOD_S = 8.0  # per measured flood window
    RAMP_S = 1.0
    N_CLIENTS = 6  # closed-loop clients per tenant (12 vs 4 seats: both
    # tenants keep the waiting queue populated, so EVERY admission is a
    # fair-share arbitration, not a default pick of the only waiter
    cfg = EngineConfig.tiny()
    # few seats + single-token decode windows: admission (where fair share
    # acts) happens often, and in-flight rows resolve every token so the
    # realtime probe's seat preemption lands immediately
    cfg = cfg.replace(
        scheduler=replace(
            cfg.scheduler, max_num_seqs=4, decode_buckets=(4,),
            decode_window=1, max_num_batched_tokens=32,
            prefill_buckets=(16, 32),
        )
    )
    engine = LLMEngine(cfg)
    srv = EngineServer(engine, served_model_name="tiny")
    client = TestClient(TestServer(srv.build_app()))
    await client.start_server()
    try:
        body = {"model": "tiny", "prompt": [5, 6, 7, 8],
                "temperature": 0.0, "max_tokens": 24, "ignore_eos": True}

        def stamps(tenant, priority, weight):
            return {"x-tenant-id": tenant, "x-priority": priority,
                    "x-tenant-weight": str(weight)}

        async def one(headers=None):
            r = await client.post("/v1/completions", json=body,
                                  headers=headers or {})
            await r.read()
            return r.status

        async def settle_compiles(timeout_s=60.0):
            """Wait until no background XLA compile is queued or running —
            the compiler's idle gate fires exactly when a flood stops, i.e.
            right inside the next measurement window."""
            t_end = time.monotonic() + timeout_s
            while time.monotonic() < t_end:
                with engine.runner._bg_lock:
                    if not engine.runner._bg_inflight:
                        return
                await asyncio.sleep(0.25)

        # warm up every compile the bench touches: a concurrent burst hits
        # the multi-row prefill/decode buckets the floods will use, then
        # wait out the background compiles — they otherwise steal CPU from
        # the first measured window
        for _ in range(2):
            statuses = await asyncio.gather(*[one() for _ in range(12)])
            assert all(s == 200 for s in statuses)
        await asyncio.sleep(1.0)
        await settle_compiles()

        import threading

        import aiohttp as _aiohttp

        port = client.server.port
        url = f"http://127.0.0.1:{port}/v1/completions"

        def flood_thread(stop_evt, header_sets):
            """Closed-loop flood clients on their OWN thread + event loop:
            real clients are remote, so their task churn must not share
            the probe's loop (a TestClient-colocation artifact that
            otherwise dominates the probe's first-byte latency)."""

            async def run():
                async with _aiohttp.ClientSession() as s:
                    async def fl(h):
                        while not stop_evt.is_set():
                            try:
                                async with s.post(
                                    url, json=body, headers=h or {}
                                ) as r:
                                    await r.read()
                            except _aiohttp.ClientError:
                                pass
                            await asyncio.sleep(0.005)

                    await asyncio.gather(*[
                        fl(h) for h in header_sets for _ in range(N_CLIENTS)
                    ])

            asyncio.run(run())

        def start_flood(header_sets):
            stop_evt = threading.Event()
            t = threading.Thread(
                target=flood_thread, args=(stop_evt, header_sets),
                daemon=True,
            )
            t.start()
            return stop_evt, t

        def tenant_tokens():
            counters, _ = engine.scheduler.accounting.snapshot()
            return {t: c.get("generation_tokens", 0)
                    for t, c in counters.items()}

        async def run_flood(header_sets, window_s):
            """Run closed-loop floods; returns generation-token deltas per
            tenant over the post-ramp window."""
            stop_evt, t = start_flood(header_sets)
            await asyncio.sleep(RAMP_S)
            t0, before = time.monotonic(), tenant_tokens()
            await asyncio.sleep(window_s)
            after, elapsed = tenant_tokens(), time.monotonic() - t0
            stop_evt.set()
            t.join(timeout=10)
            delta = {t: after.get(t, 0) - before.get(t, 0) for t in after}
            return delta, elapsed

        # 1) QoS OFF: unstamped flood FIRST (fair share latches on the
        # first stamped request — this measures the pre-QoS FIFO path)
        off_delta, off_s = await run_flood([None], FLOOD_S)
        qos_off_tps = round(sum(off_delta.values()) / off_s, 1)

        # 2) unloaded realtime probe TTFT (stamped: latches QoS)
        rt = stamps("probe", "realtime", 1)

        async def probe_ttft():
            t0 = time.monotonic()
            r = await client.post(
                "/v1/completions",
                json=dict(body, max_tokens=4, stream=True), headers=rt,
            )
            async for _ in r.content:
                break  # first SSE byte = first token out
            await r.read()
            return time.monotonic() - t0

        # compiles queued during the flood fire at its end (idle gate) —
        # wait them out; two discard probes warm the streaming path; a
        # full collect keeps the flood's garbage from pausing the probes
        import gc

        await settle_compiles()
        for _ in range(2):
            await probe_ttft()
        gc.collect()
        unloaded = []
        for _ in range(20):
            unloaded.append(await probe_ttft())
            await asyncio.sleep(0.1)  # engine goes idle between arrivals
        unloaded.sort()

        # 3) weighted 3:1 flood. Probes ride the flood FIRST — back to back
        # with the unloaded baseline, so box-level noise can't drift
        # between the two sides of the TTFT ratio — then share +
        # throughput are measured in a clean probe-free window (a
        # preempting probe perturbs both)
        heavy = stamps("heavy", "batch", 3)
        light = stamps("light", "batch", 1)
        stop_evt, flood_t = start_flood([heavy, light])
        await asyncio.sleep(RAMP_S)
        gc.collect()
        loaded = []
        t_end = time.monotonic() + FLOOD_S / 2
        while time.monotonic() < t_end:
            loaded.append(await probe_ttft())
            await asyncio.sleep(0.25)
        loaded.sort()
        await asyncio.sleep(1.0)  # probe preemption recompute settles
        t0, before = time.monotonic(), tenant_tokens()
        await asyncio.sleep(FLOOD_S)
        after, on_s = tenant_tokens(), time.monotonic() - t0
        stop_evt.set()
        flood_t.join(timeout=10)

        h_tok = after.get("heavy", 0) - before.get("heavy", 0)
        l_tok = after.get("light", 0) - before.get("light", 0)
        qos_on_tps = round((h_tok + l_tok) / on_s, 1)
        share = h_tok / max(1, h_tok + l_tok)

        def p50(lat):
            return round(lat[len(lat) // 2] * 1e3, 2) if lat else None

        return {
            "weights": "heavy=3 light=1 (both batch), probe realtime",
            "flood_clients_per_tenant": N_CLIENTS,
            "seats": 4,
            "heavy_tokens": h_tok,
            "light_tokens": l_tok,
            "heavy_share": round(share, 3),
            "target_share": 0.75,
            "share_within_10pct": bool(abs(share - 0.75) <= 0.10),
            "probe_ttft_unloaded_p50_ms": p50(unloaded),
            "probe_ttft_loaded_p50_ms": p50(loaded),
            "probe_ttft_ratio": (
                round(p50(loaded) / p50(unloaded), 2)
                if unloaded and loaded else None
            ),
            "probes": len(loaded),
            "qos_off_tokens_s": qos_off_tps,
            "qos_on_tokens_s": qos_on_tps,
            "qos_overhead_frac": (
                round(1.0 - qos_on_tps / qos_off_tps, 3)
                if qos_off_tps else None
            ),
        }
    finally:
        await client.close()
        engine.runner.shutdown(wait=True)


def _saturation_bench() -> dict:
    """Saturation & goodput telemetry proof (docs/29-saturation-slo.md),
    CPU-only so it survives a wedged TPU tunnel:

    - **ledger exactness** — a flood engineered to hit every waste path
      (pipeline rollbacks via mid-window stops, pool-pressure preemptions,
      deadline expiry, QoS shed eviction, mid-flight aborts) must leave
      the goodput ledger balanced EXACTLY: delivered + wasted == sampled
      at quiescence, with every event class actually exercised.
    - **metering overhead** — the same decode wave on two engines, step
      metering off vs on, alternating reps: the meter's cost must be a
      measured number (bar: ≤ ~2% p50 wave latency), not an assertion.
    """
    import time as _t
    from dataclasses import replace

    import numpy as np

    from vllm_production_stack_tpu.engine.config import EngineConfig
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.qos import TenantContext

    # -- part 1: ledger exactness under a mixed-fate flood -----------------
    cfg = EngineConfig.tiny()
    cfg = cfg.replace(
        cache=replace(cfg.cache, num_blocks=16),  # tight pool: preemptions
        scheduler=replace(
            cfg.scheduler, max_num_seqs=4, decode_buckets=(4,),
            prefill_buckets=(16, 32, 64), max_num_batched_tokens=64,
            decode_window=4, max_waiting_requests=8,
        ),
    )
    eng = LLMEngine(cfg)
    rng = np.random.RandomState(7)
    vocab = cfg.model.vocab_size
    counts = {"submitted": 0, "deadline_armed": 0, "aborted": 0,
              "shed_marked": 0}
    for wave in range(8):
        rids = []
        for i in range(10):
            kind = (wave + i) % 4
            sampling = SamplingParams(
                max_tokens=int(rng.randint(3, 24)), temperature=0.0,
                ignore_eos=True,
            )
            deadline = None
            tenant = None
            if kind == 1:
                # expires while queued or mid-decode
                deadline = _t.monotonic() + float(rng.uniform(0.01, 0.08))
                counts["deadline_armed"] += 1
            elif kind == 2:
                # batch-class long decodes: the preemption/eviction victim
                # pool (also latches the QoS paths)
                tenant = TenantContext(
                    tenant_id="batch", priority=2, weight=1.0
                )
                sampling = SamplingParams(
                    max_tokens=32, temperature=0.0, ignore_eos=True
                )
            elif kind == 3:
                # stop on a spread of ids: greedy tokens from random
                # weights hit one mid-window, forcing overshoot discards
                # and pipeline rollbacks at the finish
                sampling = SamplingParams(
                    max_tokens=24, temperature=0.0,
                    stop_token_ids=tuple(
                        int(t) for t in rng.randint(1, vocab, size=48)
                    ),
                )
            prompt = [int(t) for t in
                      rng.randint(1, vocab, size=int(rng.randint(4, 24)))]
            rids.append(eng.add_request(
                prompt_token_ids=prompt, sampling=sampling,
                deadline=deadline, tenant=tenant,
            ))
            counts["submitted"] += 1
        steps = 0
        rt_sent = False
        while eng.has_unfinished() and steps < 400:
            eng.step()
            steps += 1
            if steps == 4 and len(rids) > 5:
                # severed mid-flight (client disconnect shape): a request
                # deep enough in the wave to be running or queued, not the
                # newest (the shed victim below targets that end)
                if eng.abort_request(rids[5]):
                    counts["aborted"] += 1
            if steps == 6 and not rt_sent:
                # realtime arrival with seats full of batch-class decodes:
                # priority seat preemption (the preempted victim keeps its
                # pending tokens — fate settles at its eventual finish)
                rt_sent = True
                eng.add_request(
                    prompt_token_ids=[int(t) for t in
                                      rng.randint(1, vocab, size=6)],
                    sampling=SamplingParams(
                        max_tokens=6, temperature=0.0, ignore_eos=True
                    ),
                    tenant=TenantContext(
                        tenant_id="rt", priority=0, weight=1.0
                    ),
                )
                counts["submitted"] += 1
            if steps == 10 and eng.scheduler.mark_shed_victim(0):
                # a realtime-rank arrival claims a lower-priority victim
                # (the admission gate's evict path) — after the preemption
                # above, the newest waiting victim may carry pending
                # tokens, exercising wasted{shed_evicted}
                counts["shed_marked"] += 1
    # bounded drain: a wedged regression must still report the ledger
    # diagnostic (unbalanced + pending) instead of eating the phase timeout
    drain_steps = 0
    while eng.has_unfinished() and drain_steps < 2000:
        eng.step()
        drain_steps += 1
    balance = eng.goodput_balance()
    events = {
        "rollbacks": int(eng.timing["rollback_n"]),
        "preemptions": eng.scheduler.total_preemptions,
        "deadline_expired": eng.scheduler.deadline_expired_total,
        "shed_evictions": eng.scheduler.shed_evictions,
        **counts,
    }
    sat = eng.stats().saturation
    eng.runner.shutdown(wait=True)

    # -- part 2: metering overhead (off vs on, alternating reps) -----------
    cfg2 = EngineConfig.tiny()
    cfg2 = cfg2.replace(
        scheduler=replace(
            cfg2.scheduler, max_num_seqs=8, decode_buckets=(8,),
            prefill_buckets=(16, 32, 64), max_num_batched_tokens=64,
            decode_window=4,
        ),
    )
    engines = {
        mode: LLMEngine(cfg2.replace(step_metering=mode))
        for mode in (False, True)
    }
    prompts = [
        [int(t) for t in rng.randint(1, vocab, size=16)] for _ in range(8)
    ]
    wave_sampling = SamplingParams(
        max_tokens=32, temperature=0.0, ignore_eos=True
    )
    for e in engines.values():  # pay every XLA compile before measuring
        e.generate(prompts, wave_sampling)
        e.generate(prompts, wave_sampling)
    REPS = 12
    times: dict[bool, list[float]] = {False: [], True: []}
    for _ in range(REPS):
        for mode in (False, True):
            t0 = time.perf_counter()
            outs = engines[mode].generate(prompts, wave_sampling)
            times[mode].append(time.perf_counter() - t0)
            assert sum(len(o["token_ids"]) for o in outs) == 8 * 32
    for e in engines.values():
        e.runner.shutdown(wait=True)

    def p50(xs):
        return sorted(xs)[len(xs) // 2]

    off_p50, on_p50 = p50(times[False]), p50(times[True])
    gen_tokens = 8 * 32
    return {
        "ledger": balance,
        "balanced": bool(balance["balanced"]),
        "events": events,
        "all_waste_paths_hit": all(
            balance["wasted"].get(r, 0) > 0
            for r in ("rollback", "preempted_recompute", "deadline_expired",
                      "severed", "shed_evicted", "overshoot")
        ),
        "meter_snapshot": {
            k: sat.get(k)
            for k in ("decode_seat_occupancy", "padding_waste_frac",
                      "achieved_flops_per_s", "mfu")
        },
        "metering": {
            "reps": REPS,
            "off_p50_ms": round(off_p50 * 1e3, 2),
            "on_p50_ms": round(on_p50 * 1e3, 2),
            "off_tok_s": round(gen_tokens / off_p50, 1),
            "on_tok_s": round(gen_tokens / on_p50, 1),
            "p50_overhead_pct": round((on_p50 / off_p50 - 1.0) * 100.0, 2),
            "min_overhead_pct": round(
                (min(times[True]) / min(times[False]) - 1.0) * 100.0, 2
            ),
        },
    }


def _kvflow_bench() -> dict:
    """KV-hierarchy flow telemetry proof (docs/30-kv-flow-telemetry.md),
    CPU-only so it survives a wedged TPU tunnel:

    - **attribution exactness** — a mixed warm/cold/remote-resident prompt
      flood across two engines sharing one remote store must leave the
      hydration partition EXACT: hbm_hit + host_reload + disk_load +
      remote_fetch + recomputed == prompt tokens, with every source class
      actually exercised.
    - **bandwidth honesty** — for every (tier, direction) that moved
      blocks, the meter's bandwidth estimate must sit within 20% of
      hand-computed bytes/elapsed, where bytes are derived independently
      from the model's analytic per-block KV size (kv_block_bytes), not
      read back from the meter.
    - **metering overhead** — the same reload-heavy wave on two engines,
      --kv-flow-metering off vs on, alternating reps: the meters' cost
      must be a measured number (bar: ≤ ~2% p50), not an assertion.
    """
    import tempfile
    import time as _t
    from dataclasses import replace

    import numpy as np

    from vllm_production_stack_tpu.engine.config import EngineConfig
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.memory import kv_block_bytes
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.kvstore.server import run_in_thread

    BS = 8
    rng = np.random.RandomState(11)
    url, stop_store, _server = run_in_thread(capacity_bytes=1 << 26)
    tmp = tempfile.mkdtemp(prefix="bench-kvflow-")

    def make_engine(disk_dir: str, host_blocks=10, metering=True):
        cfg = EngineConfig.tiny()
        return LLMEngine(cfg.replace(
            cache=replace(
                cfg.cache, block_size=BS, num_blocks=14,  # tight: evicts
                num_host_blocks=host_blocks, disk_kv_dir=disk_dir,
                disk_kv_gib=0.05, remote_kv_url=url,
            ),
            scheduler=replace(
                cfg.scheduler, max_num_seqs=2, max_num_batched_tokens=64,
                decode_buckets=(2,), prefill_buckets=(32, 64),
                decode_window=4,
            ),
            kv_flow_metering=metering,
        ))

    vocab = 512
    GREEDY = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)

    def prompt(seed, n=4 * BS):
        return [int(t) for t in
                np.random.RandomState(seed).randint(1, vocab, size=n)]

    # -- part 1: attribution exactness across a warm/cold/remote mix -------
    eng_a = make_engine(f"{tmp}/a")
    warm = [prompt(s) for s in range(6)]
    for p in warm:  # seed pass: all recomputed; churn evicts older ones
        eng_a.generate([p], GREEDY)
    # re-issue NEWEST-first: the last-seeded prompt's blocks are still
    # HBM-resident (hbm_hit), older ones were pushed down the hierarchy
    # (host ring → disk) by the churn, plus fresh cold prompts to keep
    # recomputed > 0
    for p in list(reversed(warm)) + [prompt(100 + s) for s in range(2)]:
        eng_a.generate([p], GREEDY)
    eng_a.host_tier.flush()  # resolve pending offloads → remote writes
    assert eng_a.remote_tier.drain(timeout=30), "remote store drain hung"

    # engine B: same weights fingerprint (same config+seed), FRESH local
    # tiers — warm prompts can only come from the remote store
    eng_b = make_engine(f"{tmp}/b")
    for p in warm[:3]:
        eng_b.generate([p], GREEDY)

    def attribution(eng):
        snap = eng.flow.snapshot()
        hyd = dict(snap["hydration"])
        hyd["total"] = sum(hyd.values())
        return hyd, snap

    hyd_a, snap_a = attribution(eng_a)
    hyd_b, snap_b = attribution(eng_b)
    exact_a = hyd_a["total"] == eng_a._prompt_tokens
    exact_b = hyd_b["total"] == eng_b._prompt_tokens
    sources_hit = {
        "hbm_hit": hyd_a["hbm_hit"] > 0,
        "host_reload": hyd_a["host_reload"] > 0,
        "disk_load": hyd_a["disk_load"] > 0,
        "remote_fetch": hyd_b["remote_fetch"] > 0,
        "recomputed": hyd_a["recomputed"] > 0,
    }

    # -- bandwidth honesty: meter estimate vs analytic bytes / elapsed -----
    # per-block KV bytes from the model config alone (the disk tier adds a
    # ~100 B frame header per block — inside the 20% tolerance)
    blk_bytes = kv_block_bytes(
        eng_a.config.model, BS, 1, 1,
        kv_dtype=eng_a.config.cache.resolved_kv_dtype(
            eng_a.config.model.dtype
        ),
    )
    bandwidth: dict[str, dict] = {}
    bw_ok = True
    for eng, tag in ((eng_a, "a"), (eng_b, "b")):
        snap = eng.flow.snapshot()
        for key, blocks in snap["blocks"].items():
            if blocks <= 0:
                continue
            secs = snap["seconds_hist"][key]["sum"]
            hand = blocks * blk_bytes / secs if secs > 0 else 0.0
            meter = snap["bandwidth_bytes_per_s"][key]
            rel = abs(meter - hand) / hand if hand > 0 else 1.0
            bandwidth[f"{tag}:{key}"] = {
                "blocks": blocks,
                "meter_bytes": snap["bytes"][key],
                "hand_bytes": blocks * blk_bytes,
                "elapsed_s": round(secs, 6),
                "meter_bytes_per_s": round(meter, 1),
                "hand_bytes_per_s": round(hand, 1),
                "rel_err": round(rel, 4),
            }
            if rel > 0.20:
                bw_ok = False
    signal = eng_a.hydration_signal()
    eng_a.runner.shutdown(wait=True)
    eng_b.runner.shutdown(wait=True)

    # -- part 2: metering overhead (off vs on, alternating reps) -----------
    # a reload-heavy wave: the working set exceeds HBM, so every wave
    # exercises the metered offload/reload paths, not just decode
    engines = {
        mode: make_engine(f"{tmp}/ovh-{mode}", metering=mode)
        for mode in (False, True)
    }
    ovh_prompts = [prompt(300 + s) for s in range(5)]
    for e in engines.values():  # pay XLA compiles + first-touch paths
        for p in ovh_prompts:
            e.generate([p], GREEDY)
        for p in ovh_prompts:
            e.generate([p], GREEDY)
    REPS = 14
    times: dict[bool, list[float]] = {False: [], True: []}
    for rep in range(REPS):
        # alternate which mode runs first each rep so slow clock/cache
        # drift cancels instead of always taxing the second mode
        order = (False, True) if rep % 2 == 0 else (True, False)
        for mode in order:
            t0 = _t.perf_counter()
            for p in ovh_prompts:
                engines[mode].generate([p], GREEDY)
            times[mode].append(_t.perf_counter() - t0)
    for e in engines.values():
        e.runner.shutdown(wait=True)
    stop_store()

    def p50(xs):
        return sorted(xs)[len(xs) // 2]

    off_p50, on_p50 = p50(times[False]), p50(times[True])
    return {
        "attribution": {
            "engine_a": hyd_a,
            "engine_b": hyd_b,
            "prompt_tokens_a": eng_a._prompt_tokens,
            "prompt_tokens_b": eng_b._prompt_tokens,
            "exact": bool(exact_a and exact_b),
            "sources_hit": sources_hit,
            "all_sources_hit": all(sources_hit.values()),
        },
        "bandwidth": bandwidth,
        "bandwidth_within_20pct": bool(bw_ok),
        "hydration_signal": {
            k: signal[k]
            for k in ("fetch_bandwidth_bytes_per_s", "prefill_flops_per_s",
                      "flops_per_token", "block_bytes")
        },
        "metering": {
            "reps": REPS,
            "off_p50_ms": round(off_p50 * 1e3, 2),
            "on_p50_ms": round(on_p50 * 1e3, 2),
            "p50_overhead_pct": round((on_p50 / off_p50 - 1.0) * 100.0, 2),
            "min_overhead_pct": round(
                (min(times[True]) / min(times[False]) - 1.0) * 100.0, 2
            ),
        },
    }


def _hydration_bench() -> dict:
    """Compute-or-load hydration planner proof (docs/31-hydration-
    planner.md), CPU-only so it survives a wedged TPU tunnel. The
    acceptance shape: cold-prefix 8k-token prompts resident on
    disk/remote (seeded by one engine, measured on fresh engines with
    the same weights fingerprint), TTFT for

    - **compute-only** (`--kv-hydration off`): full prefill, the
      lower-tier residency ignored;
    - **load-only** (`--kv-hydration sync`): the legacy blocking
      whole-prefix reload;
    - **planner** (`--kv-hydration auto`): chunked tier fetches
      pipelined with partial recompute, split chosen from MEASURED
      bandwidth vs MEASURED prefill FLOP/s.

    Remote scenario (the headline): the fetch link is throttled (a
    sleep proportional to payload bytes at the connection layer —
    INSIDE the flow meter's timing window, so the planner's bandwidth
    estimate sees the throttled truth) to the crossover point where
    fetch-everything ~= compute-everything — exactly where
    all-or-nothing policies are worst and the planner's max(fetch tail,
    compute tail) pays off. Disk scenario: the same arms against the
    local NVMe tier, unthrottled — disk is fast here, so the planner's
    job is to match load-only (reported with a 5% noise tolerance
    rather than asserted strictly). The planner engines warm honestly:
    one compute pass (FLOP/s estimate + XLA compiles — the width floor
    makes the program keys context-independent, so a short junk prompt
    warms the 8k shapes) and sync-fallback reloads of smaller resident
    prompts (the bandwidth samples that cross the TierBandwidth floor).
    The per-request hydration partition must stay EXACT on every engine
    with the planner on."""
    import time as _t
    from dataclasses import replace

    import numpy as np

    from vllm_production_stack_tpu.engine.config import EngineConfig
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.kvstore.server import run_in_thread

    import tempfile

    BS = 16
    PROMPT_TOKENS = 8192
    WARM_TOKENS = 2048
    url, stop_store, _server = run_in_thread(capacity_bytes=1 << 30)
    disk_dir = tempfile.mkdtemp(prefix="bench-hydration-")

    def make_engine(mode: str, remote: str = "", disk: str = "",
                    num_blocks: int = 560) -> LLMEngine:
        cfg = EngineConfig.tiny(max_model_len=PROMPT_TOKENS + 256)
        return LLMEngine(cfg.replace(
            cache=replace(
                cfg.cache, block_size=BS, num_blocks=num_blocks,
                num_host_blocks=16, remote_kv_url=remote,
                disk_kv_dir=disk, disk_kv_gib=1.0 if disk else 0.0,
            ),
            scheduler=replace(
                cfg.scheduler, max_num_seqs=2,
                max_num_batched_tokens=512, decode_buckets=(2,),
                prefill_buckets=(64, 512), decode_window=4,
                # ONE block-table width program: the phase measures
                # hydration, not the width compile ladder — and it makes
                # a short warmup prompt compile the 8k prompt's programs
                width_floor_blocks=600,
            ),
            kv_hydration=mode,
            kv_hydration_chunk_blocks=16,
        ))

    def prompt(seed: int, n: int) -> list[int]:
        return [int(t) for t in
                np.random.RandomState(seed).randint(1, 500, size=n)]

    GREEDY = SamplingParams(max_tokens=2, temperature=0.0, ignore_eos=True)
    target = prompt(1, PROMPT_TOKENS)
    warms = [prompt(10 + i, WARM_TOKENS) for i in range(2)]
    junk_small = prompt(98, 1024)  # compile warmup (width floor: same keys)
    junk_big = prompt(99, PROMPT_TOKENS)  # seeding churn only

    # -- seed BOTH lower tiers: engine A computes everything; churn pushes
    # every block through the ring, whose evictions persist to disk AND
    # write through to the remote store
    eng_a = make_engine("sync", remote=url, disk=disk_dir)
    ref_tokens = eng_a.generate([target], GREEDY)[0]["token_ids"]
    for w in warms:
        eng_a.generate([w], GREEDY)
    eng_a.generate([junk_big], GREEDY)  # evicts target+warm blocks
    eng_a.host_tier.flush()
    assert eng_a.remote_tier.drain(timeout=120), "remote store drain hung"
    seeded_remote = eng_a.remote_tier.stats.stores
    seeded_disk = eng_a.host_tier.disk.stats.stores
    eng_a.runner.shutdown(wait=True)

    def first_token_latency(eng: LLMEngine, ids: list[int]):
        t0 = _t.perf_counter()
        rid = eng.add_request(prompt_token_ids=ids, sampling=GREEDY)
        ttft = None
        tok = None
        while eng.has_unfinished():
            for out in eng.step():
                if out.request_id == rid and out.new_token_ids and ttft is None:
                    ttft = _t.perf_counter() - t0
                    tok = out.new_token_ids[0]
        return ttft, tok

    def throttle_store(bytes_per_s: float) -> None:
        """Slow every remote fetch connection (shared AND the hydrator's
        dedicated one) to `bytes_per_s` — the sleep happens inside
        fetch_run's metering window, so TierBandwidth measures the
        throttled link, exactly what a WAN-attached store looks like.
        Disk IO is untouched (the sleep keys on the /v1/mget path)."""
        from vllm_production_stack_tpu.kvstore import client as kvclient

        inner = kvclient._Conn.request

        def slowed(self, method, path, body=None, headers=None):
            status, hdrs, payload = inner(
                self, method, path, body=body, headers=headers
            )
            if path == "/v1/mget":
                _t.sleep(len(payload) / bytes_per_s)
            return status, hdrs, payload

        kvclient._Conn.request = slowed

    def run_arm(mode: str, remote: str = "", disk: str = "",
                warm_prompts=()):  # -> (ttft, first_token, details)
        eng = make_engine(mode, remote=remote, disk=disk)
        eng.generate([junk_small], GREEDY)  # XLA compiles + FLOP/s sample
        for w in warm_prompts:  # sync-fallback loads: bandwidth samples
            eng.generate([w], GREEDY)
        sig = eng.hydration_signal()
        ttft, tok = first_token_latency(eng, target)
        snap = eng.flow.snapshot()
        hyd = snap["hydration"]
        details = {
            "ttft_s": round(ttft, 3),
            "decisions": dict(snap["decisions"]),
            "partition_exact": sum(hyd.values()) == eng._prompt_tokens,
            "measured_before_run": dict(sig["fetch_bandwidth_measured"]),
        }
        eng.runner.shutdown(wait=True)
        return ttft, tok, details

    # -- remote scenario (throttled to the crossover) ----------------------
    ttft_c, tok_c, det_c = run_arm("off", remote=url)
    region_blocks = PROMPT_TOKENS // BS - 1
    from vllm_production_stack_tpu.engine.memory import kv_block_bytes

    tiny = EngineConfig.tiny(max_model_len=PROMPT_TOKENS + 256)
    blk_bytes = kv_block_bytes(
        tiny.model, BS, 1, 1,
        kv_dtype=tiny.cache.resolved_kv_dtype(tiny.model.dtype),
    )
    region_bytes = region_blocks * (blk_bytes + 160)  # + frame header
    bw = region_bytes / max(ttft_c, 0.05)
    throttle_store(bw)
    ttft_l, tok_l, det_l = run_arm("sync", remote=url)
    ttft_p, tok_p, det_p = run_arm("auto", remote=url, warm_prompts=warms)
    remote = {
        "compute_only": det_c,
        "load_only": det_l,
        "planner": det_p,
        "throttle_bytes_per_s": round(bw, 1),
        "tokens_agree": tok_c == tok_l == tok_p,
        "planner_ttft_le_min": bool(ttft_p <= min(ttft_c, ttft_l)),
        "speedup_vs_best_baseline": round(min(ttft_c, ttft_l) / ttft_p, 3),
    }

    # -- disk scenario (local NVMe, unthrottled: the planner should LOAD
    # nearly everything and match load-only; 5% noise tolerance) -----------
    d_ttft_l, d_tok_l, d_det_l = run_arm("sync", disk=disk_dir)
    d_ttft_p, d_tok_p, d_det_p = run_arm(
        "auto", disk=disk_dir, warm_prompts=warms[:1]
    )
    stop_store()
    disk = {
        "compute_only_ttft_s": det_c["ttft_s"],  # compute is tier-blind
        "load_only": d_det_l,
        "planner": d_det_p,
        "tokens_agree": d_tok_l == d_tok_p == tok_c,
        "planner_ttft_le_min_5pct": bool(
            d_ttft_p <= min(ttft_c, d_ttft_l) * 1.05
        ),
    }

    return {
        "workload": {
            "prompt_tokens": PROMPT_TOKENS,
            "block_size": BS,
            "seeded_remote_blocks": seeded_remote,
            "seeded_disk_blocks": seeded_disk,
        },
        "remote": remote,
        "disk": disk,
        "planner_ttft_le_min": remote["planner_ttft_le_min"],
        "speedup_vs_best_baseline": remote["speedup_vs_best_baseline"],
    }


def _kvquant_bench() -> dict:
    """At-rest KV quantization proof (docs/38-kv-quantization.md),
    CPU-only so it survives a wedged TPU tunnel. Two arms, identical
    except for ``--kv-at-rest-codec``: a pool-precision-at-rest baseline
    and int4+per-group-scales. Each arm seeds a shared remote kvstore
    with a cold 4k-token prefix under its OWN codec fingerprint (the
    mixed-fleet namespace rule — the arms can share one store because
    they can never adopt each other's bytes), then a FRESH engine per
    arm reloads the prefix over a bandwidth-throttled link with sync
    hydration (the blocking whole-prefix reload: TTFT ~ wire bytes /
    link bandwidth, no planner cleverness to confound the codec's
    contribution — the throttle sleeps on the WIRE payload, so smaller
    frames are faster automatically, exactly like a real WAN link).

    Acceptance shape: the int4 arm moves >=3.5x fewer wire bytes per
    KVFlowMeter (the logical/wire quotient is the
    tpu:kv_tier_compression_ratio gauge), beats the baseline's TTFT,
    keeps the per-request hydration partition EXACT, and greedy decode
    tokens agree with the compute-from-scratch reference (int4's
    per-element dequant error is bounded by scale/2 — docs/38 — far
    below the argmax margin)."""
    import time as _t
    from dataclasses import replace

    import numpy as np

    from vllm_production_stack_tpu.engine.config import EngineConfig
    from vllm_production_stack_tpu.engine.engine import LLMEngine
    from vllm_production_stack_tpu.engine.request import SamplingParams
    from vllm_production_stack_tpu.kvstore.server import run_in_thread

    BS = 16
    PROMPT_TOKENS = 4096
    url, stop_store, _server = run_in_thread(capacity_bytes=1 << 30)

    def make_engine(codec: str) -> LLMEngine:
        cfg = EngineConfig.tiny(max_model_len=PROMPT_TOKENS + 256)
        return LLMEngine(cfg.replace(
            cache=replace(
                cfg.cache, block_size=BS, num_blocks=352,
                num_host_blocks=16, remote_kv_url=url,
                kv_at_rest_codec=codec,
            ),
            scheduler=replace(
                cfg.scheduler, max_num_seqs=2,
                max_num_batched_tokens=512, decode_buckets=(2,),
                prefill_buckets=(64, 512), decode_window=4,
                # ONE block-table width program (hydration-bench idiom):
                # the phase measures the codec, not the compile ladder
                width_floor_blocks=300,
            ),
            kv_hydration="sync",
        ))

    def prompt(seed: int, n: int) -> list[int]:
        return [int(t) for t in
                np.random.RandomState(seed).randint(1, 500, size=n)]

    GREEDY = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    target = prompt(1, PROMPT_TOKENS)
    junk_small = prompt(98, 1024)  # compile warmup (width floor: same keys)
    # churn: enough distinct blocks that EVERY target block is evicted
    # through the ring (pool 320 blocks; target 256 + churn 512 >> 320)
    churn = [prompt(99 + i, PROMPT_TOKENS) for i in range(2)]

    def seed_store(codec: str):
        """Engine A computes the target from scratch (the greedy token
        reference) and churns junk through the pool so ALL the target's
        blocks spill through the ring, whose evictions write through to
        the remote store under `codec`'s at-rest form + fingerprint."""
        eng = make_engine(codec)
        ref = eng.generate([target], GREEDY)[0]["token_ids"]
        for c in churn:
            eng.generate([c], GREEDY)
        eng.host_tier.flush()
        assert eng.remote_tier.drain(timeout=120), "remote store drain hung"
        stores = eng.remote_tier.stats.stores
        eng.runner.shutdown(wait=True)
        return ref, stores

    ref_base, seeded_base = seed_store("none")
    ref_int4, seeded_int4 = seed_store("int4")

    # throttle AFTER seeding: every /v1/mget connection (fetch side only)
    # sleeps proportional to its WIRE payload — the link the baseline arm
    # crawls over is byte-for-byte the link the int4 arm flies over
    region_blocks = PROMPT_TOKENS // BS - 1
    from vllm_production_stack_tpu.engine.memory import kv_block_bytes

    tiny = EngineConfig.tiny(max_model_len=PROMPT_TOKENS + 256)
    pool_dtype = tiny.cache.resolved_kv_dtype(tiny.model.dtype)
    blk_bytes = kv_block_bytes(tiny.model, BS, 1, 1, kv_dtype=pool_dtype)
    region_bytes = region_blocks * (blk_bytes + 160)  # + frame header
    bw = region_bytes / 4.0  # baseline reload ~4s of pure link time

    from vllm_production_stack_tpu.kvstore import client as kvclient

    inner = kvclient._Conn.request

    def slowed(self, method, path, body=None, headers=None):
        status, hdrs, payload = inner(
            self, method, path, body=body, headers=headers
        )
        if path == "/v1/mget":
            _t.sleep(len(payload) / bw)
        return status, hdrs, payload

    kvclient._Conn.request = slowed

    def run_arm(codec: str) -> dict:
        eng = make_engine(codec)
        eng.generate([junk_small], GREEDY)  # XLA compiles (not resident)
        t0 = _t.perf_counter()
        rid = eng.add_request(prompt_token_ids=target, sampling=GREEDY)
        ttft = None
        tokens: list[int] = []
        while eng.has_unfinished():
            for out in eng.step():
                if out.request_id != rid:
                    continue
                if out.new_token_ids and ttft is None:
                    ttft = _t.perf_counter() - t0
                tokens.extend(out.new_token_ids)
        snap = eng.flow.snapshot()
        hyd = snap["hydration"]
        details = {
            "ttft_s": round(ttft, 3),
            "tokens": tokens,
            "wire_bytes_in": snap["bytes"]["remote/in"],
            "logical_bytes_in": snap["logical_bytes"]["remote/in"],
            "compression_ratio": round(
                snap["compression_ratio"]["remote/in"], 3
            ),
            "remote_fetch_tokens": hyd["remote_fetch"],
            "partition_exact": sum(hyd.values()) == eng._prompt_tokens,
        }
        eng.runner.shutdown(wait=True)
        return details

    base = run_arm("none")
    quant = run_arm("int4")
    stop_store()

    reduction = base["wire_bytes_in"] / max(quant["wire_bytes_in"], 1)
    return {
        "workload": {
            "prompt_tokens": PROMPT_TOKENS,
            "block_size": BS,
            "pool_dtype": str(pool_dtype),
            "seeded_blocks": {"base": seeded_base, "int4": seeded_int4},
            "throttle_bytes_per_s": round(bw, 1),
        },
        "base_at_rest": base,
        "int4_at_rest": quant,
        "wire_reduction_x": round(reduction, 3),
        "wire_reduction_ge_3p5": bool(reduction >= 3.5),
        "int4_beats_base_ttft": bool(quant["ttft_s"] < base["ttft_s"]),
        "ttft_speedup": round(base["ttft_s"] / max(quant["ttft_s"], 1e-9), 3),
        "partition_exact_all": bool(
            base["partition_exact"] and quant["partition_exact"]
        ),
        "tokens_identical": bool(
            ref_base == ref_int4 == base["tokens"] == quant["tokens"]
        ),
    }


async def _fleet_bench() -> dict:
    """Fleet-coherence telemetry baselines (docs/32-fleet-telemetry.md),
    CPU-only pre-preflight: M=3 REAL router apps × N=4 fake engines, the
    three numbers ROADMAP 1's multi-replica refactor must beat, measured
    through real wire traffic:

    1. **convergence**: a 10k-event KV storm pumped (with real publish
       timestamps) through POST /kv/events into each replica's embedded
       index + the controller — publish→apply lag p50/p95 per replica,
       plus the replica-restart arc: a cold replica's divergence on
       GET /fleet rises to the full slice, then heals to 0 after resync.
    2. **stickiness**: session flood spread across 3 routers with
       IDENTICAL ring membership → violation rate must be 0; the same
       flood with one router's membership forcibly skewed (a phantom
       backend the others don't list) → violations > 0 (detection proven,
       ring divergence flagged on /fleet).
    3. **tenant accounting**: a 3-replica flood against a 20 req/s tenant
       budget — each replica's local bucket admits the full budget, so the
       controller's fleet rollup must measure utilization ≈ 3× and
       over-admission ≈ 2; the single-router baseline measures ≈ 1× / ≈ 0.
    """
    import asyncio
    import socket

    import numpy as np
    from aiohttp import web

    import aiohttp
    from vllm_production_stack_tpu.engine.kv_cache import KVBlockPool
    from vllm_production_stack_tpu.engine.kv_controller import KVController
    from vllm_production_stack_tpu.fleet import SessionStickinessAudit
    from vllm_production_stack_tpu.qos import TenantTable
    from vllm_production_stack_tpu.router.app import build_app
    from vllm_production_stack_tpu.router.args import parse_args

    N_ENGINES, BLOCK = 4, 16
    N_REPLICAS = 3
    STORM_EVENTS = 10_000
    STORM_BATCH = 512

    runners: list[web.AppRunner] = []

    async def serve(app) -> tuple[web.AppRunner, str]:
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        runners.append(runner)
        return runner, f"http://127.0.0.1:{runner.addresses[0][1]}"

    # -- fake engines: real HTTP servers that answer /v1/completions and
    # feed the REAL engine-side stickiness audit from the router stamps
    audits: list[SessionStickinessAudit] = []
    engine_urls: list[str] = []

    def engine_app(audit_holder: list) -> web.Application:
        async def completions(request):
            audit_holder[0].observe_headers(request.headers)
            return web.json_response({
                "id": "cmpl-fleet", "object": "text_completion",
                "choices": [{"index": 0, "text": "ok",
                             "finish_reason": "stop"}],
            })

        app = web.Application()
        app.router.add_post("/v1/completions", completions)
        return app

    tenants_yaml = {
        "acme": {"api_key": "k-acme", "requests_per_s": 20.0},
    }
    import tempfile

    import yaml as _yaml

    tenant_file = tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", delete=False
    )
    _yaml.safe_dump(tenants_yaml, tenant_file)
    tenant_file.close()

    try:
        for _ in range(N_ENGINES):
            holder = [None]
            _, url = await serve(engine_app(holder))
            holder[0] = SessionStickinessAudit(self_url=url)
            audits.append(holder[0])
            engine_urls.append(url)

        controller = KVController(
            engine_urls, mode="indexed",
            tenant_table=TenantTable.from_dict(tenants_yaml),
        )
        controller.fleet.rate_window_s = 10.0
        _, controller_url = await serve(controller.build_app())

        def router_args(replica: str, backends: list[str], policy: str,
                        with_qos: bool = False):
            argv = [
                "--static-backends", ",".join(backends),
                # static model list: skips the one-shot /v1/models probe
                # (the fake engines only serve /v1/completions)
                "--static-models", ";".join(["tiny"] * len(backends)),
                "--router-replica-id", replica,
                "--fleet-report-url", controller_url,
                "--fleet-report-interval", "0.25",
                # this phase measures the UNSCALED baseline (the N-way
                # bucket-split over-admission PR 9 quantified); the
                # fleet_scale phase proves the budget-scaling fix
                "--fleet-budget-scaling", "off",
                "--breaker-failure-threshold", "0",
            ]
            if policy == "session":
                argv += ["--routing-logic", "session",
                         "--session-key", "x-user-id"]
            else:
                argv += ["--routing-logic", "kvaware",
                         "--kv-index-mode", "embedded",
                         "--kv-index-tokenizer", "byte"]
            if with_qos:
                argv += ["--tenant-table-file", tenant_file.name]
            return parse_args(argv)

        # ---------------- 1. convergence storm -------------------------
        pools = [KVBlockPool(4096, BLOCK) for _ in range(N_ENGINES)]
        replicas = []
        for i in range(N_REPLICAS):
            runner, url = await serve(build_app(router_args(
                f"replica-{i}", engine_urls, "kvaware"
            )))
            replicas.append((runner.app["state"], url))

        async with aiohttp.ClientSession() as sess:
            async def publish(url: str, payload: dict):
                async with sess.post(url + "/kv/events", json=payload) as r:
                    assert r.status == 200, await r.text()
                    return await r.json()

            subscriber_urls = [u for _, u in replicas] + [controller_url]
            # snapshot-first (empty pools), mirroring the publisher's
            # first-contact resync
            for i, pool in enumerate(pools):
                epoch, seq, hashes = pool.snapshot_events()
                for sub in subscriber_urls:
                    await publish(sub, {
                        "engine": engine_urls[i], "epoch": epoch,
                        "block_size": BLOCK, "snapshot": True, "seq": seq,
                        "hashes": [f"{h:x}" for h in hashes],
                        "ts": time.time(),
                    })
            # admit ~STORM_EVENTS blocks across the pools (each admission
            # emits one sequenced event with its emit wall-time)
            rng = np.random.RandomState(11)
            per_engine = STORM_EVENTS // N_ENGINES
            for pool in pools:
                parent = pool.root_hash()
                for _ in range(per_engine):
                    blk = pool.allocate()
                    assert blk is not None
                    parent = pool.register_full_block(
                        blk, parent,
                        tuple(int(t) for t in rng.randint(1, 30000, BLOCK)),
                    )
            # pump the storm: real drain_timed batches (publish ts = the
            # oldest event's emit time, so lag includes in-buffer dwell)
            # POSTed to every subscriber over real wire
            events_pumped = 0
            for i, pool in enumerate(pools):
                while True:
                    seq_start, events, oldest_ts = (
                        pool.events.drain_timed(STORM_BATCH)
                    )
                    if not events:
                        break
                    events_pumped += len(events)
                    for sub in subscriber_urls:
                        reply = await publish(sub, {
                            "engine": engine_urls[i],
                            "epoch": pool.events.epoch,
                            "block_size": BLOCK, "seq_start": seq_start,
                            "events": events, "ts": oldest_ts,
                        })
                        assert reply.get("status") == "ok", reply

            def lag_pcts(state) -> dict:
                lags = sorted(state.policy.index.convergence.drain())
                if not lags:
                    return {"p50_ms": None, "p95_ms": None, "batches": 0}
                pick = lambda p: round(  # noqa: E731
                    lags[min(len(lags) - 1, int(p * len(lags)))] * 1e3, 3
                )
                return {"p50_ms": pick(0.50), "p95_ms": pick(0.95),
                        "batches": len(lags)}

            convergence = {
                f"replica-{i}": lag_pcts(state)
                for i, (state, _) in enumerate(replicas)
            }

            # replica-restart arc: a COLD index (replica-3 boots fresh)
            # reports positions without the storm → /fleet divergence is
            # the full authoritative slice; a snapshot resync heals it
            cold_runner, cold_url = await serve(build_app(router_args(
                "replica-cold", engine_urls, "kvaware"
            )))
            cold_state = cold_runner.app["state"]
            await cold_state.fleet_reporter.report_once()
            async with sess.get(controller_url + "/fleet") as r:
                fleet_before = await r.json()
            div_before = {
                rep["replica"]: rep["divergence_blocks"]
                for rep in fleet_before["replicas"]
            }
            for i, pool in enumerate(pools):
                epoch, seq, hashes = pool.snapshot_events()
                await publish(cold_url, {
                    "engine": engine_urls[i], "epoch": epoch,
                    "block_size": BLOCK, "snapshot": True, "seq": seq,
                    "hashes": [f"{h:x}" for h in hashes],
                    "ts": time.time(),
                })
            await cold_state.fleet_reporter.report_once()
            async with sess.get(controller_url + "/fleet") as r:
                fleet_after = await r.json()
            div_after = {
                rep["replica"]: rep["divergence_blocks"]
                for rep in fleet_after["replicas"]
            }

            # ---------------- 2. stickiness audit ----------------------
            def reset_audits():
                for holder in audits:
                    holder.violations = {
                        k: 0 for k in holder.violations
                    }
                    holder._sessions.clear()
                    holder.observed = 0

            async def session_flood(router_urls: list[str],
                                    sessions: int = 48,
                                    rounds: int = 4) -> dict:
                reset_audits()
                n = 0
                for rnd in range(rounds):
                    tasks = []
                    for s in range(sessions):
                        url = router_urls[(s + rnd) % len(router_urls)]
                        tasks.append(sess.post(
                            url + "/v1/completions",
                            json={"model": "tiny", "prompt": "hello"},
                            headers={"x-user-id": f"sess-{s}"},
                        ))
                    for resp in await asyncio.gather(*tasks):
                        n += 1
                        await resp.read()
                violations = {}
                for holder in audits:
                    for k, v in holder.counts().items():
                        violations[k] = violations.get(k, 0) + v
                return {
                    "requests": n,
                    "violations": violations,
                    "violation_rate": round(
                        sum(violations.values()) / max(1, n), 4
                    ),
                }

            session_routers = []
            for i in range(N_REPLICAS):
                runner, url = await serve(build_app(router_args(
                    f"sess-{i}", engine_urls, "session"
                )))
                session_routers.append(url)
            sticky_identical = await session_flood(session_routers)

            # forced membership skew: one replica also lists a PHANTOM
            # backend (a closed port — connect refused, breakers off), the
            # ring-divergence scenario a stale discovery view produces.
            # Sessions the skewed ring maps to the phantom fail over and
            # arrive stamped owner=phantom → non_owner_delivery; sessions
            # re-ringed after the phantom's removal flip owners →
            # owner_changed.
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            phantom = f"http://127.0.0.1:{s.getsockname()[1]}"
            s.close()
            skew_runner, skew_url = await serve(build_app(router_args(
                "sess-skewed", engine_urls + [phantom], "session"
            )))
            sticky_skewed = await session_flood(
                session_routers[:2] + [skew_url]
            )
            # deterministic ring-divergence snapshot: after a failover the
            # skewed ring re-syncs to the shrunken candidate list (phantom
            # evicted), momentarily matching the healthy replicas' hash.
            # Route one session that maps to a LIVE engine last — its
            # first pick re-syncs the ring to the full 5-node membership
            # and no failover follows, so the report carries the skew.
            from vllm_production_stack_tpu.router.hashring import HashRing

            probe_ring = HashRing()
            for u in engine_urls + [phantom]:
                probe_ring.add_node(u)
            live_sid = next(
                f"probe-{i}" for i in range(1000)
                if probe_ring.get_node(f"probe-{i}") != phantom
            )
            r = await sess.post(
                skew_url + "/v1/completions",
                json={"model": "tiny", "prompt": "x"},
                headers={"x-user-id": live_sid},
            )
            await r.read()
            await skew_runner.app["state"].fleet_reporter.report_once()
            # the unskewed replicas report on their own 0.25s interval
            await asyncio.sleep(0.4)
            async with sess.get(controller_url + "/fleet") as r:
                ring_divergent = (await r.json())["ring_divergent"]

            # ---------------- 3. fleet tenant accounting ----------------
            async def tenant_flood(router_urls: list[str],
                                   window_s: float = 6.0,
                                   offered_rps: float = 40.0) -> dict:
                t_end = time.monotonic() + window_s
                admitted = throttled = 0
                interval = 1.0 / offered_rps

                async def client(url: str):
                    nonlocal admitted, throttled
                    while time.monotonic() < t_end:
                        t0 = time.monotonic()
                        async with sess.post(
                            url + "/v1/completions",
                            json={"model": "tiny", "prompt": "hi"},
                            headers={"Authorization": "Bearer k-acme"},
                        ) as r:
                            await r.read()
                            if r.status == 200:
                                admitted += 1
                            elif r.status == 429:
                                throttled += 1
                        dt = interval - (time.monotonic() - t0)
                        if dt > 0:
                            await asyncio.sleep(dt)

                # 2 clients per router × offered_rps pacing each ≈ well
                # over the 20 req/s budget per replica
                await asyncio.gather(*[
                    client(u) for u in router_urls for _ in range(2)
                ])
                return {
                    "admitted": admitted, "throttled": throttled,
                    "admitted_rps": round(admitted / window_s, 2),
                }

            qos_routers = []
            qos_states = []
            for i in range(N_REPLICAS):
                runner, url = await serve(build_app(router_args(
                    f"qos-{i}", engine_urls, "session", with_qos=True
                )))
                qos_routers.append(url)
                qos_states.append(runner.app["state"])
            fleet_flood = await tenant_flood(qos_routers)
            # force a final report round so the controller sees the full
            # flood window before we read the rollup
            for st in qos_states:
                await st.fleet_reporter.report_once()
            async with sess.get(controller_url + "/fleet") as r:
                rollup = (await r.json())["tenants"].get("acme", {})

            baseline_runner, baseline_url = await serve(build_app(
                router_args("qos-solo", engine_urls, "session",
                            with_qos=True)
            ))
            controller.fleet._replicas.clear()  # fresh rollup window
            baseline_flood = await tenant_flood([baseline_url])
            await baseline_runner.app["state"].fleet_reporter.report_once()
            async with sess.get(controller_url + "/fleet") as r:
                baseline_rollup = (await r.json())["tenants"].get("acme", {})

        return {
            "replicas": N_REPLICAS,
            "engines": N_ENGINES,
            "convergence": {
                "storm_events": events_pumped,
                "per_replica_lag": convergence,
                "restart_divergence_blocks": {
                    "cold": div_before.get("replica-cold"),
                    "healed": div_after.get("replica-cold"),
                },
            },
            "stickiness": {
                "identical_membership": sticky_identical,
                "skewed_membership": sticky_skewed,
                "detection_proven": (
                    sticky_identical["violation_rate"] == 0.0
                    and sum(sticky_skewed["violations"].values()) > 0
                ),
                "ring_divergent_flagged": bool(ring_divergent),
            },
            "tenant_accounting": {
                "budget_rps": 20.0,
                "fleet_3_replicas": {
                    **fleet_flood,
                    "limit_utilization": rollup.get("limit_utilization"),
                    "overadmission_ratio": rollup.get("overadmission_ratio"),
                },
                "single_router_baseline": {
                    **baseline_flood,
                    "limit_utilization":
                        baseline_rollup.get("limit_utilization"),
                    "overadmission_ratio":
                        baseline_rollup.get("overadmission_ratio"),
                },
            },
        }
    finally:
        import os as _os

        for runner in reversed(runners):
            await runner.cleanup()
        _os.unlink(tenant_file.name)


def _phase_fleet_main() -> None:
    """Subprocess entry for the CPU-only fleet-coherence bench. Forces CPU
    before anything touches jax — runs pre-preflight, so the multi-replica
    baselines survive a wedged TPU tunnel."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_fleet_bench())
    print(json.dumps({"fleet": result}), flush=True)


async def _fleet_scale_client(spec: dict) -> dict:
    """One load-generator process (bench.py --phase fleet_scale_client):
    closed-loop or paced completions, or long-hold SSE streams, against a
    list of router URLs. Separate OS processes so the CLIENT is never the
    serialization point when measuring multi-router aggregate req/s."""
    import asyncio

    import aiohttp

    from vllm_production_stack_tpu.utils.system import raise_fd_limit

    raise_fd_limit(200_000)
    routers = spec["routers"]
    mode = spec.get("mode", "throughput")
    seconds = float(spec.get("seconds", 6.0))
    conc = int(spec.get("concurrency", 32))
    prefix = spec.get("session_prefix", "s")
    body_base = {
        "model": spec.get("model", "tiny"),
        "prompt": "hello fleet",
        "max_tokens": int(spec.get("max_tokens", 1)),
    }
    if spec.get("tokens_per_sec"):
        # fake-engine pacing knob: slow token gaps = long-held streams
        body_base["tokens_per_sec"] = spec["tokens_per_sec"]
    headers_base = {}
    if spec.get("auth"):
        headers_base["Authorization"] = f"Bearer {spec['auth']}"
    # "errors" = anything that wasn't a 200/429 INCLUDING client-side
    # transport faults; "server_5xx" counts only actual 5xx statuses so
    # the outage drill's "kept serving" claim isn't contradicted by a
    # load-client connection blip
    counts = {"completed": 0, "throttled": 0, "errors": 0, "server_5xx": 0}
    retry_after: list[str] = []

    conn = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(
        total=None, sock_connect=60, sock_read=180
    )
    async with aiohttp.ClientSession(
        connector=conn, timeout=timeout
    ) as sess:
        async def stream_worker(i: int) -> None:
            url = routers[i % len(routers)]
            try:
                async with sess.post(
                    url + "/v1/completions",
                    json={**body_base, "stream": True},
                    headers={**headers_base, "x-user-id": f"{prefix}-{i}"},
                ) as r:
                    async for _ in r.content:
                        pass
                    counts["completed" if r.status == 200 else "errors"] += 1
            except Exception:
                counts["errors"] += 1

        async def loop_worker(i: int) -> None:
            url = routers[i % len(routers)]
            sid = f"{prefix}-{i}"
            paced = spec.get("paced_rps")
            interval = (1.0 / paced) if paced else 0.0
            t_end = time.monotonic() + seconds
            while time.monotonic() < t_end:
                t0 = time.monotonic()
                try:
                    async with sess.post(
                        url + "/v1/completions", json=body_base,
                        headers={**headers_base, "x-user-id": sid},
                    ) as r:
                        await r.read()
                        if r.status == 200:
                            counts["completed"] += 1
                        elif r.status == 429:
                            counts["throttled"] += 1
                            ra = r.headers.get("Retry-After")
                            if ra and len(retry_after) < 4:
                                retry_after.append(ra)
                        else:
                            counts["errors"] += 1
                            if r.status >= 500:
                                counts["server_5xx"] += 1
                except Exception:
                    counts["errors"] += 1
                if interval:
                    dt = interval - (time.monotonic() - t0)
                    if dt > 0:
                        await asyncio.sleep(dt)

        t0 = time.monotonic()
        worker = stream_worker if mode == "streams" else loop_worker
        await asyncio.gather(*(worker(i) for i in range(conc)))
        elapsed = time.monotonic() - t0
    return {**counts, "elapsed_s": round(elapsed, 3),
            "retry_after_sample": retry_after}


def _phase_fleet_scale_client_main() -> None:
    import asyncio

    os.environ["JAX_PLATFORMS"] = "cpu"
    spec = json.loads(sys.argv[sys.argv.index("--spec") + 1])
    print(json.dumps(asyncio.run(_fleet_scale_client(spec))), flush=True)


async def _fleet_scale_bench() -> dict:
    """Horizontal router scale-out (docs/34-fleet-routing.md), CPU-only
    pre-preflight — the EXECUTION half of ROADMAP 1, measured over real
    OS processes and real wire (the PR 9 `fleet` phase measures what
    breaks; this phase proves the fixes):

    1. **throughput scaling**: M ∈ {1,2,3} router processes × N=4 fake
       engine processes, one load-generator PROCESS per router — the
       aggregate req/s curve in M (near-linear when the host has the
       cores; `host_cores` rides the JSON so a serialized 1-core result
       reads as what it is).
    2. **10k concurrent streams**: long-held SSE streams spread across
       the M=3 fleet; peak sum of `tpu:router_active_streams` across
       replicas, with engine-side stickiness violations staying ~0 under
       stable membership (identical rings by construction).
    3. **fleet-scaled tenant budgets**: the 3-replica flood from PR 9's
       phase, now with --fleet-budget-scaling on — over-admission must
       fall from ≈2 to ≈0 with no admission-path hop; 429 Retry-After is
       sampled from the SCALED buckets.
    4. **controller-outage drill**: the controller process is killed
       mid-flood — replicas degrade to the full local budget inside
       ~3 report intervals and KEEP SERVING (fail open).
    5. **cold-replica heal**: a 10k-block engine publishes (real
       KVEventPublisher, fan-out) to 2 embedded-index replicas + the
       controller; a freshly booted replica's divergence on /fleet reads
       the full slice, then the publisher's own background resync heals
       it to 0 — no human, no per-request controller hop.
    """
    import asyncio
    import shlex
    import socket
    import tempfile

    import aiohttp
    import yaml as _yaml

    N_ENGINES = 4
    BUDGET_RPS = 30.0
    LOAD_S = float(os.environ.get("FLEET_SCALE_SECONDS", "6"))
    STREAM_TARGET = int(os.environ.get("FLEET_SCALE_STREAMS", "10000"))
    REPORT_INTERVAL = 0.25

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    procs: list[subprocess.Popen] = []
    runners = []

    def spawn(module: str, args: list[str]) -> subprocess.Popen:
        p = subprocess.Popen(
            [sys.executable, "-m", module, *args], cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        procs.append(p)
        return p

    tenant_file = tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                              delete=False)
    _yaml.safe_dump(
        {"acme": {"api_key": "k-acme", "requests_per_s": BUDGET_RPS}},
        tenant_file,
    )
    tenant_file.close()

    sess = aiohttp.ClientSession(
        connector=aiohttp.TCPConnector(limit=0),
        timeout=aiohttp.ClientTimeout(total=15),
    )

    async def wait_http(url: str, path: str = "/health",
                        timeout_s: float = 60.0) -> None:
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                async with sess.get(url + path) as r:
                    if r.status < 500:
                        return
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise RuntimeError(f"{url}{path} never came up")
            await asyncio.sleep(0.2)

    async def scrape_gauge(url: str, name: str) -> float | None:
        async with sess.get(url + "/metrics") as r:
            text = await r.text()
        for line in text.splitlines():
            if line.startswith(name + " ") or line.startswith(name + "{"):
                try:
                    return float(line.rsplit(" ", 1)[1])
                except ValueError:
                    return None
        return None

    async def run_clients(specs: list[dict]) -> list[dict]:
        """One load-generator subprocess per spec; parse each last line."""
        children = []
        for spec in specs:
            children.append(await asyncio.create_subprocess_exec(
                sys.executable, os.path.join(REPO, "bench.py"),
                "--phase", "fleet_scale_client",
                "--spec", json.dumps(spec),
                cwd=REPO, env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL,
            ))
        outs = []
        for i, child in enumerate(children):
            out, _ = await child.communicate()
            lines = out.decode(errors="replace").strip().splitlines()
            if not lines:
                # a client that died before printing (import error, OOM)
                # must surface as a named failure, not an IndexError
                outs.append({
                    "completed": 0, "throttled": 0, "errors": 0,
                    "server_5xx": 0, "retry_after_sample": [],
                    "error": f"load client {i} produced no output "
                             f"(rc={child.returncode})",
                })
                continue
            outs.append(json.loads(lines[-1]))
        return outs

    try:
        # -- shared fleet: engines + controller -------------------------
        engine_ports = [free_port() for _ in range(N_ENGINES)]
        engine_urls = [f"http://127.0.0.1:{p}" for p in engine_ports]
        for port, url in zip(engine_ports, engine_urls):
            spawn("vllm_production_stack_tpu.testing.fake_engine", [
                "--port", str(port), "--model", "tiny",
                "--tokens-per-sec", "2000",
                "--self-url", url, "--no-request-log",
            ])
        ctrl_port = free_port()
        ctrl_url = f"http://127.0.0.1:{ctrl_port}"
        ctrl_proc = spawn("vllm_production_stack_tpu.engine.kv_controller", [
            "--host", "127.0.0.1", "--port", str(ctrl_port),
            "--tenant-table-file", tenant_file.name,
            # rate window < the flood length, so utilization measures the
            # steady state instead of diluting over pre-flood idle time
            "--fleet-rate-window", "10",
        ])
        for url in engine_urls:
            await wait_http(url)
        await wait_http(ctrl_url)

        def router_cmd(replica: str, port: int) -> list[str]:
            return [
                "--host", "127.0.0.1", "--port", str(port),
                "--static-backends", ",".join(engine_urls),
                "--static-models", ";".join(["tiny"] * N_ENGINES),
                "--routing-logic", "session", "--session-key", "x-user-id",
                "--router-replica-id", replica,
                "--fleet-report-url", ctrl_url,
                "--fleet-report-interval", str(REPORT_INTERVAL),
                "--tenant-table-file", tenant_file.name,
                "--breaker-failure-threshold", "0",
                "--request-tracing", "off",
            ]

        # -- 1. throughput scaling in M ----------------------------------
        throughput: dict = {}
        routers3: list[str] = []
        router3_procs: list[subprocess.Popen] = []
        for m in (1, 2, 3):
            ports = [free_port() for _ in range(m)]
            urls = [f"http://127.0.0.1:{p}" for p in ports]
            # replica ids OVERLAP across runs (r0..r{m-1}) so the
            # controller's fleet view counts exactly m live replicas
            # instead of accumulating dead ids from earlier runs
            batch = [
                spawn("vllm_production_stack_tpu.router.app",
                      router_cmd(f"r{i}", port))
                for i, port in enumerate(ports)
            ]
            for url in urls:
                await wait_http(url)
            outs = await run_clients([
                {"mode": "throughput", "routers": [url],
                 "seconds": LOAD_S, "concurrency": 48,
                 "max_tokens": 1, "session_prefix": f"m{m}-{j}"}
                for j, url in enumerate(urls)
            ])
            total = sum(o["completed"] for o in outs)
            throughput[f"m{m}"] = {
                "req_per_s": round(total / LOAD_S, 1),
                "completed": total,
                "errors": sum(o["errors"] for o in outs),
            }
            if m == 3:
                routers3, router3_procs = urls, batch
            else:
                for p in batch:
                    p.terminate()
                for p in batch:
                    p.wait(timeout=15)
        m1 = throughput["m1"]["req_per_s"] or 1.0
        throughput["scaling_m3_over_m1"] = round(
            throughput["m3"]["req_per_s"] / m1, 2
        )
        throughput["host_cores"] = os.cpu_count()
        if (os.cpu_count() or 1) < 5:
            throughput["note"] = (
                "router/engine/client processes timeshare "
                f"{os.cpu_count()} core(s) — aggregate req/s is "
                "serialized by the host, not the architecture"
            )

        # -- 2. 10k concurrent long-held streams through M=3 -------------
        n_clients = 4
        per_client = STREAM_TARGET // n_clients
        stream_clients = [
            asyncio.create_task(run_clients([
                {"mode": "streams", "routers": routers3,
                 "concurrency": per_client, "max_tokens": 4,
                 "tokens_per_sec": 0.2,  # 4 tokens @ 5s gap ≈ 20s hold
                 "session_prefix": f"st{j}"}
            ]))
            for j in range(n_clients)
        ]
        peak_streams = 0.0
        while not all(t.done() for t in stream_clients):
            total = 0.0
            for url in routers3:
                v = await scrape_gauge(url, "tpu:router_active_streams")
                total += v or 0.0
            peak_streams = max(peak_streams, total)
            await asyncio.sleep(0.5)
        stream_outs = [t.result()[0] for t in stream_clients]
        violations = 0
        observed = 0
        for url in engine_urls:
            async with sess.get(url + "/debug/stickiness") as r:
                snap = await r.json()
            violations += sum(snap["violations"].values())
            observed += snap["observed"]
        streams = {
            "target": STREAM_TARGET,
            "peak_active_streams": int(peak_streams),
            "completed": sum(o["completed"] for o in stream_outs),
            "errors": sum(o["errors"] for o in stream_outs),
            "stickiness": {
                "observed": observed,
                "violations": violations,
                "violation_rate": round(violations / max(1, observed), 6),
            },
        }

        # -- 3. fleet-scaled tenant budgets ------------------------------
        # reporters have long since learned replicas=3 (0.25s interval)
        FLOOD_S = 12.0
        tenant_specs = [
            {"mode": "tenant", "routers": [url], "seconds": FLOOD_S,
             "concurrency": 2, "paced_rps": 12.5, "max_tokens": 1,
             "auth": "k-acme", "session_prefix": f"t{j}"}
            for j, url in enumerate(routers3)
        ]
        scale_gauge = await scrape_gauge(
            routers3[0], "tpu:router_tenant_budget_scale"
        )
        outs = await run_clients(tenant_specs)
        await asyncio.sleep(3 * REPORT_INTERVAL)  # final reports land
        async with sess.get(ctrl_url + "/fleet") as r:
            rollup = (await r.json())["tenants"].get("acme", {})
        scaled = {
            "budget_rps": BUDGET_RPS,
            "offered_rps": 75.0,
            "admitted": sum(o["completed"] for o in outs),
            "throttled": sum(o["throttled"] for o in outs),
            "admitted_rps": round(
                sum(o["completed"] for o in outs) / FLOOD_S, 2
            ),
            "limit_utilization": rollup.get("limit_utilization"),
            "overadmission_ratio": rollup.get("overadmission_ratio"),
            "budget_scale_gauge": scale_gauge,
            "retry_after_sample": next(
                (o["retry_after_sample"] for o in outs
                 if o["retry_after_sample"]), []
            ),
        }

        # -- 4. controller-outage drill ----------------------------------
        ctrl_proc.terminate()
        ctrl_proc.wait(timeout=15)
        # a failed report past 3 intervals degrades budgets to full-local
        await asyncio.sleep(6 * REPORT_INTERVAL + 1.0)
        outs = await run_clients([
            dict(spec, seconds=6.0) for spec in tenant_specs
        ])
        outage = {
            "admitted": sum(o["completed"] for o in outs),
            "admitted_rps": round(
                sum(o["completed"] for o in outs) / 6.0, 2
            ),
            "errors_5xx": sum(o.get("server_5xx", 0) for o in outs),
            "client_errors": sum(o["errors"] for o in outs),
            "budget_scale_gauge": await scrape_gauge(
                routers3[0], "tpu:router_tenant_budget_scale"
            ),
            "kept_serving": sum(o["completed"] for o in outs) > 0,
            "degraded_to_full_local": None,  # filled below
        }
        outage["degraded_to_full_local"] = (
            outage["budget_scale_gauge"] == 1.0
            and outage["admitted_rps"] > 1.5 * BUDGET_RPS
        )
        for p in router3_procs:
            p.terminate()

        # -- 5. cold-replica heal through publisher fan-out --------------
        from aiohttp import web

        from vllm_production_stack_tpu.engine.kv_cache import KVBlockPool
        from vllm_production_stack_tpu.engine.kv_controller import (
            KVController,
        )
        from vllm_production_stack_tpu.engine.kv_events import (
            KVEventPublisher,
        )
        from vllm_production_stack_tpu.router.app import build_app
        from vllm_production_stack_tpu.router.args import parse_args

        BLOCK = 16
        HEAL_BLOCKS = 10_000

        async def serve(app, port: int = 0):
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", port)
            await site.start()
            runners.append(runner)
            return runner, runner.addresses[0][1]

        heal_ctrl = KVController(["http://e0"], mode="indexed")
        _, heal_ctrl_port = await serve(heal_ctrl.build_app())
        heal_ctrl_url = f"http://127.0.0.1:{heal_ctrl_port}"

        def heal_router_args(replica: str):
            return parse_args([
                "--static-backends", "http://e0",
                "--static-models", "tiny",
                "--routing-logic", "kvaware",
                "--kv-index-mode", "embedded",
                "--kv-index-tokenizer", "byte",
                "--router-replica-id", replica,
                "--fleet-report-url", heal_ctrl_url,
                "--fleet-report-interval", "0.2",
            ])

        _, port_a = await serve(build_app(heal_router_args("warm")))
        cold_port = free_port()

        pool = KVBlockPool(HEAL_BLOCKS + 16, BLOCK)

        async def snapshot_fn():
            return pool.snapshot_events()

        pub = KVEventPublisher(
            [f"http://127.0.0.1:{port_a}",
             f"http://127.0.0.1:{cold_port}", heal_ctrl_url],
            "http://e0", pool.events, snapshot_fn, BLOCK, lambda: sess,
            interval_s=0.05, jitter_frac=0.0,
        )
        parent = pool.root_hash()
        rng_base = 0
        for _ in range(HEAL_BLOCKS):
            blk = pool.allocate()
            assert blk is not None
            parent = pool.register_full_block(
                blk, parent,
                tuple(range(rng_base, rng_base + BLOCK)),
            )
            rng_base += BLOCK
        pub.start()
        # warm replica + controller converge; the cold replica's
        # subscriber keeps failing (port closed) and stays snapshot-owed
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if heal_ctrl.index.stats()["hashes"] >= HEAL_BLOCKS:
                break
            await asyncio.sleep(0.2)
        # pause publishing only to take a deterministic COLD reading (on
        # a fast host the background snapshot can land before the first
        # fleet report); production publishers never pause — the heal
        # below runs through the same background loop
        await pub.stop()
        # boot the cold replica on the pre-registered address; its first
        # fleet report carries an EMPTY index -> divergence = full slice
        runner_cold, _ = await serve(
            build_app(heal_router_args("cold")), cold_port
        )
        cold_state = runner_cold.app["state"]
        await cold_state.fleet_reporter.report_once()
        async with sess.get(heal_ctrl_url + "/fleet") as r:
            before = {
                x["replica"]: x["divergence_blocks"]
                for x in (await r.json())["replicas"]
            }
        # ...and the publisher's own background fan-out heals it: the
        # cold subscriber answers the next batch with "resync", gets the
        # snapshot, divergence returns to 0 — no human anywhere
        pub.start()
        t_heal0 = time.monotonic()
        healed = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            await cold_state.fleet_reporter.report_once()
            async with sess.get(heal_ctrl_url + "/fleet") as r:
                div = {
                    x["replica"]: x["divergence_blocks"]
                    for x in (await r.json())["replicas"]
                }.get("cold")
            if div == 0:
                healed = div
                break
            await asyncio.sleep(0.2)
        heal_time = time.monotonic() - t_heal0
        await pub.stop()
        cold_heal = {
            "blocks": HEAL_BLOCKS,
            "divergence_cold": before.get("cold"),
            "divergence_healed": healed,
            "heal_time_s": round(heal_time, 2),
            "publisher": pub.debug_snapshot(),
        }

        return {
            "engines": N_ENGINES,
            "host_cores": os.cpu_count(),
            "throughput": throughput,
            "streams": streams,
            "tenant_budget": {
                "fleet_scaled_3_replicas": scaled,
                "controller_outage_drill": outage,
            },
            "cold_replica": cold_heal,
            "command_shape": shlex.join(
                ["python", "-m", "vllm_production_stack_tpu.router.app",
                 *router_cmd("rN", 0)][:8]
            ) + " ...",
        }
    finally:
        for runner in reversed(runners):
            try:
                await runner.cleanup()
            except Exception:
                pass
        await sess.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        os.unlink(tenant_file.name)


def _phase_fleet_scale_main() -> None:
    """Subprocess entry for the CPU-only horizontal-scale-out bench.
    Forces CPU before anything touches jax — runs pre-preflight, so the
    multi-replica execution evidence survives a wedged TPU tunnel."""
    import asyncio

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_fleet_scale_bench())
    print(json.dumps({"fleet_scale": result}), flush=True)


def _phase_hydration_main() -> None:
    """Subprocess entry for the CPU-only hydration-planner bench. Forces
    CPU before anything touches jax — runs pre-preflight, so the
    compute-or-load evidence survives a wedged TPU tunnel."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = _hydration_bench()
    print(json.dumps({"hydration": result}), flush=True)


def _phase_kvquant_main() -> None:
    """Subprocess entry for the CPU-only at-rest KV quantization bench.
    Forces CPU before anything touches jax — runs pre-preflight, so the
    codec evidence survives a wedged TPU tunnel."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = _kvquant_bench()
    print(json.dumps({"kvquant": result}), flush=True)


async def _peer_bench() -> dict:
    """Peer-engine KV tier: priced route-vs-migrate vs owner-affinity
    under skewed prefix popularity (docs/35-peer-kv-reuse.md). CPU-only,
    pre-preflight — router + fake engines, no jax on the hot path.

    Scenario: 3 fake-engine-backed engines, 4 decode seats each; ONE
    engine owns the hot prefix (its residency fed into the router's
    embedded cluster KV index exactly as a publisher would, and its
    warm-prefix model marked warm). A closed-loop flood where 85% of
    requests share that hot prefix then runs twice against fresh fleets:

    - **affinity** (--kv-migrate-scoring off): KV-aware routing follows
      the prefix owner, so the hot traffic serializes behind ONE
      engine's seats while the other two idle;
    - **priced** (--kv-migrate-scoring priced): once the owner's load/
      TTFT exceeds the least-loaded engine's wait plus the measured
      migration cost, requests route there with x-kv-owner-hint stamped —
      the fake pays the (cheap) peer-pull once per engine, the prefix
      warms fleet-wide, and all three engines serve the hot traffic.

    Reported: aggregate completion tok/s and TTFT p50/p99 per arm, the
    router's migrate-decision counts, and the engines' peer-pull/cold-
    prefill counters. The acceptance bar: priced beats affinity on
    aggregate tok/s OR TTFT p99 (it should win both)."""
    import asyncio

    import aiohttp
    from aiohttp import web

    from vllm_production_stack_tpu.kv_index import chain_hashes
    from vllm_production_stack_tpu.router.app import build_app
    from vllm_production_stack_tpu.router.args import parse_args
    from vllm_production_stack_tpu.testing.fake_engine import FakeEngine
    from vllm_production_stack_tpu.utils.tokenizer import hashing_tokenizer

    N_ENGINES, SEATS, BLOCK = 3, 4, 16
    CLIENTS, DURATION_S, HOT_SHARE = 24, 8.0, 0.85
    GEN_TOKENS = 24
    # ~8KB hot prefix => ~8k byte-tokens: far above the 256-token
    # threshold, and a 4s cold prefill at 2000 tok/s vs 0.2s peer pull
    hot_prefix = "the shared system prompt " * 400
    # tpulint: allow(async-blocking) — constructing the byte tokenizer is
    # a trivial object init, not a tokenize; encoding runs off-loop below
    tok = hashing_tokenizer("byte")

    runners: list[web.AppRunner] = []

    async def serve(app) -> tuple[web.AppRunner, str]:
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        runners.append(runner)
        return runner, f"http://127.0.0.1:{runner.addresses[0][1]}"

    async def run_arm(scoring: str) -> dict:
        engines: list[FakeEngine] = []
        urls: list[str] = []
        arm_runners: list[web.AppRunner] = []
        for _ in range(N_ENGINES):
            eng = FakeEngine(
                model="fake-model", tokens_per_sec=120.0,
                default_tokens=GEN_TOKENS, log_requests=False,
                seats=SEATS, prefill_tps=2000.0, peer_pull_tps=40000.0,
                kv_bytes_per_token=4096.0,
            )
            runner = web.AppRunner(eng.build_app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            arm_runners.append(runner)
            engines.append(eng)
            urls.append(f"http://127.0.0.1:{runner.addresses[0][1]}")
        owner_eng, owner_url = engines[0], urls[0]
        owner_eng.warm_prefixes.add(
            hot_prefix[: FakeEngine.WARM_KEY_CHARS]
        )
        router_runner, router_url = await serve(build_app(parse_args([
            "--static-backends", ",".join(urls),
            "--static-models", ";".join(["fake-model"] * N_ENGINES),
            "--routing-logic", "kvaware",
            "--kv-index-mode", "embedded",
            "--kv-index-tokenizer", "byte",
            "--kv-migrate-scoring", scoring,
            "--engine-stats-interval", "0.5",
            "--request-stats-window", "5",
            "--breaker-failure-threshold", "0",
        ])))
        # feed the owner's hot-prefix residency into the embedded index
        # exactly as its KV event publisher would (snapshot POST);
        # tokenize+hash off-loop (multi-KB prompt)
        hashes = await asyncio.get_running_loop().run_in_executor(
            None, lambda: chain_hashes(tok.encode(hot_prefix), BLOCK)
        )
        async with aiohttp.ClientSession() as sess:
            async with sess.post(router_url + "/kv/events", json={
                "engine": owner_url, "epoch": "bench", "block_size": BLOCK,
                "snapshot": True, "seq": 0,
                "hashes": [f"{h:x}" for h in hashes],
            }) as resp:
                assert resp.status == 200, await resp.text()

            # closed-loop flood: each client loops hot/cold prompts until
            # the deadline; TTFT = first SSE chunk
            t_end = time.monotonic() + DURATION_S
            ttfts: list[float] = []
            done_tokens = [0]
            failures = [0]

            async def client(i: int) -> None:
                r = 0
                while time.monotonic() < t_end:
                    r += 1
                    hot = (i * 31 + r) % 100 < HOT_SHARE * 100
                    prompt = (
                        hot_prefix + f" user{i} round{r}"
                        if hot else f"cold prompt {i}-{r} " * 30
                    )
                    t0 = time.monotonic()
                    try:
                        async with sess.post(
                            router_url + "/v1/completions",
                            json={"model": "fake-model", "prompt": prompt,
                                  "max_tokens": GEN_TOKENS, "stream": True},
                        ) as resp:
                            if resp.status != 200:
                                failures[0] += 1
                                continue
                            first = True
                            async for _ in resp.content.iter_any():
                                if first:
                                    ttfts.append(time.monotonic() - t0)
                                    first = False
                            done_tokens[0] += GEN_TOKENS
                    except aiohttp.ClientError:
                        failures[0] += 1

            t0 = time.monotonic()
            await asyncio.gather(*(client(i) for i in range(CLIENTS)))
            elapsed = time.monotonic() - t0

            # migrate-decision counters off the router's own /metrics
            async with sess.get(router_url + "/metrics") as resp:
                metrics_text = await resp.text()

        def count(decision: str) -> float:
            needle = (
                "tpu:router_kv_migrate_decisions_total"
                f'{{decision="{decision}"}} '
            )
            for ln in metrics_text.splitlines():
                if ln.startswith(needle):
                    return float(ln.split()[-1])
            return 0.0

        ttfts.sort()

        def pct(p: float) -> float:
            if not ttfts:
                return 0.0
            return round(ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))], 4)

        for runner in arm_runners:
            await runner.cleanup()
            runners.remove(runner)
        return {
            "scoring": scoring,
            "agg_tok_per_s": round(done_tokens[0] / elapsed, 1),
            "requests": len(ttfts),
            "failures": failures[0],
            "ttft_p50_s": pct(0.50),
            "ttft_p99_s": pct(0.99),
            "decisions": {"owner": count("owner"),
                          "migrate": count("migrate")},
            "owner_requests": owner_eng.total_requests,
            "per_engine_requests": [e.total_requests for e in engines],
            "peer_pulls": sum(e.peer_pulls for e in engines),
            "cold_prefills": sum(e.cold_prefills for e in engines),
        }

    async def bit_identical_check() -> dict:
        """REAL-engine half of the acceptance bar: engine A computes a
        prompt, engine B pulls it over the actual peer tier (owner hint,
        /kv/peer_contains + /kv/peer_fetch, frame adoption) — tokens must
        be bit-equal to A's compute and the hydration partition exact."""
        import numpy as np

        from vllm_production_stack_tpu.engine.config import (
            CacheConfig, EngineConfig, ModelConfig, SchedulerConfig,
        )
        from vllm_production_stack_tpu.engine.engine import LLMEngine
        from vllm_production_stack_tpu.engine.kv_flow import TierBandwidth
        from vllm_production_stack_tpu.engine.request import SamplingParams
        from vllm_production_stack_tpu.engine.server import EngineServer

        bs = 8
        greedy = SamplingParams(
            max_tokens=8, temperature=0.0, ignore_eos=True
        )

        def tiny(peer: bool) -> LLMEngine:
            return LLMEngine(EngineConfig(
                model=ModelConfig.tiny(),
                cache=CacheConfig(
                    block_size=bs, num_blocks=64, num_host_blocks=4,
                ),
                scheduler=SchedulerConfig(
                    max_num_seqs=2, max_num_batched_tokens=64,
                    decode_buckets=(2,), prefill_buckets=(32, 64),
                    decode_window=4,
                ),
                kv_hydration="planner" if peer else "sync",
                kv_hydration_chunk_blocks=2,
                kv_peer_fetch=peer,
            ))

        prompt = [int(t) for t in
                  np.random.RandomState(13).randint(1, 500, size=6 * bs)]
        loop = asyncio.get_running_loop()
        eng_a = tiny(peer=False)
        ref = eng_a.generate([prompt], greedy)[0]["token_ids"]
        runner = web.AppRunner(
            EngineServer(eng_a, served_model_name="tiny").build_app()
        )
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        a_url = f"http://127.0.0.1:{runner.addresses[0][1]}"
        try:
            eng_b = tiny(peer=True)
            # cross the sample floor + seed a compute-rate estimate (the
            # planner's trust gate), exactly like the tier-1 tests
            eng_b.flow.record("peer", "in", TierBandwidth.MIN_BYTES, 32, 0.01)
            eng_b.flow.record("peer", "in", TierBandwidth.MIN_BYTES, 32, 0.01)
            eng_b.generate([[7] * bs], greedy)
            t0 = time.perf_counter()
            got = await loop.run_in_executor(
                None,
                lambda: eng_b.generate(
                    [prompt], greedy, kv_owner_hint=a_url
                )[0]["token_ids"],
            )
            http_latency = time.perf_counter() - t0
            snap = eng_b.flow.snapshot()
            hyd = snap["hydration"]
            partition_exact = sum(hyd.values()) == eng_b._prompt_tokens
            result = {
                "tokens_equal": got == ref,
                "peer_fetch_tokens": hyd.get("peer_fetch", 0),
                "partition_exact": partition_exact,
                "latency_s": round(http_latency, 3),
                "wire_bytes": snap["bytes"].get("peer/in", 0),
            }
            assert result["tokens_equal"], (got, ref)
            assert result["peer_fetch_tokens"] > 0, hyd
            assert partition_exact, hyd
            await loop.run_in_executor(
                None, lambda: eng_b.runner.shutdown(True)
            )
        finally:
            await runner.cleanup()
        eng_a.runner.shutdown(wait=True)
        return result

    def device_arm() -> dict:
        """Device-transport half of the acceptance bar (docs/39): two REAL
        OS processes sharing a mesh group — the puller's hydration fetch
        lane moves the owner's pages over the shard-flip collective
        instead of HTTP, and the worker itself asserts token-identity vs
        a from-scratch oracle plus (device, in)-only metering. Reported
        side by side with the HTTP arm's latency/bytes above."""
        from vllm_production_stack_tpu.parallel.distributed import (
            run_multiprocess_device_peer_dryrun,
        )

        outs = run_multiprocess_device_peer_dryrun(timeout_s=240)
        result = {"ok": True}
        for line in "\n".join(outs).splitlines():
            if "DEVPEER_DRYRUN_OK" not in line:
                continue
            for tok in line.split():
                for key in ("pulled_bytes", "latency_s", "served_bytes"):
                    if tok.startswith(key + "="):
                        result[key] = float(tok.split("=", 1)[1])
        return result

    try:
        affinity = await run_arm("off")
        priced = await run_arm("priced")
        bit_identical = await bit_identical_check()
        loop = asyncio.get_running_loop()
        device = await loop.run_in_executor(None, device_arm)
    finally:
        for runner in runners:
            await runner.cleanup()

    return {
        "engines": N_ENGINES,
        "seats_per_engine": SEATS,
        "clients": CLIENTS,
        "hot_share": HOT_SHARE,
        "affinity": affinity,
        "priced": priced,
        "bit_identical": bit_identical,
        # HTTP arm (bit_identical.latency_s/wire_bytes) vs device arm
        # (device.latency_s/pulled_bytes): the same pull over both wires
        "device": device,
        "speedup_tok_per_s": (
            round(priced["agg_tok_per_s"] / affinity["agg_tok_per_s"], 2)
            if affinity["agg_tok_per_s"] else None
        ),
        "ttft_p99_ratio": (
            round(affinity["ttft_p99_s"] / priced["ttft_p99_s"], 2)
            if priced["ttft_p99_s"] else None
        ),
        # the acceptance bar (ISSUE 13): priced must beat owner-affinity
        # on aggregate tok/s or TTFT p99 under skewed popularity
        "priced_beats_affinity": bool(
            priced["agg_tok_per_s"] > affinity["agg_tok_per_s"]
            or priced["ttft_p99_s"] < affinity["ttft_p99_s"]
        ),
        "migrations_happened": priced["decisions"]["migrate"] > 0,
    }


def _phase_peer_main() -> None:
    """Subprocess entry for the CPU-only peer route-vs-migrate bench.
    Forces CPU before anything touches jax — runs pre-preflight, so the
    cluster-reuse evidence survives a wedged TPU tunnel."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_peer_bench())
    print(json.dumps({"peer": result}), flush=True)


async def _rebalance_bench() -> dict:
    """Dynamic P/D pool rebalancing (docs/40-pool-rebalancing.md):
    a decode-heavy workload shift against a statically partitioned
    4-engine fleet (3 prefill + 1 decode, the wrong split for the
    traffic). CPU-only, pre-preflight — fake engines + real router +
    real KV controller hosting the real rebalancer, everything over
    actual aiohttp wire.

    - **static** (rebalancer off): decode queue-wait p95 blows through
      the TpuSeatStarvation trigger (>1s queued while most of the
      fleet's seats sit idle) and STAYS there — the imbalance needs a
      human;
    - **rebalance** (rebalancer on): the controller diagnoses the
      decode-starved pool from the routers' fleet reports, drains the
      least-loaded prefill engine, flips it via POST /role, and the
      starvation condition clears — with ZERO failed requests and ZERO
      severed streams (asserted; the 2-phase router path re-picks around
      the drain refusals mid-flip);
    - **chaos arms**: the flip target killed mid-drain (episode must
      abandon, traffic must keep flowing) and a black-holed controller
      (engines + routers fail open — the actuator's death must never
      take the data plane with it)."""
    import asyncio
    import tempfile

    import aiohttp
    from aiohttp import web

    from vllm_production_stack_tpu.engine.kv_controller import KVController
    from vllm_production_stack_tpu.engine.rebalancer import RebalanceConfig
    from vllm_production_stack_tpu.router.app import build_app
    from vllm_production_stack_tpu.router.args import parse_args
    from vllm_production_stack_tpu.testing.fake_engine import FakeEngine

    N_PREFILL, N_DECODE, SEATS = 3, 1, 2
    CLIENTS, GEN_TOKENS, TOKENS_PER_SEC = 12, 10, 40.0
    TRIGGER_S = 1.0  # TpuSeatStarvation's queue-wait threshold

    async def serve(app) -> tuple[web.AppRunner, str]:
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        return runner, f"http://127.0.0.1:{runner.addresses[0][1]}"

    async def run_arm(rebalance_on: bool, duration_s: float,
                      chaos: str = "") -> dict:
        runners: list[web.AppRunner] = []
        state_dir = tempfile.mkdtemp(prefix="rebalance-bench-")
        ctrl_url = ""
        controller = None
        if chaos == "blackhole_controller":
            from vllm_production_stack_tpu.testing.faults import black_hole
            hole, port = await black_hole()
            ctrl_url = f"http://127.0.0.1:{port}"
        else:
            controller = KVController([], rebalance=RebalanceConfig(
                enabled=rebalance_on, interval_s=0.2, observe_s=1.0,
                cooldown_s=3.0, verify_window_s=1.0,
                min_prefill=1, min_decode=1,
                queue_wait_trigger_s=TRIGGER_S, occupancy_rich_max=0.5,
                drain_timeout_s=10.0, unreachable_limit=3,
                episode_timeout_s=60.0, engine_cooldown_s=5.0,
                state_file=state_dir + "/rebalancer.json",
            ))
            ctrl_runner, ctrl_url = await serve(controller.build_app())
            runners.append(ctrl_runner)

        engines: list[FakeEngine] = []
        urls: list[str] = []
        labels: list[str] = []
        url_runner: dict[str, web.AppRunner] = {}
        for i in range(N_PREFILL + N_DECODE):
            role = "prefill" if i < N_PREFILL else "decode"
            eng = FakeEngine(
                model="fake-model", tokens_per_sec=TOKENS_PER_SEC,
                default_tokens=GEN_TOKENS, log_requests=False,
                seats=SEATS, prefill_tps=4000.0, role=role,
                kv_controller_url=ctrl_url,
            )
            runner, url = await serve(eng.build_app())
            runners.append(runner)
            eng.self_url = url
            await eng._register()  # startup ran before self_url was known
            engines.append(eng)
            urls.append(url)
            labels.append(role)
            url_runner[url] = runner

        router_runner, router_url = await serve(build_app(parse_args([
            "--static-backends", ",".join(urls),
            "--static-models", ";".join(["fake-model"] * len(urls)),
            "--static-model-labels", ",".join(labels),
            "--routing-logic", "disaggregated_prefill",
            "--prefill-model-labels", "prefill",
            "--decode-model-labels", "decode",
            "--engine-stats-interval", "0.5",
            "--fleet-report-url", ctrl_url,
            "--fleet-report-interval", "0.3",
            "--breaker-failure-threshold", "0",
        ])))
        runners.append(router_runner)

        t_end = time.monotonic() + duration_s
        t0_arm = time.monotonic()
        ttfts: list[float] = []
        completed = [0]
        failures = [0]  # non-200 / transport errors — must stay 0
        dropped = [0]   # 200 streams that never saw a clean [DONE]
        killed = {"url": None}

        async def client(i: int, sess: aiohttp.ClientSession) -> None:
            r = 0
            while time.monotonic() < t_end:
                r += 1
                prompt = f"pool shift load {i}-{r} " * 8
                t0 = time.monotonic()
                try:
                    async with sess.post(
                        router_url + "/v1/completions",
                        json={"model": "fake-model", "prompt": prompt,
                              "max_tokens": GEN_TOKENS, "stream": True},
                    ) as resp:
                        if resp.status != 200:
                            failures[0] += 1
                            continue
                        first, clean = True, False
                        async for line in resp.content:
                            if first:
                                ttfts.append(time.monotonic() - t0)
                                first = False
                            if line.decode().strip() == "data: [DONE]":
                                clean = True
                        if clean:
                            completed[0] += 1
                        else:
                            dropped[0] += 1
                except aiohttp.ClientError:
                    failures[0] += 1

        # starvation timeline off the controller's own /rebalance view:
        # the TpuSeatStarvation shape — queued work past the trigger
        # while most of the fleet's decode seats sit empty
        samples: list[dict] = []

        def starved_now(pools: dict) -> tuple[bool, float, float]:
            members = [p for pool in pools.values() for p in pool.values()]
            if not members:
                return False, 0.0, 0.0
            decode_qw = [p["queue_wait_p95"]
                         for p in pools.get("decode", {}).values()]
            mean_occ = (sum(p["seat_occupancy"] for p in members)
                        / len(members))
            max_qw = max(decode_qw) if decode_qw else 0.0
            return (bool(decode_qw and max_qw > TRIGGER_S
                         and mean_occ < 0.5), max_qw, mean_occ)

        async def sampler(sess: aiohttp.ClientSession) -> None:
            if controller is None:
                return  # black-holed controller has no view to sample
            while time.monotonic() < t_end:
                try:
                    async with sess.get(ctrl_url + "/rebalance") as resp:
                        snap = await resp.json()
                    starved, max_qw, mean_occ = starved_now(
                        snap.get("pools") or {})
                    samples.append({
                        "t": round(time.monotonic() - t0_arm, 2),
                        "starved": starved,
                        "decode_qw_max_s": round(max_qw, 2),
                        "mean_occupancy": round(mean_occ, 2),
                        "phase": snap.get("phase"),
                    })
                    if (chaos == "kill_mid_drain"
                            and killed["url"] is None
                            and snap.get("episode")):
                        victim = snap["episode"]["engine"]
                        await url_runner[victim].cleanup()
                        runners.remove(url_runner[victim])
                        killed["url"] = victim
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.2)

        try:
            async with aiohttp.ClientSession() as sess:
                await asyncio.gather(
                    sampler(sess),
                    *(client(i, sess) for i in range(CLIENTS)),
                )
                snap = {}
                if controller is not None:
                    async with sess.get(ctrl_url + "/rebalance") as resp:
                        snap = await resp.json()
        finally:
            for runner in runners:
                await runner.cleanup()
            if chaos == "blackhole_controller":
                hole.close()

        elapsed = time.monotonic() - t0_arm
        ttfts.sort()

        def pct(p: float) -> float:
            if not ttfts:
                return 0.0
            return round(ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))], 3)

        tail = [s for s in samples if s["t"] > duration_s - 1.5]
        # run-length compress the starvation timeline: transitions only
        transitions = [s for i, s in enumerate(samples)
                       if i == 0 or s["starved"] != samples[i - 1]["starved"]]
        return {
            "timeline": transitions,
            "tail": tail,
            "rebalancer": "on" if rebalance_on else "off",
            "chaos": chaos or None,
            "completed": completed[0],
            "req_per_s": round(completed[0] / elapsed, 1),
            "failures": failures[0],
            "dropped_streams": dropped[0],
            "ttft_p50_s": pct(0.50),
            "ttft_p99_s": pct(0.99),
            "starvation_tripped": any(s["starved"] for s in samples),
            "starved_at_end": (bool(tail) and all(s["starved"]
                                                  for s in tail)),
            "cleared_at_end": (bool(tail) and not any(s["starved"]
                                                      for s in tail)),
            "flips": (snap.get("flips") if snap else None),
            "final_roles": {u: e.role for u, e in zip(urls, engines)},
            "role_flips": sum(e.role_flips for e in engines),
            "killed_engine": killed["url"],
        }

    static = await run_arm(rebalance_on=False, duration_s=7.0)
    dynamic = await run_arm(rebalance_on=True, duration_s=14.0)
    kill = await run_arm(rebalance_on=True, duration_s=10.0,
                         chaos="kill_mid_drain")
    blackhole = await run_arm(rebalance_on=False, duration_s=5.0,
                              chaos="blackhole_controller")

    # the acceptance bar (ISSUE 18): the static pool trips the
    # starvation condition and stays starved; the rebalancer flips a
    # role and CLEARS it with zero failed requests and zero severed
    # streams; both chaos arms finish with traffic still flowing
    assert static["starvation_tripped"], static
    assert static["starved_at_end"], static
    assert dynamic["starvation_tripped"], dynamic
    assert dynamic["flips"] and dynamic["flips"]["completed"] >= 1, dynamic
    assert dynamic["cleared_at_end"], dynamic
    for arm in (static, dynamic, kill, blackhole):
        assert arm["failures"] == 0, arm
        assert arm["dropped_streams"] == 0, arm
        assert arm["completed"] > 0, arm
    assert kill["killed_engine"] is not None, kill
    assert kill["flips"] and kill["flips"]["abandoned"] >= 1, kill

    return {
        "engines": N_PREFILL + N_DECODE,
        "initial_split": {"prefill": N_PREFILL, "decode": N_DECODE},
        "clients": CLIENTS,
        "static": static,
        "rebalance": dynamic,
        "chaos_kill_mid_drain": kill,
        "chaos_blackhole_controller": blackhole,
        "starvation_cleared_by_flip": bool(
            dynamic["starvation_tripped"] and dynamic["cleared_at_end"]
        ),
        "zero_dropped_streams": all(
            a["failures"] == 0 and a["dropped_streams"] == 0
            for a in (static, dynamic, kill, blackhole)
        ),
    }


def _phase_rebalance_main() -> None:
    """Subprocess entry for the CPU-only P/D pool-rebalancing bench.
    Forces CPU before anything touches jax — runs pre-preflight, so the
    role-flip evidence survives a wedged TPU tunnel."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_rebalance_bench())
    print(json.dumps({"rebalance": result}), flush=True)


def _phase_kvflow_main() -> None:
    """Subprocess entry for the CPU-only KV-flow telemetry bench. Forces
    CPU before anything touches jax — runs pre-preflight, so the flow
    evidence survives a wedged TPU tunnel."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = _kvflow_bench()
    print(json.dumps({"kvflow": result}), flush=True)


def _phase_saturation_main() -> None:
    """Subprocess entry for the CPU-only saturation/goodput bench. Forces
    CPU before anything touches jax — runs pre-preflight, so the goodput
    evidence survives a wedged TPU tunnel."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = _saturation_bench()
    print(json.dumps({"saturation": result}), flush=True)


def _phase_fairness_main() -> None:
    """Subprocess entry for the CPU-only multi-tenant fairness bench.
    Forces CPU before anything touches jax — like routing/robustness, this
    phase must report numbers even when the TPU tunnel is wedged."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_fairness_bench())
    print(json.dumps({"fairness": result}), flush=True)


def _phase_robustness_main() -> None:
    """Subprocess entry for the CPU-only robustness bench (shed latency +
    drain time). Forces CPU before anything touches jax — this phase must
    report numbers even when the TPU tunnel is wedged."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_robustness_bench())
    print(json.dumps({"robustness": result}), flush=True)


def _phase_tracing_main() -> None:
    """Subprocess entry for the CPU-only tracing-overhead bench (same
    flood, spine off vs on). Forces CPU before anything touches jax."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_tracing_bench())
    print(json.dumps({"tracing": result}), flush=True)


def _phase_blackbox_main() -> None:
    """Subprocess entry for the CPU-only flight-recorder/watchdog bench
    (three named wedges + recorder noise floor, docs/37-flight-recorder
    .md). Forces CPU before anything touches jax — this phase diagnoses
    wedges, so its own evidence must survive one."""
    import asyncio
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    result = asyncio.run(_blackbox_bench())
    print(json.dumps({"blackbox": result}), flush=True)


def _phase_micro_main() -> None:
    """Subprocess entry: enable the persistent compile cache, run the
    microbench (+ the step-loop attribution bench), print its JSON."""
    from bench_livestack import enable_persistent_cache

    enable_persistent_cache()
    micro = run_microbench()
    try:
        micro["steploop"] = run_steploop_bench()
    except Exception as e:  # never lose the headline number to the rider
        micro["steploop"] = {"error": str(e)}
    print(json.dumps({"microbench": micro}), flush=True)


def _phase_preflight_main() -> None:
    """Subprocess entry: touch the chip with one trivial dispatch. The
    tunnel has been observed to wedge for HOURS after a killed bench
    (grants hang in jax init) — when that happens every phase would eat
    its full timeout; this makes the failure mode one cheap, explicit
    section instead.

    Watchdog (r04 timed out, r05 wedged with no TPU dispatch): a daemon
    timer hard-kills this subprocess after PREFLIGHT_HARD_TIMEOUT_S
    (default 300 s, below the parent's kill window) having FIRST written
    the ENGINE-NATIVE postmortem artifact (flightrec.write_postmortem:
    thread stacks + redacted env + the wedged init stage — the same JSON
    black box a stalled serving engine dumps, docs/37-flight-recorder.md)
    and printed a structured diagnostic naming that file plus which init
    stage wedged (import / devices / dispatch), elapsed time, and the jax
    platform env. The parent then reports a named failure mode instead of
    a bare timeout, the r04/r05 wedge finally leaves a FILE behind, and
    the chip frees minutes sooner for nothing-else-to-lose retries."""
    import faulthandler
    import threading

    t0 = time.monotonic()
    stage = {"name": "import-jax"}
    hard_s = float(os.environ.get("PREFLIGHT_HARD_TIMEOUT_S", "300"))

    def watchdog() -> None:
        diag = {
            "error": f"watchdog: preflight wedged after {hard_s:.0f}s",
            "stage": stage["name"],
            "elapsed_s": round(time.monotonic() - t0, 1),
            "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
            "tpu_library": os.environ.get("TPU_LIBRARY_PATH", ""),
            "hint": "tunnel grant hang — kill stale jax processes / "
                    "re-establish the TPU tunnel before retrying",
        }
        # named failure mode FIRST, hard-exit ARMED second, postmortem
        # file last: the dump targets a filesystem that may itself be
        # wedged (hung PVC/NFS), and a blocked write must never suppress
        # the diagnostic or the kill this watchdog exists for
        print(json.dumps({"preflight": diag}), flush=True)
        faulthandler.dump_traceback()  # stderr merges into the phase log
        backstop = threading.Timer(20.0, lambda: os._exit(3))
        backstop.daemon = True
        backstop.start()
        try:
            from vllm_production_stack_tpu.engine.flightrec import (
                write_postmortem,
            )

            path, _doc = write_postmortem(
                os.environ.get("POSTMORTEM_DIR", "/tmp/tpu-postmortem"),
                "bench_preflight",
                f"preflight wedged at stage {stage['name']} after "
                f"{hard_s:.0f}s",
                sections={"preflight": dict(diag)},
            )
            diag["postmortem"] = path
        except Exception as e:
            diag["postmortem_error"] = f"{type(e).__name__}: {e}"
        # re-print WITH the artifact path — the phase parser keeps the
        # LAST JSON line, so a successful dump names its file and a hung
        # one still left the first diagnostic (+ the backstop exit)
        print(json.dumps({"preflight": diag}), flush=True)
        os._exit(3)

    timer = threading.Timer(hard_s, watchdog)
    timer.daemon = True
    timer.start()
    import jax
    import jax.numpy as jnp

    stage["name"] = "enumerate-devices"
    dev = jax.devices()[0]
    stage["name"] = "first-dispatch"
    val = int(jax.jit(lambda a: a + 1)(jnp.int32(41)))
    assert val == 42, val
    timer.cancel()
    print(json.dumps({"preflight": {
        "platform": dev.platform,
        "device": str(dev),
        "first_dispatch_s": round(time.monotonic() - t0, 1),
    }}), flush=True)


def main() -> None:
    if "--phase" in sys.argv:
        phase = sys.argv[sys.argv.index("--phase") + 1]
        if phase == "preflight":
            _phase_preflight_main()
        elif phase == "routing":
            _phase_routing_main()
        elif phase == "robustness":
            _phase_robustness_main()
        elif phase == "fairness":
            _phase_fairness_main()
        elif phase == "tracing":
            _phase_tracing_main()
        elif phase == "blackbox":
            _phase_blackbox_main()
        elif phase == "structured":
            _phase_structured_main()
        elif phase == "compile":
            _phase_compile_main()
        elif phase == "saturation":
            _phase_saturation_main()
        elif phase == "kvflow":
            _phase_kvflow_main()
        elif phase == "hydration":
            _phase_hydration_main()
        elif phase == "kvquant":
            _phase_kvquant_main()
        elif phase == "peer":
            _phase_peer_main()
        elif phase == "rebalance":
            _phase_rebalance_main()
        elif phase == "fleet":
            _phase_fleet_main()
        elif phase == "fleet_scale":
            _phase_fleet_scale_main()
        elif phase == "fleet_scale_client":
            _phase_fleet_scale_client_main()
        else:
            assert phase == "micro", phase
            _phase_micro_main()
        return

    # -1) routing lookup bench: pure host-side CPU code, runs BEFORE the
    # chip preflight so the KV-index routing numbers land in the tail even
    # when the TPU tunnel is wedged (every BENCH_r0*.json so far)
    routing = _run_phase(
        "routing", ["bench.py", "--phase", "routing"],
        timeout_s=300, key="routing", min_needed_s=60.0,
    )

    # -0.5) robustness (shed latency + drain time): also CPU-only — the
    # BENCH trajectory captures regressions in how fast overload is turned
    # away and how long the drain barrier holds a terminating pod
    robustness = _run_phase(
        "robustness", ["bench.py", "--phase", "robustness"],
        timeout_s=300, key="robustness", min_needed_s=60.0,
    )

    # -0.25) multi-tenant fairness (weighted decode share + realtime-probe
    # TTFT under flood + qos-off overhead): CPU-only, same wedge-proofing
    fairness = _run_phase(
        "fairness", ["bench.py", "--phase", "fairness"],
        timeout_s=300, key="fairness", min_needed_s=60.0,
    )

    # -0.125) request-tracing spine overhead (same flood, spine off vs
    # on): CPU-only — the observability layer's cost stays a measured
    # number in the BENCH trajectory, not an assertion
    tracing = _run_phase(
        "tracing", ["bench.py", "--phase", "tracing"],
        timeout_s=300, key="tracing", min_needed_s=60.0,
    )

    # -0.1) flight recorder / watchdog / postmortems (docs/37-flight-
    # recorder.md): three injected wedges each NAMED by the watchdog with
    # a postmortem file, plus the recorder's ≤2% p50 noise floor —
    # CPU-only, pre-preflight BY DESIGN: this phase exists because the
    # chip wedge produces no other evidence
    blackbox = _run_phase(
        "blackbox", ["bench.py", "--phase", "blackbox"],
        timeout_s=420, key="blackbox", min_needed_s=90.0,
    )

    # -0.08) structured output (docs/41-structured-output.md): agent
    # swarm of forced schema-constrained tool calls — valid rate 1.0 on
    # vs ~0 off, mask overhead at matched decode length, TTFT, shared-
    # prefix hit rate, zero recompiles after warmup, serial==pipelined —
    # CPU-only, pre-preflight, same wedge-proofing
    structured = _run_phase(
        "structured", ["bench.py", "--phase", "structured"],
        timeout_s=420, key="structured", min_needed_s=90.0,
    )

    # -0.07) XLA compile telemetry (docs/42-compile-telemetry.md): the
    # pad-up zero-mid-traffic-compile guarantee measured on a mixed
    # workload, the recompile-storm detector tripped on purpose, and the
    # watch's own overhead at the noise floor — CPU-only, pre-preflight
    compile_ph = _run_phase(
        "compile", ["bench.py", "--phase", "compile"],
        timeout_s=420, key="compile", min_needed_s=90.0,
    )

    # -0.0625) saturation & goodput (docs/29-saturation-slo.md): ledger
    # exactness under a rollback+preemption+deadline flood + step-meter
    # overhead — CPU-only, pre-preflight, same wedge-proofing
    saturation = _run_phase(
        "saturation", ["bench.py", "--phase", "saturation"],
        timeout_s=300, key="saturation", min_needed_s=60.0,
    )

    # -0.03125) KV-hierarchy flow telemetry (docs/30-kv-flow-telemetry.md):
    # hydration-attribution exactness + per-tier bandwidth honesty + flow-
    # meter overhead — CPU-only, pre-preflight, same wedge-proofing
    kvflow = _run_phase(
        "kvflow", ["bench.py", "--phase", "kvflow"],
        timeout_s=300, key="kvflow", min_needed_s=60.0,
    )

    # -0.015625) compute-or-load hydration planner (docs/31-hydration-
    # planner.md): TTFT on cold remote-resident 8k prompts, planner vs
    # load-only vs compute-only — CPU-only, pre-preflight (survives a
    # wedged chip, the r04/r05 lesson)
    hydration = _run_phase(
        "hydration", ["bench.py", "--phase", "hydration"],
        timeout_s=540, key="hydration", min_needed_s=120.0,
    )

    # -0.013) at-rest KV quantization (docs/38-kv-quantization.md):
    # int4+scales vs pool-precision at rest on a throttled remote link —
    # wire-byte reduction, TTFT, exact hydration partition, greedy token
    # identity — CPU-only, pre-preflight, same wedge-proofing
    kvquant = _run_phase(
        "kvquant", ["bench.py", "--phase", "kvquant"],
        timeout_s=480, key="kvquant", min_needed_s=120.0,
    )

    # -0.0117) peer-engine KV tier (docs/35-peer-kv-reuse.md): priced
    # route-vs-migrate vs owner-affinity under skewed prefix popularity —
    # CPU-only, pre-preflight (fake engines + real router, no chip)
    peer = _run_phase(
        "peer", ["bench.py", "--phase", "peer"],
        # the device arm spawns a 2-process jax.distributed dryrun that
        # cold-compiles the shard-flip program — budget for it
        timeout_s=480, key="peer", min_needed_s=60.0,
    )

    # -0.009) dynamic P/D pool rebalancing (docs/40-pool-rebalancing.md):
    # a decode-heavy shift against a mislabeled 4-engine fleet — static
    # pools trip and HOLD the seat-starvation condition; the rebalancer
    # flips a role and clears it with zero failed/severed streams, and
    # both chaos arms (target killed mid-drain, black-holed controller)
    # finish with traffic flowing — CPU-only, pre-preflight
    rebalance = _run_phase(
        "rebalance", ["bench.py", "--phase", "rebalance"],
        timeout_s=300, key="rebalance", min_needed_s=90.0,
    )

    # -0.0078125) fleet-coherence telemetry (docs/32-fleet-telemetry.md):
    # the ROADMAP-1 baselines — convergence lag across 3 router replicas
    # after a 10k-event storm, stickiness-violation detection, fleet
    # tenant over-admission vs 1 router — CPU-only, pre-preflight
    fleet = _run_phase(
        "fleet", ["bench.py", "--phase", "fleet"],
        timeout_s=300, key="fleet", min_needed_s=60.0,
    )

    # -0.00390625) horizontal router scale-out (docs/34-fleet-routing.md):
    # the execution half of ROADMAP 1 — aggregate req/s in M router
    # processes, 10k concurrent streams, fleet-scaled tenant budgets with
    # a controller-outage drill, and the cold-replica fan-out heal —
    # CPU-only, pre-preflight
    fleet_scale = _run_phase(
        "fleet_scale", ["bench.py", "--phase", "fleet_scale"],
        timeout_s=540, key="fleet_scale", min_needed_s=120.0,
    )

    # 0) chip preflight: one trivial dispatch. A wedged tunnel fails HERE
    # in minutes with an explicit section; the heavy phases are then
    # reported skipped instead of serially eating their timeouts
    preflight = _run_phase(
        "preflight", ["bench.py", "--phase", "preflight"],
        timeout_s=420, key="preflight", min_needed_s=60.0,
    )
    if preflight.get("error"):
        for section in ("microbench", "livestack", "northstar", "int8_8b",
                        "int8_8b_kvauto"):
            _emit(section, {"skipped": "chip preflight failed "
                                       "(tunnel wedged or no device)"})
        out = {
            "metric": "served_northstar_throughput",
            "value": 0.0,
            "unit": "req/s",
            "vs_baseline": 0.0,
            "error": "chip preflight failed — no TPU dispatch possible",
            "preflight": preflight,
            "routing": routing,
            "robustness": robustness,
            "fairness": fairness,
            "tracing": tracing,
            "blackbox": blackbox,
            "structured": structured,
            "compile": compile_ph,
            "saturation": saturation,
            "kvflow": kvflow,
            "hydration": hydration,
            "kvquant": kvquant,
            "peer": peer,
            "rebalance": rebalance,
            "fleet": fleet,
            "fleet_scale": fleet_scale,
            "total_elapsed_s": round(time.monotonic() - _t_start, 1),
        }
        out["regressions"] = _regression_gate(out)
        print(json.dumps(out), flush=True)
        return

    # 1) cheap + fast: guarantees the tail is never empty
    micro = _run_phase("microbench", ["bench.py", "--phase", "micro"],
                       timeout_s=720, key="microbench")

    # 2) THE HEADLINE: the served stack (real router + engine processes),
    #    closed-loop saturation + open-loop offered-QPS @ 2 req/s.
    #    The child's --budget-s is derived FROM the kill window (minus
    #    teardown slack) so it always plans to finish before the parent
    #    would signal its group.
    live_cap = min(1620.0, _remaining() - 30.0)
    livestack = _run_phase(
        "livestack",
        ["bench_livestack.py", "--budget-s", str(max(0.0, live_cap - 120.0))],
        timeout_s=live_cap, key="livestack", min_needed_s=420.0,
    )
    if livestack.get("error") and _remaining() > 1500:
        # the dev tunnel occasionally drops a compile — one retry
        live_cap = min(1320.0, _remaining() - 30.0)
        livestack = _run_phase(
            "livestack",
            ["bench_livestack.py", "--budget-s",
             str(max(0.0, live_cap - 120.0))],
            timeout_s=live_cap, key="livestack", min_needed_s=420.0,
        )

    # 3) the reference's headline model on ONE 16 GiB chip via int8 —
    # BEFORE the in-process ceiling: if the global budget runs short, the
    # 8B capture (a verdict ask) survives and the attribution-only
    # northstar is what gets skipped. Prefill stays on the XLA path until
    # the paged flash-prefill kernel's on-chip sweep lands — decode uses
    # the chip-validated Pallas kernel that makes 8B-class decode fit
    int8_8b = _run_phase(
        "int8_8b",
        ["bench_northstar.py", "--model", "llama-3-8b",
         "--quantization", "int8", "--users", "8", "--rounds", "3",
         "--block-size", "32", "--attention-backend", "pallas",
         "--prefill-attention-backend", "xla", "--kv-cache-dtype", "fp8",
         "--num-blocks", "1600", "--max-model-len", "6144"],
        timeout_s=1000, key="northstar", min_needed_s=300.0,
    )

    # 3b) the fp8-KV-pool arm's `auto` (bf16-pool) counterpart — the
    # ROADMAP item-4 datapoint: same 8B workload, pool at bf16, so only
    # HALF the blocks fit in the same HBM slice (800 x 32 = 25.6k tokens
    # vs fp8's 51.2k). Reported next to int8_8b: decode tok/s, effective
    # KV token capacity, and prefix hit rate quantify what fp8 KV buys
    int8_8b_kvauto = _run_phase(
        "int8_8b_kvauto",
        ["bench_northstar.py", "--model", "llama-3-8b",
         "--quantization", "int8", "--users", "8", "--rounds", "3",
         "--block-size", "32", "--attention-backend", "pallas",
         "--prefill-attention-backend", "xla", "--kv-cache-dtype", "auto",
         "--num-blocks", "800", "--max-model-len", "6144"],
        timeout_s=1000, key="northstar", min_needed_s=300.0,
    )

    # 4) in-process ceiling on the same workload shape (attribution)
    northstar = _run_phase("northstar", ["bench_northstar.py"],
                           timeout_s=800, key="northstar",
                           min_needed_s=240.0)

    served = livestack.get("req_per_s") or 0.0
    open_loop = livestack.get("open_loop") or {}
    out = {
        "metric": "served_northstar_throughput",
        "value": served,
        "unit": "req/s",
        "vs_baseline": round(served / SERVED_BASELINE_REQ_S, 3),
        "served_ttft_p50_s": livestack.get("ttft_p50_s"),
        "served_ttft_p90_s": livestack.get("ttft_p90_s"),
        "open_loop_qps": open_loop.get("offered_qps"),
        "open_loop_ttft_p50_s": open_loop.get("ttft_p50_s"),
        "open_loop_ttft_p90_s": open_loop.get("ttft_p90_s"),
        "livestack": livestack,
        "northstar": northstar,
        "int8_8b": int8_8b,
        "int8_8b_kvauto": int8_8b_kvauto,
        "microbench": micro,
        "routing": routing,
        "robustness": robustness,
        "fairness": fairness,
        "tracing": tracing,
        "blackbox": blackbox,
        "structured": structured,
        "compile": compile_ph,
        "saturation": saturation,
        "kvflow": kvflow,
        "hydration": hydration,
        "kvquant": kvquant,
        "peer": peer,
        "rebalance": rebalance,
        "fleet": fleet,
        "fleet_scale": fleet_scale,
        "total_elapsed_s": round(time.monotonic() - _t_start, 1),
    }
    out["regressions"] = _regression_gate(out)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
